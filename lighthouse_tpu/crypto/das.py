"""PeerDAS data-availability-sampling cells (EIP-7594 shape).

The reference's cell functions are TODO stubs returning zeros
(/root/reference/crypto/kzg/src/lib.rs:169-216, "use proper crypto once
ckzg merges das branch"); this module implements the real polynomial
math: a blob's evaluations extend onto the doubled domain (Reed-Solomon
rate-1/2), cells are the bit-reversal-permuted cosets of that extended
domain, and any half of the cells recovers the rest via the
vanishing-polynomial / coset-division algorithm.

Cell KZG multi-proofs ride the setup's monomial halves
(compute_cells_and_kzg_proofs / verify_cell_kzg_proof below);
`verify_cells_match_blob` remains the data-level check for callers
holding the blob.  Corruption among RECEIVED cells during recovery is
detected whenever the caller supplies more than the minimum half (at
exactly half there is no redundancy — proof-verify cells first).

All arithmetic is over the BLS scalar field; the FFTs are host-side
python ints today (the fr limb kernel in ops/fr.py is the device path
for these butterflies when DAS hits the hot path).
"""

from __future__ import annotations

from lighthouse_tpu.crypto.kzg import (
    BLS_MODULUS,
    KzgError,
    _bit_reversal_permutation,
    _compute_roots_of_unity,
    bls_field_to_bytes,
    bytes_to_bls_field,
)


def _bytes_to_field_elements(data: bytes, count: int) -> list[int]:
    if len(data) != count * 32:
        raise KzgError(f"expected {count} field elements")
    return [bytes_to_bls_field(data[i:i + 32])
            for i in range(0, len(data), 32)]

# mainnet: 4096-wide blobs -> 8192 extended evaluations -> 128 cells of
# 64 field elements.  Smaller (dev) widths scale the cell size down,
# keeping 128 cells whenever the extension has at least 128 points.
CELLS_PER_EXT_BLOB = 128


def _cell_geometry(width: int) -> tuple[int, int]:
    ext = 2 * width
    n_cells = min(CELLS_PER_EXT_BLOB, ext)
    return n_cells, ext // n_cells


def _fft(vals: list[int], roots: list[int], inverse: bool = False) -> list[int]:
    """Iterative radix-2 NTT over the scalar field; `roots` is the full
    n-th root-of-unity list for n == len(vals)."""
    n = len(vals)
    if n == 1:
        return list(vals)
    assert n & (n - 1) == 0
    out = _bit_reversal_permutation(list(vals))
    step = 1
    while step < n:
        stride = n // (2 * step)
        for start in range(0, n, 2 * step):
            for k in range(step):
                idx = (n - k * stride) % n if inverse else k * stride
                w = roots[idx]
                a = out[start + k]
                b = out[start + k + step] * w % BLS_MODULUS
                out[start + k] = (a + b) % BLS_MODULUS
                out[start + k + step] = (a - b) % BLS_MODULUS
        step *= 2
    if inverse:
        n_inv = pow(n, -1, BLS_MODULUS)
        out = [v * n_inv % BLS_MODULUS for v in out]
    return out


def _poly_coeffs_from_blob(blob: bytes, width: int) -> list[int]:
    """Blob evaluations (brp domain order) -> monomial coefficients."""
    evals_brp = _bytes_to_field_elements(blob, width)
    evals = _bit_reversal_permutation(evals_brp)   # brp is an involution
    roots = _compute_roots_of_unity(width)
    return _fft(evals, roots, inverse=True)


def compute_cells(blob: bytes, settings) -> list[bytes]:
    """Extend the blob onto the doubled domain and split into cells.

    Cell c holds the extended evaluations at positions
    [c·cell_size, (c+1)·cell_size) of the BIT-REVERSED extended domain
    (so each cell is a coset — the structure recovery relies on)."""
    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    coeffs = _poly_coeffs_from_blob(blob, width)
    ext_roots = _compute_roots_of_unity(2 * width)
    ext_evals = _fft(coeffs + [0] * width, ext_roots)
    ext_brp = _bit_reversal_permutation(ext_evals)
    return [
        b"".join(bls_field_to_bytes(v)
                 for v in ext_brp[c * cell_size:(c + 1) * cell_size])
        for c in range(n_cells)
    ]


def cells_to_blob(cells: list[bytes], settings) -> bytes:
    """First half of the (brp) extended evaluations IS the blob."""
    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    if len(cells) != n_cells:
        raise KzgError(f"need all {n_cells} cells, got {len(cells)}")
    joined = b"".join(cells)
    return joined[: width * 32]


def _cell_field_elements(cell: bytes, cell_size: int) -> list[int]:
    if len(cell) != cell_size * 32:
        raise KzgError("cell has the wrong size")
    return _bytes_to_field_elements(cell, cell_size)


def recover_all_cells(cell_ids: list[int], cells: list[bytes],
                      settings) -> list[bytes]:
    """Erasure recovery: any >= half of the cells reconstructs all of
    them (vanishing-polynomial + coset-division, the c-kzg das
    algorithm the reference is waiting on).

    Steps: build Z(x) vanishing on the missing cells' cosets (each coset
    is {h·w : w^cell_size = 1}, so its vanishing factor is the sparse
    x^cell_size - h^cell_size); FFT-multiply E·Z, divide on a shifted
    coset where Z has no roots, and re-extend."""
    width = settings.width
    ext = 2 * width
    n_cells, cell_size = _cell_geometry(width)
    if len(cell_ids) != len(cells):
        raise KzgError("cell_ids and cells length mismatch")
    if len(set(cell_ids)) != len(cell_ids):
        raise KzgError("duplicate cell ids")
    if any(not 0 <= c < n_cells for c in cell_ids):
        raise KzgError("cell id out of range")
    if len(cell_ids) < n_cells // 2:
        raise KzgError(
            f"need at least {n_cells // 2} cells, got {len(cell_ids)}")
    have = dict(zip(cell_ids, cells))
    if len(have) == n_cells:
        return [have[c] for c in range(n_cells)]

    ext_roots = _compute_roots_of_unity(ext)
    # brp position -> natural extended-domain position
    nat_of_brp = _bit_reversal_permutation(list(range(ext)))

    # received evaluations in NATURAL order (0 at missing positions)
    e_nat = [0] * ext
    for cid, cell in have.items():
        for k, v in enumerate(_cell_field_elements(cell, cell_size)):
            e_nat[nat_of_brp[cid * cell_size + k]] = v

    # Z(x) = prod over missing cells of (x^cell_size - h_c^cell_size),
    # h_c the first root of the cell's coset
    z = [1]
    for cid in range(n_cells):
        if cid in have:
            continue
        h = ext_roots[nat_of_brp[cid * cell_size]]
        hc = pow(h, cell_size, BLS_MODULUS)
        nz = [0] * (len(z) + cell_size)
        for i, c in enumerate(z):
            nz[i] = (nz[i] - c * hc) % BLS_MODULUS
            nz[i + cell_size] = (nz[i + cell_size] + c) % BLS_MODULUS
        z = nz
    z_coeffs = z + [0] * (ext - len(z))

    z_evals = _fft(z_coeffs, ext_roots)
    ez_evals = [e * zv % BLS_MODULUS for e, zv in zip(e_nat, z_evals)]
    ez_coeffs = _fft(ez_evals, ext_roots, inverse=True)

    # divide on the coset g·domain (g a non-root shift): DZ/Z there,
    # then unshift (the primitive root is outside every power-of-two
    # root subgroup, so Z has no roots on the shifted coset)
    from lighthouse_tpu.crypto.kzg import PRIMITIVE_ROOT_OF_UNITY

    shift = PRIMITIVE_ROOT_OF_UNITY
    shift_pows = [pow(shift, i, BLS_MODULUS) for i in range(ext)]
    ezc_shift = [c * s % BLS_MODULUS for c, s in zip(ez_coeffs, shift_pows)]
    zc_shift = [c * s % BLS_MODULUS
                for c, s in zip(z_coeffs, shift_pows)]
    ez_on_coset = _fft(ezc_shift, ext_roots)
    z_on_coset = _fft(zc_shift, ext_roots)
    d_on_coset = [
        e * pow(zv, -1, BLS_MODULUS) % BLS_MODULUS
        for e, zv in zip(ez_on_coset, z_on_coset)
    ]
    d_shift = _fft(d_on_coset, ext_roots, inverse=True)
    shift_inv = pow(shift, -1, BLS_MODULUS)
    inv_pows = [pow(shift_inv, i, BLS_MODULUS) for i in range(ext)]
    d_coeffs = [c * s % BLS_MODULUS for c, s in zip(d_shift, inv_pows)]
    if any(v != 0 for v in d_coeffs[width:]):
        raise KzgError("recovered polynomial exceeds blob degree "
                       "(inconsistent cells)")

    full_evals = _fft(d_coeffs, ext_roots)
    full_brp = _bit_reversal_permutation(full_evals)
    out = []
    for c in range(n_cells):
        got = have.get(c)
        if got is None:
            got = b"".join(
                bls_field_to_bytes(v)
                for v in full_brp[c * cell_size:(c + 1) * cell_size])
        out.append(got)
    # received cells must be consistent with the recovered polynomial
    for cid, cell in have.items():
        want = full_brp[cid * cell_size:(cid + 1) * cell_size]
        if _cell_field_elements(cell, cell_size) != want:
            raise KzgError(f"cell {cid} inconsistent with recovery")
    return out


# --- cell KZG multi-proofs ---------------------------------------------------
#
# Proof for cell c: π_c = [q_c(τ)]₁ with q_c = (p − I_c) / Z_c, where
# I_c interpolates p on cell c's coset and Z_c(x) = x^cs − h_c^cs is the
# coset's vanishing polynomial (sparse — synthetic division is O(n)).
# Verification: e(C − [I_c(τ)]₁, −G₂) · e(π_c, [Z_c(τ)]₂) == 1 with
# [Z_c(τ)]₂ = τ^cs·G₂ − h_c^cs·G₂ from the setup's G2 monomials.
# (The functions the reference stubs out pending c-kzg's das branch.)


def _coset_start(cid: int, cell_size: int, ext_roots, nat_of_brp) -> int:
    return ext_roots[nat_of_brp[cid * cell_size]]


def _require_monomials(settings, cell_size: int):
    if settings.g1_monomial is None or settings.g2_monomial is None \
            or len(settings.g2_monomial) <= cell_size:
        raise KzgError(
            "cell proofs need the setup's monomial points "
            "(g1_monomial/g2_monomial in the ceremony file)")


_CELL_PROOF_FUSED_MIN_WIDTH = 256   # device-batch at production widths
_CELL_PROOF_MAX_LANES = 1 << 17     # chunk cells to bound HBM footprint


def _batched_cell_proof_msms(q_lists: list[list[int]], settings
                             ) -> list:
    """All cells' quotient MSMs as chunked fused dispatches on the
    unified MSM plane (ops/msm, plain g1 track).

    The per-cell loop below issues one device MSM PER CELL (128
    dispatches per blob on a proposer).  Here lanes lay out s-major
    (lane s·G + g = monomial point s weighted by cell g's coefficient)
    through ONE windowed scan + segment sum per chunk; chunk size caps
    resident lanes so the 16-entry per-lane window tables stay inside
    HBM.  Returns affine (x, y) int pairs or cv.INF per cell."""
    import numpy as np

    from lighthouse_tpu.ops import bigint as bi
    from lighthouse_tpu.ops import ec
    from lighthouse_tpu.ops import msm as _msm

    seg_pad = _msm.bucket(len(q_lists[0]))
    chunk = max(1, _CELL_PROOF_MAX_LANES // seg_pad)
    chunk = 1 << (chunk.bit_length() - 1)   # floor to a power of two
    mono = settings.g1_monomial[:seg_pad] + [None] * max(
        0, seg_pad - len(settings.g1_monomial))
    mx = ec.ints_to_mont_limbs(
        [p[0] if p is not None else 0 for p in mono])
    my = ec.ints_to_mont_limbs(
        [p[1] if p is not None else 0 for p in mono])
    out = []
    for c0 in range(0, len(q_lists), chunk):
        qs = q_lists[c0:c0 + chunk]
        g = len(qs)
        g_pad = _msm.bucket(g)
        lanes = seg_pad * g_pad
        xs = np.zeros((lanes, bi.L), np.uint32)
        ys = np.zeros((lanes, bi.L), np.uint32)
        scalars = [0] * lanes
        for s in range(seg_pad):
            base = s * g_pad
            row_x, row_y = mx[s], my[s]
            for gi, q in enumerate(qs):
                k = q[s] if s < len(q) else 0
                if k and mono[s] is not None:
                    xs[base + gi] = row_x
                    ys[base + gi] = row_y
                    scalars[base + gi] = k
        digits = ec.scalars_to_digits(scalars, n_bits=256)
        X, Y, Z = _msm.fold_device(xs, ys, digits, g_pad)
        out.extend(_msm.jacobian_rows_to_affine(X[:g], Y[:g], Z[:g]))
    return out


def compute_cells_and_kzg_proofs(blob: bytes, settings
                                 ) -> tuple[list[bytes], list[bytes]]:
    """Cells + one KZG multi-proof per cell.

    Production widths batch ALL cells' quotient MSMs into chunked fused
    dispatches (_batched_cell_proof_msms) instead of one device MSM per
    cell; dev widths keep the per-cell g1_lincomb path."""
    from lighthouse_tpu.crypto import kzg as _kzg
    from lighthouse_tpu.crypto.bls import curve as cv

    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    _require_monomials(settings, cell_size)
    cells = compute_cells(blob, settings)
    coeffs = _poly_coeffs_from_blob(blob, width)
    ext_roots = _compute_roots_of_unity(2 * width)
    nat_of_brp = _bit_reversal_permutation(list(range(2 * width)))
    q_lists = []
    for cid in range(n_cells):
        h = _coset_start(cid, cell_size, ext_roots, nat_of_brp)
        a = pow(h, cell_size, BLS_MODULUS)
        # synthetic division by x^cs − a: top-down, q_j = p_{j+cs} + a·q_{j+cs}
        q = [0] * max(width - cell_size, 1)
        for j in range(width - cell_size - 1, -1, -1):
            carry = q[j + cell_size] if j + cell_size < len(q) else 0
            q[j] = (coeffs[j + cell_size] + a * carry) % BLS_MODULUS
        q_lists.append(q)
    if width >= _CELL_PROOF_FUSED_MIN_WIDTH:
        pts = _batched_cell_proof_msms(q_lists, settings)
        proofs = [cv.g1_to_bytes(p) for p in pts]
    else:
        proofs = [cv.g1_to_bytes(
            _kzg.g1_lincomb(settings.g1_monomial[:len(q)], q))
            for q in q_lists]
    return cells, proofs


def _interpolation_commitment(cell: bytes, cid: int, settings):
    """[I_c(τ)]₁ for the cell's claimed evaluations."""
    from lighthouse_tpu.crypto import kzg as _kzg

    n_cells, cell_size = _cell_geometry(settings.width)
    coeffs = _interpolation_coeffs(cell, cid, settings)
    return _kzg.g1_lincomb(settings.g1_monomial[:cell_size], coeffs)


def _interpolation_coeffs(cell: bytes, cid: int, settings) -> list[int]:
    """Monomial coefficients of I_c (coset inverse-NTT, cs ≤ 64 so the
    O(cs²) direct transform is fine) — split out so the fused batch
    verifier can fold them straight onto the monomial setup points."""
    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    ext_roots = _compute_roots_of_unity(2 * width)
    nat_of_brp = _bit_reversal_permutation(list(range(2 * width)))
    vals = _cell_field_elements(cell, cell_size)
    # evaluation points: x_k = ext_roots[nat_of_brp[cid*cs + k]] = h·ω^{e_k}
    h = _coset_start(cid, cell_size, ext_roots, nat_of_brp)
    h_inv = pow(h, -1, BLS_MODULUS)
    # coset exponents e_k with x_k = h·ω^{e_k}, ω of order cs on the
    # doubled domain: ω = ext_roots[2*width // cell_size ... ] — recover
    # e_k directly from the position ratio
    omega = ext_roots[(2 * width // cell_size) % (2 * width)]
    # map each point to its ω-power via a lookup (cs entries)
    pow_of = {pow(omega, j, BLS_MODULUS): j for j in range(cell_size)}
    reordered = [0] * cell_size
    for k in range(cell_size):
        x = ext_roots[nat_of_brp[cid * cell_size + k]]
        j = pow_of[x * h_inv % BLS_MODULUS]
        reordered[j] = vals[k]
    cs_inv = pow(cell_size, -1, BLS_MODULUS)
    coeffs = []
    for m in range(cell_size):
        acc = 0
        for j, v in enumerate(reordered):
            acc = (acc + v * pow(omega, (-j * m) % cell_size, BLS_MODULUS)
                   ) % BLS_MODULUS
        coeffs.append(acc * cs_inv % BLS_MODULUS
                      * pow(h_inv, m, BLS_MODULUS) % BLS_MODULUS)
    return coeffs


def verify_cell_kzg_proof(commitment_bytes: bytes, cell_id: int,
                          cell: bytes, proof_bytes: bytes,
                          settings) -> bool:
    """e(C − [I(τ)]₁, −G₂) · e(π, [Z(τ)]₂) == 1."""
    from lighthouse_tpu.crypto.bls import curve as cv

    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    _require_monomials(settings, cell_size)
    if not 0 <= int(cell_id) < n_cells:
        return False
    try:
        commitment = cv.g1_from_bytes(commitment_bytes)
        proof = cv.g1_from_bytes(proof_bytes)
        interp = _interpolation_commitment(cell, int(cell_id), settings)
    except (ValueError, KzgError):
        return False
    ext_roots = _compute_roots_of_unity(2 * width)
    nat_of_brp = _bit_reversal_permutation(list(range(2 * width)))
    h = _coset_start(int(cell_id), cell_size, ext_roots, nat_of_brp)
    a = pow(h, cell_size, BLS_MODULUS)
    z_tau_g2 = cv.g2_add(
        settings.g2_monomial[cell_size],
        cv.g2_neg(cv.g2_mul(cv.g2_generator(), a)))
    c_minus_i = cv.g1_add(commitment, cv.g1_neg(interp)) \
        if interp is not cv.INF else commitment
    from lighthouse_tpu.crypto.kzg import _pairing_check

    return _pairing_check([
        (c_minus_i, cv.g2_neg(cv.g2_generator())),
        (proof, z_tau_g2),
    ])


def verify_cell_kzg_proof_batch(commitments: list[bytes],
                                cell_ids: list[int], cells: list[bytes],
                                proofs: list[bytes], settings) -> bool:
    """Batch cell-proof verification (every triplet must hold).

    Production batches (>= 8 cells — a PeerDAS sampling round checks
    hundreds) fold into ONE fused dispatch by random linear combination:
    each cell check  e(Cᵢ − Iᵢ, −G₂)·e(πᵢ, (τⁿ − aᵢ)G₂) == 1  (n =
    cell_size, aᵢ = hᵢⁿ the coset vanishing constant) rewrites as
    e(Cᵢ − Iᵢ + aᵢπᵢ, −G₂)·e(πᵢ, τⁿG₂) == 1, so with verifier scalars
    rᵢ the whole batch is

      e(Σ rᵢ(Cᵢ − Iᵢ + aᵢπᵢ), −G₂) · e(Σ rᵢπᵢ, τⁿG₂) == 1

    — the exact 2-MSM + 2-pairing shape of kzg._kzg_fused_check (the
    blob batch path), with τⁿG₂ = g2_monomial[cell_size] in the second
    slot.  The interpolation commitments Iᵢ never materialize: their
    monomial coefficients fold onto the g1_monomial setup points with
    AGGREGATED scalars −Σᵢ rᵢ·coeffᵢₘ (cell_size extra lanes total, not
    per cell).  Small batches keep the per-cell loop.  Matches the
    reference's c-kzg verify_cell_kzg_proof_batch fold
    (/root/reference/crypto/kzg/src/lib.rs cell-proof surface)."""
    n = len(commitments)
    if not (n == len(cell_ids) == len(cells) == len(proofs)):
        return False
    if n < 8:
        return all(
            verify_cell_kzg_proof(c, cid, cell, pf, settings)
            for c, cid, cell, pf in zip(commitments, cell_ids, cells,
                                        proofs))

    import hashlib
    import secrets

    from lighthouse_tpu.crypto import kzg as _kzg
    from lighthouse_tpu.crypto.bls import curve as cv

    width = settings.width
    n_cells, cell_size = _cell_geometry(width)
    try:
        _require_monomials(settings, cell_size)
    except KzgError:
        return False
    try:
        cs_pts = [cv.g1_from_bytes(b) for b in commitments]
        pi_pts = [cv.g1_from_bytes(b) for b in proofs]
        coeffs = []
        for cid, cell in zip(cell_ids, cells):
            if not 0 <= int(cid) < n_cells:
                return False
            coeffs.append(_interpolation_coeffs(cell, int(cid), settings))
    except (ValueError, KzgError):
        return False

    seed = hashlib.sha256(
        b"LHTPU_RLC_CELL_BATCH_" + width.to_bytes(16, "big")
        + n.to_bytes(16, "big") + b"".join(commitments)
        + b"".join(proofs)
        + b"".join(int(c).to_bytes(8, "big") for c in cell_ids)
        + secrets.token_bytes(32)).digest()
    r = int.from_bytes(seed, "big") % BLS_MODULUS
    r_list = [pow(r, i + 1, BLS_MODULUS) for i in range(n)]

    ext_roots = _compute_roots_of_unity(2 * width)
    nat_of_brp = _bit_reversal_permutation(list(range(2 * width)))
    lhs_points = list(cs_pts)
    lhs_scalars = list(r_list)
    mono_scalars = [0] * cell_size
    for ri, cid, cf, pi in zip(r_list, cell_ids, coeffs, pi_pts):
        for m_i, cm in enumerate(cf):
            mono_scalars[m_i] = (mono_scalars[m_i] - ri * cm) % BLS_MODULUS
        h = _coset_start(int(cid), cell_size, ext_roots, nat_of_brp)
        a = pow(h, cell_size, BLS_MODULUS)
        lhs_points.append(pi)
        lhs_scalars.append(ri * a % BLS_MODULUS)
    lhs_points.extend(settings.g1_monomial[:cell_size])
    lhs_scalars.extend(mono_scalars)
    return _kzg._kzg_fused_check(
        lhs_points, lhs_scalars, pi_pts, r_list, settings,
        tau_g2=settings.g2_monomial[cell_size],
        cache_attr="_fused_g2_rows_cell")


def verify_cells_match_blob(cells: list[bytes], cell_ids: list[int],
                            blob: bytes, settings) -> bool:
    """Check cells against the blob they claim to extend (the data-level
    check available without cell multi-proofs)."""
    n_cells, _ = _cell_geometry(settings.width)
    if len(cells) != len(cell_ids):
        return False
    if any(not 0 <= cid < n_cells for cid in cell_ids):
        return False
    expected = compute_cells(blob, settings)
    return all(expected[cid] == cell
               for cid, cell in zip(cell_ids, cells))


__all__ = [
    "CELLS_PER_EXT_BLOB",
    "cells_to_blob",
    "compute_cells",
    "recover_all_cells",
    "verify_cells_match_blob",
]
