"""EIP-2335 encrypted BLS keystores.

Rebuild of /root/reference/crypto/eth2_keystore: scrypt or PBKDF2 key
derivation + AES-128-CTR encryption + sha256 checksum, serialized as the
standard keystore JSON.
"""

from __future__ import annotations

import hashlib
import json
import secrets
import unicodedata
import uuid

# `cryptography` is an optional dependency (AES-128-CTR only): importing
# this module must not fail where it is absent — keystore tests
# importorskip on it, and everything else in crypto/ stays usable.
try:
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )
except ImportError:  # pragma: no cover - environment-dependent
    Cipher = algorithms = modes = None


class KeystoreError(ValueError):
    pass


def _normalize_password(password: str) -> bytes:
    norm = unicodedata.normalize("NFKD", password)
    # strip C0/C1 control codes and DEL per EIP-2335
    return "".join(
        c for c in norm
        if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F)).encode()


def _kdf(password: bytes, params: dict) -> bytes:
    fn = params["function"]
    p = params["params"]
    salt = bytes.fromhex(p["salt"])
    if fn == "scrypt":
        return hashlib.scrypt(
            password, salt=salt, n=p["n"], r=p["r"], p=p["p"],
            dklen=p["dklen"], maxmem=256 * 1024 * 1024)
    if fn == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            p["prf"].removeprefix("hmac-"), password, salt, p["c"], p["dklen"])
    raise KeystoreError(f"unsupported kdf {fn}")


def _aes128ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    if Cipher is None:
        raise KeystoreError(
            "the optional `cryptography` package is required for "
            "EIP-2335 keystore encryption/decryption and is not "
            "installed")
    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def encrypt(secret: bytes, password: str, *, path: str = "",
            kdf: str = "scrypt", description: str = "") -> dict:
    """Secret -> EIP-2335 keystore dict."""
    pw = _normalize_password(password)
    salt = secrets.token_bytes(32)
    if kdf == "scrypt":
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": 262144, "r": 8, "p": 1,
                       "salt": salt.hex()},
            "message": "",
        }
    else:
        kdf_module = {
            "function": "pbkdf2",
            "params": {"dklen": 32, "c": 262144, "prf": "hmac-sha256",
                       "salt": salt.hex()},
            "message": "",
        }
    dk = _kdf(pw, kdf_module)
    iv = secrets.token_bytes(16)
    cipher_message = _aes128ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_message).digest()

    from lighthouse_tpu.crypto import bls

    pubkey = ""
    if len(secret) == 32:
        try:
            pubkey = bls.SecretKey.from_bytes(secret).public_key() \
                .to_bytes().hex()
        except Exception as e:
            from lighthouse_tpu.common.metrics import record_swallowed

            record_swallowed("keystore.pubkey_derive", e)
            pubkey = ""
    return {
        "crypto": {
            "kdf": kdf_module,
            "checksum": {"function": "sha256", "params": {},
                         "message": checksum.hex()},
            "cipher": {"function": "aes-128-ctr", "params": {"iv": iv.hex()},
                       "message": cipher_message.hex()},
        },
        "description": description,
        "pubkey": pubkey,
        "path": path,
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


def decrypt(keystore: dict, password: str) -> bytes:
    """EIP-2335 keystore dict -> secret bytes (raises on bad password)."""
    if keystore.get("version") != 4:
        raise KeystoreError("only version-4 keystores supported")
    crypto = keystore["crypto"]
    pw = _normalize_password(password)
    dk = _kdf(pw, crypto["kdf"])
    cipher_message = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_message).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("invalid password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError("unsupported cipher")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, cipher_message)


def save(keystore: dict, path) -> None:
    with open(path, "w") as f:
        json.dump(keystore, f, indent=2)


def load(path) -> dict:
    with open(path) as f:
        return json.load(f)
