"""EIP-2333 BLS key derivation (HKDF tree).

Rebuild of /root/reference/crypto/eth2_key_derivation: hkdf_mod_r master
key generation and the Lamport-based child derivation, from the EIP-2333
specification, on the python stdlib (hashlib/hmac).
"""

from __future__ import annotations

import hashlib
import hmac

from lighthouse_tpu.crypto.bls.fields import R as CURVE_ORDER

_SALT0 = b"BLS-SIG-KEYGEN-SALT-"


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    """IKM -> secret key scalar in (0, r)."""
    salt = _SALT0
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % CURVE_ORDER
    return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> list[bytes]:
    prk = _hkdf_extract(salt, ikm)
    okm = _hkdf_expand(prk, b"", 255 * 32)
    return [okm[i:i + 32] for i in range(0, 255 * 32, 32)]


def _flip_bits(data: bytes) -> bytes:
    return bytes(b ^ 0xFF for b in data)


def parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport = _ikm_to_lamport_sk(ikm, salt)
    lamport += _ikm_to_lamport_sk(_flip_bits(ikm), salt)
    return hashlib.sha256(
        b"".join(hashlib.sha256(chunk).digest() for chunk in lamport)
    ).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed must be at least 32 bytes (EIP-2333)")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    return hkdf_mod_r(parent_sk_to_lamport_pk(parent_sk, index))


def derive_path(seed: bytes, path: str) -> int:
    """EIP-2334 path derivation, e.g. 'm/12381/3600/0/0/0'."""
    parts = path.strip().split("/")
    if parts[0] != "m":
        raise ValueError("path must start with m")
    sk = derive_master_sk(seed)
    for p in parts[1:]:
        if not p.isdigit():
            raise ValueError(f"invalid path component {p!r}")
        sk = derive_child_sk(sk, int(p))
    return sk


def validator_keys(seed: bytes, index: int) -> tuple[int, int]:
    """(signing_sk, withdrawal_sk) for validator `index` per EIP-2334."""
    withdrawal = derive_path(seed, f"m/12381/3600/{index}/0")
    signing = derive_child_sk(withdrawal, 0)
    return signing, withdrawal
