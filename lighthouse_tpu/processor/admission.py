"""Admission control + degradation ladder for the beacon processor.

Mainnet-width ingest (tens of thousands of unaggregated attestations plus
thousands of aggregates per slot) can outrun the verification plane for
whole slots at a time.  Before this layer the only overload behaviour was
a silent drop-oldest on four LIFO queues; now every queue has an explicit
policy and every discard is accounted:

- **drop-oldest** stays for gossip flood lanes (newest gossip is the most
  likely to still matter), but each drop increments
  ``processor_shed_total{work_type,reason}`` and is traced;
- **reject-newest with backoff signaling** for RPC/API lanes: the
  :class:`Admission` verdict a rejected ``submit`` returns carries a
  ``retry_after_s`` hint the HTTP/RPC surface can turn into a 503 +
  Retry-After;
- a **degradation ladder** sheds the cheapest-to-regenerate work first
  when sustained pressure builds:

  ====  ===================  ===========================================
  rung  name                 behaviour
  ====  ===================  ===========================================
  0     normal               full service
  1     coalesce             batch flush deadlines stretch by
                             ``LHTPU_SHED_COALESCE_FACTOR`` so sweeps run
                             bigger (fewer, fuller device batches — the
                             cheapest defense: a merged bitfield is a
                             pairing never paid for)
  2     shed_unaggregated    new unaggregated attestations are shed at
                             admission (aggregates carry ~committee-width
                             more value per pairing, so they survive one
                             rung longer)
  3     shed_aggregates      aggregates shed too; only blocks, chain
                             segments and the other protected lanes are
                             admitted
  ====  ===================  ===========================================

The ladder is driven by per-lane queue-depth EWMAs swept by the
processor's dedicated sweeper task (the manager loop can park on an
unbounded worker acquire — exactly when the ladder must keep
observing), with the PR 4 circuit-breaker shape: *escalation* needs
``LHTPU_SHED_UP_SWEEPS`` consecutive sweeps above the high watermark
(consecutive faults open the breaker), the band between the watermarks
holds the rung (hysteresis — no flapping on a noisy boundary), and a
sweep that finds every governed lane back at/below the low watermark
snaps straight to normal (the half-open probe succeeding closes the
breaker in one observation; the acceptance drill is "recovered within
one sweep of the storm ending").

This module is deliberately WorkType-agnostic (lanes are opaque dict
keys supplied by the processor) so it imports nothing from
beacon_processor and stays trivially unit-testable.
"""

from __future__ import annotations

import threading

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed

NORMAL = 0
COALESCE = 1
SHED_UNAGGREGATED = 2
SHED_AGGREGATES = 3

RUNG_NAMES = ("normal", "coalesce", "shed_unaggregated", "shed_aggregates")


class Admission(int):
    """Truthy/falsy ``submit`` verdict (bool-compatible: existing callers
    keep doing ``if not bp.submit(...)``) carrying the shed reason and a
    backoff hint for reject-newest lanes."""

    reason: str | None
    retry_after_s: float

    def __new__(cls, accepted: bool, reason: str | None = None,
                retry_after_s: float = 0.0) -> "Admission":
        self = super().__new__(cls, 1 if accepted else 0)
        self.reason = reason
        self.retry_after_s = retry_after_s
        return self

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Admission(accepted={bool(self)}, reason={self.reason!r}, "
                f"retry_after_s={self.retry_after_s})")


ACCEPTED = Admission(True)


class AdmissionController:
    """Queue-depth EWMAs + the degradation ladder.

    ``governed`` are the lanes whose pressure drives the ladder (the
    attestation flood lanes); ``shed_order`` lists them cheapest-first —
    rung ``SHED_UNAGGREGATED`` sheds ``shed_order[0]``, rung
    ``SHED_AGGREGATES`` sheds ``shed_order[:2]``.

    Thread model: ``shed_reason``/``flush_factor`` are read from any
    producer thread (single int/dict reads of immutable-enough state);
    ``sweep`` mutates under a lock and is called from the processor's
    sweeper task (and directly by drills/tests).
    """

    def __init__(
        self,
        governed: tuple,
        shed_order: tuple,
        high: float | None = None,
        low: float | None = None,
        alpha: float | None = None,
        up_sweeps: int | None = None,
        coalesce_factor: float | None = None,
        retry_base_s: float | None = None,
    ):
        self.governed = tuple(governed)
        self.shed_order = tuple(shed_order)
        self.high = high if high is not None else envreg.get_float(
            "LHTPU_ADMIT_HIGH", 0.75)
        self.low = low if low is not None else envreg.get_float(
            "LHTPU_ADMIT_LOW", 0.25)
        self.alpha = alpha if alpha is not None else envreg.get_float(
            "LHTPU_ADMIT_EWMA_ALPHA", 0.4)
        self.up_sweeps = max(1, up_sweeps if up_sweeps is not None
                             else envreg.get_int("LHTPU_SHED_UP_SWEEPS", 2))
        self.coalesce_factor = (
            coalesce_factor if coalesce_factor is not None
            else envreg.get_float("LHTPU_SHED_COALESCE_FACTOR", 4.0))
        self.retry_base_s = (
            retry_base_s if retry_base_s is not None
            else envreg.get_float("LHTPU_ADMIT_RETRY_S", 0.25))
        self.rung = NORMAL
        self.sweeps = 0           # lifetime sweep count (drill surface)
        self._streak = 0          # consecutive sweeps above high watermark
        self._ewma: dict = {}
        self._lock = threading.Lock()
        self._shed_lanes: frozenset = frozenset()

    # -- producer-side reads (any thread) ----------------------------------

    def shed_reason(self, lane) -> str | None:
        """Non-None when the ladder sheds this lane at admission."""
        if lane in self._shed_lanes:
            return ("ladder_unaggregated" if lane == self.shed_order[0]
                    else "ladder_aggregates")
        return None

    def flush_factor(self) -> float:
        """Batch-flush deadline multiplier (>1 from rung COALESCE up)."""
        return self.coalesce_factor if self.rung >= COALESCE else 1.0

    def retry_after_s(self, depth: int, limit: int) -> float:
        """Backoff hint for a reject-newest lane: scales with how far
        over the line the producer is pushing."""
        fullness = depth / max(limit, 1)
        return round(self.retry_base_s * max(1.0, fullness + self.rung), 3)

    def pressure(self, lane) -> float:
        return self._ewma.get(lane, 0.0)

    # -- manager-side sweep -------------------------------------------------

    def sweep(self, depths: dict) -> int:
        """One ladder observation over ``{lane: (depth, limit)}``.
        Returns the rung in force after the sweep."""
        with self._lock:
            self.sweeps += 1
            instant_max = 0.0
            ewma_max = 0.0
            for lane in self.governed:
                depth, limit = depths.get(lane, (0, 1))
                instant = depth / max(limit, 1)
                prev = self._ewma.get(lane, 0.0)
                cur = self.alpha * instant + (1.0 - self.alpha) * prev
                self._ewma[lane] = cur
                instant_max = max(instant_max, instant)
                ewma_max = max(ewma_max, cur)
            old = self.rung
            if instant_max <= self.low:
                # storm over: snap to normal in ONE sweep (half-open
                # probe success) and forget the smoothed history so the
                # next storm is judged fresh
                self.rung = NORMAL
                self._streak = 0
                if old != NORMAL:
                    for lane in self.governed:
                        self._ewma[lane] = instant_max
            elif ewma_max >= self.high:
                self._streak += 1
                if self._streak >= self.up_sweeps:
                    self.rung = min(SHED_AGGREGATES, self.rung + 1)
                    self._streak = 0
            else:
                # hysteresis band: hold the rung, reset the streak
                self._streak = 0
            self._shed_lanes = frozenset(
                self.shed_order[: max(0, self.rung - COALESCE)])
            if self.rung != old:
                self._record_transition(old, self.rung)
            return self.rung

    def _record_transition(self, old: int, new: int) -> None:
        try:
            REGISTRY.gauge(
                "processor_ladder_rung",
                "degradation ladder rung in force "
                "(0 normal .. 3 shed_aggregates)").set(new)
            REGISTRY.counter(
                "processor_ladder_transitions_total",
                "degradation ladder rung changes, by direction and rung",
            ).labels(direction="up" if new > old else "down",
                     rung=RUNG_NAMES[new]).inc()
            from lighthouse_tpu.common import flight_recorder as flight
            from lighthouse_tpu.common import tracing

            with tracing.span("beacon_processor.ladder",
                              from_rung=RUNG_NAMES[old],
                              to_rung=RUNG_NAMES[new]):
                pass
            # every rung change is a black-box event: after a trip, the
            # dump shows the ladder walking up under pressure
            flight.emit("ladder", plane="admission", old=RUNG_NAMES[old],
                        new=RUNG_NAMES[new], sweeps=self.sweeps)
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            record_swallowed("admission.ladder_transition", e)


__all__ = [
    "ACCEPTED",
    "Admission",
    "AdmissionController",
    "COALESCE",
    "NORMAL",
    "RUNG_NAMES",
    "SHED_AGGREGATES",
    "SHED_UNAGGREGATED",
]
