"""Sustained-ingest firehose driver: continuous arrival, storms, books.

The flood bench (bench.py --child-flood) answers "how fast does one
pre-built batch verify"; this module answers the ROADMAP item 1
question: what happens when arrival NEVER stops.  It drives a
:class:`~lighthouse_tpu.processor.BeaconProcessor` with a continuous
per-subnet payload stream, holds a target number of events in flight,
optionally opens an :class:`~lighthouse_tpu.ops.faults.IngestPlan`
storm (burst / slow-consumer stall / duplicate flood / invalid-signature
flood), and keeps double-entry books the acceptance drill audits:

    enqueued == processed + shed + still-queued   (per work type)

Every discard the processor makes is visible in
``processor_shed_total{work_type,reason}``; :func:`ledger` recomputes
the invariant from the in-process mirrors and reports any unaccounted
remainder (which must be zero).

Used by ``bench.py --child-firehose`` (real attestations through the
chain's batch-BLS pipeline) and by the zero-XLA drills in
tests/test_processor.py (dummy payloads, same queue policies).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable

from lighthouse_tpu.ops import faults
from lighthouse_tpu.processor.beacon_processor import (
    BeaconProcessor,
    WorkEvent,
    WorkType,
    queue_wait_histogram,
)


def queue_wait_percentiles(wt: WorkType,
                           qs: tuple[float, ...] = (0.5, 0.99)) -> dict:
    """Interpolated quantiles of the enqueue->dequeue wait for one work
    type, read from the beacon_processor_queue_wait_seconds histogram
    (the PR 1 tracing's labeled series)."""
    child = queue_wait_histogram().labels(work_type=wt.name.lower())
    with child._lock:
        counts = list(child.counts)
        n = child.n
        buckets = child.buckets
    out = {}
    for q in qs:
        key = f"p{int(q * 100)}"
        if n == 0:
            out[key] = 0.0
            continue
        target = q * n
        cum = 0
        lo = 0.0
        value = buckets[-1]
        for b, c in zip(buckets, counts[:-1]):
            if c and cum + c >= target:
                value = lo + (b - lo) * ((target - cum) / c)
                break
            cum += c
            lo = b
        out[key] = value
    return out


def ledger(bp: BeaconProcessor) -> dict:
    """Double-entry audit of the processor's books.

    Per work type: enqueued, processed, shed (by reason), still queued,
    and ``unaccounted = enqueued - processed - shed - queued`` — the
    firehose acceptance criterion is that unaccounted is zero for every
    lane once the processor drains."""
    out: dict[str, dict] = {}
    m = bp.metrics
    for wt in WorkType:
        enq = m.enqueued.get(wt, 0)
        if not enq:
            continue
        shed = {r: n for (w, r), n in m.shed.items() if w is wt}
        row = {
            "enqueued": enq,
            "processed": m.processed.get(wt, 0),
            "shed": shed,
            "queued": bp.queue_len(wt),
        }
        row["unaccounted"] = (row["enqueued"] - row["processed"]
                              - sum(shed.values()) - row["queued"])
        out[wt.name.lower()] = row
    return out


def unaccounted_total(bp: BeaconProcessor) -> int:
    return sum(row["unaccounted"] for row in ledger(bp).values())


@dataclass
class PhaseStats:
    label: str
    seconds: float = 0.0
    submitted: int = 0
    accepted: int = 0
    shed_at_admission: int = 0
    processed_delta: int = 0
    rung_max: int = 0
    rung_end: int = 0

    @property
    def per_s(self) -> float:
        return self.processed_delta / self.seconds if self.seconds else 0.0


class FirehoseDriver:
    """Continuous-arrival pump over one batchable work-type lane.

    ``make_payload(i)`` produces the i-th honest payload (the caller
    decides whether that is a real attestation or a test token);
    ``corrupt(payload)`` produces an invalid-signature variant for
    ``mode="invalid"`` storms.  ``process_batch`` is wrapped so
    slow-consumer storms can stall it via
    :func:`lighthouse_tpu.ops.faults.consumer_stall_s`.
    """

    def __init__(
        self,
        bp: BeaconProcessor,
        make_payload: Callable[[int], Any],
        process_batch: Callable[[list], Any],
        work_type: WorkType = WorkType.GOSSIP_ATTESTATION,
        corrupt: Callable[[Any], Any] | None = None,
    ):
        self.bp = bp
        self.work_type = work_type
        self.make_payload = make_payload
        self.corrupt = corrupt
        self._inner_process = process_batch
        self._seq = 0

    def _process(self, payloads: list) -> Any:
        # slow-consumer stalls are injected by the PROCESSOR's own
        # dispatch wrapper (beacon_processor._with_ingest_stall) — the
        # storm hits the real consumer path, not a harness shim
        return self._inner_process(payloads)

    def _payload_stream(self, plan: faults.IngestPlan | None
                        ) -> Iterable[Any]:
        """One storm-shaped arrival wave: honest payloads, plus
        duplicate / invalid copies per the plan."""
        while True:
            payload = self.make_payload(self._seq)
            self._seq += 1
            yield payload
            if plan is None:
                continue
            copies = max(0, int(plan.factor) - 1)
            if plan.mode == "dup":
                for _ in range(copies):
                    yield payload
            elif plan.mode == "invalid" and self.corrupt is not None:
                for _ in range(copies):
                    yield self.corrupt(payload)

    async def run_phase(
        self,
        label: str,
        seconds: float,
        inflight_target: int,
        plan: faults.IngestPlan | None = None,
        on_tick: Callable[["PhaseStats"], None] | None = None,
    ) -> PhaseStats:
        """Hold ``inflight_target`` events resident in the lane's queue
        for ``seconds`` (arrival refills whatever the consumer drains —
        sustained ingest, not a one-shot batch).  Under a ``burst``
        storm the refill target multiplies by ``plan.factor``, pushing
        the lane over its watermarks on purpose.

        A phase with ``plan=None`` does not clear an externally-armed
        plan (LHTPU_INGEST_FAULT_MODE / install_ingest_plan): the
        background storm keeps blowing, shapes this phase's arrival,
        and is restored after any phase that installed its own."""
        prior = faults.snapshot_ingest_plan()
        if plan is not None:
            faults.install_ingest_plan(plan)
        else:
            plan = faults.active_ingest_plan()
        stats = PhaseStats(label=label)
        wt = self.work_type
        m = self.bp.metrics
        processed0 = m.processed.get(wt, 0)
        stream = self._payload_stream(plan)
        t0 = time.monotonic()
        deadline = t0 + seconds
        target = inflight_target
        if plan is not None and plan.mode == "burst":
            target = int(inflight_target * max(1.0, plan.factor))
        try:
            while True:
                now = time.monotonic()
                if now >= deadline:
                    break
                deficit = target - self.bp.queue_len(wt)
                for _ in range(max(0, deficit)):
                    payload = next(stream)
                    verdict = self.bp.submit(WorkEvent(
                        wt, payload=payload, process_batch=self._process))
                    stats.submitted += 1
                    if verdict:
                        stats.accepted += 1
                    else:
                        stats.shed_at_admission += 1
                stats.rung_max = max(stats.rung_max, self.bp.admission.rung)
                if on_tick is not None:
                    stats.seconds = now - t0
                    stats.processed_delta = m.processed.get(wt, 0) - processed0
                    on_tick(stats)
                # yield to the manager loop; the flush interval is the
                # natural arrival granularity
                await asyncio.sleep(self.bp.batch_flush_ms / 1000.0)
        finally:
            faults.restore_ingest_plan(prior)
        stats.seconds = time.monotonic() - t0
        stats.processed_delta = m.processed.get(wt, 0) - processed0
        stats.rung_max = max(stats.rung_max, self.bp.admission.rung)
        stats.rung_end = self.bp.admission.rung
        return stats


__all__ = [
    "FirehoseDriver",
    "PhaseStats",
    "ledger",
    "queue_wait_percentiles",
    "unaccounted_total",
]
