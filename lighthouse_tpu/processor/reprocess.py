"""Work reprocessing: early blocks, unknown-block attestations, rpc retries.

Rebuild of /root/reference/beacon_node/beacon_processor/src/
work_reprocessing_queue.rs: messages that arrive before their dependencies
(a block before its slot starts; attestations for a block still in flight)
are parked and re-queued when the dependency lands or a timeout passes
(:40-51 — early blocks fire 5 ms into their slot, unknown-block
attestations wait up to 12 s, rpc blocks 4 s).
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable

from lighthouse_tpu.pool.accounting import record_pool_dropped
from lighthouse_tpu.processor.beacon_processor import BeaconProcessor, WorkEvent

# reference work_reprocessing_queue.rs:40-51
ADDITIONAL_QUEUED_BLOCK_DELAY = 0.005
QUEUED_ATTESTATION_DELAY = 12.0
QUEUED_RPC_BLOCK_DELAY = 4.0
MAX_QUEUED_ATTESTATIONS = 16_384


@dataclass
class _Parked:
    event: WorkEvent
    expires: float
    root: bytes | None = None


class ReprocessQueue:
    """Parks work until a dependency root is seen or a deadline passes."""

    def __init__(self, processor: BeaconProcessor):
        self.processor = processor
        self._by_root: dict[bytes, list[_Parked]] = defaultdict(list)
        self._timers: list[tuple[float, WorkEvent]] = []
        self._n_parked = 0
        self._task: asyncio.Task | None = None
        self._stopped = False

    # -- parking -----------------------------------------------------------

    def park_until_slot(self, event: WorkEvent, slot_start_unix: float):
        """Early block: re-queue ADDITIONAL_QUEUED_BLOCK_DELAY into its slot."""
        fire_at = slot_start_unix + ADDITIONAL_QUEUED_BLOCK_DELAY
        delay = max(0.0, fire_at - time.time())
        self._timers.append((time.monotonic() + delay, event))

    def park_for_block(self, event: WorkEvent, block_root: bytes,
                       timeout: float = QUEUED_ATTESTATION_DELAY) -> bool:
        """Attestation/aggregate for an unknown block: requeue when the
        block is imported, or drop after `timeout` (reference behaviour:
        expired unknown-block attestations are discarded, :447)."""
        if self._n_parked >= MAX_QUEUED_ATTESTATIONS:
            record_pool_dropped("reprocess", "capacity")
            return False
        self._by_root[block_root].append(
            _Parked(event, time.monotonic() + timeout, block_root))
        self._n_parked += 1
        return True

    def park_delayed(self, event: WorkEvent, delay: float = QUEUED_RPC_BLOCK_DELAY):
        """Fixed-delay retry (rpc blocks)."""
        self._timers.append((time.monotonic() + delay, event))

    # -- signals -----------------------------------------------------------

    def on_block_imported(self, block_root: bytes):
        """Dependency landed: flush everything parked on this root."""
        for parked in self._by_root.pop(block_root, []):
            self._n_parked -= 1
            self.processor.submit(parked.event)

    # -- timer pump --------------------------------------------------------

    async def start(self):
        if self._task is None:
            self._stopped = False
            self._task = asyncio.ensure_future(self._pump())

    async def stop(self):
        self._stopped = True
        if self._task is not None:
            t, self._task = self._task, None
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass

    async def _pump(self):
        while not self._stopped:
            now = time.monotonic()
            due = [e for at, e in self._timers if at <= now]
            self._timers = [(at, e) for at, e in self._timers if at > now]
            for e in due:
                self.processor.submit(e)
            # expire unknown-root attestations — an accounted discard:
            # the block never arrived and the parked work dies here
            for root in list(self._by_root):
                keep = []
                for p in self._by_root[root]:
                    if p.expires <= now:
                        self._n_parked -= 1
                        record_pool_dropped("reprocess", "expired")
                    else:
                        keep.append(p)
                if keep:
                    self._by_root[root] = keep
                else:
                    self._by_root.pop(root, None)
            await asyncio.sleep(0.005)


class DuplicateCache:
    """In-flight dedup of block roots (reference lib.rs:397-423): the first
    handler to claim a root gets a guard; concurrent claims are rejected
    until the guard is released."""

    def __init__(self):
        self._inflight: set[bytes] = set()

    def check_and_insert(self, root: bytes) -> bool:
        if root in self._inflight:
            return False
        self._inflight.add(root)
        return True

    def release(self, root: bytes):
        self._inflight.discard(root)

    def __contains__(self, root: bytes) -> bool:
        return root in self._inflight
