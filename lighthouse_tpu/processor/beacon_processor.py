"""Priority work scheduler — the single place device-sized batches form.

Rebuild of the reference beacon_processor
(/root/reference/beacon_node/beacon_processor/src/lib.rs): a manager loop
over per-work-type bounded queues with an explicit priority order
(lib.rs:950-977), a capped worker pool, and opportunistic batch formation
for attestations/aggregates (lib.rs:977-1010).

TPU-first deltas from the reference:
- The reference drains at most 64 queued attestations into one batch
  (lib.rs:196-203) because its batch verifier is CPU-bound.  Here the batch
  cap defaults to 2048 lanes and adds a time-based flush, because the device
  batch-pairing kernel wants large, padded, bucketed batches (SURVEY.md §7:
  "raise the 64-item cap, add time-based flush").
- Queues are deques of work events; batch formation concatenates event
  payloads so the BLS backend sees one contiguous lane batch.

Concurrency model: asyncio manager + thread-pool executor for CPU/device
work (the reference's tokio manager + blocking worker pool,
task_executor::spawn_blocking).
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from enum import Enum, auto
from typing import Any, Awaitable, Callable

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common import tracing
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed
from lighthouse_tpu.ops import faults
from lighthouse_tpu.processor.admission import (
    ACCEPTED,
    Admission,
    AdmissionController,
)


class WorkType(Enum):
    """Work taxonomy (reference Work enum, lib.rs:552-618)."""

    # highest priority: chain structure
    CHAIN_SEGMENT = auto()
    CHAIN_SEGMENT_BACKFILL = auto()
    RPC_BLOCK = auto()
    RPC_BLOBS = auto()
    # delayed re-imports
    DELAYED_IMPORT_BLOCK = auto()
    # gossip block parts
    GOSSIP_BLOCK = auto()
    GOSSIP_BLOB_SIDECAR = auto()
    # API priorities
    API_REQUEST_P0 = auto()
    API_REQUEST_P1 = auto()
    # aggregates before unaggregated attestations
    GOSSIP_AGGREGATE = auto()
    GOSSIP_AGGREGATE_BATCH = auto()
    GOSSIP_ATTESTATION = auto()
    GOSSIP_ATTESTATION_BATCH = auto()
    # remaining gossip
    GOSSIP_SYNC_SIGNATURE = auto()
    GOSSIP_SYNC_CONTRIBUTION = auto()
    GOSSIP_VOLUNTARY_EXIT = auto()
    GOSSIP_PROPOSER_SLASHING = auto()
    GOSSIP_ATTESTER_SLASHING = auto()
    GOSSIP_BLS_TO_EXECUTION_CHANGE = auto()
    GOSSIP_LIGHT_CLIENT_UPDATE = auto()
    # Req/Resp serving
    STATUS = auto()
    BLOCKS_BY_RANGE_REQUEST = auto()
    BLOCKS_BY_ROOT_REQUEST = auto()
    BLOBS_BY_RANGE_REQUEST = auto()
    BLOBS_BY_ROOT_REQUEST = auto()
    LIGHT_CLIENT_BOOTSTRAP_REQUEST = auto()
    UNKNOWN_BLOCK_ATTESTATION = auto()
    UNKNOWN_BLOCK_AGGREGATE = auto()


# Manager poll order (reference lib.rs:950-977): chain segments, then rpc
# blocks, delayed imports, gossip blocks/blobs, P0 API, aggregates,
# attestations, then everything else.
PRIORITY_ORDER: tuple[WorkType, ...] = (
    WorkType.CHAIN_SEGMENT,
    WorkType.RPC_BLOCK,
    WorkType.RPC_BLOBS,
    WorkType.CHAIN_SEGMENT_BACKFILL,
    WorkType.DELAYED_IMPORT_BLOCK,
    WorkType.GOSSIP_BLOCK,
    WorkType.GOSSIP_BLOB_SIDECAR,
    WorkType.API_REQUEST_P0,
    WorkType.GOSSIP_AGGREGATE,
    WorkType.GOSSIP_ATTESTATION,
    WorkType.UNKNOWN_BLOCK_AGGREGATE,
    WorkType.UNKNOWN_BLOCK_ATTESTATION,
    WorkType.GOSSIP_SYNC_CONTRIBUTION,
    WorkType.GOSSIP_SYNC_SIGNATURE,
    WorkType.API_REQUEST_P1,
    WorkType.GOSSIP_ATTESTER_SLASHING,
    WorkType.GOSSIP_PROPOSER_SLASHING,
    WorkType.GOSSIP_VOLUNTARY_EXIT,
    WorkType.GOSSIP_BLS_TO_EXECUTION_CHANGE,
    WorkType.GOSSIP_LIGHT_CLIENT_UPDATE,
    WorkType.STATUS,
    WorkType.BLOCKS_BY_RANGE_REQUEST,
    WorkType.BLOCKS_BY_ROOT_REQUEST,
    WorkType.BLOBS_BY_RANGE_REQUEST,
    WorkType.BLOBS_BY_ROOT_REQUEST,
    WorkType.LIGHT_CLIENT_BOOTSTRAP_REQUEST,
)

# queues that drop the OLDEST item when full (gossip floods); everything
# else rejects the newest with a backoff hint (reference
# FifoQueue/LifoQueue split).  Either way the discard is accounted in
# processor_shed_total{work_type,reason} — overload may degrade service,
# never the books.
_LIFO_TYPES = {
    WorkType.GOSSIP_ATTESTATION,
    WorkType.GOSSIP_AGGREGATE,
    WorkType.GOSSIP_SYNC_SIGNATURE,
    WorkType.GOSSIP_SYNC_CONTRIBUTION,
}

# lanes the degradation ladder must never shed AND the scheduler must
# never starve: chain structure always lands.  One worker slot is
# reserved for these — a saturated attestation plane can occupy at most
# max_workers - 1 slots (the reserve is how GOSSIP_BLOCK/CHAIN_SEGMENT
# stay verifiably live during a flood drill).
_PROTECTED_TYPES = frozenset({
    WorkType.CHAIN_SEGMENT,
    WorkType.CHAIN_SEGMENT_BACKFILL,
    WorkType.RPC_BLOCK,
    WorkType.RPC_BLOBS,
    WorkType.DELAYED_IMPORT_BLOCK,
    WorkType.GOSSIP_BLOCK,
    WorkType.GOSSIP_BLOB_SIDECAR,
})

# longest a deadline flush may be held for coalescing while the dispatch
# thread is busy: bounds queue wait for sub-max batches when back-to-back
# flights of another work type keep the thread saturated
_COALESCE_HOLD_MAX_S = 0.5

# work types eligible for batch formation: (batch type, per-event lanes)
_BATCHABLE = {
    WorkType.GOSSIP_ATTESTATION: WorkType.GOSSIP_ATTESTATION_BATCH,
    WorkType.GOSSIP_AGGREGATE: WorkType.GOSSIP_AGGREGATE_BATCH,
}


def queue_wait_histogram():
    """The beacon_processor_queue_wait_seconds family (this module is
    its sole owner; the firehose driver reads quantiles through here)."""
    return REGISTRY.histogram(
        "beacon_processor_queue_wait_seconds",
        "enqueue->dequeue wait per work event, by work type")


def _with_ingest_stall(batch_fn, payloads):
    """Batch-callable wrapper run ON the dispatch/worker thread: honors
    an active slow-consumer ingest storm (ops/faults.IngestPlan
    mode=stall, armable via LHTPU_INGEST_FAULT_MODE) so chaos drills can
    wedge the REAL consumer, not just a bench harness."""
    stall = faults.consumer_stall_s()
    if stall:
        time.sleep(stall)
    return batch_fn(payloads)


def _record_inflight(n: int) -> None:
    """Mirror the dispatch-thread occupancy into the
    bls_pipeline_inflight_batches gauge (owned by ops/dispatch_pipeline;
    lazy import keeps this module importable without jax)."""
    try:
        from lighthouse_tpu.ops.dispatch_pipeline import record_inflight

        record_inflight(n)
    except (ImportError, AttributeError, KeyError, TypeError,
            ValueError) as e:
        record_swallowed("beacon_processor.record_inflight", e)


def default_queue_lengths(active_validator_count: int) -> dict[WorkType, int]:
    """Queue bounds scaled from the active validator count
    (reference lib.rs:96-183: attestation queue = validators/32, etc.)."""
    n = max(active_validator_count, 1024)
    return {
        WorkType.GOSSIP_ATTESTATION: max(4096, n // 32),
        WorkType.GOSSIP_AGGREGATE: 4096,
        WorkType.GOSSIP_SYNC_SIGNATURE: max(2048, n // 64),
        WorkType.GOSSIP_SYNC_CONTRIBUTION: 1024,
        WorkType.GOSSIP_BLOCK: 1024,
        WorkType.GOSSIP_BLOB_SIDECAR: 1024,
        WorkType.RPC_BLOCK: 1024,
        WorkType.RPC_BLOBS: 1024,
        WorkType.CHAIN_SEGMENT: 64,
        WorkType.CHAIN_SEGMENT_BACKFILL: 64,
        WorkType.API_REQUEST_P0: 1024,
        WorkType.API_REQUEST_P1: 1024,
        WorkType.UNKNOWN_BLOCK_ATTESTATION: 4096,
        WorkType.UNKNOWN_BLOCK_AGGREGATE: 1024,
    }


@dataclass
class WorkEvent:
    """One unit of work.

    `process` runs on a worker (sync callables go to the thread pool,
    async callables are awaited).  For batchable types, `process_batch`
    receives a list of payloads when the manager forms a batch
    (reference Work::GossipAttestation {process_individual, process_batch},
    lib.rs:552-557).
    """

    work_type: WorkType
    process: Callable[[], Any] | Callable[[], Awaitable[Any]] | None = None
    payload: Any = None
    process_batch: Callable[[list[Any]], Any] | None = None
    drop_during_sync: bool = False
    enqueued_at: float = field(default_factory=time.monotonic)


@dataclass
class ProcessorMetrics:
    enqueued: dict[WorkType, int] = field(default_factory=dict)
    processed: dict[WorkType, int] = field(default_factory=dict)
    dropped: dict[WorkType, int] = field(default_factory=dict)
    # (work_type, reason) -> count; the in-process mirror of the labeled
    # processor_shed_total family.  Invariant the firehose drill holds:
    # enqueued == processed + shed + still-queued, per work type.
    shed: dict[tuple[WorkType, str], int] = field(default_factory=dict)
    batches_formed: int = 0
    batch_lanes: int = 0
    # submit() races from producer threads: a bare read-modify-write
    # would lose counts exactly when the books matter most (under flood)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def bump(self, table: dict, wt: WorkType, by: int = 1):
        with self._lock:
            table[wt] = table.get(wt, 0) + by

    def bump_shed(self, wt: WorkType, reason: str, by: int = 1):
        with self._lock:
            key = (wt, reason)
            self.shed[key] = self.shed.get(key, 0) + by

    def shed_total(self, wt: WorkType | None = None) -> int:
        return sum(n for (w, _r), n in self.shed.items()
                   if wt is None or w is wt)


class BeaconProcessor:
    """Manager + worker pool (reference BeaconProcessor::spawn_manager,
    lib.rs:758)."""

    def __init__(
        self,
        max_workers: int = 4,
        max_batch: int = 2048,
        batch_flush_ms: float = 50.0,
        queue_lengths: dict[WorkType, int] | None = None,
        work_journal: Callable[[str], None] | None = None,
        dispatch_wedge_s: float | None = None,
        dispatch_restart_max: int | None = None,
        dispatch_restart_window_s: float | None = None,
    ):
        self.max_workers = max(2, max_workers)
        self.max_batch = max_batch
        self.batch_flush_ms = batch_flush_ms
        self._lengths = queue_lengths or default_queue_lengths(0)
        self._queues: dict[WorkType, deque[WorkEvent]] = {
            wt: deque() for wt in WorkType}
        self.metrics = ProcessorMetrics()
        # test hook: emits one token per scheduling decision (reference
        # work_journal_tx, lib.rs:925-935)
        self._journal = work_journal
        self._idle = asyncio.Semaphore(self.max_workers)
        self._wakeup = asyncio.Event()
        self._stopped = False
        self._manager_task: asyncio.Task | None = None
        self._sweeper_task: asyncio.Task | None = None
        # True while the manager holds popped-but-unscheduled work
        # (parked on _idle.acquire); read by drain()
        self._manager_holding = False
        self._executor = ThreadPoolExecutor(max_workers=self.max_workers)
        # ONE dedicated dispatch thread for device batches: batch work
        # from every batchable type serializes here back-to-back, so the
        # device stays saturated while the manager keeps draining queues
        # on the loop and the general pool serves per-event work.  The
        # thread count is the contract — two concurrent device batch
        # dispatches would interleave their host/device stages.
        self._dispatch_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="bp-dispatch")
        # --- dispatch-thread supervisor: a wedged or dead dispatch
        # thread must not stall batch verification forever.  Each batch
        # awaits its executor future under a wedge deadline; on timeout
        # (or a dead executor) the batch re-runs on the general worker
        # pool (the synchronous path) and the dispatch executor is
        # replaced — storm-limited so a persistently wedging device
        # pins batch work to the synchronous path instead of spawning
        # threads unboundedly.  Restart bookkeeping is mutated only on
        # the event loop.
        # explicit zeros are honored: wedge 0 disables the supervisor,
        # restart-max 0 means never restart (sync-only recovery)
        self.dispatch_wedge_s = (
            dispatch_wedge_s if dispatch_wedge_s is not None
            else envreg.get_float("LHTPU_DISPATCH_WEDGE_S", 600.0))
        self.dispatch_restart_max = (
            dispatch_restart_max if dispatch_restart_max is not None
            else envreg.get_int("LHTPU_DISPATCH_RESTART_MAX", 3))
        self.dispatch_restart_window_s = (
            dispatch_restart_window_s
            if dispatch_restart_window_s is not None
            else envreg.get_float("LHTPU_DISPATCH_RESTART_WINDOW_S", 300.0))
        self._dispatch_restarts: deque[float] = deque()  # restart stamps
        self._dispatch_generation = 0
        self.dispatch_restart_count = 0  # lifetime total (test surface)
        # batches currently on (or queued for) the dispatch thread;
        # mutated only on the event loop
        self._dispatch_inflight = 0
        self._inflight: set[asyncio.Task] = set()
        # first-seen timestamps for batch flush decisions (the flush
        # deadline is computed at sweep time so the ladder's
        # coalesce-harder rung can stretch it for already-queued work)
        self._batch_first_seen: dict[WorkType, float] = {}
        # --- admission control: per-WorkType watermarks + the
        # degradation ladder over the flood lanes (processor/admission).
        # Swept from the manager loop; drills call sweep_now() directly.
        self.admission = AdmissionController(
            governed=(WorkType.GOSSIP_ATTESTATION, WorkType.GOSSIP_AGGREGATE),
            shed_order=(WorkType.GOSSIP_ATTESTATION,
                        WorkType.GOSSIP_AGGREGATE))
        self.admit_sweep_s = envreg.get_float("LHTPU_ADMIT_SWEEP_S", 0.05)
        # unprotected (flood-lane) work currently scheduled; the manager
        # keeps this strictly below max_workers so one slot always
        # remains for _PROTECTED_TYPES.  Mutated only on the event loop.
        self._unprotected_inflight = 0
        self._shed_counter = REGISTRY.counter(
            "processor_shed_total",
            "work events discarded by admission control / queue policy, "
            "by work type and reason")
        # sheds awaiting their aggregated trace event (flushed per sweep)
        self._shed_pending: dict[tuple[WorkType, str], int] = {}
        # labeled registry families (one series per WorkType label);
        # ProcessorMetrics above stays as the in-process test surface
        self._wait_hist = queue_wait_histogram()
        self._batch_hist = REGISTRY.histogram(
            "beacon_processor_batch_size_lanes",
            "lanes per formed device batch, by work type",
            buckets=(1, 8, 32, 64, 128, 256, 512, 1024, 2048, 4096))
        self._event_counter = REGISTRY.counter(
            "beacon_processor_events_total",
            "work events by work type and outcome "
            "(enqueued/dropped/processed)")
        # labeled children memoized per (family, type[, outcome]):
        # submit()/dequeue run once per gossip event at flood scale, so
        # the per-call cost must stay one observe()/inc()
        self._label_memo: dict[tuple, Any] = {}
        # the books go LIVE: enqueued == processed + shed + queued is a
        # registered invariant monitor (weakref-backed; the newest
        # processor instance owns the "processor_books" name)
        from lighthouse_tpu.common import monitors as _monitors

        _monitors.register_processor_books(self)

    def _labeled(self, family, wt: WorkType, outcome: str | None = None,
                 reason: str | None = None):
        key = (family.name, wt, outcome, reason)
        child = self._label_memo.get(key)
        if child is None:
            labels = {"work_type": wt.name.lower()}
            if outcome is not None:
                labels["outcome"] = outcome
            if reason is not None:
                labels["reason"] = reason
            child = self._label_memo[key] = family.labels(**labels)
        return child

    def _account_shed(self, wt: WorkType, reason: str, n: int = 1) -> None:
        """EVERY discard of queued (or submitted) work funnels through
        here: the labeled processor_shed_total series, the in-process
        mirrors, and (aggregated per sweep) a trace event.  The firehose
        acceptance criterion — zero unaccounted drops — is this helper
        being the only discard path.

        Tracing is deferred: a span per shed event would take the
        tracer's process-wide lock once per gossip message exactly when
        tens of thousands/s are being shed, so sheds accumulate in
        ``_shed_pending`` and ``sweep_now`` emits ONE span per
        (work_type, reason) carrying the count since the last sweep."""
        self.metrics.bump(self.metrics.dropped, wt, n)
        self.metrics.bump_shed(wt, reason, n)
        self._labeled(self._event_counter, wt, "dropped").inc(n)
        self._labeled(self._shed_counter, wt, reason=reason).inc(n)
        with self.metrics._lock:
            key = (wt, reason)
            self._shed_pending[key] = self._shed_pending.get(key, 0) + n

    def _trace_pending_sheds(self) -> None:
        from lighthouse_tpu.common import flight_recorder as flight

        with self.metrics._lock:
            pending, self._shed_pending = self._shed_pending, {}
        for (wt, reason), n in pending.items():
            with tracing.span("beacon_processor.shed",
                              work_type=wt.name.lower(), reason=reason,
                              count=n):
                pass
            # aggregated per sweep (never per message): the black box
            # shows WHAT was shed in the window before a trip
            flight.emit("shed", plane="processor",
                        work_type=wt.name.lower(), reason=reason, count=n)

    def shed_queue(self, wt: WorkType, reason: str = "purged") -> int:
        """Discard EVERYTHING queued on one lane, accounted under
        ``reason`` — the operator's backlog purge (a poisoned or stale
        backlog after a storm is often worth less than the fresh traffic
        behind it).  Returns the number of events shed."""
        q = self._queues[wt]
        n = 0
        while True:
            try:
                q.popleft()
            except IndexError:
                break
            n += 1
        if n:
            self._account_shed(wt, reason, n)
        self._batch_first_seen.pop(wt, None)
        return n

    # -- submission (any task/thread) -------------------------------------

    def submit(self, event: WorkEvent) -> Admission:
        """Enqueue work.  Returns a truthy :class:`Admission` when the
        event was queued; a falsy one (with ``reason`` and, for
        reject-newest lanes, a ``retry_after_s`` backoff hint) when it
        was shed.  A LIFO gossip lane over its limit still accepts the
        newest event and sheds its OLDEST instead — that drop is
        accounted but the submitted event lands, so the call returns
        accepted."""
        wt = event.work_type
        q = self._queues[wt]
        limit = self._lengths.get(wt, 1024)
        self.metrics.bump(self.metrics.enqueued, wt)
        self._labeled(self._event_counter, wt, "enqueued").inc()
        reason = self.admission.shed_reason(wt)
        if reason is not None:
            # degradation-ladder shed: refused at the door, before any
            # queue state is touched
            self._account_shed(wt, reason)
            self._wakeup.set()
            return Admission(False, reason=reason)
        if len(q) >= limit:
            if wt in _LIFO_TYPES:
                try:
                    q.popleft()  # drop oldest, keep newest
                except IndexError:
                    # racing producers both saw a full queue and the
                    # manager drained it first — nothing was discarded,
                    # so nothing is accounted (a phantom shed would
                    # break the zero-unaccounted-drops books the other
                    # way: shed counted with no event missing)
                    pass
                else:
                    self._account_shed(wt, "queue_full_drop_oldest")
            else:
                self._account_shed(wt, "queue_full_reject_newest")
                self._wakeup.set()
                return Admission(
                    False, reason="queue_full_reject_newest",
                    retry_after_s=self.admission.retry_after_s(
                        len(q), limit))
        q.append(event)
        # deliberately lock-free, like the deques (module docstring):
        # the worst interleaving with the manager's pop is a batch
        # window stamped one flush interval early/late, self-healing on
        # the next sweep — a lock here would sit on every submit
        if wt in _BATCHABLE and wt not in self._batch_first_seen:
            self._batch_first_seen[wt] = time.monotonic()  # lhlint: allow(LH1003) — benign by design: single GIL-atomic setitem, staleness bounded by the flush interval
        self._wakeup.set()
        return ACCEPTED

    def queue_len(self, wt: WorkType) -> int:
        return len(self._queues[wt])

    # -- manager loop ------------------------------------------------------

    async def start(self):
        if self._manager_task is None:
            self._stopped = False
            self._manager_task = asyncio.ensure_future(self._manager())
            self._sweeper_task = asyncio.ensure_future(self._sweeper())

    async def stop(self, drain: bool = True):
        if drain:
            await self.drain()
        self._stopped = True
        self._wakeup.set()
        if self._manager_task is not None:
            await self._manager_task
            self._manager_task = None
        if self._sweeper_task is not None:
            self._sweeper_task.cancel()
            try:
                await self._sweeper_task
            except asyncio.CancelledError:
                pass
            self._sweeper_task = None

    async def _sweeper(self):
        """Dedicated ladder-sweep cadence.  The manager loop cannot own
        it: it parks on an unbounded ``_idle.acquire()`` whenever every
        worker is busy — which is exactly the overload moment the ladder
        must keep observing (a wedged dispatch batch would otherwise
        freeze escalation for the whole wedge deadline)."""
        while not self._stopped:
            self.sweep_now()
            await asyncio.sleep(self.admit_sweep_s or 0.05)

    async def drain(self):
        """Wait until every queue is empty and all workers are idle.
        ``_manager_holding`` covers the window where the manager has
        POPPED work but is still parked on ``_idle.acquire()`` — queues
        and inflight are both empty there, yet work exists; returning
        then would break every books-balance assertion built on
        drain."""
        while True:
            busy = (any(self._queues[wt] for wt in WorkType)
                    or self._inflight or self._manager_holding)
            if not busy:
                return
            await asyncio.sleep(0.002)

    async def _manager(self):
        while not self._stopped:
            event_or_batch = self._next_work()
            if event_or_batch is None:
                self._wakeup.clear()
                # re-check with a timeout so batch flush deadlines fire
                try:
                    await asyncio.wait_for(
                        self._wakeup.wait(), timeout=self.batch_flush_ms / 1000.0)
                except asyncio.TimeoutError:
                    pass
                continue
            first = (event_or_batch[0] if isinstance(event_or_batch, list)
                     else event_or_batch)
            unprotected = first.work_type not in _PROTECTED_TYPES
            self._manager_holding = True
            try:
                await self._idle.acquire()
                if unprotected:
                    self._unprotected_inflight += 1
                task = asyncio.ensure_future(
                    self._run_work(event_or_batch, unprotected))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
            finally:
                self._manager_holding = False

    def sweep_now(self) -> int:
        """One admission-ladder observation over the governed queue
        depths (the dedicated _sweeper task runs this at
        LHTPU_ADMIT_SWEEP_S cadence; drills/tests call it directly).
        Also flushes the aggregated shed trace events accumulated since
        the last sweep."""
        self._trace_pending_sheds()
        return self.admission.sweep({
            wt: (len(self._queues[wt]), self._lengths.get(wt, 1024))
            for wt in self.admission.governed})

    def _journal_emit(self, token: str):
        if self._journal is not None:
            self._journal(token)

    def _next_work(self):
        """Pick the highest-priority queue with work; form batches
        opportunistically for attestations/aggregates.

        Priority isolation: unprotected (flood-lane) work is only
        scheduled while at least one worker slot stays free for
        _PROTECTED_TYPES, so a saturated attestation plane can never
        occupy the slot a gossip block or chain segment needs."""
        now = time.monotonic()
        reserve_busy = (
            self._unprotected_inflight >= max(1, self.max_workers - 1))
        flush_s = (self.batch_flush_ms / 1000.0
                   * self.admission.flush_factor())
        for wt in PRIORITY_ORDER:
            q = self._queues[wt]
            if not q:
                continue
            if reserve_busy and wt not in _PROTECTED_TYPES:
                continue
            if wt in _BATCHABLE:
                n = len(q)
                first_seen = self._batch_first_seen.get(wt)
                deadline = (now if first_seen is None
                            else first_seen + flush_s)
                # cross-batch coalescing: while a batch is in flight on
                # the dispatch thread, deadline flushes HOLD — events
                # arriving during the flight merge into one next sweep
                # (bounded by max_batch) instead of trickling out as
                # many small batches queued behind the device.  A full
                # queue still forms immediately: a max_batch sweep is
                # already maximal and keeps the device fed back-to-back.
                # The hold is time-bounded (_COALESCE_HOLD_MAX_S past
                # the deadline): under a sustained flood of another
                # work type the dispatch thread may never go idle, and
                # a sub-max queue must not be starved forever.
                if n >= self.max_batch or (now >= deadline and (
                        self._dispatch_inflight == 0
                        or now - deadline >= _COALESCE_HOLD_MAX_S)):
                    take = min(n, self.max_batch)
                    events = [q.popleft() for _ in range(take)]
                    if not q:
                        self._batch_first_seen.pop(wt, None)
                    # non-empty remainder keeps its (already expired)
                    # window, so it flushes on the next sweep — same
                    # behaviour the absolute-deadline bookkeeping had
                    wait_child = self._labeled(self._wait_hist, wt)
                    for e in events:
                        wait_child.observe(now - e.enqueued_at)
                    if take == 1:
                        self._journal_emit(wt.name)
                        return events[0]
                    self.metrics.batches_formed += 1
                    self.metrics.batch_lanes += take
                    self._labeled(self._batch_hist, wt).observe(take)
                    self._journal_emit(f"{_BATCHABLE[wt].name}({take})")
                    return events
                # not enough lanes yet and deadline pending: let lower
                # priorities run while the batch accumulates
                continue
            self._journal_emit(wt.name)
            event = q.popleft()
            self._labeled(self._wait_hist, wt).observe(
                now - event.enqueued_at)
            return event
        return None

    async def _run_work(self, work, unprotected: bool = False):
        try:
            if isinstance(work, list):
                await self._run_batch(work)
            else:
                await self._run_one(work)
        finally:
            if unprotected:
                self._unprotected_inflight -= 1
            self._idle.release()
            self._wakeup.set()

    async def _run_one(self, event: WorkEvent):
        fn = event.process
        if fn is None:
            if event.process_batch is not None:
                # a deadline flush can hand over a single batchable
                # event; it must still ride the dispatch thread as a
                # 1-lane batch, not be dropped for lacking `process`
                await self._run_batch([event])
            return
        wt_label = event.work_type.name.lower()
        try:
            with tracing.span("beacon_processor.work", work_type=wt_label):
                if asyncio.iscoroutinefunction(fn):
                    await fn()
                else:
                    loop = asyncio.get_running_loop()
                    res = await loop.run_in_executor(self._executor, fn)
                    if asyncio.iscoroutine(res):
                        await res
        except Exception as e:  # worker panics must not kill the manager
            record_swallowed("beacon_processor.worker", e)
        self.metrics.bump(self.metrics.processed, event.work_type)
        self._labeled(self._event_counter, event.work_type,
                      "processed").inc()

    async def _run_batch(self, events: list[WorkEvent]):
        wt = events[0].work_type
        batch_fn = events[0].process_batch
        if batch_fn is None:
            for e in events:
                await self._run_one(e)
            return
        payloads = [e.payload for e in events]
        self._dispatch_inflight += 1
        _record_inflight(self._dispatch_inflight)
        try:
            with tracing.span("beacon_processor.batch",
                              work_type=wt.name.lower(),
                              lanes=len(events)):
                await self._dispatch_batch(batch_fn, payloads)
        except Exception as e:  # batch panics must not kill the manager
            record_swallowed("beacon_processor.batch", e)
        finally:
            self._dispatch_inflight -= 1
            _record_inflight(self._dispatch_inflight)
        self.metrics.bump(self.metrics.processed, wt, len(events))
        self._labeled(self._event_counter, wt, "processed").inc(len(events))

    # -- dispatch-thread supervisor ----------------------------------------

    async def _dispatch_batch(self, batch_fn, payloads):
        """Run one batch on the dedicated dispatch thread under the wedge
        deadline; recover through the synchronous worker-pool path when
        the thread is dead or wedged.

        Recovery RE-RUNS the batch callable: batch handlers must
        tolerate re-execution INCLUDING concurrent execution — the
        abandoned thread, if merely slow rather than dead, may still be
        inside the same batch while the synchronous copy runs.  That is
        the same contract concurrent gossip/RPC copies of one block
        already impose (verification is idempotent; dup gates and
        observed-caches absorb the replay, and the verify paths are
        thread-safe per tests/test_lock_contracts.py)."""
        loop = asyncio.get_running_loop()
        if self._restart_budget_exhausted():
            # PINNED: the storm limiter is saturated, so the current
            # dispatch executor is presumed wedged-and-unreplaceable —
            # go straight to the synchronous path instead of queueing
            # behind it for another full wedge deadline per batch
            await loop.run_in_executor(self._executor, _with_ingest_stall,
                                       batch_fn, payloads)
            return
        gen = self._dispatch_generation
        try:
            fut = loop.run_in_executor(
                self._dispatch_executor, _with_ingest_stall, batch_fn,
                payloads)
        except RuntimeError as e:
            # executor shut down / thread unspawnable: a DEAD dispatch
            # thread — replace it and serve this batch synchronously
            self._recover_dispatch("dead", gen, e)
            await loop.run_in_executor(self._executor, _with_ingest_stall,
                                       batch_fn, payloads)
            return
        wedge = self.dispatch_wedge_s
        if not wedge or wedge <= 0:
            await fut
            return
        try:
            await asyncio.wait_for(fut, timeout=wedge)
        except asyncio.TimeoutError:
            # WEDGED: the thread has been inside one batch past the
            # deadline.  Abandon it (the cancelled future detaches; the
            # thread keeps its GIL turns until it dies with the old
            # executor), restart, and drain this batch synchronously.
            self._recover_dispatch("wedged", gen, None)
            await loop.run_in_executor(self._executor, _with_ingest_stall,
                                       batch_fn, payloads)

    def _restart_budget_exhausted(self) -> bool:
        """True while the restart-storm limiter is saturated (prunes
        stamps older than the window first)."""
        now = time.monotonic()
        while (self._dispatch_restarts
               and now - self._dispatch_restarts[0]
               > self.dispatch_restart_window_s):
            self._dispatch_restarts.popleft()
        return len(self._dispatch_restarts) >= self.dispatch_restart_max

    def _recover_dispatch(self, reason: str, gen: int,
                          exc: BaseException | None) -> None:
        """Replace the dispatch executor (restart-storm-limited) and
        account the fault.  ``gen`` is the generation the failing batch
        was submitted under: if another batch already triggered the
        restart, this one only falls back synchronously."""
        restarted = False
        if gen == self._dispatch_generation:
            if not self._restart_budget_exhausted():
                self._dispatch_restarts.append(time.monotonic())
                self._dispatch_generation += 1
                self.dispatch_restart_count += 1
                old = self._dispatch_executor
                self._dispatch_executor = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=(
                        f"bp-dispatch-{self._dispatch_generation}"))
                old.shutdown(wait=False)  # abandon the wedged thread
                restarted = True
            # else: storm limiter — queued batches keep timing out onto
            # the synchronous path until the window drains
        try:
            REGISTRY.counter(
                "beacon_processor_dispatch_restarts_total",
                "dispatch-thread supervisor interventions, by reason and "
                "action",
            ).labels(reason=reason,
                     action="restarted" if restarted else "sync_only").inc()
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            record_swallowed("beacon_processor.dispatch_restart_counter", e)
        # a wedged/dead dispatch thread is a trip condition: the black
        # box dumps with the batches and faults that preceded the wedge
        from lighthouse_tpu.common import flight_recorder as flight

        flight.trip("dispatch_wedge", wedge=reason,
                    restarted=restarted,
                    generation=self._dispatch_generation,
                    inflight=self._dispatch_inflight)
        if exc is not None:
            record_swallowed(f"beacon_processor.dispatch_{reason}", exc)
