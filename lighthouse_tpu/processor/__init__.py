"""Work scheduling: priority queues, worker pool, device-sized batching.

Reference: /root/reference/beacon_node/beacon_processor.
"""

from lighthouse_tpu.processor.admission import (
    Admission,
    AdmissionController,
)
from lighthouse_tpu.processor.beacon_processor import (
    PRIORITY_ORDER,
    BeaconProcessor,
    ProcessorMetrics,
    WorkEvent,
    WorkType,
    default_queue_lengths,
)
from lighthouse_tpu.processor.reprocess import (
    ADDITIONAL_QUEUED_BLOCK_DELAY,
    QUEUED_ATTESTATION_DELAY,
    QUEUED_RPC_BLOCK_DELAY,
    DuplicateCache,
    ReprocessQueue,
)

__all__ = [
    "Admission",
    "AdmissionController",
    "BeaconProcessor",
    "WorkEvent",
    "WorkType",
    "ProcessorMetrics",
    "PRIORITY_ORDER",
    "default_queue_lengths",
    "ReprocessQueue",
    "DuplicateCache",
    "ADDITIONAL_QUEUED_BLOCK_DELAY",
    "QUEUED_ATTESTATION_DELAY",
    "QUEUED_RPC_BLOCK_DELAY",
]
