"""ClientBuilder: assemble a full beacon node from config.

Rebuild of /root/reference/beacon_node/client/src/builder.rs: wire
store -> eth1 -> beacon chain -> processor -> network -> HTTP API ->
timers -> notifier, each stage optional per config, returning a `Client`
whose lifecycle the TaskExecutor supervises.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from lighthouse_tpu import types as T
from lighthouse_tpu.common.logging import Logger
from lighthouse_tpu.common.metrics import record_swallowed
from lighthouse_tpu.common.task_executor import TaskExecutor


@dataclass
class ClientConfig:
    network: str = "devnet"
    network_config_path: str | None = None
    datadir: str | None = None          # None = in-memory store
    http_enabled: bool = True
    http_port: int = 0                   # 0 = ephemeral
    metrics_enabled: bool = True
    execution_endpoint: str | None = None
    execution_jwt_hex: str | None = None
    eth1_endpoint: object | None = None  # in-process endpoint object
    slasher_enabled: bool = False
    slasher_backend: str = "native"
    n_genesis_validators: int = 64
    genesis_fork: str = "capella"
    verify_signatures: bool = True
    sync_tolerance_slots: int = 1
    # checkpoint sync: bootstrap from a remote node's finalized state
    # instead of genesis (reference beacon_node/src/config.rs:506-527)
    checkpoint_sync_url: str | None = None
    # tests/simulators drive slots manually; real nodes follow the wall
    # clock (reference SystemTimeSlotClock vs TestingSlotClock)
    manual_slot_clock: bool = False
    # interop genesis time; None = now.  Nodes that must share a devnet
    # genesis pass the same explicit value (determinism)
    genesis_time: int | None = None
    # dev-only: build deterministic mock payloads when no EL is
    # configured.  None = auto (dev networks only); production networks
    # without an EL must FAIL to propose, not forge payloads
    dev_mock_payloads: bool | None = None
    # BLS data plane: "auto" = device pipeline when a TPU is attached,
    # pure-Python reference otherwise; or force tpu/reference/fake
    # (reference seam: crypto/bls/src/lib.rs:86-141 backend selection)
    bls_backend: str = "auto"
    # UPnP NAT traversal for the discovery port (reference enables by
    # default with --disable-upnp as the opt-out)
    upnp_enabled: bool = False
    # socket networking: None = no wire stack (in-process fabric only,
    # the simulator's mode); 0 = ephemeral port.  boot_nodes are
    # "host:port" UDP discovery addresses to bootstrap from
    # (reference beacon_node/src/config.rs listen-address/boot-nodes)
    listen_port: int | None = None
    # "tcp" | "quic" — the stream transport under the wire stack
    # (reference runs TCP and QUIC listeners side by side)
    wire_transport: str = "tcp"
    boot_nodes: tuple = ()
    # external block builder (MEV) endpoint; None = local payloads only
    builder_url: str | None = None
    # KZG ceremony output (consensus-specs trusted_setup_4096.json
    # format) for deneb blob verification; None = no KZG (dev networks
    # can run pre-deneb or pass a dev setup programmatically)
    trusted_setup_path: str | None = None
    # remote monitoring service URL; None = disabled (reference
    # --monitoring-endpoint, common/monitoring_api/src/lib.rs:51)
    monitoring_endpoint: str | None = None
    # dev-only slot pacing override: a process-fleet devnet walks slots
    # at seconds, not the preset's 6/12; None = the spec's own value
    seconds_per_slot: int | None = None
    # deterministic wire identity (the peer id is the Ed25519 key's
    # fingerprint): a fleet node keeps its peer id across SIGKILL +
    # relaunch, so partition sets installed by name stay valid.  None =
    # a random identity per start (production)
    identity_seed: str | None = None
    # in-process interop duty loop: (lo, hi) assigns interop validators
    # [lo, hi) to a VC thread inside this node — the process-fleet
    # equivalent of the simulator's per-node validator split.  None =
    # no duties (a plain beacon node)
    interop_vc_range: tuple | None = None


@dataclass
class Client:
    config: ClientConfig
    spec: object
    chain: object
    executor: TaskExecutor
    http_server: object | None = None
    processor: object | None = None
    network: object | None = None
    services: dict = field(default_factory=dict)
    lockfile: object | None = None

    def stop(self) -> None:
        if self.http_server is not None:
            self.http_server.stop()
        upnp = self.services.get("upnp")
        if upnp is not None:
            upnp.stop()
        wire = self.services.get("wire")
        if wire is not None:
            wire.stop()
        self.executor.shutdown("client stop")
        # snapshot fork choice + head AFTER the workers stop so a
        # mid-import mutation can't tear the snapshot (reference persists
        # on shutdown), then close the store so the dirty-shutdown marker
        # flips to clean — the next open skips the integrity sweep
        try:
            self.chain.persist()
        except Exception as e:
            record_swallowed("client.stop_persist", e)
        try:
            self.chain.store.close()
        except Exception as e:
            record_swallowed("client.stop_close", e)
        if self.lockfile is not None:
            self.lockfile.release()


class ClientBuilder:
    def __init__(self, config: ClientConfig):
        self.config = config
        self.log = Logger("client")
        self.spec: T.ChainSpec | None = None
        self.genesis_state = None
        self.chain = None
        self.executor = TaskExecutor("bn")
        self._el = None
        self._eth1 = None
        self._anchor_block = None
        self._lockfile = None

    # -- stages (each returns self, builder-style) ------------------------

    def load_spec(self) -> "ClientBuilder":
        from lighthouse_tpu.client.network_config import (
            load_network_config,
            spec_for_network,
        )
        from lighthouse_tpu.crypto import bls

        # pin "auto" to its resolution at startup: validates the choice
        # once and keeps per-batch verify calls resolution-free
        backend = self.config.bls_backend
        if backend == "auto":
            backend = bls.resolve_auto_backend()
            self.log.info("bls backend: auto -> %s" % backend)
        else:
            self.log.info("bls backend: %s" % backend)
        bls.set_backend(backend)

        cfg = self.config
        if cfg.network_config_path:
            self.spec = load_network_config(cfg.network_config_path)
        else:
            self.spec = spec_for_network(cfg.network)
        if cfg.seconds_per_slot:
            import dataclasses

            self.spec = dataclasses.replace(
                self.spec, seconds_per_slot=int(cfg.seconds_per_slot))
        return self

    def genesis(self, state=None) -> "ClientBuilder":
        import time

        from lighthouse_tpu.state_transition import genesis_state

        if state is not None:
            self.genesis_state = state
        elif self.config.checkpoint_sync_url:
            return self.checkpoint_sync(self.config.checkpoint_sync_url)
        else:
            fork = self.config.genesis_fork
            if self.spec.fork_at_epoch(0) != fork:
                # An interop genesis state is built AT `fork`, so the
                # schedule's epoch-0 fork must agree: otherwise every
                # fork_at_epoch() consumer (block classes, payload
                # production, upgrade sweeps) addresses fields the state
                # does not carry — e.g. a capella-at-0 schedule over an
                # altair state kills each proposal on a missing
                # latest_execution_payload_header.  Re-pin the schedule
                # so --genesis-fork means what it says (the in-process
                # LocalNetwork pins its spec the same way).
                self.spec = self.spec.with_forks_at(0, through=fork)
                self.log.info("fork schedule pinned to interop genesis "
                              "fork", fork=fork)
            # interop genesis anchored NOW by default so a wall-clock
            # slot clock starts at slot 0 (the reference's interop
            # genesis_time); explicit genesis_time keeps multi-node
            # devnets deterministic
            g_time = (self.config.genesis_time
                      if self.config.genesis_time is not None
                      else int(time.time()))
            self.genesis_state = genesis_state(
                self.config.n_genesis_validators, self.spec, fork,
                genesis_time=g_time)
        return self

    def checkpoint_sync(self, url: str) -> "ClientBuilder":
        """Bootstrap from a remote node's finalized state + block
        (reference ClientBuilder checkpoint-sync path: download the
        finalized pair, anchor the chain on it, backfill later)."""
        from lighthouse_tpu import types as T
        from lighthouse_tpu.api.client import BeaconNodeClient

        remote = BeaconNodeClient(url)
        state_raw, fork = remote.state_ssz("finalized")
        t = T.make_types(self.spec.preset)
        state = t.beacon_state_class(fork).deserialize(state_raw)
        block_raw = remote.block_ssz("finalized")
        block = t.decode_signed_block(block_raw)
        if block is None:
            raise RuntimeError("checkpoint block undecodable")
        # the two 'finalized' fetches are not atomic — finalization may
        # advance between them; the block MUST be the one the state's
        # latest_block_header describes or the anchor is incoherent
        from lighthouse_tpu.chain.beacon_chain import BeaconChain

        want = BeaconChain._anchor_block_root(state)
        got = block.message.hash_tree_root()
        if got != want:
            raise RuntimeError(
                f"checkpoint block {got.hex()[:16]} does not match the "
                f"checkpoint state's anchor root {want.hex()[:16]} "
                "(finalization advanced mid-download? retry)")
        self.genesis_state = state
        self._anchor_block = block
        self.log.info(
            "checkpoint sync bootstrap", slot=int(state.slot), fork=fork)
        return self

    def execution_layer(self) -> "ClientBuilder":
        cfg = self.config
        if cfg.execution_endpoint is None:
            return self
        from lighthouse_tpu.execution import EngineApiClient, ExecutionLayer

        secret = bytes.fromhex(cfg.execution_jwt_hex or "00" * 32)
        self._el = ExecutionLayer(
            [EngineApiClient(cfg.execution_endpoint, secret)])
        return self

    def eth1(self) -> "ClientBuilder":
        if self.config.eth1_endpoint is None:
            return self
        from lighthouse_tpu.eth1 import Eth1Service, Eth1ServiceConfig

        self._eth1 = Eth1Service(
            self.config.eth1_endpoint, self.spec,
            Eth1ServiceConfig(follow_distance=min(
                self.spec.eth1_follow_distance, 16)))
        return self

    def beacon_chain(self) -> "ClientBuilder":
        import os

        from lighthouse_tpu.chain.beacon_chain import BeaconChain
        from lighthouse_tpu.store import HotColdDB, NativeKVStore

        store = None
        if self.config.datadir:
            os.makedirs(self.config.datadir, exist_ok=True)
            # node-scoped flight dumps: unless LHTPU_FLIGHT_DIR pins a
            # directory, this node's black box lands under its OWN
            # datadir — N nodes on one host must never race one dir
            from lighthouse_tpu.common import flight_recorder as _flight

            _flight.set_default_dump_dir(
                os.path.join(self.config.datadir, "flight"))
            # exclusive datadir ownership: two nodes sharing one DB would
            # corrupt it (reference common/lockfile)
            from lighthouse_tpu.common.utils import Lockfile

            self._lockfile = Lockfile(
                os.path.join(self.config.datadir, "beacon.lock")).acquire()
            hot = NativeKVStore(os.path.join(self.config.datadir, "hot.db"))
            cold = NativeKVStore(os.path.join(self.config.datadir, "cold.db"))
            from lighthouse_tpu.common import env as envreg

            if envreg.get("LHTPU_STORE_FAULT_MODE"):
                # operator chaos drill: deterministic crash/corruption
                # injection at the store commit points (store/crash)
                from lighthouse_tpu.store import CrashPointStore

                hot = CrashPointStore.from_env(hot)
                self.log.warn("store fault injection armed",
                              mode=envreg.get("LHTPU_STORE_FAULT_MODE"))
            store = HotColdDB(self.spec, hot=hot, cold=cold)
            if store.recovery:
                self.log.warn("store integrity sweep repaired records",
                              repairs=store.recovery)
        from lighthouse_tpu.common.slot_clock import (
            ManualSlotClock,
            SystemTimeSlotClock,
        )

        kzg_settings = None
        if self.config.trusted_setup_path:
            from lighthouse_tpu.crypto.kzg import KzgSettings

            kzg_settings = KzgSettings.load_trusted_setup(
                self.config.trusted_setup_path)
            self.log.info("trusted setup loaded",
                          path=self.config.trusted_setup_path,
                          width=kzg_settings.width)
        clock_cls = (ManualSlotClock if self.config.manual_slot_clock
                     else SystemTimeSlotClock)
        self.chain = BeaconChain(
            self.spec, self.genesis_state, store=store,
            slot_clock=clock_cls(
                int(self.genesis_state.genesis_time),
                self.spec.seconds_per_slot),
            verify_signatures=self.config.verify_signatures,
            kzg_settings=kzg_settings,
            execution_layer=self._el)
        if self.config.builder_url:
            from lighthouse_tpu.execution.builder_api import BuilderApiClient

            self.chain.builder_client = BuilderApiClient(
                self.config.builder_url)
            self.log.info("builder attached", url=self.config.builder_url)
        allow_mock = self.config.dev_mock_payloads
        if allow_mock is None:
            allow_mock = self.config.network in ("devnet", "minimal")
        if self._el is None and allow_mock:
            # dev networks without an EL build deterministic mock
            # payloads (the reference test/sim mock EL); production
            # networks keep the execution_payload_required failure
            from lighthouse_tpu.execution.mock_el import build_mock_payload

            chain = self.chain
            chain.mock_payload = (
                lambda slot, c=chain: build_mock_payload(c, slot))
        if self._anchor_block is not None:
            # persist the checkpoint anchor block so sync/API can serve it
            self.chain.store.put_block(
                self.chain.genesis_block_root, self._anchor_block)
        if self.config.datadir:
            # disk-backed nodes resume a prior run's fork choice + head
            if self.chain.try_resume():
                # the fresh interop genesis above may carry a NEW
                # genesis_time; the resumed chain's slots are anchored at
                # the PERSISTED genesis — realign the wall clock or every
                # duty/sync computation runs against the wrong slot
                chain = self.chain
                chain.slot_clock = type(chain.slot_clock)(
                    chain.fork_choice.genesis_time,
                    self.spec.seconds_per_slot)
                self.log.info(
                    "resumed from disk",
                    head_slot=int(self.chain.head_state.slot),
                    mode=self.chain.resume_mode)
        if self._eth1 is not None:
            self.chain.eth1_service = self._eth1
        if self.config.slasher_enabled:
            import os as _os

            from lighthouse_tpu.slasher import SlasherService
            from lighthouse_tpu.slasher.slasher import (
                Slasher,
                SlasherConfig,
            )

            cfg = SlasherConfig(
                backend=self.config.slasher_backend,
                db_path=None if self.config.slasher_backend == "memory"
                else _os.path.join(self.config.datadir, "slasher.db"))
            self.chain.slasher = SlasherService(
                self.chain, slasher=Slasher(
                    self.chain.spec, self.chain.t, config=cfg,
                    n_validators=len(self.chain.head_state.validators)))
        return self

    def build(self) -> Client:
        try:
            return self._build()
        except Exception:
            # a failed assembly must not leave the datadir locked against
            # the caller's own retry
            if self._lockfile is not None:
                self._lockfile.release()
                self._lockfile = None
            raise

    def _build(self) -> Client:
        from lighthouse_tpu.processor import BeaconProcessor

        if self.spec is None:
            self.load_spec()
        if self.genesis_state is None:
            self.genesis()
        if self._el is None:
            self.execution_layer()
        if self._eth1 is None:
            self.eth1()
        if self.chain is None:
            self.beacon_chain()

        client = Client(self.config, self.spec, self.chain, self.executor,
                        lockfile=self._lockfile)
        client.processor = processor = BeaconProcessor()
        # the observatory roll-up (api.node_rollup) audits the processor
        # ledger through the chain handle, same as the simulator's nodes
        self.chain.beacon_processor = processor

        def _processor_loop(exit_event):
            """Dedicated asyncio loop for the beacon processor — the
            client is thread-structured, the processor's manager +
            ladder sweeper are asyncio.  Cross-thread submissions rely
            on the manager's bounded flush-interval wait: a wakeup lost
            to the thread boundary is recovered within batch_flush_ms."""
            import asyncio as _asyncio

            loop = _asyncio.new_event_loop()
            _asyncio.set_event_loop(loop)

            async def main():
                await processor.start()
                while not exit_event.is_set():
                    await _asyncio.sleep(0.1)
                await processor.stop(drain=False)

            loop.run_until_complete(main())
            loop.close()

        self.executor.spawn(_processor_loop, "beacon-processor")
        # operator chaos drill: an LHTPU_INGEST_FAULT_MODE storm arms
        # here, same discipline as the LHTPU_STORE_FAULT_* crash knobs —
        # mode=stall wedges the real batch consumer
        # (beacon_processor._with_ingest_stall); burst/dup/invalid shape
        # firehose-driver arrival in drills
        from lighthouse_tpu.ops import faults as _faults

        # network-plane chaos drill: LHTPU_PEERFAULT_* arms Byzantine
        # peer faults (stall/empty/truncate/malformed/wrong_chain/
        # equivocate/flap) at the rpc request seam, same discipline as
        # the store/ingest knobs above
        peer_plan = _faults.peer_plan_from_env()
        if peer_plan is not None:
            _faults.install_peer_plans((peer_plan,))
            self.log.warn("peer fault injection armed",
                          mode=peer_plan.mode,
                          peers=",".join(sorted(peer_plan.peers))
                          if peer_plan.peers else "*")

        ingest_plan = _faults.ingest_plan_from_env()
        if ingest_plan is not None:
            # the storm self-expires after LHTPU_INGEST_FAULT_S (<=0 =
            # unbounded) — a forgotten drill knob must not wedge the
            # consumer forever
            _faults.install_ingest_plan(
                ingest_plan, duration_s=ingest_plan.duration_s)
            self.log.warn("ingest storm armed", mode=ingest_plan.mode,
                          factor=ingest_plan.factor,
                          duration_s=ingest_plan.duration_s)

        # the observatory plane: invariant monitors (processor/sync/
        # backfill books register themselves at construction) get their
        # background sweeper; LHTPU_OBS_SWEEP_S<=0 / LHTPU_OBS_ARMED=0
        # leaves them sweep-on-demand only
        from lighthouse_tpu.common import monitors as _monitors

        if _monitors.MONITORS.start():
            self.log.info("invariant watchdog sweeping",
                          monitors=",".join(_monitors.MONITORS.names()))

        # the persistent AOT program store: stored executables serve
        # every jit entry's first dispatch (source=store_hit) and the
        # background prewarmer compiles the misses while the PR 4/PR 6
        # ladders keep serving on the reference backends.  Directory:
        # LHTPU_AOT_STORE_DIR, defaulting to <datadir>/aot_programs for
        # a durable node; LHTPU_AOT_STORE=0 kills the whole plane.
        import os

        from lighthouse_tpu.common import env as _envreg
        from lighthouse_tpu.ops import program_store as _pstore

        aot_dir = _envreg.get("LHTPU_AOT_STORE_DIR") or (
            os.path.join(self.config.datadir, "aot_programs")
            if self.config.datadir else None)
        aot_store = _pstore.configure(aot_dir) if aot_dir else None
        if aot_store is not None:
            self.log.info("aot program store armed", dir=str(aot_dir))

            from lighthouse_tpu.ops import prewarm as _prewarm

            def _prewarm_task(exit_event):
                report = _prewarm.run(stop_event=exit_event)
                if report.get("ran"):
                    self.log.info(
                        "aot prewarm complete",
                        **{k: v for k, v in report["counts"].items() if v},
                        seconds=report["seconds"], scale=report["scale"])
                elif report.get("skipped"):
                    self.log.info("aot prewarm skipped",
                                  reason=report["skipped"])

            self.executor.spawn(_prewarm_task, "aot-prewarm")

        if self.config.listen_port is not None:
            self._wire_network(client)

        if self.config.http_enabled:
            from lighthouse_tpu.api import HttpServer

            client.http_server = HttpServer(
                self.chain, port=self.config.http_port).start()
            self.log.info("http api listening",
                          port=client.http_server.port)

        if self.config.interop_vc_range:
            self._interop_vc(client)

        # per-slot services: eth1 follow + slasher batches + notifier
        # (reference timer + notifier + slasher service)
        def slot_tick():
            chain = self.chain
            if chain.eth1_service is not None:
                chain.eth1_service.update()
            if chain.slasher is not None:
                chain.slasher.tick(chain.current_slot())

        self.executor.spawn_periodic(
            slot_tick, self.spec.seconds_per_slot, "slot-services")

        def notify():
            head = self.chain.head_state
            self.log.info(
                "slot status", slot=self.chain.current_slot(),
                head_slot=int(head.slot),
                validators=len(head.validators),
                finalized_epoch=int(self.chain.fork_choice.finalized.epoch))

        self.executor.spawn_periodic(
            notify, self.spec.seconds_per_slot, "notifier")

        if self.config.monitoring_endpoint:
            from lighthouse_tpu.common.system_health import (
                MonitoringHttpClient,
            )

            mon = MonitoringHttpClient(
                self.config.monitoring_endpoint,
                chain=self.chain,
                store=getattr(self.chain, "store", None),
                network=getattr(client.network, "peer_manager", None),
                eth1=self.chain.eth1_service,
                datadir=self.config.datadir or "/")
            mon.auto_update(self.executor, ("beaconnode", "system"))
            client.services["monitoring"] = mon
            self.log.info("remote monitoring enabled",
                          endpoint=self.config.monitoring_endpoint)
        return client

    def _interop_vc(self, client: Client) -> None:
        """In-process interop duty loop: the process-fleet analogue of
        the simulator's per-node validator split.  One thread paces the
        wall clock and runs the full VC tick a third into each slot
        (the attestation-deadline shape) — gossip-delivered blocks from
        OTHER nodes land before this node's attesters vote."""
        from lighthouse_tpu.testing import interop_secret_key
        from lighthouse_tpu.validator import ValidatorClient, ValidatorStore

        lo, hi = self.config.interop_vc_range
        store = ValidatorStore(
            self.spec, bytes(self.genesis_state.genesis_validators_root))
        for i in range(int(lo), int(hi)):
            store.add_validator(interop_secret_key(i), index=i)
        router = (client.network.router
                  if client.network is not None else None)
        vc = ValidatorClient(self.chain, store, router=router)
        client.services["interop_vc"] = vc
        chain = self.chain
        self.log.info("interop duty loop armed", validators=hi - lo)

        def duty_loop(exit_event):
            from lighthouse_tpu.common.metrics import record_swallowed

            # a (re)started node picks up duties at the NEXT slot: the
            # in-progress slot's proposal window is already compromised
            last = chain.slot_clock.current_slot()
            while not exit_event.is_set():
                clock = chain.slot_clock  # re-read: resume realigns it
                offset = clock.seconds_per_slot / 3.0
                slot = clock.current_slot()
                if slot <= last or clock.seconds_into_slot() < offset:
                    exit_event.wait(0.05)
                    continue
                last = slot
                try:
                    vc.run_slot(slot)
                except Exception as e:
                    # a failed duty tick misses ITS slot only — the
                    # loop keeps the node's remaining duties alive
                    record_swallowed("client.interop_vc", e)

        self.executor.spawn(duty_loop, "interop-vc")

    def _wire_network(self, client: Client) -> None:
        """Socket network stack: TCP gossip/RPC + UDP discovery
        (reference network service assembly, network/src/service.rs:160)."""
        from lighthouse_tpu.network.router import fork_digest
        from lighthouse_tpu.network.service import NetworkService
        from lighthouse_tpu.network.wire import WireFabric

        fabric = WireFabric(
            identity_seed=self.config.identity_seed,
            listen_port=self.config.listen_port,
            fork_digest=fork_digest(self.chain),
            transport=self.config.wire_transport)
        svc = NetworkService(self.chain, fabric, fabric.peer_id,
                             scheduled_subnets=False,
                             processor=client.processor)
        client.network = svc
        client.services["wire"] = fabric
        # the HTTP API's node/* endpoints read peers/identity through the
        # chain handle (same pattern as subnet_service)
        self.chain.network_service = svc
        self.log.info("wire network up", peer_id=fabric.peer_id,
                      port=fabric.listen_port)

        if self.config.upnp_enabled:
            # hold a UDP mapping for the discovery port on the LAN
            # gateway (reference nat.rs construct_upnp_mappings)
            from lighthouse_tpu.network.upnp import (
                UpnpService,
                discover_internal_ip,
            )

            local_ip = discover_internal_ip()
            if local_ip is None:
                self.log.warn(
                    "upnp disabled: no routable LAN interface address")
            else:
                upnp_svc = UpnpService(local_ip, fabric.listen_port)
                upnp_svc.start()
                svc.upnp = upnp_svc
                client.services["upnp"] = upnp_svc

        boot_nodes = tuple(self.config.boot_nodes)

        def bootstrap(_exit_event):
            for addr in boot_nodes:
                try:
                    n = svc.discover_and_connect(addr)
                    self.log.info("bootstrap done", boot=addr, peers=n)
                except Exception as e:
                    self.log.warn("bootstrap failed", boot=addr, err=str(e))

        if boot_nodes:
            self.executor.spawn(bootstrap, "wire-bootstrap")

        def net_tick():
            svc.on_slot(self.chain.current_slot())
            try:
                # chase any peer that is ahead (reference range-sync tick)
                svc.sync.sync()
            except Exception as e:
                self.log.warn("range sync tick failed", err=str(e))

        self.executor.spawn_periodic(
            net_tick, self.spec.seconds_per_slot, "net-slot")
