"""Client assembly (reference beacon_node/client + eth2_network_config)."""

from lighthouse_tpu.client.builder import Client, ClientBuilder, ClientConfig
from lighthouse_tpu.client.network_config import (
    built_in_networks,
    load_network_config,
    spec_for_network,
    spec_from_config_dict,
)

__all__ = [
    "Client",
    "ClientBuilder",
    "ClientConfig",
    "built_in_networks",
    "load_network_config",
    "spec_for_network",
    "spec_from_config_dict",
]
