"""Network configuration presets + config.yaml loading.

Rebuild of /root/reference/common/eth2_network_config (built-in configs:
mainnet/minimal-style config.yaml -> runtime ChainSpec) and the
config.yaml parsing half of consensus/types/src/chain_spec.rs: UPPER_SNAKE
keys map onto ChainSpec fields, fork versions are 0x-hex, unknown keys are
ignored (forward compatibility, as the reference does for new-fork keys).
"""

from __future__ import annotations

import dataclasses

from lighthouse_tpu import types as T

# config.yaml key -> ChainSpec field (the subset this client consumes)
_KEY_MAP = {
    "PRESET_BASE": None,  # handled specially
    "CONFIG_NAME": "config_name",
    "SECONDS_PER_SLOT": "seconds_per_slot",
    "GENESIS_DELAY": "genesis_delay",
    "MIN_GENESIS_TIME": "min_genesis_time",
    "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT":
        "min_genesis_active_validator_count",
    "MIN_DEPOSIT_AMOUNT": "min_deposit_amount",
    "MAX_EFFECTIVE_BALANCE": "max_effective_balance",
    "EJECTION_BALANCE": "ejection_balance",
    "ETH1_FOLLOW_DISTANCE": "eth1_follow_distance",
    "MIN_VALIDATOR_WITHDRAWABILITY_DELAY":
        "min_validator_withdrawability_delay",
    "SHARD_COMMITTEE_PERIOD": "shard_committee_period",
    "INACTIVITY_SCORE_BIAS": "inactivity_score_bias",
    "INACTIVITY_SCORE_RECOVERY_RATE": "inactivity_score_recovery_rate",
    "MIN_PER_EPOCH_CHURN_LIMIT": "min_per_epoch_churn_limit",
    "CHURN_LIMIT_QUOTIENT": "churn_limit_quotient",
    "MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT":
        "max_per_epoch_activation_churn_limit",
    "PROPOSER_SCORE_BOOST": "proposer_score_boost",
    "GENESIS_FORK_VERSION": "genesis_fork_version",
    "ALTAIR_FORK_VERSION": "altair_fork_version",
    "ALTAIR_FORK_EPOCH": "altair_fork_epoch",
    "BELLATRIX_FORK_VERSION": "bellatrix_fork_version",
    "BELLATRIX_FORK_EPOCH": "bellatrix_fork_epoch",
    "CAPELLA_FORK_VERSION": "capella_fork_version",
    "CAPELLA_FORK_EPOCH": "capella_fork_epoch",
    "DENEB_FORK_VERSION": "deneb_fork_version",
    "DENEB_FORK_EPOCH": "deneb_fork_epoch",
    "ELECTRA_FORK_VERSION": "electra_fork_version",
    "ELECTRA_FORK_EPOCH": "electra_fork_epoch",
    "DEPOSIT_CONTRACT_ADDRESS": "deposit_contract_address",
}

_VERSION_KEYS = {k for k in _KEY_MAP if k.endswith("_FORK_VERSION")}


def spec_from_config_dict(cfg: dict) -> T.ChainSpec:
    base = (T.ChainSpec.minimal()
            if str(cfg.get("PRESET_BASE", "mainnet")).lower() == "minimal"
            else T.ChainSpec.mainnet())
    updates = {}
    for key, value in cfg.items():
        fname = _KEY_MAP.get(str(key))
        if fname is None:
            continue  # unknown/unused keys are forward-compatible
        if key in _VERSION_KEYS or key == "DEPOSIT_CONTRACT_ADDRESS":
            if isinstance(value, int):
                # YAML 1.1 reads unquoted 0x... as an integer
                width = 4 if key in _VERSION_KEYS else 20
                updates[fname] = value.to_bytes(width, "big")
            else:
                s = str(value)
                updates[fname] = bytes.fromhex(
                    s[2:] if s.startswith("0x") else s)
        elif fname == "config_name":
            updates[fname] = str(value)
        else:
            updates[fname] = int(value)
    return dataclasses.replace(base, **updates)


def load_network_config(path: str) -> T.ChainSpec:
    """Parse a config.yaml into a ChainSpec."""
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"{path}: not a config mapping")
    return spec_from_config_dict(cfg)


# Built-in networks (reference built_in_network_configs/): the spec values
# the client can run without external files.
_BUILT_IN = {
    "mainnet": lambda: T.ChainSpec.mainnet(),
    "minimal": lambda: T.ChainSpec.minimal(),
    # devnet: minimal preset with all forks from genesis — the config the
    # in-process simulator and tests run
    "devnet": lambda: T.ChainSpec.minimal().with_forks_at(
        0, through="capella"),
}


def built_in_networks() -> list[str]:
    return sorted(_BUILT_IN)


def spec_for_network(name: str) -> T.ChainSpec:
    try:
        return _BUILT_IN[name]()
    except KeyError:
        raise ValueError(
            f"unknown network {name!r}; built-ins: {built_in_networks()}, "
            "or pass a config.yaml path via --network-config")
