"""Network configuration presets + config.yaml loading.

Rebuild of /root/reference/common/eth2_network_config (built-in configs:
mainnet/minimal-style config.yaml -> runtime ChainSpec) and the
config.yaml parsing half of consensus/types/src/chain_spec.rs: UPPER_SNAKE
keys map onto ChainSpec fields, fork versions are 0x-hex, unknown keys are
ignored (forward compatibility, as the reference does for new-fork keys).
"""

from __future__ import annotations

import dataclasses

from lighthouse_tpu import types as T

# config.yaml key -> ChainSpec field (the subset this client consumes)
_KEY_MAP = {
    "PRESET_BASE": None,  # handled specially
    "CONFIG_NAME": "config_name",
    "SECONDS_PER_SLOT": "seconds_per_slot",
    "GENESIS_DELAY": "genesis_delay",
    "MIN_GENESIS_TIME": "min_genesis_time",
    "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT":
        "min_genesis_active_validator_count",
    "MIN_DEPOSIT_AMOUNT": "min_deposit_amount",
    "MAX_EFFECTIVE_BALANCE": "max_effective_balance",
    "EJECTION_BALANCE": "ejection_balance",
    "ETH1_FOLLOW_DISTANCE": "eth1_follow_distance",
    "MIN_VALIDATOR_WITHDRAWABILITY_DELAY":
        "min_validator_withdrawability_delay",
    "SHARD_COMMITTEE_PERIOD": "shard_committee_period",
    "INACTIVITY_SCORE_BIAS": "inactivity_score_bias",
    "INACTIVITY_SCORE_RECOVERY_RATE": "inactivity_score_recovery_rate",
    "MIN_PER_EPOCH_CHURN_LIMIT": "min_per_epoch_churn_limit",
    "CHURN_LIMIT_QUOTIENT": "churn_limit_quotient",
    "MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT":
        "max_per_epoch_activation_churn_limit",
    "PROPOSER_SCORE_BOOST": "proposer_score_boost",
    "GENESIS_FORK_VERSION": "genesis_fork_version",
    "ALTAIR_FORK_VERSION": "altair_fork_version",
    "ALTAIR_FORK_EPOCH": "altair_fork_epoch",
    "BELLATRIX_FORK_VERSION": "bellatrix_fork_version",
    "BELLATRIX_FORK_EPOCH": "bellatrix_fork_epoch",
    "CAPELLA_FORK_VERSION": "capella_fork_version",
    "CAPELLA_FORK_EPOCH": "capella_fork_epoch",
    "DENEB_FORK_VERSION": "deneb_fork_version",
    "DENEB_FORK_EPOCH": "deneb_fork_epoch",
    "ELECTRA_FORK_VERSION": "electra_fork_version",
    "ELECTRA_FORK_EPOCH": "electra_fork_epoch",
    "DEPOSIT_CONTRACT_ADDRESS": "deposit_contract_address",
}

_VERSION_KEYS = {k for k in _KEY_MAP if k.endswith("_FORK_VERSION")}


def spec_from_config_dict(cfg: dict) -> T.ChainSpec:
    base = (T.ChainSpec.minimal()
            if str(cfg.get("PRESET_BASE", "mainnet")).lower() == "minimal"
            else T.ChainSpec.mainnet())
    updates = {}
    for key, value in cfg.items():
        fname = _KEY_MAP.get(str(key))
        if fname is None:
            continue  # unknown/unused keys are forward-compatible
        if key in _VERSION_KEYS or key == "DEPOSIT_CONTRACT_ADDRESS":
            if isinstance(value, int):
                # YAML 1.1 reads unquoted 0x... as an integer
                width = 4 if key in _VERSION_KEYS else 20
                updates[fname] = value.to_bytes(width, "big")
            else:
                s = str(value)
                updates[fname] = bytes.fromhex(
                    s[2:] if s.startswith("0x") else s)
        elif fname == "config_name":
            updates[fname] = str(value)
        else:
            updates[fname] = int(value)
    return dataclasses.replace(base, **updates)


def load_network_config(path: str) -> T.ChainSpec:
    """Parse a config.yaml into a ChainSpec."""
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"{path}: not a config mapping")
    return spec_from_config_dict(cfg)


def _holesky() -> T.ChainSpec:
    """Public Holesky testnet constants (reference
    built_in_network_configs/holesky/config.yaml)."""
    return dataclasses.replace(
        T.ChainSpec.mainnet(),
        config_name="holesky",
        min_genesis_active_validator_count=16384,
        min_genesis_time=1695902100,
        genesis_delay=300,
        genesis_fork_version=bytes.fromhex("01017000"),
        altair_fork_version=bytes.fromhex("02017000"),
        altair_fork_epoch=0,
        bellatrix_fork_version=bytes.fromhex("03017000"),
        bellatrix_fork_epoch=0,
        capella_fork_version=bytes.fromhex("04017000"),
        capella_fork_epoch=256,
        deneb_fork_version=bytes.fromhex("05017000"),
        deneb_fork_epoch=29696,
        electra_fork_version=bytes.fromhex("06017000"),
        # unscheduled at the reference snapshot (config.yaml pins
        # FAR_FUTURE); operators on live networks override via
        # --network-config with the scheduled epoch
        electra_fork_epoch=T.FAR_FUTURE_EPOCH,
        ejection_balance=28_000_000_000,
        deposit_chain_id=17000,
        deposit_network_id=17000,
        deposit_contract_address=bytes.fromhex(
            "4242424242424242424242424242424242424242"),
    )


def _sepolia() -> T.ChainSpec:
    """Public Sepolia testnet constants (reference
    built_in_network_configs/sepolia/config.yaml)."""
    return dataclasses.replace(
        T.ChainSpec.mainnet(),
        config_name="sepolia",
        min_genesis_active_validator_count=1300,
        min_genesis_time=1655647200,
        genesis_delay=86400,
        genesis_fork_version=bytes.fromhex("90000069"),
        altair_fork_version=bytes.fromhex("90000070"),
        altair_fork_epoch=50,
        bellatrix_fork_version=bytes.fromhex("90000071"),
        bellatrix_fork_epoch=100,
        capella_fork_version=bytes.fromhex("90000072"),
        capella_fork_epoch=56832,
        deneb_fork_version=bytes.fromhex("90000073"),
        deneb_fork_epoch=132608,
        electra_fork_version=bytes.fromhex("90000074"),
        electra_fork_epoch=T.FAR_FUTURE_EPOCH,  # unscheduled at snapshot
        deposit_chain_id=11155111,
        deposit_network_id=11155111,
        deposit_contract_address=bytes.fromhex(
            "7f02C3E3c98b133055B8B348B2Ac625669Ed295D"),
    )


# Built-in networks (reference built_in_network_configs/): the spec values
# the client can run without external files.
_BUILT_IN = {
    "mainnet": lambda: T.ChainSpec.mainnet(),
    "minimal": lambda: T.ChainSpec.minimal(),
    "holesky": _holesky,
    "sepolia": _sepolia,
    # devnet: minimal preset with all forks from genesis — the config the
    # in-process simulator and tests run
    "devnet": lambda: T.ChainSpec.minimal().with_forks_at(
        0, through="capella"),
}


def built_in_networks() -> list[str]:
    return sorted(_BUILT_IN)


def spec_for_network(name: str) -> T.ChainSpec:
    try:
        return _BUILT_IN[name]()
    except KeyError:
        raise ValueError(
            f"unknown network {name!r}; built-ins: {built_in_networks()}, "
            "or pass a config.yaml path via --network-config")
