"""Attestation + sync-committee subnet subscription scheduling.

Rebuild of /root/reference/beacon_node/network/src/subnet_service/: the
node does NOT listen to all 64 attestation subnets.  It keeps
(a) long-lived subnets derived deterministically from its node id and the
epoch (spec `compute_subscribed_subnets`), rotating per subscription
period, and (b) short-lived subscriptions opened one slot ahead of each
aggregator duty and closed when the duty's slot passes.  The router
consults this service to decide which `beacon_attestation_{n}` topics to
join (bandwidth sharding — SURVEY §2.9-7).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

SUBNETS_PER_NODE = 2
EPOCHS_PER_SUBSCRIPTION = 256          # spec EPOCHS_PER_SUBNET_SUBSCRIPTION
ADVANCE_SLOTS = 1                      # subscribe this many slots early


def compute_subscribed_subnets(node_id: bytes, epoch: int,
                               subnet_count: int = 64,
                               subnets_per_node: int = SUBNETS_PER_NODE,
                               ) -> list[int]:
    """Deterministic long-lived subnets for a node id at an epoch.

    Same shape as the spec's computation: a prefix of the node id plus
    the subscription period index seeds a permutation; we use sha256
    where the spec uses the shuffling hash — the property that matters
    (uniform, deterministic, rotating each period) is preserved."""
    period = epoch // EPOCHS_PER_SUBSCRIPTION
    out = []
    for i in range(subnets_per_node):
        digest = hashlib.sha256(
            node_id[:8] + period.to_bytes(8, "little")
            + i.to_bytes(8, "little")).digest()
        out.append(int.from_bytes(digest[:8], "little") % subnet_count)
    return sorted(set(out))


def compute_subnet_for_attestation(spec, slot: int, committee_index: int,
                                   committees_per_slot: int) -> int:
    """Spec ``compute_subnet_for_attestation``: the gossip subnet an
    attestation for (slot, committee) belongs on.  The firehose bench
    and the router's publish path share this so per-subnet fan-in and
    fan-out can never disagree about the mapping."""
    slots_since_epoch_start = slot % spec.slots_per_epoch
    committees_since_epoch_start = (
        committees_per_slot * slots_since_epoch_start)
    return ((committees_since_epoch_start + committee_index)
            % spec.attestation_subnet_count)


@dataclass
class _ShortLived:
    subnet: int
    start_slot: int     # subscribe at start_slot (duty slot - advance)
    end_slot: int       # unsubscribe after this slot


class AttestationSubnetService:
    """Tracks required subnets over time; the router polls
    `update(current_slot)` each slot and applies the subscribe /
    unsubscribe deltas it returns."""

    def __init__(self, spec, node_id: bytes):
        self.spec = spec
        self.node_id = node_id
        self._short: list[_ShortLived] = []
        self._active: set[int] = set()

    # -- duty registration (from the VC's subscriptions API) ---------------

    def subscribe_for_duty(self, slot: int, committee_index: int,
                           is_aggregator: bool) -> None:
        """Reference validator_subscriptions: aggregators need the subnet
        feed around their duty slot."""
        if not is_aggregator:
            return
        subnet = committee_index % self.spec.attestation_subnet_count
        self._short.append(_ShortLived(
            subnet, max(0, slot - ADVANCE_SLOTS), slot))

    # -- per-slot scheduling ------------------------------------------------

    def required_subnets(self, slot: int) -> set[int]:
        epoch = self.spec.compute_epoch_at_slot(slot)
        required = set(compute_subscribed_subnets(
            self.node_id, epoch, self.spec.attestation_subnet_count))
        for s in self._short:
            if s.start_slot <= slot <= s.end_slot:
                required.add(s.subnet)
        return required

    def update(self, slot: int) -> tuple[set[int], set[int]]:
        """Returns (to_subscribe, to_unsubscribe) deltas and drops
        expired short-lived entries."""
        self._short = [s for s in self._short if s.end_slot >= slot]
        required = self.required_subnets(slot)
        to_sub = required - self._active
        to_unsub = self._active - required
        self._active = required
        return to_sub, to_unsub

    @property
    def active(self) -> set[int]:
        return set(self._active)


class SyncSubnetService:
    """Sync-committee subnet scheduling: subscribe to the subnets where
    this node's validators serve for the whole sync-committee period
    (reference subnet_service sync half)."""

    def __init__(self, spec):
        self.spec = spec
        self._active: set[int] = set()

    def set_duty_subnets(self, subnets: set[int]) -> tuple[set[int], set[int]]:
        to_sub = subnets - self._active
        to_unsub = self._active - subnets
        self._active = set(subnets)
        return to_sub, to_unsub

    @property
    def active(self) -> set[int]:
        return set(self._active)


__all__ = [
    "AttestationSubnetService",
    "SyncSubnetService",
    "compute_subnet_for_attestation",
    "compute_subscribed_subnets",
    "EPOCHS_PER_SUBSCRIPTION",
    "SUBNETS_PER_NODE",
]
