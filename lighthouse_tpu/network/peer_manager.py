"""Peer scoring, ban management and connection bookkeeping.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/peer_manager/
(peerdb/score.rs:3-32 + peerdb.rs connection states): scores live in
[-100, 100] and decay toward zero with a 10-minute half-life; crossing
the disconnect threshold sheds the peer, crossing the ban threshold bans
it until the decayed score recovers (the reference's
score-based-unban-after-decay behaviour); the manager also tracks
connection state and picks pruning victims when over the target peer
count (peer_manager/mod.rs prune_excess_peers)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

MAX_SCORE = 100.0
MIN_SCORE = -100.0
BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
HALFLIFE_S = 600.0
TARGET_PEERS = 64

# standard penalty/reward magnitudes (peer_manager score actions)
PENALTIES = {
    "low": -1.0,
    "mid": -10.0,
    "high": -25.0,
    "fatal": -100.0,
}
REWARDS = {
    "valid_message": 0.5,
    "useful_response": 1.0,
}


@dataclass
class PeerInfo:
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    banned: bool = False
    connected: bool = False
    # per-topic invalid-message counts (gossipsub scoring's per-topic
    # mesh penalties, service/gossipsub_scoring_parameters.rs)
    topic_penalties: dict = field(default_factory=dict)


class PeerManager:
    def __init__(self, clock=time.monotonic, target_peers: int = TARGET_PEERS):
        self.peers: dict[str, PeerInfo] = {}
        self.clock = clock
        self.target_peers = target_peers
        # report()/score() are read-modify-write and callers arrive on
        # the wire event loop, the wire worker pool AND the slot thread
        self._lock = threading.RLock()

    def _info(self, peer: str) -> PeerInfo:
        info = self.peers.get(peer)
        if info is None:
            info = self.peers[peer] = PeerInfo(last_update=self.clock())
        return info

    def _decay(self, info: PeerInfo):
        now = self.clock()
        dt = now - info.last_update
        if dt > 0:
            info.score *= 0.5 ** (dt / HALFLIFE_S)
            info.last_update = now
        # score-based unban: a banned peer whose decayed score recovered
        # above the threshold is eligible again (score.rs unban flow)
        if info.banned and info.score > BAN_THRESHOLD:
            info.banned = False

    def report(self, peer: str, action: str, topic: str | None = None):
      with self._lock:
        info = self._info(peer)
        self._decay(info)
        delta = PENALTIES.get(action, REWARDS.get(action, 0.0))
        if topic is not None and delta < 0:
            info.topic_penalties[topic] = \
                info.topic_penalties.get(topic, 0) + 1
        info.score = max(MIN_SCORE, min(MAX_SCORE, info.score + delta))
        if info.score <= BAN_THRESHOLD:
            info.banned = True

    def score(self, peer: str) -> float:
        with self._lock:
            info = self._info(peer)
            self._decay(info)
            return info.score

    def is_banned(self, peer: str) -> bool:
        with self._lock:
            info = self._info(peer)
            self._decay(info)
            return info.banned

    def should_disconnect(self, peer: str) -> bool:
        return self.score(peer) <= DISCONNECT_THRESHOLD

    def accept_connection(self, peer: str) -> bool:
        """Gate for inbound dials: banned peers are refused at the door
        (peerdb.rs BanResult)."""
        return not self.is_banned(peer)

    # -- connection bookkeeping -------------------------------------------

    def mark_connected(self, peer: str):
        with self._lock:
            self._info(peer).connected = True

    def mark_disconnected(self, peer: str):
        with self._lock:
            self._info(peer).connected = False

    def connected_peers(self) -> list[str]:
        return [p for p, i in self.peers.items() if i.connected]

    def excess_peers(self) -> list[str]:
        """Worst-scoring connected peers beyond the target count — the
        pruning victims (peer_manager/mod.rs prune_excess_peers)."""
        connected = self.connected_peers()
        n_excess = len(connected) - self.target_peers
        if n_excess <= 0:
            return []
        connected.sort(key=lambda p: self.score(p))
        return connected[:n_excess]

    def good_peers(self) -> list[str]:
        # decay-aware: a long-quiet banned peer is eligible again, the
        # same verdict is_banned()/accept_connection() would give
        return [p for p in list(self.peers) if not self.is_banned(p)]
