"""Peer scoring and ban management.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/peer_manager/
peerdb/score.rs:3-32: scores live in [-100, 100], decay toward zero, and
crossing the ban threshold disconnects the peer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

MAX_SCORE = 100.0
MIN_SCORE = -100.0
BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
HALFLIFE_S = 600.0

# standard penalty/reward magnitudes (peer_manager score actions)
PENALTIES = {
    "low": -1.0,
    "mid": -10.0,
    "high": -25.0,
    "fatal": -100.0,
}
REWARDS = {
    "valid_message": 0.5,
    "useful_response": 1.0,
}


@dataclass
class PeerInfo:
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    banned: bool = False


class PeerManager:
    def __init__(self, clock=time.monotonic):
        self.peers: dict[str, PeerInfo] = {}
        self.clock = clock

    def _info(self, peer: str) -> PeerInfo:
        info = self.peers.get(peer)
        if info is None:
            info = self.peers[peer] = PeerInfo(last_update=self.clock())
        return info

    def _decay(self, info: PeerInfo):
        now = self.clock()
        dt = now - info.last_update
        if dt > 0:
            info.score *= 0.5 ** (dt / HALFLIFE_S)
            info.last_update = now

    def report(self, peer: str, action: str):
        info = self._info(peer)
        self._decay(info)
        delta = PENALTIES.get(action, REWARDS.get(action, 0.0))
        info.score = max(MIN_SCORE, min(MAX_SCORE, info.score + delta))
        if info.score <= BAN_THRESHOLD:
            info.banned = True

    def score(self, peer: str) -> float:
        info = self._info(peer)
        self._decay(info)
        return info.score

    def is_banned(self, peer: str) -> bool:
        return self._info(peer).banned

    def should_disconnect(self, peer: str) -> bool:
        return self.score(peer) <= DISCONNECT_THRESHOLD

    def good_peers(self) -> list[str]:
        return [p for p, i in self.peers.items() if not i.banned]
