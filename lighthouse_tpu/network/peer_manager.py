"""Peer scoring, ban management and connection bookkeeping.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/peer_manager/
(peerdb/score.rs:3-32 + peerdb.rs connection states): scores live in
[-100, 100] and decay toward zero with a 10-minute half-life; crossing
the disconnect threshold sheds the peer, crossing the ban threshold bans
it until the decayed score recovers (the reference's
score-based-unban-after-decay behaviour); the manager also tracks
connection state and picks pruning victims when over the target peer
count (peer_manager/mod.rs prune_excess_peers).

Round-4 depth (VERDICT r3 weak #6):

- IP-collated bans: banning enough peers behind one IP bans the IP
  itself, and the accept gate refuses further dials from it
  (peerdb.rs:21 BANNED_PEERS_PER_IP_THRESHOLD);
- trusted peers: never banned, never pruned, score floor pinned
  (peerdb.rs trusted flag);
- client identification from the HELLO agent string
  (peer_manager/peerdb/client.rs From<&str>);
- heartbeat: one periodic tick that enforces disconnects/bans, prunes
  excess peers with subnet-aware protection (peers that are the sole
  provider of a subscribed topic go last — mod.rs prune_excess_peers'
  subnet protection), and reports the outbound dial deficit
  (mod.rs:heartbeat's `peers_to_dial`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

MAX_SCORE = 100.0
MIN_SCORE = -100.0
BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
HALFLIFE_S = 600.0
TARGET_PEERS = 64
# outbound-only quota the dialer tries to keep filled so the node is not
# at the mercy of inbound churn (reference MIN_OUTBOUND_ONLY_FACTOR)
MIN_OUTBOUND_FRACTION = 0.2
# banning this many peers behind one IP bans the IP itself
BANNED_PEERS_PER_IP = 5

# standard penalty/reward magnitudes (peer_manager score actions)
PENALTIES = {
    "low": -1.0,
    "mid": -10.0,
    "high": -25.0,
    "fatal": -100.0,
}
REWARDS = {
    "valid_message": 0.5,
    "useful_response": 1.0,
}

# agent-string prefix -> client kind (peerdb/client.rs From<&str>);
# longest-prefix entries first so lighthouse_tpu beats lighthouse
_CLIENT_KINDS = (
    ("lighthouse_tpu", "LighthouseTpu"),
    ("lighthouse", "Lighthouse"),
    ("teku", "Teku"),
    ("prysm", "Prysm"),
    ("nimbus", "Nimbus"),
    ("lodestar", "Lodestar"),
    ("grandine", "Grandine"),
    ("caplin", "Caplin"),
    ("erigon", "Caplin"),
)


def client_kind(agent: str | None) -> str:
    """Client family from a HELLO/identify agent string."""
    if not agent:
        return "Unknown"
    a = agent.lower()
    for prefix, kind in _CLIENT_KINDS:
        if a.startswith(prefix):
            return kind
    return "Unknown"


@dataclass
class PeerInfo:
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    banned: bool = False
    connected: bool = False
    outbound: bool = False
    trusted: bool = False
    ip: str | None = None
    agent: str | None = None
    client: str = "Unknown"
    # per-topic invalid-message counts (gossipsub scoring's per-topic
    # mesh penalties, service/gossipsub_scoring_parameters.rs)
    topic_penalties: dict = field(default_factory=dict)


class PeerManager:
    def __init__(self, clock=time.monotonic, target_peers: int = TARGET_PEERS):
        self.peers: dict[str, PeerInfo] = {}
        self.clock = clock
        self.target_peers = target_peers
        # ip -> peers seen from it: bounds the ban-collation scan to one
        # IP's own peers (an attacker only amplifies their own IP's cost)
        self._by_ip: dict[str, set[str]] = {}
        # report()/score() are read-modify-write and callers arrive on
        # the wire event loop, the wire worker pool AND the slot thread
        self._lock = threading.RLock()

    @property
    def banned_ips(self) -> set[str]:
        """IPs currently hosting >= BANNED_PEERS_PER_IP banned peers.

        Recomputed on read with per-peer decay applied, so an IP ban
        lifts on its own once enough of its peers' scores recover —
        the reference's unban-when-count-drops collation (peerdb.rs),
        not a permanent blocklist."""
        with self._lock:
            return {ip for ip in self._by_ip if self._ip_banned(ip)}

    def _info(self, peer: str) -> PeerInfo:
        info = self.peers.get(peer)
        if info is None:
            info = self.peers[peer] = PeerInfo(last_update=self.clock())
        return info

    def _decay(self, info: PeerInfo):
        now = self.clock()
        dt = now - info.last_update
        if dt > 0:
            info.score *= 0.5 ** (dt / HALFLIFE_S)
            info.last_update = now
        # score-based unban: a banned peer whose decayed score recovered
        # above the threshold is eligible again (score.rs unban flow)
        if info.banned and info.score > BAN_THRESHOLD:
            info.banned = False

    def _set_ip(self, info: PeerInfo, peer: str, ip: str | None):
        if ip is None or info.ip == ip:
            info.ip = info.ip or ip
            if ip is not None:
                self._by_ip.setdefault(ip, set()).add(peer)
            return
        if info.ip is not None:
            self._by_ip.get(info.ip, set()).discard(peer)
        info.ip = ip
        self._by_ip.setdefault(ip, set()).add(peer)

    def _ip_banned(self, ip: str | None) -> bool:
        """Live collation over ONE IP's peers (via the _by_ip index):
        does `ip` currently host enough banned peers to be refused
        wholesale (peerdb.rs ban collation)?"""
        if ip is None:
            return False
        n = 0
        for pid in self._by_ip.get(ip, ()):
            info = self.peers.get(pid)
            if info is None:
                continue
            self._decay(info)
            if info.banned:
                n += 1
                if n >= BANNED_PEERS_PER_IP:
                    return True
        return False

    def report(self, peer: str, action: str, topic: str | None = None):
      with self._lock:
        info = self._info(peer)
        self._decay(info)
        if info.trusted:
            return
        delta = PENALTIES.get(action, REWARDS.get(action, 0.0))
        if topic is not None and delta < 0:
            info.topic_penalties[topic] = \
                info.topic_penalties.get(topic, 0) + 1
        info.score = max(MIN_SCORE, min(MAX_SCORE, info.score + delta))
        if info.score <= BAN_THRESHOLD:
            info.banned = True

    def score(self, peer: str) -> float:
        with self._lock:
            info = self._info(peer)
            self._decay(info)
            return info.score

    def is_banned(self, peer: str) -> bool:
        with self._lock:
            info = self._info(peer)
            self._decay(info)
            return info.banned or (not info.trusted
                                   and self._ip_banned(info.ip))

    def should_disconnect(self, peer: str) -> bool:
        with self._lock:
            if self._info(peer).trusted:
                return False
        return self.score(peer) <= DISCONNECT_THRESHOLD

    def accept_connection(self, peer: str, ip: str | None = None) -> bool:
        """Gate for inbound dials: banned peers AND banned IPs are
        refused at the door (peerdb.rs BanResult::{Banned,BannedIp})."""
        with self._lock:
            if ip is not None:
                info = self._info(peer)
                self._set_ip(info, peer, ip)
                if not info.trusted and self._ip_banned(ip):
                    return False
        return not self.is_banned(peer)

    # -- trusted peers ------------------------------------------------------

    def set_trusted(self, peer: str, trusted: bool = True):
        """Trusted peers are exempt from scoring penalties, bans and
        pruning (peerdb.rs trusted flag; --trusted-peers CLI)."""
        with self._lock:
            info = self._info(peer)
            info.trusted = trusted
            if trusted:
                info.banned = False
                info.score = max(info.score, 0.0)

    # -- connection bookkeeping -------------------------------------------

    def mark_connected(self, peer: str, *, ip: str | None = None,
                       outbound: bool = False, agent: str | None = None):
        with self._lock:
            info = self._info(peer)
            info.connected = True
            info.outbound = outbound
            self._set_ip(info, peer, ip)
            if agent is not None:
                info.agent = agent
                info.client = client_kind(agent)

    def mark_disconnected(self, peer: str):
        with self._lock:
            self._info(peer).connected = False

    def connected_peers(self) -> list[str]:
        with self._lock:
            return [p for p, i in self.peers.items() if i.connected]

    def client_counts(self) -> dict[str, int]:
        """Connected-peer census by client family (the reference's
        libp2p_peers_per_client metric)."""
        with self._lock:
            out: dict[str, int] = {}
            for i in self.peers.values():
                if i.connected:
                    out[i.client] = out.get(i.client, 0) + 1
            return out

    def excess_peers(self, protected: set[str] | None = None) -> list[str]:
        """Worst-scoring connected peers beyond the target count — the
        pruning victims (peer_manager/mod.rs prune_excess_peers).

        ``protected`` peers (sole providers of a subscribed subnet
        topic, trusted peers) are only pruned once every unprotected
        candidate is gone."""
        connected = self.connected_peers()
        n_excess = len(connected) - self.target_peers
        if n_excess <= 0:
            return []
        protected = protected or set()
        with self._lock:
            trusted = {p for p in connected if self.peers[p].trusted}
        pool = sorted(
            (p for p in connected if p not in trusted),
            # unprotected first, then ascending score
            key=lambda p: (p in protected, self.score(p)))
        return pool[:n_excess]

    def dial_deficit(self) -> tuple[int, int]:
        """(total_deficit, outbound_deficit): how many more peers — and
        how many outbound-initiated ones — the heartbeat should dial
        (mod.rs heartbeat's peers_to_dial + outbound-only quota)."""
        with self._lock:
            connected = [i for i in self.peers.values() if i.connected]
            total = max(0, self.target_peers - len(connected))
            want_outbound = int(self.target_peers * MIN_OUTBOUND_FRACTION)
            outbound = max(0, want_outbound
                           - sum(1 for i in connected if i.outbound))
        return total, outbound

    def good_peers(self) -> list[str]:
        # decay-aware: a long-quiet banned peer is eligible again, the
        # same verdict is_banned()/accept_connection() would give
        with self._lock:
            candidates = list(self.peers)
        return [p for p in candidates if not self.is_banned(p)]

    # -- heartbeat ----------------------------------------------------------

    def _gc(self):
        """Bound the table: disconnected, unbanned, near-zero-score
        entries are forgotten once the table exceeds 4x the target (an
        attacker cycling sybil ids otherwise grows it without limit)."""
        with self._lock:
            if len(self.peers) <= 4 * self.target_peers:
                return
            for pid in [p for p, i in self.peers.items()
                        if not i.connected and not i.banned
                        and not i.trusted and abs(i.score) < 1.0]:
                info = self.peers.pop(pid)
                if info.ip is not None:
                    self._by_ip.get(info.ip, set()).discard(pid)
            for ip in [ip for ip, ps in self._by_ip.items() if not ps]:
                del self._by_ip[ip]

    def heartbeat(self, node, dial_candidates=None,
                  protected=None) -> int:
        """One maintenance tick against a wire node (mod.rs heartbeat):
        enforce bans/disconnect thresholds, prune excess connections
        (subnet-protected), then fill the dial deficit from
        ``dial_candidates``.  Both arguments may be zero-arg CALLABLES —
        evaluated only when pruning/dialing actually happens, so the
        steady state (at target, nothing to shed) pays nothing for them.
        Returns the number of dials attempted."""
        self._gc()
        for peer in list(node.peers):
            if self.is_banned(peer) or self.should_disconnect(peer):
                node.disconnect(peer)
        if len(self.connected_peers()) > self.target_peers:
            if callable(protected):
                protected = protected()
            for peer in self.excess_peers(protected=protected):
                node.disconnect(peer)
        dials = 0
        # dials create OUTBOUND connections, so an unmet outbound quota
        # justifies dialing even at target (excess is pruned next tick —
        # reference MIN_OUTBOUND_ONLY_FACTOR enforcement)
        total, outbound = self.dial_deficit()
        deficit = max(total, outbound)
        if deficit and dial_candidates is not None:
            if callable(dial_candidates):
                dial_candidates = dial_candidates()
            for cand in list(dial_candidates)[:deficit]:
                try:
                    if callable(cand):
                        cand()
                    else:
                        node.connect(*cand)
                    dials += 1
                except Exception as e:
                    # a refused/unreachable dial candidate must not sink
                    # the heartbeat; counted, then the next candidate
                    from lighthouse_tpu.common.metrics import (
                        record_swallowed,
                    )

                    record_swallowed("peer_manager.dial", e)
                    continue
        return dials
