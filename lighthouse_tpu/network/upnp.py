"""UPnP IGD port mapping (NAT traversal).

Rebuild of the reference's NAT strategy
(/root/reference/beacon_node/network/src/nat.rs:20-60, which drives the
igd crate): discover the Internet Gateway Device over SSDP, read its
external IP, refuse to advertise through a gateway whose external
address is itself private (double NAT), then hold a UDP discovery-port
mapping with a 3600 s lease renewed at half-life.  The SSDP/SOAP
protocol work the reference delegates to `igd_next` is implemented
here directly on the stdlib (socket + http.client + ElementTree).

Offline posture: this box has zero egress, so production behaviour is
exercised against an in-process fake gateway (tests/test_upnp.py); a
real LAN gateway speaks the same two messages (M-SEARCH, SOAP POST).
"""

from __future__ import annotations

import ipaddress
import socket
import threading
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass

from lighthouse_tpu.common.logging import Logger

SSDP_ADDR = ("239.255.255.250", 1900)
IGD_SEARCH_TARGET = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)

# reference nat.rs MAPPING_DURATION / MAPPING_TIMEOUT
MAPPING_DURATION_S = 3600
RENEW_EVERY_S = MAPPING_DURATION_S / 2


class UpnpError(Exception):
    pass


def discover_internal_ip() -> str | None:
    """LAN-facing source IP for port-mapping requests.

    A UDP socket "connected" toward the SSDP multicast group makes the
    kernel pick the interface it would route UPnP traffic through — no
    packet is sent (UDP connect only sets the destination).  This beats
    ``gethostbyname(gethostname())``, which on many hosts resolves to
    127.0.x.x via /etc/hosts and would register a useless loopback
    mapping on the gateway.  Returns None when no usable (non-loopback,
    specified) LAN address exists; callers skip UPnP rather than map a
    wrong address."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(SSDP_ADDR)
            ip = s.getsockname()[0]
    except OSError:
        return None
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return None
    if addr.is_loopback or addr.is_unspecified:
        return None
    return ip


@dataclass
class Gateway:
    """One WAN*Connection control endpoint on a discovered IGD."""

    control_url: str
    service_type: str

    def _soap(self, action: str, args: dict[str, str]) -> dict[str, str]:
        body_args = "".join(
            f"<{k}>{v}</{k}>" for k, v in args.items())
        envelope = (
            '<?xml version="1.0"?>'
            '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/"'
            ' s:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
            f'<s:Body><u:{action} xmlns:u="{self.service_type}">'
            f'{body_args}</u:{action}></s:Body></s:Envelope>')
        req = urllib.request.Request(
            self.control_url, data=envelope.encode(),
            headers={
                "Content-Type": 'text/xml; charset="utf-8"',
                "SOAPAction": f'"{self.service_type}#{action}"',
            }, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                raw = resp.read()
        except Exception as e:  # HTTPError carries the UPnPError body
            raise UpnpError(f"SOAP {action} failed: {e}") from e
        out: dict[str, str] = {}
        for el in ET.fromstring(raw).iter():
            tag = el.tag.rsplit("}", 1)[-1]
            if el.text is not None and not tag.endswith(("Envelope", "Body")):
                out[tag] = el.text
        return out

    def external_ip(self) -> str:
        resp = self._soap("GetExternalIPAddress", {})
        ip = resp.get("NewExternalIPAddress")
        if not ip:
            raise UpnpError("gateway returned no external IP")
        return ip

    def add_port(self, proto: str, external_port: int, internal_ip: str,
                 internal_port: int, lease_s: int = MAPPING_DURATION_S,
                 description: str = "lighthouse_tpu discovery") -> None:
        self._soap("AddPortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": str(external_port),
            "NewProtocol": proto.upper(),
            "NewInternalPort": str(internal_port),
            "NewInternalClient": internal_ip,
            "NewEnabled": "1",
            "NewPortMappingDescription": description,
            "NewLeaseDuration": str(lease_s),
        })

    def delete_port(self, proto: str, external_port: int) -> None:
        self._soap("DeletePortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": str(external_port),
            "NewProtocol": proto.upper(),
        })


def discover_gateway(timeout: float = 3.0,
                     ssdp_addr: tuple[str, int] = SSDP_ADDR) -> Gateway:
    """SSDP M-SEARCH for an IGD, then fetch + parse its description to
    the WAN*Connection control URL.  ``ssdp_addr`` is parameterized so
    tests (and UPnP 1.1 unicast search) can target a specific responder.
    """
    msg = ("M-SEARCH * HTTP/1.1\r\n"
           f"HOST: {ssdp_addr[0]}:{ssdp_addr[1]}\r\n"
           'MAN: "ssdp:discover"\r\n'
           "MX: 2\r\n"
           f"ST: {IGD_SEARCH_TARGET}\r\n\r\n")
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        sock.sendto(msg.encode(), ssdp_addr)
        while True:
            try:
                data, _ = sock.recvfrom(4096)
            except socket.timeout:
                raise UpnpError("no UPnP gateway responded") from None
            location = None
            for line in data.decode(errors="replace").split("\r\n"):
                k, _, v = line.partition(":")
                if k.strip().lower() == "location":
                    location = v.strip()
            if location:
                break
    finally:
        sock.close()
    return _gateway_from_description(location)


def _gateway_from_description(location: str) -> Gateway:
    try:
        with urllib.request.urlopen(location, timeout=5) as resp:
            desc = resp.read()
    except Exception as e:
        raise UpnpError(f"cannot fetch device description: {e}") from e
    root = ET.fromstring(desc)

    def findall(tag):
        return [el for el in root.iter() if el.tag.rsplit("}", 1)[-1] == tag]

    for svc in findall("service"):
        st = ctl = None
        for child in svc:
            tag = child.tag.rsplit("}", 1)[-1]
            if tag == "serviceType":
                st = (child.text or "").strip()
            elif tag == "controlURL":
                ctl = (child.text or "").strip()
        if st in WAN_SERVICES and ctl:
            return Gateway(urllib.parse.urljoin(location, ctl), st)
    raise UpnpError("gateway advertises no WAN*Connection service")


class UpnpService:
    """Holds the discovery-port UDP mapping alive (reference
    construct_upnp_mappings' loop), exposing a status string for the
    node API / logs: mapped | no_gateway | double_nat | error."""

    def __init__(self, internal_ip: str, port: int,
                 ssdp_addr: tuple[str, int] = SSDP_ADDR,
                 renew_every_s: float = RENEW_EVERY_S):
        self.internal_ip = internal_ip
        self.port = int(port)
        self.ssdp_addr = ssdp_addr
        self.renew_every_s = renew_every_s
        self.log = Logger("upnp")
        self.status = "idle"
        self.external_ip: str | None = None
        self.renewals = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def map_once(self) -> bool:
        """One discover → external-ip → map pass.  Returns mapped?"""
        try:
            gw = discover_gateway(ssdp_addr=self.ssdp_addr)
        except UpnpError as e:
            self.status = "no_gateway"
            self.log.debug(f"no gateway: {e}")
            return False
        try:
            ext = gw.external_ip()
            if ipaddress.ip_address(ext).is_private:
                # reference nat.rs: a private external address means
                # double NAT — mapping there advertises a dead address
                self.status = "double_nat"
                self.log.warn(f"gateway external address {ext} is private")
                return False
            gw.add_port("UDP", self.port, self.internal_ip, self.port,
                        MAPPING_DURATION_S)
        except UpnpError as e:
            self.status = "error"
            self.log.warn(f"mapping failed: {e}")
            return False
        self.external_ip = ext
        self.status = "mapped"
        self.renewals += 1
        self.log.info(
            f"discovery UDP port {self.port} mapped (external {ext})")
        return True

    def start(self) -> None:
        def loop():
            while not self._stop.is_set():
                self.map_once()
                if self._stop.wait(self.renew_every_s):
                    return

        self._thread = threading.Thread(
            target=loop, name="upnp", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
