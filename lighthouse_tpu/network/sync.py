"""Sync manager: range sync + parent-lookup sync.

Rebuild of /root/reference/beacon_node/network/src/sync/ (manager.rs:1-34,
range_sync/, block_lookups/): STATUS handshakes pick a peer ahead of us,
BlocksByRange batches walk from our finalized slot to the peer's head, and
unknown-parent blocks trigger a backwards lookup chase capped in depth.
"""

from __future__ import annotations

from dataclasses import dataclass

from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOCKS_BY_RANGE,
    P_BLOCKS_BY_ROOT,
    P_STATUS,
    RpcError,
    StatusMessage,
)

BATCH_SIZE = 32
MAX_LOOKUP_DEPTH = 16


@dataclass
class PeerStatus:
    head_slot: int
    head_root: bytes
    finalized_epoch: int


class SyncManager:
    def __init__(self, chain, rpc_ep, router, peer_manager):
        self.chain = chain
        self.rpc = rpc_ep
        self.router = router
        self.peers = peer_manager
        self.statuses: dict[str, PeerStatus] = {}

    # -- status -------------------------------------------------------------

    def status_handshake(self, peer: str) -> PeerStatus | None:
        try:
            chunks = self.rpc.request(
                peer, P_STATUS, self.router.local_status().serialize())
        except RpcError:
            self.peers.report(peer, "mid")
            return None
        if not chunks:
            return None
        remote = StatusMessage.deserialize(chunks[0])
        st = PeerStatus(
            head_slot=int(remote.head_slot),
            head_root=bytes(remote.head_root),
            finalized_epoch=int(remote.finalized_epoch),
        )
        self.statuses[peer] = st
        self.peers.report(peer, "useful_response")  # register as connected
        return st

    # -- range sync ----------------------------------------------------------

    def sync_to_peer(self, peer: str) -> int:
        """Range-sync toward `peer`'s head; returns blocks imported."""
        status = self.statuses.get(peer) or self.status_handshake(peer)
        if status is None:
            return 0
        imported = 0
        local_head = int(self.chain.head_state.slot)
        slot = local_head + 1
        while slot <= status.head_slot:
            req = BlocksByRangeRequest(
                start_slot=slot, count=BATCH_SIZE, step=1)
            try:
                chunks = self.rpc.request(
                    peer, P_BLOCKS_BY_RANGE, req.serialize())
            except RpcError:
                self.peers.report(peer, "mid")
                break
            if not chunks:
                break
            for raw in chunks:
                block = self._decode_block(raw)
                if block is None:
                    self.peers.report(peer, "high")
                    return imported
                try:
                    root = self.chain.process_block(block, source="rpc")
                    if root is not None:
                        imported += 1
                except Exception:
                    self.peers.report(peer, "mid")
                    return imported
            self.peers.report(peer, "useful_response")
            slot += BATCH_SIZE
        return imported

    def sync(self) -> int:
        """Pick the best peer ahead of us and range-sync to it
        (manager.rs's RangeSync target selection)."""
        local = int(self.chain.head_state.slot)
        best, best_slot = None, local
        for peer in self.peers.good_peers():
            st = self.statuses.get(peer) or self.status_handshake(peer)
            if st is not None and st.head_slot > best_slot:
                best, best_slot = peer, st.head_slot
        if best is None:
            return 0
        return self.sync_to_peer(best)

    # -- lookup sync ----------------------------------------------------------

    def lookup_unknown_parent(self, peer: str, block) -> int:
        """Chase missing ancestors by root, then import the chain segment
        (block_lookups/)."""
        chain_segment = [block]
        parent = bytes(block.message.parent_root)
        for _ in range(MAX_LOOKUP_DEPTH):
            if parent in self.chain.fork_choice.proto:
                break
            try:
                chunks = self.rpc.request(peer, P_BLOCKS_BY_ROOT, parent)
            except RpcError:
                return 0
            if not chunks:
                return 0
            got = self._decode_block(chunks[0])
            if got is None or got.message.hash_tree_root() != parent:
                self.peers.report(peer, "high")
                return 0
            chain_segment.append(got)
            parent = bytes(got.message.parent_root)
        else:
            return 0  # exceeded depth without finding a known ancestor
        imported = 0
        for blk in reversed(chain_segment):
            try:
                if self.chain.process_block(blk, source="rpc") is not None:
                    imported += 1
            except Exception:
                break
        return imported

    def _decode_block(self, raw: bytes):
        return self.chain.t.decode_signed_block(raw)
