"""Sync manager: supervised range sync + parent-lookup sync.

Rebuild of /root/reference/beacon_node/network/src/sync/ (manager.rs,
range_sync/chain.rs + chain_collection.rs, block_lookups/): STATUS
handshakes pick peers ahead of us, peers advertising the SAME target
head merge into one syncing chain (concurrent-chain dedup), and each
BlocksByRange batch runs a retry state machine — a failed or lying
download moves to another pool peer with the offender downscored, up to
LHTPU_SYNC_BATCH_ATTEMPTS (range_sync/batch.rs's
MAX_BATCH_DOWNLOAD_ATTEMPTS).  Batch contents are validated against the
request (slot window, chunk-count bound, ascending order, intra-batch
parent linkage) before a single block is executed, so a lying peer
costs one round trip, not a poisoned import.

Byzantine hardening (the PAPER.md §L5/§L8 adversarial serving model):

- **Cross-batch linkage.** A batch's first block must attach to KNOWN
  history (its parent in fork choice).  An empty response can no longer
  silently advance the cursor past real history: empty windows are
  recorded as *provisional* and only confirmed when a later served
  block links across them.  When it does not, the windows are
  re-requested from different pool peers; blocks recovered there prove
  the original server withheld history and it is downscored hard
  (``sync_downscores_total{reason="withheld_window"}``).
- **Progress watchdog + per-target accounting.** A chain making no
  batch progress for LHTPU_SYNC_STALL_S is abandoned and its peers
  re-pooled; targets are retried at most LHTPU_SYNC_CHAIN_ATTEMPTS
  times (the PR 8 ladder shape, per advertised (head_root, head_slot)).
- **Books.** Every batch attempt lands in exactly one of
  imported/retried/abandoned, so the invariant
  ``requested == imported + retried + abandoned`` holds at all times
  (``sync_batch_requests_total`` vs ``sync_batches_total{outcome}``);
  every penalty issued by the sync plane is reason-labeled in
  ``sync_downscores_total{reason}``.

Unknown-parent blocks trigger a backwards lookup chase capped in depth,
single-flight per root with a failed-chase cache (block_lookups dedup
hardening).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed
from lighthouse_tpu.common.tracing import add_attrs, span
from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOCKS_BY_RANGE,
    P_BLOCKS_BY_ROOT,
    P_STATUS,
    RpcError,
    StatusMessage,
)

BATCH_SIZE = 32               # default; LHTPU_SYNC_BATCH_SIZE overrides
MAX_BATCH_ATTEMPTS = 5        # default; LHTPU_SYNC_BATCH_ATTEMPTS overrides
MAX_LOOKUP_DEPTH = 16
FAILED_LOOKUP_CACHE = 512
#: unconfirmed empty windows tolerated before the chain counts as
#: wedged (a peer set serving nothing but empties toward an advertised
#: head is withholding — or the head was equivocated)
MAX_PENDING_WINDOWS = 8
#: remembered sync targets for per-target attempt accounting
TARGET_CACHE = 64


def _batch_size() -> int:
    return max(1, envreg.get_int("LHTPU_SYNC_BATCH_SIZE", BATCH_SIZE)
               or BATCH_SIZE)


def _batch_attempts() -> int:
    return max(1, envreg.get_int("LHTPU_SYNC_BATCH_ATTEMPTS",
                                 MAX_BATCH_ATTEMPTS) or MAX_BATCH_ATTEMPTS)


@dataclass
class PeerStatus:
    head_slot: int
    head_root: bytes
    finalized_epoch: int


class SyncManager:
    def __init__(self, chain, rpc_ep, router, peer_manager):
        self.chain = chain
        self.rpc = rpc_ep
        self.router = router
        self.peers = peer_manager
        self.statuses: dict[str, PeerStatus] = {}
        # handshakes land from both the bootstrap thread and the
        # net-slot loop; the status table and the downscore tally are
        # the two cells both write (the books keep their documented
        # lock-free single-writer ordering)
        self._ledger_lock = threading.Lock()
        self._inflight_lookups: set[bytes] = set()
        self._failed_lookups: OrderedDict[bytes, None] = OrderedDict()
        # per-advertised-target abandoned-attempt accounting (PR 8
        # ladder shape: a target that keeps wedging is skipped)
        self._chain_attempts: OrderedDict[tuple, int] = OrderedDict()
        self._target_root: bytes | None = None
        self._last_chain_ok = True
        # the books: requested == imported + retried + abandoned, always
        self.books = {"requested": 0, "imported": 0, "retried": 0,
                      "abandoned": 0}
        # attempts between their "requested" bump and terminal outcome —
        # the live books monitor compares the deficit against this, so
        # mid-attempt sweeps never read as violations
        self.inflight_attempts = 0
        self.downscores = 0
        # the books go LIVE: the invariant watchdog sweeps them
        # (weakref-backed; the newest manager owns the name)
        from lighthouse_tpu.common import monitors as _monitors

        _monitors.register_sync_books(self)

    # -- accounting (the LH604 funnels) -------------------------------------

    def _account_batch(self, outcome: str) -> None:
        """One batch attempt lands in exactly one outcome bucket; the
        requested counter is bumped separately per attempt so the books
        invariant is checkable from the metrics alone."""
        if outcome == "requested":
            # inflight BEFORE the requested bump: the watchdog thread
            # sweeping between the two statements must never observe
            # deficit > inflight (a false books_violation trip)
            self.inflight_attempts += 1
            self.books[outcome] += 1
            REGISTRY.counter(
                "sync_batch_requests_total",
                "range-sync batch download attempts issued").inc()
        else:
            # outcome lands BEFORE inflight releases (the mirror-image
            # ordering constraint: deficit shrinks first, window after)
            self.books[outcome] += 1
            self.inflight_attempts = max(0, self.inflight_attempts - 1)
            REGISTRY.counter(
                "sync_batches_total",
                "range-sync batch attempts by terminal outcome",
            ).labels(outcome=outcome).inc()

    def _record_chain(self, outcome: str) -> None:
        REGISTRY.counter(
            "sync_chains_total",
            "syncing-chain attempts by outcome",
        ).labels(outcome=outcome).inc()

    def _account_lookup(self, outcome: str) -> None:
        REGISTRY.counter(
            "sync_lookups_total",
            "parent-lookup chases by outcome",
        ).labels(outcome=outcome).inc()

    def _downscore(self, peer: str, level: str, reason: str) -> None:
        """EVERY penalty the sync plane issues goes through here:
        reason-labeled in sync_downscores_total and tallied in the
        local ledger (zero-unaccounted-downscores discipline)."""
        with self._ledger_lock:
            self.downscores += 1
        REGISTRY.counter(
            "sync_downscores_total",
            "peer downscores issued by the sync plane, by reason",
        ).labels(reason=reason).inc()
        from lighthouse_tpu.common import flight_recorder as flight

        flight.emit("downscore", plane="sync", peer=peer, level=level,
                    reason=reason)
        self.peers.report(peer, level)

    def books_balanced(self) -> bool:
        b = self.books
        return b["requested"] == (b["imported"] + b["retried"]
                                  + b["abandoned"])

    # -- status -------------------------------------------------------------

    def status_handshake(self, peer: str) -> PeerStatus | None:
        try:
            chunks = self.rpc.request(
                peer, P_STATUS, self.router.local_status().serialize())
        except RpcError:
            self._downscore(peer, "mid", "rpc_error")
            return None
        if not chunks:
            return None
        try:
            remote = StatusMessage.deserialize(chunks[0])
        except Exception as e:
            record_swallowed("sync.status_decode", e)
            self._downscore(peer, "high", "decode")
            return None
        st = PeerStatus(
            head_slot=int(remote.head_slot),
            head_root=bytes(remote.head_root),
            finalized_epoch=int(remote.finalized_epoch),
        )
        with self._ledger_lock:
            self.statuses[peer] = st
        self.peers.report(peer, "useful_response")  # register as connected
        return st

    # -- range sync ----------------------------------------------------------

    def _download_batch(self, peer: str, start: int,
                        count: int) -> list | None:
        """One BlocksByRange round trip, VALIDATED against the request
        before anything executes (range_sync/batch.rs received-block
        checks): chunk count bounded by the request, every block inside
        [start, start+count), slots strictly ascending, and each block's
        parent_root chaining to its batch predecessor.  Violations
        downscore the peer hard and fail the attempt."""
        req = BlocksByRangeRequest(start_slot=start, count=count, step=1)
        try:
            chunks = self.rpc.request(peer, P_BLOCKS_BY_RANGE,
                                      req.serialize())
        except RpcError:
            self._downscore(peer, "mid", "rpc_error")
            return None
        if len(chunks) > count:
            # a peer may serve FEWER blocks (skipped slots), never more
            self._downscore(peer, "high", "overserve")
            return None
        blocks = []
        prev_slot = -1
        prev_root = None
        for raw in chunks:
            block = self._decode_block(raw)
            if block is None:
                self._downscore(peer, "high", "decode")
                return None
            slot = int(block.message.slot)
            if not (start <= slot < start + count) or slot <= prev_slot:
                self._downscore(peer, "high", "window")
                return None
            if prev_root is not None and \
                    bytes(block.message.parent_root) != prev_root:
                self._downscore(peer, "high", "broken_linkage")
                return None
            prev_slot = slot
            prev_root = block.message.hash_tree_root()
            blocks.append(block)
        return blocks

    def _resolve_pending(self, pool: list[str], pending: list,
                         exclude: str) -> int:
        """A served block failed to link across provisional empty
        windows: re-request each window from DIFFERENT peers.  Blocks
        recovered there prove the original server withheld history —
        it is downscored hard and the blocks are imported.  Returns the
        number of recovered blocks imported."""
        recovered = 0
        for window in list(pending):
            wstart, wcount, wpeer = window
            for cand in pool:
                if cand == wpeer or cand == exclude:
                    continue
                self._account_batch("requested")
                blocks = self._download_batch(cand, wstart, wcount)
                if blocks is None:
                    self._account_batch("retried")
                    continue
                if not blocks:
                    # this candidate agrees the window is empty; ask the
                    # next one — unanimity leaves the window provisional
                    self._account_batch("imported")
                    continue
                if bytes(blocks[0].message.parent_root) \
                        not in self.chain.fork_choice.proto:
                    self._downscore(cand, "high", "broken_linkage")
                    self._account_batch("retried")
                    continue
                n, ok = self._process_blocks(cand, blocks)
                recovered += n
                if ok:
                    self._account_batch("imported")
                    self._downscore(wpeer, "high", "withheld_window")
                    pending.remove(window)
                    break
                self._account_batch("retried")
        return recovered

    def _process_blocks(self, peer: str, blocks: list) -> tuple[int, bool]:
        """Execute validated blocks; (imported, ok).  A rejection
        attributes blame to the serving peer; unexpected processing
        faults are accounted, never silently swallowed."""
        from lighthouse_tpu.chain.block_verification import BlockError

        imported = 0
        for block in blocks:
            try:
                if self.chain.process_block(block,
                                            source="rpc") is not None:
                    imported += 1
            except BlockError as e:
                if str(e) == "duplicate":
                    continue      # earlier attempt imported a prefix
                self._downscore(peer, "high", "invalid_block")
                return imported, False
            except Exception as e:
                record_swallowed("sync.process_block", e)
                self._downscore(peer, "mid", "process_error")
                return imported, False
        return imported, True

    def _execute_batch(self, pool: list[str], start: int, count: int,
                       batch_no: int,
                       pending: list) -> tuple[int, str, str | None]:
        """Run one batch through the retry machine: (imported, outcome,
        serving_peer) with outcome in {"ok", "empty", "failed"}.

        A failed download or a processing rejection moves the batch to
        the next pool peer (the offender already downscored); after
        LHTPU_SYNC_BATCH_ATTEMPTS the whole chain attempt is abandoned —
        exactly the pressure shape of range_sync's batch state machine.
        ``batch_no`` rotates the starting peer so consecutive batches
        spread over the pool instead of hammering its head."""
        attempts = _batch_attempts()
        failed: set[str] = set()
        recovered = 0   # blocks imported while disproving empty windows
        for attempt in range(attempts):
            cands = [p for p in pool if p not in failed] or list(pool)
            peer = cands[(batch_no + attempt) % len(cands)]
            last = attempt == attempts - 1
            self._account_batch("requested")
            t0 = time.perf_counter()
            with span("sync.batch", slot=start, peer=peer, count=count):
                blocks = self._download_batch(peer, start, count)
                if blocks is None:
                    add_attrs(outcome="download_failed")
                    failed.add(peer)
                    self._account_batch("abandoned" if last else "retried")
                    continue
                if not blocks:
                    # provisional: confirmed only when later blocks link
                    # across this window (or disproven and re-requested)
                    add_attrs(outcome="empty")
                    self._account_batch("imported")
                    self.peers.report(peer, "useful_response")
                    self._observe_batch(time.perf_counter() - t0)
                    return 0, "empty", peer
                if bytes(blocks[0].message.parent_root) \
                        not in self.chain.fork_choice.proto:
                    # does not attach to known history: an earlier empty
                    # window may have withheld the connecting blocks, our
                    # own head may sit on a side branch (chase the
                    # ancestors by root — the block_lookups fallback), or
                    # THIS peer serves a fabricated chain
                    if pending:
                        recovered += self._resolve_pending(
                            pool, pending, exclude=peer)
                    if bytes(blocks[0].message.parent_root) \
                            not in self.chain.fork_choice.proto:
                        self.lookup_unknown_parent(peer, blocks[0])
                    if bytes(blocks[0].message.parent_root) \
                            in self.chain.fork_choice.proto:
                        pass     # recovered the missing history; import
                    else:
                        add_attrs(outcome="unlinked")
                        self._downscore(peer, "high", "broken_linkage")
                        failed.add(peer)
                        self._account_batch(
                            "abandoned" if last else "retried")
                        continue
                imported, ok = self._process_blocks(peer, blocks)
                self._observe_batch(time.perf_counter() - t0)
                if ok:
                    add_attrs(outcome="imported", imported=imported)
                    self._account_batch("imported")
                    self.peers.report(peer, "useful_response")
                    # real blocks linked through: earlier provisional
                    # windows are confirmed honest skips
                    pending.clear()
                    return imported + recovered, "ok", peer
                add_attrs(outcome="process_failed")
                failed.add(peer)
                self._account_batch("abandoned" if last else "retried")
        return recovered, "failed", None

    def _observe_batch(self, seconds: float) -> None:
        REGISTRY.histogram(
            "sync_batch_seconds",
            "range-sync batch wall time (download+validate+process)",
        ).observe(seconds)

    def _sync_chain(self, pool: list[str], target_slot: int) -> int:
        """Drive one syncing chain batch-by-batch; returns blocks
        imported.  Sets ``_last_chain_ok`` for the caller's per-target
        accounting: False means the chain was abandoned (wedged, lying
        pool, or unreachable target)."""
        imported = 0
        self._last_chain_ok = True
        target_root = self._target_root
        bsize = _batch_size()
        stall_s = envreg.get_float("LHTPU_SYNC_STALL_S", 20.0) or 0.0
        slot = int(self.chain.head_state.slot) + 1
        # provisional empty windows awaiting linkage confirmation
        pending: list[tuple[int, int, str]] = []
        served: list[str] = []   # peers whose batches we accepted
        last_progress = time.monotonic()
        batch_no = 0
        while slot <= target_slot:
            count = min(bsize, target_slot - slot + 1)
            n, outcome, peer = self._execute_batch(pool, slot, count,
                                                   batch_no, pending)
            batch_no += 1
            if outcome == "failed":
                imported += n   # blocks recovered from disproven windows
                self._last_chain_ok = False
                break
            if outcome == "empty":
                pending.append((slot, count, peer))
                if len(pending) > MAX_PENDING_WINDOWS:
                    # nothing but withheld windows toward an advertised
                    # head: the pool is lying (or the head equivocated)
                    for _, _, wpeer in pending:
                        self._downscore(wpeer, "mid", "withheld_window")
                    self._last_chain_ok = False
                    break
            else:
                imported += n
                if peer is not None and peer not in served:
                    served.append(peer)
                last_progress = time.monotonic()
            slot += count
            if stall_s and time.monotonic() - last_progress > stall_s:
                self._last_chain_ok = False   # wedged: abandon, re-pool
                break
        else:
            # reached the target window; the advertised head must have
            # actually materialized or the chain was a fiction
            if pending:
                for wpeer in dict.fromkeys(p for _, _, p in pending):
                    self._downscore(wpeer, "mid", "withheld_window")
                self._last_chain_ok = False
            if target_root is not None and not pending and \
                    target_root not in self.chain.fork_choice.proto:
                # every batch "succeeded" yet the advertised head never
                # materialized: the pool served a consistent but
                # NON-CANONICAL branch (or a fiction).  Blame the peers
                # whose batches we accepted — a wrong-chain server looks
                # honest batch-by-batch, only the end state convicts it.
                for wpeer in served:
                    self._downscore(wpeer, "mid", "wrong_chain")
                self._last_chain_ok = False
        return imported

    def sync_to_peer(self, peer: str) -> int:
        """Range-sync toward `peer`'s head; returns blocks imported."""
        status = self.statuses.get(peer) or self.status_handshake(peer)
        if status is None:
            return 0
        self._target_root = bytes(status.head_root)
        try:
            n = self._sync_chain([peer], status.head_slot)
        finally:
            self._target_root = None
        self._record_chain("completed" if self._last_chain_ok
                           else "abandoned")
        return n

    def sync(self) -> int:
        """Group peers ahead of us by advertised target and range-sync
        the best-supported chain (chain_collection.rs: one syncing chain
        per target, peers pooled — never duplicate batch work for peers
        that advertise the same head).  An abandoned chain falls through
        to the next-best target with its peers re-pooled; targets that
        keep wedging are skipped after LHTPU_SYNC_CHAIN_ATTEMPTS."""
        local = int(self.chain.head_state.slot)
        chains: dict[tuple[bytes, int], list[str]] = {}
        for peer in self.peers.good_peers():
            st = self.statuses.get(peer) or self.status_handshake(peer)
            if st is not None and st.head_slot > local:
                chains.setdefault(
                    (st.head_root, st.head_slot), []).append(peer)
        if not chains:
            return 0
        budget = max(1, envreg.get_int("LHTPU_SYNC_CHAIN_ATTEMPTS", 3) or 3)
        total = 0
        # most-supported target first; ties to the higher head
        for key, pool in sorted(
                chains.items(),
                key=lambda kv: (len(kv[1]), kv[0][1]), reverse=True):
            attempts = self._chain_attempts.get(key, 0)
            if attempts >= budget:
                continue          # exhausted target (already accounted)
            # re-pool on retry: rotate the pool so a prior attempt's
            # wrong-chain/wedged server is not first in line again
            k = attempts % len(pool)
            pool = pool[k:] + pool[:k]
            self._target_root = bytes(key[0])
            try:
                total += self._sync_chain(pool, key[1])
            finally:
                self._target_root = None
            if self._last_chain_ok:
                self._chain_attempts.pop(key, None)
                self._record_chain("completed")
                break
            self._chain_attempts[key] = attempts + 1
            while len(self._chain_attempts) > TARGET_CACHE:
                self._chain_attempts.popitem(last=False)
            self._record_chain("abandoned")
        return total

    # -- lookup sync ----------------------------------------------------------

    def lookup_unknown_parent(self, peer: str, block) -> int:
        """Chase missing ancestors by root, then import the chain segment
        (block_lookups/).  Single-flight per block root — concurrent
        unknown-parent triggers for the same block (gossip + rpc races)
        must not spawn duplicate chases — and terminally failed chases
        are cached so a spammy peer cannot re-trigger the same dead
        walk."""
        root = bytes(block.message.hash_tree_root())
        parent = bytes(block.message.parent_root)
        if root in self._inflight_lookups or \
                parent in self._failed_lookups:
            return 0
        self._inflight_lookups.add(root)
        try:
            return self._lookup_chase(peer, block, parent)
        finally:
            self._inflight_lookups.discard(root)

    def _mark_failed_lookup(self, parent: bytes):
        self._failed_lookups[parent] = None
        while len(self._failed_lookups) > FAILED_LOOKUP_CACHE:
            self._failed_lookups.popitem(last=False)

    def _lookup_chase(self, peer: str, block, parent: bytes) -> int:
        from lighthouse_tpu.chain.block_verification import BlockError

        chain_segment = [block]
        for _ in range(MAX_LOOKUP_DEPTH):
            if parent in self.chain.fork_choice.proto:
                break
            if parent in self._failed_lookups:
                # a previous chase already proved this ancestor
                # unreachable: don't re-walk the live prefix to it
                self._account_lookup("cached_dead_end")
                return 0
            try:
                chunks = self.rpc.request(peer, P_BLOCKS_BY_ROOT, parent)
            except RpcError:
                self._downscore(peer, "mid", "rpc_error")
                self._account_lookup("failed")
                return 0
            if not chunks:
                self._mark_failed_lookup(parent)
                self._account_lookup("dead_end")
                return 0
            got = self._decode_block(chunks[0])
            if got is None or got.message.hash_tree_root() != parent:
                self._downscore(peer, "high", "lied_root")
                self._account_lookup("failed")
                return 0
            chain_segment.append(got)
            parent = bytes(got.message.parent_root)
        else:
            # depth budget exhausted — NOT evidence the ancestor is
            # unreachable (a fresh chase from a closer descendant could
            # succeed), so nothing is cached as failed
            self._account_lookup("depth_exhausted")
            return 0
        imported = 0
        for blk in reversed(chain_segment):
            try:
                if self.chain.process_block(blk, source="rpc") is not None:
                    imported += 1
            except BlockError as e:
                if str(e) == "duplicate":
                    continue      # racing gossip import won; keep walking
                self._downscore(peer, "mid", "invalid_block")
                self._account_lookup("failed")
                return imported
            except Exception as e:
                record_swallowed("sync.lookup_import", e)
                self._account_lookup("failed")
                return imported
        self._account_lookup("imported" if imported else "noop")
        return imported

    def _decode_block(self, raw: bytes):
        try:
            return self.chain.t.decode_signed_block(raw)
        except Exception as e:
            # malformed bytes from a hostile peer: the CALLER downscores
            # + accounts the failed attempt through the reason funnel
            record_swallowed("sync.decode_block", e)
            return None  # lhlint: allow(LH604)
