"""Sync manager: range sync + parent-lookup sync.

Rebuild of /root/reference/beacon_node/network/src/sync/ (manager.rs,
range_sync/chain.rs + chain_collection.rs, block_lookups/): STATUS
handshakes pick peers ahead of us, peers advertising the SAME target
head merge into one syncing chain (concurrent-chain dedup), and each
BlocksByRange batch runs a retry state machine — a failed or lying
download moves to another pool peer with the offender downscored, up to
MAX_BATCH_ATTEMPTS (range_sync/batch.rs's
MAX_BATCH_DOWNLOAD_ATTEMPTS).  Batch contents are validated against the
request (slot window, ascending order, intra-batch parent linkage)
before a single block is executed, so a lying peer costs one round
trip, not a poisoned import.  Unknown-parent blocks trigger a
backwards lookup chase capped in depth, single-flight per root with a
failed-chase cache (block_lookups dedup hardening).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOCKS_BY_RANGE,
    P_BLOCKS_BY_ROOT,
    P_STATUS,
    RpcError,
    StatusMessage,
)

BATCH_SIZE = 32
MAX_BATCH_ATTEMPTS = 5        # download+process tries across the pool
MAX_LOOKUP_DEPTH = 16
FAILED_LOOKUP_CACHE = 512


@dataclass
class PeerStatus:
    head_slot: int
    head_root: bytes
    finalized_epoch: int


class SyncManager:
    def __init__(self, chain, rpc_ep, router, peer_manager):
        self.chain = chain
        self.rpc = rpc_ep
        self.router = router
        self.peers = peer_manager
        self.statuses: dict[str, PeerStatus] = {}
        self._inflight_lookups: set[bytes] = set()
        self._failed_lookups: OrderedDict[bytes, None] = OrderedDict()

    # -- status -------------------------------------------------------------

    def status_handshake(self, peer: str) -> PeerStatus | None:
        try:
            chunks = self.rpc.request(
                peer, P_STATUS, self.router.local_status().serialize())
        except RpcError:
            self.peers.report(peer, "mid")
            return None
        if not chunks:
            return None
        remote = StatusMessage.deserialize(chunks[0])
        st = PeerStatus(
            head_slot=int(remote.head_slot),
            head_root=bytes(remote.head_root),
            finalized_epoch=int(remote.finalized_epoch),
        )
        self.statuses[peer] = st
        self.peers.report(peer, "useful_response")  # register as connected
        return st

    # -- range sync ----------------------------------------------------------

    def _download_batch(self, peer: str, start: int,
                        count: int) -> list | None:
        """One BlocksByRange round trip, VALIDATED against the request
        before anything executes (range_sync/batch.rs received-block
        checks): every block inside [start, start+count), slots strictly
        ascending, and each block's parent_root chaining to its batch
        predecessor.  Violations downscore the peer hard and fail the
        attempt."""
        req = BlocksByRangeRequest(start_slot=start, count=count, step=1)
        try:
            chunks = self.rpc.request(peer, P_BLOCKS_BY_RANGE,
                                      req.serialize())
        except RpcError:
            self.peers.report(peer, "mid")
            return None
        blocks = []
        prev_slot = -1
        prev_root = None
        for raw in chunks:
            block = self._decode_block(raw)
            if block is None:
                self.peers.report(peer, "high")
                return None
            slot = int(block.message.slot)
            if not (start <= slot < start + count) or slot <= prev_slot:
                self.peers.report(peer, "high")   # outside window / order
                return None
            if prev_root is not None and \
                    bytes(block.message.parent_root) != prev_root:
                self.peers.report(peer, "high")   # broken intra-batch chain
                return None
            prev_slot = slot
            prev_root = block.message.hash_tree_root()
            blocks.append(block)
        return blocks

    def _execute_batch(self, pool: list[str], start: int,
                       count: int) -> tuple[int, bool]:
        """Run one batch through the retry machine: (imported, ok).

        A failed download or a processing rejection moves the batch to
        the next pool peer (the offender already downscored); after
        MAX_BATCH_ATTEMPTS the whole chain attempt is abandoned —
        exactly the pressure shape of range_sync's batch state
        machine."""
        from lighthouse_tpu.chain.block_verification import BlockError

        failed: set[str] = set()
        for attempt in range(MAX_BATCH_ATTEMPTS):
            cands = [p for p in pool if p not in failed] or list(pool)
            peer = cands[attempt % len(cands)]
            blocks = self._download_batch(peer, start, count)
            if blocks is None:
                failed.add(peer)
                continue
            imported = 0
            ok = True
            for block in blocks:
                try:
                    if self.chain.process_block(block,
                                                source="rpc") is not None:
                        imported += 1
                except BlockError as e:
                    if str(e) == "duplicate":
                        continue      # earlier attempt imported a prefix
                    self.peers.report(peer, "high")
                    ok = False
                    break
                except Exception:
                    self.peers.report(peer, "mid")
                    ok = False
                    break
            if ok:
                self.peers.report(peer, "useful_response")
                return imported, True
            failed.add(peer)
        return 0, False

    def _sync_chain(self, pool: list[str], target_slot: int) -> int:
        imported = 0
        slot = int(self.chain.head_state.slot) + 1
        while slot <= target_slot:
            n, ok = self._execute_batch(pool, slot, BATCH_SIZE)
            if not ok:
                break
            imported += n
            slot += BATCH_SIZE
        return imported

    def sync_to_peer(self, peer: str) -> int:
        """Range-sync toward `peer`'s head; returns blocks imported."""
        status = self.statuses.get(peer) or self.status_handshake(peer)
        if status is None:
            return 0
        return self._sync_chain([peer], status.head_slot)

    def sync(self) -> int:
        """Group peers ahead of us by advertised target and range-sync
        the best-supported chain (chain_collection.rs: one syncing chain
        per target, peers pooled — never duplicate batch work for peers
        that advertise the same head)."""
        local = int(self.chain.head_state.slot)
        chains: dict[tuple[bytes, int], list[str]] = {}
        for peer in self.peers.good_peers():
            st = self.statuses.get(peer) or self.status_handshake(peer)
            if st is not None and st.head_slot > local:
                chains.setdefault(
                    (st.head_root, st.head_slot), []).append(peer)
        if not chains:
            return 0
        # most-supported target wins; ties to the higher head
        (_, target_slot), pool = max(
            chains.items(), key=lambda kv: (len(kv[1]), kv[0][1]))
        return self._sync_chain(pool, target_slot)

    # -- lookup sync ----------------------------------------------------------

    def lookup_unknown_parent(self, peer: str, block) -> int:
        """Chase missing ancestors by root, then import the chain segment
        (block_lookups/).  Single-flight per block root — concurrent
        unknown-parent triggers for the same block (gossip + rpc races)
        must not spawn duplicate chases — and terminally failed chases
        are cached so a spammy peer cannot re-trigger the same dead
        walk."""
        root = bytes(block.message.hash_tree_root())
        parent = bytes(block.message.parent_root)
        if root in self._inflight_lookups or \
                parent in self._failed_lookups:
            return 0
        self._inflight_lookups.add(root)
        try:
            return self._lookup_chase(peer, block, parent)
        finally:
            self._inflight_lookups.discard(root)

    def _mark_failed_lookup(self, parent: bytes):
        self._failed_lookups[parent] = None
        while len(self._failed_lookups) > FAILED_LOOKUP_CACHE:
            self._failed_lookups.popitem(last=False)

    def _lookup_chase(self, peer: str, block, parent: bytes) -> int:
        chain_segment = [block]
        for _ in range(MAX_LOOKUP_DEPTH):
            if parent in self.chain.fork_choice.proto:
                break
            if parent in self._failed_lookups:
                # a previous chase already proved this ancestor
                # unreachable: don't re-walk the live prefix to it
                return 0
            try:
                chunks = self.rpc.request(peer, P_BLOCKS_BY_ROOT, parent)
            except RpcError:
                self.peers.report(peer, "mid")
                return 0
            if not chunks:
                self._mark_failed_lookup(parent)
                return 0
            got = self._decode_block(chunks[0])
            if got is None or got.message.hash_tree_root() != parent:
                self.peers.report(peer, "high")   # lied about the root
                return 0
            chain_segment.append(got)
            parent = bytes(got.message.parent_root)
        else:
            # depth budget exhausted — NOT evidence the ancestor is
            # unreachable (a fresh chase from a closer descendant could
            # succeed), so nothing is cached as failed
            return 0
        imported = 0
        for blk in reversed(chain_segment):
            try:
                if self.chain.process_block(blk, source="rpc") is not None:
                    imported += 1
            except Exception:
                break
        return imported

    def _decode_block(self, raw: bytes):
        return self.chain.t.decode_signed_block(raw)
