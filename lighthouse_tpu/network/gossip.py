"""Gossip pub/sub message layer.

Rebuild of the reference's gossipsub stack
(/root/reference/beacon_node/lighthouse_network/src/service/mod.rs:112-113
and the vendored gossipsub fork) at the altitude this framework needs: a
`GossipHub` is the in-process swarm fabric — real SSZ bytes move between
endpoints, with per-topic subscription, a seen-message dedup cache, and
per-peer delivery scoring hooks.  `GossipEndpoint` is one node's handle
(the reference `Network` wrapper).  Transport is synchronous in-process
delivery; the seam (publish/subscribe over topic strings + bytes) is
exactly what a socket transport would implement.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field
from typing import Callable


def message_id(topic: str, data: bytes) -> bytes:
    """Spec-shaped message id: hash over domain + topic + payload."""
    return hashlib.sha256(
        b"\x01\x00\x00\x00" + topic.encode() + data).digest()[:20]


@dataclass
class GossipMessage:
    topic: str
    data: bytes
    source: str  # peer id of the publisher


class _SeenCache:
    def __init__(self, capacity: int = 4096):
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self.capacity = capacity

    def __contains__(self, mid: bytes) -> bool:
        return mid in self._seen

    def observe(self, mid: bytes) -> bool:
        """True if newly seen."""
        if mid in self._seen:
            return False
        self._seen[mid] = None
        while len(self._seen) > self.capacity:
            self._seen.popitem(last=False)
        return True


class GossipEndpoint:
    """One node's gossip handle: subscriptions + handlers + dedup."""

    def __init__(self, hub: "GossipHub", peer_id: str):
        self.hub = hub
        self.peer_id = peer_id
        self.handlers: dict[str, Callable[[GossipMessage], None]] = {}
        self.seen = _SeenCache()
        self.on_delivery_result: Callable[[str, str, bool], None] | None = None

    def subscribe(self, topic: str, handler: Callable[[GossipMessage], None]):
        self.handlers[topic] = handler
        self.hub._subscribe(topic, self)

    def unsubscribe(self, topic: str):
        self.handlers.pop(topic, None)
        self.hub._unsubscribe(topic, self)

    def publish(self, topic: str, data: bytes):
        self.hub.route(GossipMessage(topic, data, self.peer_id))

    def _deliver(self, msg: GossipMessage):
        if not self.seen.observe(message_id(msg.topic, msg.data)):
            return
        handler = self.handlers.get(msg.topic)
        if handler is None:
            return
        ok = True
        try:
            handler(msg)
        except Exception:
            ok = False
        if self.on_delivery_result is not None:
            self.on_delivery_result(msg.source, msg.topic, ok)


class GossipHub:
    """The in-process swarm: flood-routes published messages to every
    subscribed endpoint except the publisher."""

    def __init__(self):
        self._topics: dict[str, list[GossipEndpoint]] = defaultdict(list)
        self._endpoints: dict[str, GossipEndpoint] = {}
        self._partitions: dict[str, set[str]] = {}

    def join(self, peer_id: str) -> GossipEndpoint:
        ep = GossipEndpoint(self, peer_id)
        self._endpoints[peer_id] = ep
        return ep

    def leave(self, peer_id: str):
        ep = self._endpoints.pop(peer_id, None)
        if ep:
            for subs in self._topics.values():
                if ep in subs:
                    subs.remove(ep)

    def disconnect(self, a: str, b: str):
        """Partition two peers (fault injection for tests)."""
        self._partitions.setdefault(a, set()).add(b)
        self._partitions.setdefault(b, set()).add(a)

    def reconnect(self, a: str, b: str):
        self._partitions.get(a, set()).discard(b)
        self._partitions.get(b, set()).discard(a)

    def _subscribe(self, topic: str, ep: GossipEndpoint):
        if ep not in self._topics[topic]:
            self._topics[topic].append(ep)

    def _unsubscribe(self, topic: str, ep: GossipEndpoint):
        if ep in self._topics[topic]:
            self._topics[topic].remove(ep)

    def route(self, msg: GossipMessage):
        blocked = self._partitions.get(msg.source, set())
        for ep in list(self._topics.get(msg.topic, ())):
            if ep.peer_id == msg.source or ep.peer_id in blocked:
                continue
            ep._deliver(msg)

    @property
    def peers(self) -> list[str]:
        return list(self._endpoints)
