"""Gossip pub/sub message layer.

Rebuild of the reference's gossipsub stack
(/root/reference/beacon_node/lighthouse_network/src/service/mod.rs:112-113
and the vendored gossipsub fork) at the altitude this framework needs: a
`GossipHub` is the in-process swarm fabric — real SSZ bytes move between
endpoints, with per-topic subscription, a seen-message dedup cache, and
per-peer delivery scoring hooks.  `GossipEndpoint` is one node's handle
(the reference `Network` wrapper).  Transport is synchronous in-process
delivery; the seam (publish/subscribe over topic strings + bytes) is
exactly what a socket transport would implement.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field
from typing import Callable

from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed


def message_id(topic: str, data: bytes) -> bytes:
    """Spec-shaped message id: hash over domain + topic + payload."""
    return hashlib.sha256(
        b"\x01\x00\x00\x00" + topic.encode() + data).digest()[:20]


@dataclass
class GossipMessage:
    topic: str
    data: bytes
    source: str  # peer id of the publisher


class _SeenCache:
    """Message-id dedup ring — the FIRST line of duplicate-flood defense:
    a byte-identical replay storm dies here, before decode, before the
    processor queues, before BLS.  ``hits`` counts suppressed replays
    (the firehose dup drill reads it); capacity must cover at least one
    slot's mainnet-width traffic or a storm wider than the ring slips
    duplicates through to the (accounted) pre-BLS dedup stage."""

    def __init__(self, capacity: int = 65536):
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self.capacity = capacity
        self.hits = 0

    def __contains__(self, mid: bytes) -> bool:
        return mid in self._seen

    def observe(self, mid: bytes) -> bool:
        """True if newly seen."""
        if mid in self._seen:
            self.hits += 1
            return False
        self._seen[mid] = None
        while len(self._seen) > self.capacity:
            self._seen.popitem(last=False)
        return True


class GossipEndpoint:
    """One node's gossip handle: subscriptions + handlers + dedup."""

    def __init__(self, hub: "GossipHub", peer_id: str,
                 seen_capacity: int = 65536):
        self.hub = hub
        self.peer_id = peer_id
        self.handlers: dict[str, Callable[[GossipMessage], None]] = {}
        self.seen = _SeenCache(seen_capacity)
        self.on_delivery_result: Callable[[str, str, bool], None] | None = None

    def subscribe(self, topic: str, handler: Callable[[GossipMessage], None]):
        self.handlers[topic] = handler
        self.hub._subscribe(topic, self)

    def unsubscribe(self, topic: str):
        self.handlers.pop(topic, None)
        self.hub._unsubscribe(topic, self)

    def publish(self, topic: str, data: bytes):
        self.hub.route(GossipMessage(topic, data, self.peer_id))

    def _deliver(self, msg: GossipMessage):
        if not self.seen.observe(message_id(msg.topic, msg.data)):
            return
        handler = self.handlers.get(msg.topic)
        if handler is None:
            return
        ok = True
        try:
            handler(msg)
        except Exception as e:
            # delivery failures downscore the SENDER via the delivery-
            # result callback below; the handler error itself is counted
            from lighthouse_tpu.common.metrics import record_swallowed

            record_swallowed("gossip.deliver", e)
            ok = False
        if self.on_delivery_result is not None:
            self.on_delivery_result(msg.source, msg.topic, ok)


_FANIN_CHILDREN: dict[str, object] = {}


def record_fanin(outcome: str) -> None:
    """Count one attestation fan-in delivery outcome
    (accepted/shed/decode_error) — the single registration point of the
    gossip_fanin_total family, shared by :class:`SubnetFanIn` and the
    router's processor path so both fan-in seams keep one ledger."""
    child = _FANIN_CHILDREN.get(outcome)
    if child is None:
        child = _FANIN_CHILDREN[outcome] = REGISTRY.counter(
            "gossip_fanin_total",
            "per-subnet attestation deliveries by outcome "
            "(accepted/shed/decode_error)").labels(outcome=outcome)
    child.inc()


class SubnetFanIn:
    """Per-subnet attestation fan-in: ``beacon_attestation_{n}`` topics
    funneled into one submit callable (the beacon processor's admission
    controller), with per-subnet delivery accounting.

    Scope: the lightweight fan-in for drills and embeddings that run a
    processor WITHOUT the full Router (the firehose harness, in-process
    fabrics).  The production path is Router._on_attestation with
    ``processor=`` — it needs per-message peer identity for scoring,
    which this seam deliberately does not carry.  Both paths keep ONE
    ledger through :func:`record_fanin`: gossip deliveries do NOT call
    the verification pipeline directly — they go through ``submit``
    (which may shed under the degradation ladder or a full queue) and
    the outcome of every delivery is counted in
    ``gossip_fanin_total{outcome}``.  A decode failure is counted too: a
    hostile peer's garbage dies here at zero BLS cost.
    """

    def __init__(self, endpoint: "GossipEndpoint",
                 submit: Callable[[int, object], object],
                 decode: Callable[[bytes], object],
                 subnet_count: int = 64,
                 topic_fn: Callable[[int], str] | None = None):
        self.endpoint = endpoint
        self.submit = submit
        self.decode = decode
        self.subnet_count = subnet_count
        self.topic_fn = topic_fn or (lambda n: f"beacon_attestation_{n}")
        self.delivered: dict[int, int] = {}
        self.outcomes: dict[str, int] = {}
        self._subscribed: set[int] = set()

    def _count(self, outcome: str) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        record_fanin(outcome)

    def subscribe(self, subnets=None) -> None:
        for subnet in (range(self.subnet_count) if subnets is None
                       else subnets):
            if subnet in self._subscribed:
                continue
            self._subscribed.add(subnet)
            self.endpoint.subscribe(
                self.topic_fn(subnet),
                lambda msg, subnet=subnet: self._on_message(subnet, msg))

    def unsubscribe(self, subnets) -> None:
        for subnet in subnets:
            if subnet in self._subscribed:
                self._subscribed.discard(subnet)
                self.endpoint.unsubscribe(self.topic_fn(subnet))

    def _on_message(self, subnet: int, msg: GossipMessage) -> None:
        self.delivered[subnet] = self.delivered.get(subnet, 0) + 1
        try:
            payload = self.decode(msg.data)
        except Exception as e:
            self._count("decode_error")
            record_swallowed("gossip.fanin_decode", e)
            return
        self._count("accepted" if self.submit(subnet, payload) else "shed")


class GossipHub:
    """The in-process swarm: flood-routes published messages to every
    subscribed endpoint except the publisher."""

    def __init__(self):
        from lighthouse_tpu.network.partition import PartitionSet

        self._topics: dict[str, list[GossipEndpoint]] = defaultdict(list)
        self._endpoints: dict[str, GossipEndpoint] = {}
        self._partitions = PartitionSet()

    def join(self, peer_id: str) -> GossipEndpoint:
        ep = GossipEndpoint(self, peer_id)
        self._endpoints[peer_id] = ep
        return ep

    def leave(self, peer_id: str):
        ep = self._endpoints.pop(peer_id, None)
        if ep:
            for subs in self._topics.values():
                if ep in subs:
                    subs.remove(ep)

    def disconnect(self, a: str, b: str):
        """Partition two peers (fault injection for tests)."""
        self._partitions.disconnect(a, b)

    def reconnect(self, a: str, b: str):
        self._partitions.reconnect(a, b)

    def _subscribe(self, topic: str, ep: GossipEndpoint):
        if ep not in self._topics[topic]:
            self._topics[topic].append(ep)

    def _unsubscribe(self, topic: str, ep: GossipEndpoint):
        if ep in self._topics[topic]:
            self._topics[topic].remove(ep)

    def route(self, msg: GossipMessage):
        blocked = self._partitions.blocked_for(msg.source)
        for ep in list(self._topics.get(msg.topic, ())):
            if ep.peer_id == msg.source or ep.peer_id in blocked:
                continue
            ep._deliver(msg)

    @property
    def peers(self) -> list[str]:
        return list(self._endpoints)
