"""Per-node message router: gossip decodables + Req/Resp serving.

Rebuild of /root/reference/beacon_node/network/src/router.rs:272-434 and
network_beacon_processor/{gossip_methods,rpc_methods}.rs: decodes topic
payloads, dispatches them into the chain's verification pipelines (via the
beacon_processor when attached, directly otherwise), and serves the
Req/Resp protocols from the store.
"""

from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING

from lighthouse_tpu.network import rpc as rpc_mod
from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOBS_BY_RANGE,
    P_BLOBS_BY_ROOT,
    P_BLOCKS_BY_RANGE,
    P_BLOCKS_BY_ROOT,
    P_LC_BOOTSTRAP,
    P_LC_FINALITY,
    P_LC_OPTIMISTIC,
    P_STATUS,
    StatusMessage,
)

if TYPE_CHECKING:
    from lighthouse_tpu.chain.beacon_chain import BeaconChain

MAX_REQUEST_BLOCKS = 1024


def _compute_digest(fork_version: bytes, genesis_validators_root: bytes
                    ) -> bytes:
    """THE fork-digest formula (spec compute_fork_digest) — single
    definition shared by the current-head and all-scheduled paths so
    subscribe/publish topics can never diverge."""
    return hashlib.sha256(
        fork_version + genesis_validators_root).digest()[:4]


def fork_digest(chain) -> bytes:
    """4-byte fork digest of the chain's CURRENT head fork."""
    return _compute_digest(
        bytes(chain.head_state.fork.current_version),
        bytes(chain.head_state.genesis_validators_root))


def _topic_str(digest: bytes, kind: str) -> str:
    """THE topic encoding — shared by publish (current digest) and
    subscribe (all scheduled digests)."""
    return f"/eth2/{digest.hex()}/{kind}/ssz"


def topic(chain, kind: str) -> str:
    return _topic_str(fork_digest(chain), kind)


def scheduled_fork_digests(chain) -> list[bytes]:
    """Digests of every fork actually scheduled in the spec.  Gossip
    topics embed the digest, so a node must listen on the NEXT fork's
    topics around the boundary or it goes deaf the moment a peer's head
    crosses first (the reference subscribes new-fork topics ahead of the
    fork, network/src/service.rs fork watcher)."""
    from lighthouse_tpu.types.spec import FAR_FUTURE_EPOCH, FORKS

    spec = chain.spec
    root = bytes(chain.head_state.genesis_validators_root)
    return [_compute_digest(spec.fork_version(f), root)
            for f in FORKS if spec.fork_epoch(f) != FAR_FUTURE_EPOCH]


class Router:
    """Wires a chain + store to gossip topics and RPC protocols."""

    def __init__(self, chain: "BeaconChain", gossip_ep, rpc_ep, peer_manager,
                 on_unknown_parent=None, subnet_service=None,
                 processor=None):
        self.chain = chain
        self.gossip = gossip_ep
        self.rpc = rpc_ep
        self.peers = peer_manager
        self.on_unknown_parent = on_unknown_parent
        # optional BeaconProcessor: attestation/aggregate gossip rides
        # its admission-controlled batch queues instead of verifying
        # inline per message (mainnet-width fan-in; the ladder may shed
        # under overload and every shed is accounted by the processor)
        self.processor = processor
        # scheduled attestation-subnet subscriptions (subnet_service.py);
        # None = subscribe to all subnets (small test fabrics)
        self.subnet_service = subnet_service
        # fork digests are immutable for the chain's lifetime: compute
        # once, not per subscribe/per-slot subnet update.  The digest in
        # an incoming message's TOPIC names the sender's fork — decode
        # wire payloads by it, not by the local clock (boundary messages
        # arrive from peers whose head crossed first).
        self._fork_digests = scheduled_fork_digests(chain)
        from lighthouse_tpu.types.spec import FAR_FUTURE_EPOCH, FORKS

        root = bytes(chain.head_state.genesis_validators_root)
        self._fork_of_digest = {
            _compute_digest(chain.spec.fork_version(f), root).hex(): f
            for f in FORKS
            if chain.spec.fork_epoch(f) != FAR_FUTURE_EPOCH}
        # wire layouts for the columnar attestation decode (fixed per
        # preset; one per attestation wire format)
        from lighthouse_tpu.ssz import columnar as _col

        self._wire_layouts = {
            False: _col.layout_for(chain.spec.preset, False),
            True: _col.layout_for(chain.spec.preset, True),
        }
        # snapshot the kill switch once: a mid-run env flip must not mix
        # wire-bytes and object payloads inside one processor batch
        # (the batch handler comes from the first event of a sweep)
        self._columnar = _col.enabled()
        self._subscribe_topics()
        self._register_rpc()
        self.gossip.on_delivery_result = self._score_delivery

    # -- gossip -------------------------------------------------------------

    def _subscribe_topics(self):
        """Subscribe every scheduled fork's digest for each kind (the
        reference's fork watcher subscribes next-fork topics ahead of the
        boundary; scheduled forks are known up front here)."""
        c = self.chain

        def sub(kind: str, handler):
            for t in self._topics(kind):
                self.gossip.subscribe(t, handler)

        sub("beacon_block", self._on_block)
        sub("beacon_aggregate_and_proof", self._on_aggregate)
        if self.subnet_service is None:
            for subnet in range(c.spec.attestation_subnet_count):
                sub(f"beacon_attestation_{subnet}", self._on_attestation)
        else:
            self.update_attestation_subnets(c.current_slot())
        for i in range(c.spec.preset.max_blobs_per_block):
            sub(f"blob_sidecar_{i}", self._on_blob)
        sub("voluntary_exit", self._on_voluntary_exit)
        sub("proposer_slashing", self._on_proposer_slashing)
        sub("attester_slashing", self._on_attester_slashing)

    def _topics(self, kind: str) -> list[str]:
        return [_topic_str(d, kind) for d in self._fork_digests]

    def update_attestation_subnets(self, slot: int) -> None:
        """Apply the subnet service's per-slot subscribe/unsubscribe
        deltas (reference subnet_service → gossip topic updates)."""
        if self.subnet_service is None:
            return
        c = self.chain
        to_sub, to_unsub = self.subnet_service.update(slot)
        for subnet in to_sub:
            for t in self._topics(f"beacon_attestation_{subnet}"):
                self.gossip.subscribe(t, self._on_attestation)
        for subnet in to_unsub:
            for t in self._topics(f"beacon_attestation_{subnet}"):
                self.gossip.unsubscribe(t)

    def _topic_fork(self, topic_str: str) -> str:
        """Fork named by the digest embedded in a gossip topic; falls
        back to the local clock's fork for unknown digests."""
        from lighthouse_tpu.types.spec import ChainSpec

        c = self.chain
        try:
            digest_hex = topic_str.split("/")[2]
        except IndexError:
            digest_hex = ""
        fork = self._fork_of_digest.get(digest_hex)
        if fork is None:
            fork = c.spec.fork_at_epoch(
                c.spec.compute_epoch_at_slot(c.current_slot()))
        return fork

    def _topic_electra(self, topic_str: str) -> bool:
        from lighthouse_tpu.types.spec import ChainSpec

        return ChainSpec.fork_at_least(self._topic_fork(topic_str),
                                       "electra")

    def _score_delivery(self, source: str, topic_: str, ok: bool):
        self.peers.report(source, "valid_message" if ok else "low",
                          topic=topic_)

    def _on_block(self, msg):
        c = self.chain
        fork = c.spec.fork_at_epoch(c.spec.compute_epoch_at_slot(
            c.current_slot()))
        block = None
        # the wire block may be from the previous fork near boundaries
        for f in dict.fromkeys((fork, *reversed(c.t.forks))):
            try:
                block = c.t.signed_beacon_block_class(f).deserialize(msg.data)
                break
            except Exception:  # lhlint: allow(LH902) — fork-probe loop:
                continue       # a miss on one fork's class is expected;
                #                total failure is penalized right below
        if block is None:
            self.peers.report(msg.source, "mid")
            return
        from lighthouse_tpu.chain.block_verification import BlockError

        try:
            c.process_block(block)
        except BlockError as e:
            if "unknown_parent" in str(e) and self.on_unknown_parent:
                self.on_unknown_parent(msg.source, block)
            else:
                self.peers.report(msg.source, "mid")
                raise

    # gossip-check reject reasons that earn no peer penalty: expected
    # around slot/fork boundaries and under honest duplication
    _BENIGN_ATT_REJECTS = frozenset({
        "past_slot", "unknown_head_block", "prior_attestation_known",
        "duplicate_in_batch"})

    def _decode_gossip(self, cls, msg, count: bool = False):
        """``count=True`` only on the attestation lanes —
        gossip_fanin_total is the ATTESTATION fan-in ledger, and its
        accepted/shed/decode_error outcomes must add up per delivery."""
        try:
            return cls.deserialize(msg.data)
        except Exception:
            # counted (when in the ledger's scope), PENALIZED via the
            # existing delivery-result path: re-raising marks the
            # delivery failed and _score_delivery downgrades the sender
            if count:
                from lighthouse_tpu.network.gossip import record_fanin

                record_fanin("decode_error")
            raise

    def _verify_attestation_batch(self, pairs):
        """Batch handler for processor-queued gossip attestations: the
        payloads carry (attestation, source) so the batch path keeps the
        SAME peer-downscoring contract as the inline path — a hostile
        peer flooding invalid signatures pays for it even when its
        messages ride a 2048-lane sweep."""
        atts = [a for a, _src in pairs]
        source = {id(a): s for a, s in pairs}
        _verified, rejects = self.chain.verify_attestations_for_gossip(atts)
        for item, reason in rejects:
            if reason not in self._BENIGN_ATT_REJECTS:
                src = source.get(id(item))
                if src is not None:
                    self.peers.report(src, "low", topic="beacon_attestation")

    def _ingest_attestation_blob_batch(self, triples):
        """Columnar batch handler: payloads are RAW WIRE BYTES
        ``(blob, source, electra)`` — one strided parse decodes the
        whole sweep (ssz/columnar) and the chain's columnar lane
        verifies it; rows the lane can't handle exactly ride the scalar
        pipeline inside the same call.  Peer-downscoring contract
        identical to :meth:`_verify_attestation_batch`: non-benign
        rejects (including ``decode_error`` for a blob the scalar
        deserialize refuses) cost the sender."""
        from lighthouse_tpu.chain import columnar_ingest

        result = columnar_ingest.process_wire_batch(
            self.chain, [(blob, electra) for blob, _src, electra in triples])
        for i, reason in result.rejects:
            if reason not in self._BENIGN_ATT_REJECTS and i >= 0:
                src = triples[i][1]
                if src is not None:
                    self.peers.report(src, "low", topic="beacon_attestation")

    def _on_attestation(self, msg):
        c = self.chain
        electra = self._topic_electra(msg.topic)
        att_cls = c.t.AttestationElectra if electra else c.t.Attestation
        from lighthouse_tpu.network.gossip import record_fanin
        from lighthouse_tpu.ssz import columnar

        if self.processor is not None and self._columnar:
            from lighthouse_tpu.processor import WorkEvent, WorkType

            # columnar wire path: NO per-message object materialization
            # — an O(1) structural gate replaces the scalar deserialize
            # (property-pinned equivalent), and raw bytes ride the
            # admission queue into the one-parse-per-batch handler.
            # The fan-in ledger's per-delivery accounting is unchanged:
            # exactly one of decode_error / accepted / shed per message.
            if not columnar.validate_blob(msg.data, self._wire_layouts[
                    electra]):
                # the scalar deserialize stays AUTHORITATIVE for
                # decode_error: genuine garbage raises here (counted +
                # peer-scored via the delivery result, exactly the old
                # point); a validate_blob divergence — impossible per
                # the property suite — yields a decodable blob that
                # rides the batch path's in-batch scalar fallback
                self._decode_gossip(att_cls, msg, count=True)
            verdict = self.processor.submit(WorkEvent(
                WorkType.GOSSIP_ATTESTATION,
                payload=(msg.data, msg.source, electra),
                process_batch=self._ingest_attestation_blob_batch))
            record_fanin("accepted" if verdict else "shed")
            return

        att = self._decode_gossip(att_cls, msg, count=True)
        if self.processor is not None:
            from lighthouse_tpu.processor import WorkEvent, WorkType

            # admission-controlled queue path (columnar kill switch
            # off): the batch sweep feeds the chain's batched pipeline;
            # a SHED verdict is accounted in processor_shed_total and
            # earns the peer no penalty (overload is local, the message
            # may be honest) — invalid signatures are penalized from
            # the batch handler above
            verdict = self.processor.submit(WorkEvent(
                WorkType.GOSSIP_ATTESTATION, payload=(att, msg.source),
                process_batch=self._verify_attestation_batch))
            record_fanin("accepted" if verdict else "shed")
            return
        verified, rejects = c.verify_attestations_for_gossip([att])
        record_fanin("accepted")  # inline path: delivered + verified now
        if rejects:
            reasons = {r for _, r in rejects}
            if not reasons & self._BENIGN_ATT_REJECTS:
                self.peers.report(msg.source, "low")

    def _on_aggregate(self, msg):
        c = self.chain
        agg_cls = (c.t.SignedAggregateAndProofElectra
                   if self._topic_electra(msg.topic)
                   else c.t.SignedAggregateAndProof)
        agg = self._decode_gossip(agg_cls, msg)
        if self.processor is not None:
            from lighthouse_tpu.processor import WorkEvent, WorkType

            # parity with the inline path below: aggregate rejects are
            # not peer-scored (either path)
            self.processor.submit(WorkEvent(
                WorkType.GOSSIP_AGGREGATE, payload=agg,
                process_batch=lambda aggs: c.verify_aggregates_for_gossip(
                    list(aggs))))
            return
        c.verify_aggregates_for_gossip([agg])

    def _on_blob(self, msg):
        c = self.chain
        sidecar = c.t.BlobSidecar.deserialize(msg.data)
        c.process_gossip_blob(sidecar)

    def _on_voluntary_exit(self, msg):
        from lighthouse_tpu.types.containers import SignedVoluntaryExit

        self.chain.op_pool.insert_voluntary_exit(
            SignedVoluntaryExit.deserialize(msg.data))

    def _on_proposer_slashing(self, msg):
        from lighthouse_tpu.types.containers import ProposerSlashing

        self.chain.op_pool.insert_proposer_slashing(
            ProposerSlashing.deserialize(msg.data))

    def _on_attester_slashing(self, msg):
        c = self.chain
        self.chain.op_pool.insert_attester_slashing(
            c.t.AttesterSlashing.deserialize(msg.data))


    def _serve_blobs_by_root(self, src: str, data: bytes) -> list[bytes]:
        """Blob sidecar bundles by block root (reference
        rpc blob_sidecars_by_root protocol)."""
        if len(data) % 32:
            raise rpc_mod.RpcError("malformed roots request")
        out = []
        for i in range(0, min(len(data), 32 * MAX_REQUEST_BLOCKS), 32):
            blobs = self.chain.store.get_blobs(data[i:i + 32])
            if blobs:
                out.append(blobs)
        return out

    def _serve_lc_bootstrap(self, src: str, data: bytes) -> list[bytes]:
        """Light-client bootstrap by block root (reference rpc
        light_client_bootstrap; JSON-encoded over the fabric — the
        transport codec seam)."""
        import json as _json

        if len(data) != 32:
            raise rpc_mod.RpcError("malformed bootstrap request")
        bs = self.chain.light_client.bootstrap(data)
        if bs is None:
            return []
        return [_json.dumps({
            "header": bs.header.to_json(),
            "current_sync_committee_branch": [
                "0x" + b.hex() for b in bs.current_sync_committee_branch],
        }).encode()]

    def _serve_lc_updates_by_range(self, src: str,
                                   data: bytes) -> list[bytes]:
        """Period updates [start, start+count) — one response chunk per
        update (reference light_client_updates_by_range)."""
        import json as _json

        if len(data) != 16:
            raise rpc_mod.RpcError("malformed updates_by_range request")
        start = int.from_bytes(data[:8], "little")
        count = int.from_bytes(data[8:], "little")
        return [_json.dumps(u.to_json()).encode()
                for u in self.chain.light_client.updates_by_range(
                    start, count)]

    def _serve_lc_optimistic(self, src: str, data: bytes) -> list[bytes]:
        import json as _json

        upd = self.chain.light_client.latest_optimistic
        if upd is None:
            return []
        return [_json.dumps(upd.to_json()).encode()]

    def _serve_lc_finality(self, src: str, data: bytes) -> list[bytes]:
        import json as _json

        upd = self.chain.light_client.latest_finality
        if upd is None:
            return []
        return [_json.dumps(upd.to_json()).encode()]

    # -- publishing ---------------------------------------------------------

    def publish_lc_finality_update(self, update):
        """Gossip a fresh finality update to subscribed light clients
        (reference light_client_finality_update topic, gated behind
        --light-client-server)."""
        import json as _json

        self.gossip.publish(
            topic(self.chain, "light_client_finality_update"),
            _json.dumps(update.to_json()).encode())

    def publish_lc_optimistic_update(self, update):
        import json as _json

        self.gossip.publish(
            topic(self.chain, "light_client_optimistic_update"),
            _json.dumps(update.to_json()).encode())

    def publish_block(self, signed_block):
        self.gossip.publish(
            topic(self.chain, "beacon_block"), signed_block.serialize())

    def publish_attestation(self, attestation, subnet: int = 0):
        self.gossip.publish(
            topic(self.chain, f"beacon_attestation_{subnet}"),
            attestation.serialize())

    def publish_blob(self, sidecar):
        self.gossip.publish(
            topic(self.chain, f"blob_sidecar_{int(sidecar.index)}"),
            sidecar.serialize())

    # -- Req/Resp serving ---------------------------------------------------

    def _register_rpc(self):
        self.rpc.register(P_STATUS, self._serve_status)
        self.rpc.register(P_BLOCKS_BY_RANGE, self._serve_blocks_by_range)
        self.rpc.register(P_BLOCKS_BY_ROOT, self._serve_blocks_by_root)
        self.rpc.register(P_BLOBS_BY_RANGE, self._serve_blobs_by_range)
        self.rpc.register(P_BLOBS_BY_ROOT, self._serve_blobs_by_root)
        self.rpc.register(P_LC_BOOTSTRAP, self._serve_lc_bootstrap)
        self.rpc.register(
            rpc_mod.P_LC_UPDATES_BY_RANGE, self._serve_lc_updates_by_range)
        self.rpc.register(P_LC_OPTIMISTIC, self._serve_lc_optimistic)
        self.rpc.register(P_LC_FINALITY, self._serve_lc_finality)

    def local_status(self) -> StatusMessage:
        c = self.chain
        fin = c.finalized_checkpoint()
        return StatusMessage(
            fork_digest=fork_digest(c),
            finalized_root=fin.root,
            finalized_epoch=fin.epoch,
            head_root=c.head_root,
            head_slot=int(c.head_state.slot),
        )

    def _serve_status(self, src: str, data: bytes) -> list[bytes]:
        StatusMessage.deserialize(data)  # validate
        return [self.local_status().serialize()]

    def _serve_blocks_by_range(self, src: str, data: bytes) -> list[bytes]:
        req = BlocksByRangeRequest.deserialize(data)
        count = min(int(req.count), MAX_REQUEST_BLOCKS)
        out = []
        c = self.chain
        for slot in range(int(req.start_slot), int(req.start_slot) + count):
            root = c.block_root_at_slot(slot)
            if root is None:
                continue
            blk = c.store.get_block(root)
            if blk is not None and int(blk.message.slot) == slot:
                out.append(blk.serialize())
        return out

    def _serve_blocks_by_root(self, src: str, data: bytes) -> list[bytes]:
        if len(data) % 32:
            raise rpc_mod.RpcError("malformed roots request")
        out = []
        for i in range(0, min(len(data), 32 * MAX_REQUEST_BLOCKS), 32):
            blk = self.chain.store.get_block(data[i:i + 32])
            if blk is not None:
                out.append(blk.serialize())
        return out

    def _serve_blobs_by_range(self, src: str, data: bytes) -> list[bytes]:
        req = BlocksByRangeRequest.deserialize(data)
        count = min(int(req.count), MAX_REQUEST_BLOCKS)
        out = []
        c = self.chain
        for slot in range(int(req.start_slot), int(req.start_slot) + count):
            root = c.block_root_at_slot(slot)
            if root is None:
                continue
            blobs = c.store.get_blobs(root)
            if blobs:
                out.append(blobs)
        return out
