"""Backfill sync: reverse-fill history below a checkpoint anchor.

Rebuild of /root/reference/beacon_node/network/src/sync/backfill_sync/:
after checkpoint sync the chain starts at a finalized anchor with no
history.  Backfill requests BlocksByRange batches walking BACKWARD from
the anchor slot, verifies each batch by parent-root linkage against the
known child (no state needed — the hash chain is the proof, which is why
the reference can backfill without replaying), persists the blocks and
records canonical block roots in the freezer so the API and sync can
serve the full chain.
"""

from __future__ import annotations

from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOCKS_BY_RANGE,
    RpcError,
)
from lighthouse_tpu.store.hot_cold import P_COLD_BLOCK_ROOT, _slot_key
from lighthouse_tpu.store.kv import KeyValueOp

BATCH_SIZE = 32


class BackfillError(ValueError):
    pass


class BackfillSync:
    """Walks from `chain.anchor_slot` down to genesis, one batch per
    `process_batch` call (the reference paces batches through the
    processor's own work queue)."""

    def __init__(self, chain, rpc_ep, peer_manager,
                 terminal_root: bytes | None = None):
        self.chain = chain
        self.rpc = rpc_ep
        self.peers = peer_manager
        # the network's true genesis block root (from network config /
        # the operator), when known: backfill is only complete once the
        # hash chain provably links to it.  Without it, completion falls
        # back to reaching slot 0 / a parent-zero genesis block (trusted
        # -peer mode) — a peer omitting early blocks is then undetectable.
        self.terminal_root = terminal_root
        anchor = chain.store.get_block(chain.genesis_block_root)
        # the chain's anchor block ("genesis_block_root" is really the
        # anchor root — equal to genesis for non-checkpoint nodes); the
        # next block to fill is the anchor's PARENT
        self.expected_root = (
            bytes(anchor.message.parent_root) if anchor else b"\x00" * 32)
        self.expected_slot = int(anchor.message.slot) if anchor else 0
        # lowest slot whose freezer root entry is already written; slots
        # below it are deferred until the covering block's slot is known
        self._unfilled_upper = self.expected_slot
        self._complete = self.expected_slot == 0 or (
            terminal_root is not None and self.expected_root == terminal_root)
        if self._complete and terminal_root is not None:
            self._finalize_fill(terminal_root)

    @property
    def is_complete(self) -> bool:
        return self._complete

    def process_batch(self, peer: str) -> int:
        """Fetch + verify + store one backward batch from `peer`.
        Returns blocks imported (0 at completion)."""
        if self._complete:
            return 0
        end = self.expected_slot  # exclusive: the anchor itself is stored
        start = max(0, end - BATCH_SIZE)
        req = BlocksByRangeRequest(start_slot=start, count=end - start, step=1)
        try:
            chunks = self.rpc.request(peer, P_BLOCKS_BY_RANGE, req.serialize())
        except RpcError:
            self.peers.report(peer, "mid")
            return 0
        blocks = []
        for raw in chunks:
            blk = self._decode(raw)
            if blk is None:
                self.peers.report(peer, "high")
                return 0
            blocks.append(blk)
        # Phase 1 — verify the WHOLE batch's linkage newest-first before
        # persisting anything: each block's root must equal the expected
        # parent root carried down from the anchor.  A mid-batch break
        # must not leave half-advanced state or unrecorded freezer roots.
        verified: list[tuple[int, bytes, object]] = []
        expected = self.expected_root
        for blk in reversed(blocks):
            root = blk.message.hash_tree_root()
            if root != expected:
                # peers may omit skipped slots; a root mismatch on a
                # served block breaks the hash chain
                self.peers.report(peer, "high")
                raise BackfillError(
                    f"backfill batch broke the hash chain at slot "
                    f"{int(blk.message.slot)}")
            verified.append((int(blk.message.slot), root, blk))
            expected = bytes(blk.message.parent_root)
        # Phase 2 — persist atomically, then advance the cursor.  The
        # freezer invariant (root at slot s = latest block at or below s,
        # matching migrate_to_finalized) needs an entry for EVERY slot —
        # but a root is only written once the covering block's slot is
        # KNOWN: each served block at slot b fills [b, lowest-filled),
        # and slots below the oldest served block stay deferred until a
        # later batch reveals their covering block (so a peer serving an
        # empty window can never plant unverified root claims).
        ops: list[KeyValueOp] = []
        for _slot, root, blk in verified:
            self.chain.store.put_block(root, blk)
        for slot, root, _blk in verified:  # newest-first
            for s in range(slot, self._unfilled_upper):
                ops.append(
                    KeyValueOp(_slot_key(P_COLD_BLOCK_ROOT, s), root))
            self._unfilled_upper = min(self._unfilled_upper, slot)
        if ops:
            self.chain.store.cold.do_atomically(ops)
        # the window is exhausted even when its tail (or all) was skipped
        # slots: the next request starts below it.  Lies by omission are
        # caught later — the next served block must match expected_root.
        self.expected_slot = start
        self.expected_root = expected
        imported = len(verified)
        self.peers.report(peer, "useful_response")

        # Completion: provable when the chain links to the known terminal
        # root; otherwise slot 0 / a parent-zero genesis block.
        if self.terminal_root is not None:
            if self.expected_root == self.terminal_root:
                self._complete = True
                self._finalize_fill(self.terminal_root)
            elif start == 0:
                self.peers.report(peer, "high")
                raise BackfillError(
                    "backfill reached slot 0 without linking to the "
                    "genesis block root — peer withheld history")
        elif (self.expected_slot == 0
              or self.expected_root == b"\x00" * 32):
            self._complete = True
            if self.expected_root != b"\x00" * 32:
                self._finalize_fill(self.expected_root)
        return imported

    def _finalize_fill(self, root: bytes) -> None:
        """On completion, slots below the oldest served block are covered
        by the terminal (genesis/anchor) block."""
        ops = [KeyValueOp(_slot_key(P_COLD_BLOCK_ROOT, s), root)
               for s in range(0, self._unfilled_upper)]
        if ops:
            self.chain.store.cold.do_atomically(ops)
        self._unfilled_upper = 0

    def run(self, peer: str, max_batches: int = 10_000) -> int:
        total = 0
        for _ in range(max_batches):
            before = self.expected_slot
            total += self.process_batch(peer)
            if self._complete:
                break
            if self.expected_slot == before:
                break  # rpc failure: no progress, caller retries/rotates
        return total

    def _decode(self, raw: bytes):
        return self.chain.t.decode_signed_block(raw)


__all__ = ["BackfillError", "BackfillSync", "BATCH_SIZE"]
