"""Backfill sync: reverse-fill history below a checkpoint anchor.

Rebuild of /root/reference/beacon_node/network/src/sync/backfill_sync/:
after checkpoint sync the chain starts at a finalized anchor with no
history.  Backfill requests BlocksByRange batches walking BACKWARD from
the anchor slot, verifies each batch by parent-root linkage against the
known child (no state needed — the hash chain is the proof, which is why
the reference can backfill without replaying), persists the blocks and
records canonical block roots in the freezer so the API and sync can
serve the full chain.

Byzantine hardening (mirrors network/sync.py's discipline):

- ``run`` takes a peer POOL and rotates on :class:`BackfillError` /
  no-progress instead of raising through the caller, up to
  LHTPU_SYNC_BACKFILL_ATTEMPTS consecutive failures per window;
- a restart resumes from the freezer's lowest filled root instead of
  refilling from the anchor (the cursor is recoverable from the
  persisted hash-chain prefix);
- every batch attempt is accounted in
  ``backfill_batches_total{outcome}`` (requested == imported + retried
  + abandoned, the same books invariant as range sync) and every
  penalty in ``backfill_downscores_total{reason}``.
"""

from __future__ import annotations

import time

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed
from lighthouse_tpu.common.tracing import add_attrs, span
from lighthouse_tpu.network.rpc import (
    BlocksByRangeRequest,
    P_BLOCKS_BY_RANGE,
    RpcError,
)
from lighthouse_tpu.store.hot_cold import P_COLD_BLOCK_ROOT, _slot_key
from lighthouse_tpu.store.kv import KeyValueOp

BATCH_SIZE = 32   # default; LHTPU_SYNC_BATCH_SIZE overrides


class BackfillError(ValueError):
    pass


class BackfillSync:
    """Walks from `chain.anchor_slot` down to genesis, one batch per
    `process_batch` call (the reference paces batches through the
    processor's own work queue)."""

    def __init__(self, chain, rpc_ep, peer_manager,
                 terminal_root: bytes | None = None):
        self.chain = chain
        self.rpc = rpc_ep
        self.peers = peer_manager
        # the network's true genesis block root (from network config /
        # the operator), when known: backfill is only complete once the
        # hash chain provably links to it.  Without it, completion falls
        # back to reaching slot 0 / a parent-zero genesis block (trusted
        # -peer mode) — a peer omitting early blocks is then undetectable.
        self.terminal_root = terminal_root
        anchor = chain.store.get_block(chain.genesis_block_root)
        # the chain's anchor block ("genesis_block_root" is really the
        # anchor root — equal to genesis for non-checkpoint nodes); the
        # next block to fill is the anchor's PARENT
        self.expected_root = (
            bytes(anchor.message.parent_root) if anchor else b"\x00" * 32)
        self.expected_slot = int(anchor.message.slot) if anchor else 0
        # lowest slot whose freezer root entry is already written; slots
        # below it are deferred until the covering block's slot is known
        self._unfilled_upper = self.expected_slot
        # books: requested == imported + retried + abandoned, always
        self.books = {"requested": 0, "imported": 0, "retried": 0,
                      "abandoned": 0}
        # attempts between "requested" and their terminal outcome (the
        # live books monitor's in-flight tolerance window)
        self.inflight_attempts = 0
        self.downscores = 0
        from lighthouse_tpu.common import monitors as _monitors

        _monitors.register_backfill_books(self)
        # a prior run's progress is recoverable from the freezer's
        # hash-chain prefix: resume below it instead of refilling
        self._resume_from_freezer()
        self._complete = self.expected_slot == 0 or (
            terminal_root is not None and self.expected_root == terminal_root)
        if self._complete and terminal_root is not None:
            self._finalize_fill(terminal_root)

    # -- accounting (the LH604 funnels) -------------------------------------

    def _account(self, outcome: str) -> None:
        # ordering vs the watchdog thread: inflight grows BEFORE the
        # requested bump, and a terminal outcome lands BEFORE inflight
        # releases — a sweep between any two statements never observes
        # deficit > inflight (no false books_violation trips)
        if outcome == "requested":
            self.inflight_attempts += 1
            self.books[outcome] += 1
        else:
            self.books[outcome] += 1
            self.inflight_attempts = max(0, self.inflight_attempts - 1)
        REGISTRY.counter(
            "backfill_batches_total",
            "backfill batch attempts by outcome (requested is the "
            "attempt counter; the rest are terminal outcomes)",
        ).labels(outcome=outcome).inc()

    def _downscore(self, peer: str, level: str, reason: str) -> None:
        self.downscores += 1
        REGISTRY.counter(
            "backfill_downscores_total",
            "peer downscores issued by backfill, by reason",
        ).labels(reason=reason).inc()
        from lighthouse_tpu.common import flight_recorder as flight

        flight.emit("downscore", plane="backfill", peer=peer, level=level,
                    reason=reason)
        self.peers.report(peer, level)

    def books_balanced(self) -> bool:
        b = self.books
        return b["requested"] == (b["imported"] + b["retried"]
                                  + b["abandoned"])

    # -- cursor resume -------------------------------------------------------

    def _resume_from_freezer(self) -> None:
        """A restart used to refill from the anchor; the freezer's
        LOWEST filled root entry names the oldest block whose hash-chain
        link was already verified and persisted — resume below it."""
        cold = getattr(self.chain.store, "cold", None)
        if cold is None:
            return
        lowest = None
        try:
            for key, val in cold.iter_prefix(P_COLD_BLOCK_ROOT):
                lowest = (key, val)
                break          # iter_prefix is slot-ascending
        except Exception as e:
            # a failed resume scan leaves the cursor at the anchor — the
            # safe pre-resume behaviour, accounted as a swallowed error,
            # not a batch abandon
            record_swallowed("backfill.resume_scan", e)
            return  # lhlint: allow(LH604)
        if lowest is None:
            return
        slot = int.from_bytes(lowest[0][len(P_COLD_BLOCK_ROOT):], "big")
        if slot >= self.expected_slot:
            return             # no backfill progress below the anchor
        blk = self.chain.store.get_block(lowest[1])
        if blk is None or int(blk.message.slot) != slot:
            # deferred-fill entry whose covering block sits higher up,
            # or a missing body: not a safe resume point
            return
        self.expected_slot = slot
        self.expected_root = bytes(blk.message.parent_root)
        self._unfilled_upper = slot

    @property
    def is_complete(self) -> bool:
        return self._complete

    def rewind_to(self, child_root: bytes, child_slot: int) -> None:
        """Point the cursor so the next backward batch must serve the
        chain ENDING at ``child_root`` (window end just above
        ``child_slot``): re-verification of already-stored history —
        the chaos soak's crash-repair defense in depth.  Completion
        resets; the fill invariants (deferred roots, newest-first
        linkage) apply unchanged, and freezer entries rewritten along
        the walk carry the same canonical values they already hold."""
        self._complete = False
        self.expected_root = bytes(child_root)
        self.expected_slot = int(child_slot) + 1
        self._unfilled_upper = int(child_slot) + 1

    def process_batch(self, peer: str, last_attempt: bool = False) -> int:
        """Fetch + verify + store one backward batch from `peer`.
        Returns blocks imported (0 at completion).  ``last_attempt``
        classifies a failure as abandoned instead of retried (the
        rotation driver in :meth:`run` knows whether another attempt
        follows)."""
        if self._complete:
            return 0
        fail_outcome = "abandoned" if last_attempt else "retried"
        self._account("requested")
        end = self.expected_slot  # exclusive: the anchor itself is stored
        start = max(0, end - max(1, envreg.get_int("LHTPU_SYNC_BATCH_SIZE",
                                                   BATCH_SIZE) or BATCH_SIZE))
        req = BlocksByRangeRequest(start_slot=start, count=end - start, step=1)
        try:
            chunks = self.rpc.request(peer, P_BLOCKS_BY_RANGE, req.serialize())
        except RpcError:
            self._downscore(peer, "mid", "rpc_error")
            self._account(fail_outcome)
            return 0
        if len(chunks) > end - start:
            self._downscore(peer, "high", "overserve")
            self._account(fail_outcome)
            return 0
        if not chunks:
            # a fully-empty window is NO progress, not a license to walk
            # the cursor past (possibly withheld) history: the expected
            # child's parent provably exists below the anchor, so some
            # window down there must serve it.  The rotation driver asks
            # another peer; a genuinely all-skipped window needs a batch
            # size spanning the gap (LHTPU_SYNC_BATCH_SIZE).
            self._account(fail_outcome)
            return 0
        blocks = []
        for raw in chunks:
            blk = self._decode(raw)
            if blk is None:
                self._downscore(peer, "high", "decode")
                self._account(fail_outcome)
                return 0
            blocks.append(blk)
        # Phase 1 — verify the WHOLE batch's linkage newest-first before
        # persisting anything: each block's root must equal the expected
        # parent root carried down from the anchor.  A mid-batch break
        # must not leave half-advanced state or unrecorded freezer roots.
        verified: list[tuple[int, bytes, object]] = []
        expected = self.expected_root
        for blk in reversed(blocks):
            root = blk.message.hash_tree_root()
            if root != expected:
                # peers may omit skipped slots; a root mismatch on a
                # served block breaks the hash chain
                self._downscore(peer, "high", "broken_hash_chain")
                self._account(fail_outcome)
                raise BackfillError(
                    f"backfill batch broke the hash chain at slot "
                    f"{int(blk.message.slot)}")
            verified.append((int(blk.message.slot), root, blk))
            expected = bytes(blk.message.parent_root)
        # Phase 2 — persist atomically, then advance the cursor.  The
        # freezer invariant (root at slot s = latest block at or below s,
        # matching migrate_to_finalized) needs an entry for EVERY slot —
        # but a root is only written once the covering block's slot is
        # KNOWN: each served block at slot b fills [b, lowest-filled),
        # and slots below the oldest served block stay deferred until a
        # later batch reveals their covering block (so a peer serving an
        # empty window can never plant unverified root claims).
        ops: list[KeyValueOp] = []
        for _slot, root, blk in verified:
            self.chain.store.put_block(root, blk)
        for slot, root, _blk in verified:  # newest-first
            for s in range(slot, self._unfilled_upper):
                ops.append(
                    KeyValueOp(_slot_key(P_COLD_BLOCK_ROOT, s), root))
            self._unfilled_upper = min(self._unfilled_upper, slot)
        if ops:
            self.chain.store.cold.do_atomically(ops)
        # the window is exhausted even when its tail (or all) was skipped
        # slots: the next request starts below it.  Lies by omission are
        # caught later — the next served block must match expected_root.
        self.expected_slot = start
        self.expected_root = expected
        imported = len(verified)
        self._account("imported")
        self.peers.report(peer, "useful_response")

        # Completion: provable when the chain links to the known terminal
        # root; otherwise slot 0 / a parent-zero genesis block.
        if self.terminal_root is not None:
            if self.expected_root == self.terminal_root:
                self._complete = True
                self._finalize_fill(self.terminal_root)
            elif start == 0:
                self._downscore(peer, "high", "withheld_history")
                raise BackfillError(
                    "backfill reached slot 0 without linking to the "
                    "genesis block root — peer withheld history")
        elif (self.expected_slot == 0
              or self.expected_root == b"\x00" * 32):
            self._complete = True
            if self.expected_root != b"\x00" * 32:
                self._finalize_fill(self.expected_root)
        return imported

    def _finalize_fill(self, root: bytes) -> None:
        """On completion, slots below the oldest served block are covered
        by the terminal (genesis/anchor) block."""
        ops = [KeyValueOp(_slot_key(P_COLD_BLOCK_ROOT, s), root)
               for s in range(0, self._unfilled_upper)]
        if ops:
            self.chain.store.cold.do_atomically(ops)
        self._unfilled_upper = 0

    def run(self, peers, max_batches: int = 10_000) -> int:
        """Drive backfill to completion over a peer POOL, rotating to
        the next peer on a broken hash chain or a no-progress batch
        instead of raising through the caller.  A window that fails
        LHTPU_SYNC_BACKFILL_ATTEMPTS consecutive attempts abandons the
        run (resumable: the freezer cursor survives)."""
        pool = [peers] if isinstance(peers, str) else list(peers)
        if not pool:
            return 0
        outcome = "abandoned"
        # the window budget covers at least one full pool rotation: a
        # hostile majority must not starve the honest tail of its turn
        budget = max(1, envreg.get_int("LHTPU_SYNC_BACKFILL_ATTEMPTS", 3)
                     or 3, len(pool))
        total = 0
        idx = 0
        window_fails = 0
        for _ in range(max_batches):
            if self._complete:
                outcome = "completed"
                break
            before = self.expected_slot
            peer = pool[idx % len(pool)]
            last = window_fails + 1 >= budget
            t0 = time.perf_counter()
            with span("backfill.batch", slot=before, peer=peer):
                try:
                    n = self.process_batch(peer, last_attempt=last)
                except BackfillError as e:
                    # rotation, not propagation: the offender is already
                    # downscored and the attempt accounted
                    add_attrs(outcome="hash_chain_break", error=str(e))
                    if self.expected_slot == 0 and not self._complete:
                        # walked to slot 0 without linking the terminal
                        # root: no peer can repair persisted-but-unlinked
                        # history — stop, the operator's terminal config
                        # or the serving set is wrong
                        self._observe(time.perf_counter() - t0)
                        outcome = "terminal_mismatch"
                        break
                    n = 0
                else:
                    add_attrs(outcome="imported" if n else "no_progress",
                              imported=n)
            self._observe(time.perf_counter() - t0)
            total += n
            if self._complete:
                outcome = "completed"
                break
            if self.expected_slot == before:
                # rpc failure / withheld window: no progress — rotate
                if last:
                    break
                window_fails += 1
                idx += 1
                continue
            window_fails = 0
        else:
            outcome = "completed" if self._complete else "paced"
        self._record_run(outcome)
        return total

    def _record_run(self, outcome: str) -> None:
        REGISTRY.counter(
            "backfill_runs_total",
            "backfill run() drives by outcome (paced = max_batches "
            "reached with the fill still resumable)",
        ).labels(outcome=outcome).inc()

    def _observe(self, seconds: float) -> None:
        REGISTRY.histogram(
            "backfill_batch_seconds",
            "backfill batch wall time (download+verify+persist)",
        ).observe(seconds)

    def _decode(self, raw: bytes):
        try:
            return self.chain.t.decode_signed_block(raw)
        except Exception as e:
            # the CALLER downscores + accounts the failed attempt; this
            # is only the malformed-bytes -> None translation
            record_swallowed("backfill.decode_block", e)
            return None  # lhlint: allow(LH604)


__all__ = ["BackfillError", "BackfillSync", "BATCH_SIZE"]
