"""Req/Resp RPC layer.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/rpc/: typed
request/response protocols (Status, Goodbye, BlocksByRange, BlocksByRoot,
BlobsByRange) between peers over the in-process fabric, with a token-
bucket rate limiter per (peer, protocol) mirroring the reference's
rate_limiter.rs.  Payloads are SSZ bytes; responses are streamed as lists
of SSZ chunks (the reference's response-chunk framing).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from lighthouse_tpu.ssz import core as ssz


class RpcError(ValueError):
    pass


class RateLimited(RpcError):
    pass


# --- protocol payload containers (reference rpc/methods.rs) ----------------

class StatusMessage(ssz.Container):
    fork_digest: ssz.ByteVector(4)       # noqa: F821
    finalized_root: ssz.Bytes32
    finalized_epoch: ssz.uint64
    head_root: ssz.Bytes32
    head_slot: ssz.uint64


class BlocksByRangeRequest(ssz.Container):
    start_slot: ssz.uint64
    count: ssz.uint64
    step: ssz.uint64


class GoodbyeReason(ssz.Container):
    reason: ssz.uint64


@dataclass
class _Bucket:
    tokens: float
    last: float


class RateLimiter:
    """Token bucket per (peer, protocol) (reference rpc/rate_limiter.rs)."""

    def __init__(self, capacity: float = 64, refill_per_s: float = 16,
                 clock=time.monotonic):
        self.capacity = capacity
        self.refill = refill_per_s
        self.clock = clock
        self._buckets: dict[tuple[str, str], _Bucket] = {}

    def allow(self, peer: str, protocol: str, cost: float = 1.0) -> bool:
        now = self.clock()
        b = self._buckets.get((peer, protocol))
        if b is None:
            b = self._buckets[(peer, protocol)] = _Bucket(self.capacity, now)
        b.tokens = min(self.capacity, b.tokens + (now - b.last) * self.refill)
        b.last = now
        if b.tokens < cost:
            return False
        b.tokens -= cost
        return True


class RpcFabric:
    """In-process request routing between registered RPC endpoints."""

    def __init__(self):
        self._nodes: dict[str, "RpcEndpoint"] = {}

    def join(self, peer_id: str) -> "RpcEndpoint":
        ep = RpcEndpoint(self, peer_id)
        self._nodes[peer_id] = ep
        return ep

    def call(self, src: str, dst: str, protocol: str, data: bytes) -> list[bytes]:
        ep = self._nodes.get(dst)
        if ep is None:
            raise RpcError(f"unknown peer {dst}")
        return ep._serve(src, protocol, data)


class RpcEndpoint:
    def __init__(self, fabric: RpcFabric, peer_id: str):
        self.fabric = fabric
        self.peer_id = peer_id
        self.handlers: dict[str, Callable[[str, bytes], list[bytes]]] = {}
        self.limiter = RateLimiter()

    def register(self, protocol: str,
                 handler: Callable[[str, bytes], list[bytes]]):
        self.handlers[protocol] = handler

    def request(self, dst: str, protocol: str, data: bytes) -> list[bytes]:
        return self.fabric.call(self.peer_id, dst, protocol, data)

    def _serve(self, src: str, protocol: str, data: bytes) -> list[bytes]:
        if not self.limiter.allow(src, protocol):
            raise RateLimited(f"{src} rate-limited on {protocol}")
        handler = self.handlers.get(protocol)
        if handler is None:
            raise RpcError(f"unsupported protocol {protocol}")
        return handler(src, data)


# protocol ids (reference rpc/protocol.rs)
P_STATUS = "/eth2/beacon_chain/req/status/1"
P_GOODBYE = "/eth2/beacon_chain/req/goodbye/1"
P_BLOCKS_BY_RANGE = "/eth2/beacon_chain/req/beacon_blocks_by_range/2"
P_BLOCKS_BY_ROOT = "/eth2/beacon_chain/req/beacon_blocks_by_root/2"
P_BLOBS_BY_RANGE = "/eth2/beacon_chain/req/blob_sidecars_by_range/1"
P_BLOBS_BY_ROOT = "/eth2/beacon_chain/req/blob_sidecars_by_root/1"
P_LC_BOOTSTRAP = "/eth2/beacon_chain/req/light_client_bootstrap/1"
P_LC_UPDATES_BY_RANGE = "/eth2/beacon_chain/req/light_client_updates_by_range/1"
P_LC_OPTIMISTIC = "/eth2/beacon_chain/req/light_client_optimistic_update/1"
P_LC_FINALITY = "/eth2/beacon_chain/req/light_client_finality_update/1"
