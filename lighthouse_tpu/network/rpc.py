"""Req/Resp RPC layer.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/rpc/: typed
request/response protocols (Status, Goodbye, BlocksByRange, BlocksByRoot,
BlobsByRange) between peers over the in-process fabric, with a token-
bucket rate limiter per (peer, protocol) mirroring the reference's
rate_limiter.rs.  Payloads are SSZ bytes; responses are streamed as lists
of SSZ chunks (the reference's response-chunk framing).

Outbound requests run under :class:`RequestDiscipline` (shared by the
in-process endpoint and the socket WireRpcEndpoint): a per-request
watchdog deadline (``LHTPU_RPC_DEADLINE_S``, the PR 4 deadline idiom),
a per-peer consecutive-failure counter that trips an exponential
quarantine window (``LHTPU_RPC_FAILS`` / ``LHTPU_RPC_BACKOFF_S`` /
``LHTPU_RPC_BACKOFF_MAX_S`` — the reference's peer-scoring-fed request
backoff), and ``rpc_requests_total{protocol,outcome}`` /
``rpc_request_seconds`` accounting.  The discipline is also where the
ops/faults :class:`PeerFaultPlan` Byzantine-peer injection fires —
stalls, withheld windows, truncated/malformed chunks, wrong-chain
redirects, STATUS equivocation and mid-stream flaps are synthesized at
the requester's seam so sync/backfill supervision is exercised
deterministically on CI.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed
from lighthouse_tpu.ops import faults
from lighthouse_tpu.ssz import core as ssz


class RpcError(ValueError):
    pass


class RateLimited(RpcError):
    pass


class RpcDeadline(RpcError):
    """The request exceeded its LHTPU_RPC_DEADLINE_S watchdog deadline."""


class PeerQuarantined(RpcError):
    """The peer is inside its backoff quarantine window; the request was
    refused locally without touching the wire (fail-fast)."""


# --- protocol payload containers (reference rpc/methods.rs) ----------------

class StatusMessage(ssz.Container):
    fork_digest: ssz.ByteVector(4)       # noqa: F821
    finalized_root: ssz.Bytes32
    finalized_epoch: ssz.uint64
    head_root: ssz.Bytes32
    head_slot: ssz.uint64


class BlocksByRangeRequest(ssz.Container):
    start_slot: ssz.uint64
    count: ssz.uint64
    step: ssz.uint64


class GoodbyeReason(ssz.Container):
    reason: ssz.uint64


@dataclass
class _Bucket:
    tokens: float
    last: float


class RateLimiter:
    """Token bucket per (peer, protocol) (reference rpc/rate_limiter.rs)."""

    def __init__(self, capacity: float = 64, refill_per_s: float = 16,
                 clock=time.monotonic):
        self.capacity = capacity
        self.refill = refill_per_s
        self.clock = clock
        self._buckets: dict[tuple[str, str], _Bucket] = {}

    def allow(self, peer: str, protocol: str, cost: float = 1.0) -> bool:
        now = self.clock()
        b = self._buckets.get((peer, protocol))
        if b is None:
            b = self._buckets[(peer, protocol)] = _Bucket(self.capacity, now)
        b.tokens = min(self.capacity, b.tokens + (now - b.last) * self.refill)
        b.last = now
        if b.tokens < cost:
            return False
        b.tokens -= cost
        return True


def proto_token(protocol: str) -> str:
    """Short metric/fault-plan token for a protocol id: the name path
    segment ("status", "beacon_blocks_by_range", ...)."""
    parts = protocol.strip("/").split("/")
    return parts[-2] if len(parts) >= 2 else protocol


def _record_request(token: str, outcome: str,
                    seconds: float | None = None) -> None:
    REGISTRY.counter(
        "rpc_requests_total",
        "outbound rpc requests by protocol token and outcome",
    ).labels(protocol=token, outcome=outcome).inc()
    if seconds is not None:
        REGISTRY.histogram(
            "rpc_request_seconds",
            "outbound rpc request wall time (includes retr-able "
            "failures; quarantined fail-fasts are not timed)",
        ).observe(seconds)
    if outcome not in ("ok", "quarantined"):
        # failed requests are the story BEFORE a quarantine trip; ok
        # outcomes stay off the ring (steady traffic is not a story)
        from lighthouse_tpu.common import flight_recorder as flight

        flight.emit("rpc_fail", protocol=token, outcome=outcome)


@dataclass
class _PeerHealth:
    fails: int = 0         # consecutive failures since the last success
    quarantines: int = 0   # ladder rung: doubles the next window
    until: float = 0.0     # monotonic instant the quarantine lifts


class RequestDiscipline:
    """Per-peer deadline/backoff/quarantine + metrics + fault injection
    for outbound requests — one instance per endpoint, shared between
    the in-process and socket RPC seams.

    ``execute`` wraps the transport-specific ``issue(dst)`` callable:
    consult the peer fault plans, enforce the watchdog deadline, track
    the per-peer failure ladder, and account every outcome in
    ``rpc_requests_total{protocol,outcome}``.
    """

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._health: dict[str, _PeerHealth] = {}
        self._ordinals: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        # (peer, rung) callback when a peer crosses into quarantine —
        # the NetworkService feeds this into peer_manager scoring
        self.on_quarantine: Callable | None = None

    def quarantined_until(self, peer: str) -> float:
        """Monotonic lift instant, 0.0 when not quarantined."""
        with self._lock:
            h = self._health.get(peer)
            return h.until if h is not None else 0.0

    def execute(self, dst: str, protocol: str, data: bytes,
                issue: Callable[[str], list[bytes]]) -> list[bytes]:
        token = proto_token(protocol)
        now = self.clock()
        with self._lock:
            h = self._health.get(dst)
            if h is not None and now < h.until:
                _record_request(token, "quarantined")
                raise PeerQuarantined(
                    f"{dst} quarantined for another "
                    f"{h.until - now:.2f}s (rung {h.quarantines})")
            key = (dst, protocol)
            ordinal = self._ordinals.get(key, 0)
            self._ordinals[key] = ordinal + 1
        plan = faults.consult_peer(dst, token, ordinal)
        deadline = envreg.get_float("LHTPU_RPC_DEADLINE_S", 5.0) or 0.0

        def _issue():
            return self._issue_with_plan(dst, protocol, data, plan, issue)

        t0 = time.perf_counter()
        try:
            if deadline > 0 and not faults.under_watchdog():
                try:
                    chunks = faults.run_with_deadline(
                        _issue, deadline, f"rpc-{token}",
                        f"rpc {token} request to {dst}")
                except faults.WatchdogTimeout as e:
                    raise RpcDeadline(str(e)) from e
            else:
                chunks = _issue()
        except Exception as e:
            outcome = ("deadline" if isinstance(e, RpcDeadline)
                       else "rate_limited" if isinstance(e, RateLimited)
                       else "error")
            self._note_failure(dst)
            _record_request(token, outcome, time.perf_counter() - t0)
            raise
        self._note_success(dst)
        _record_request(token, "ok", time.perf_counter() - t0)
        return chunks

    # -- fault synthesis (PeerFaultPlan modes) ------------------------------

    def _issue_with_plan(self, dst, protocol, data, plan, issue):
        if plan is None:
            return issue(dst)
        mode = plan.mode
        if mode == "stall":
            # the deadline watchdog's job is to cut this off
            time.sleep(plan.stall_s)
            return issue(dst)
        if mode == "flap":
            raise RpcError(
                f"injected mid-stream disconnect from {dst}")
        if mode == "empty":
            return []
        if mode == "wrong_chain":
            if plan.alt_peer is None:
                return []        # no branch to serve: withhold
            return issue(plan.alt_peer)
        chunks = issue(dst)
        if mode == "truncate":
            return chunks[: len(chunks) // 2]
        if mode == "malformed":
            return [bytes(b ^ 0xA5 for b in c[:16]) + c[16:] if c
                    else b"\xa5" for c in chunks] or [b"\xa5"]
        if mode == "equivocate" and proto_token(protocol) == "status":
            out = []
            for c in chunks:
                st = StatusMessage.deserialize(c)
                bogus = hashlib.sha256(
                    bytes(st.head_root) + b"equivocate").digest()
                out.append(StatusMessage(
                    fork_digest=bytes(st.fork_digest),
                    finalized_root=bytes(st.finalized_root),
                    finalized_epoch=int(st.finalized_epoch),
                    head_root=bogus,
                    head_slot=int(st.head_slot) + plan.lift,
                ).serialize())
            return out
        return chunks

    # -- failure ladder ------------------------------------------------------

    def _note_failure(self, dst: str) -> None:
        fails_max = envreg.get_int("LHTPU_RPC_FAILS", 3) or 3
        base = envreg.get_float("LHTPU_RPC_BACKOFF_S", 0.5) or 0.5
        cap = envreg.get_float("LHTPU_RPC_BACKOFF_MAX_S", 30.0) or 30.0
        cb = rung = None
        quarantined = False
        with self._lock:
            h = self._health.setdefault(dst, _PeerHealth())
            h.fails += 1
            if h.fails >= fails_max:
                h.until = self.clock() + min(
                    base * (2.0 ** h.quarantines), cap)
                h.quarantines += 1
                h.fails = 0
                cb, rung = self.on_quarantine, h.quarantines
                quarantined = True
        if quarantined:
            # crossing into quarantine is a trip condition: the black
            # box shows the request failures (and injected peer faults)
            # that walked this peer up the ladder
            from lighthouse_tpu.common import flight_recorder as flight

            flight.emit("quarantine", peer=dst, rung=rung)
            flight.trip("peer_quarantine", peer=dst, rung=rung)
        if cb is not None:
            try:
                cb(dst, rung)
            except Exception as e:
                record_swallowed("rpc.on_quarantine", e)

    def _note_success(self, dst: str) -> None:
        with self._lock:
            h = self._health.get(dst)
            if h is not None:
                h.fails = 0
                h.quarantines = 0
                h.until = 0.0


class RpcFabric:
    """In-process request routing between registered RPC endpoints."""

    def __init__(self):
        from lighthouse_tpu.network.partition import PartitionSet

        self._nodes: dict[str, "RpcEndpoint"] = {}
        # pairwise partitions (the same PartitionSet GossipHub uses —
        # LocalNetwork.partition assumes both fabrics sever
        # identically): a partitioned pair's calls fail like a dead
        # link, which the RequestDiscipline accounts exactly like any
        # peer failure
        self._partitions = PartitionSet()

    def join(self, peer_id: str) -> "RpcEndpoint":
        ep = RpcEndpoint(self, peer_id)
        self._nodes[peer_id] = ep
        return ep

    def leave(self, peer_id: str):
        """Drop a peer's endpoint (node death): further calls to it fail
        like a dead link — the requester's RequestDiscipline accounts
        them exactly like any peer failure.  Pairwise partitions are
        kept: a node that dies partitioned restarts partitioned."""
        self._nodes.pop(peer_id, None)

    def disconnect(self, a: str, b: str):
        """Partition two peers (fault injection for drills/tests)."""
        self._partitions.disconnect(a, b)

    def reconnect(self, a: str, b: str):
        self._partitions.reconnect(a, b)

    def call(self, src: str, dst: str, protocol: str, data: bytes) -> list[bytes]:
        if self._partitions.blocked(src, dst):
            raise RpcError(f"partitioned from {dst}")
        ep = self._nodes.get(dst)
        if ep is None:
            raise RpcError(f"unknown peer {dst}")
        return ep._serve(src, protocol, data)


class RpcEndpoint:
    def __init__(self, fabric: RpcFabric, peer_id: str):
        self.fabric = fabric
        self.peer_id = peer_id
        self.handlers: dict[str, Callable[[str, bytes], list[bytes]]] = {}
        self.limiter = RateLimiter()
        self.discipline = RequestDiscipline()

    def register(self, protocol: str,
                 handler: Callable[[str, bytes], list[bytes]]):
        self.handlers[protocol] = handler

    def request(self, dst: str, protocol: str, data: bytes) -> list[bytes]:
        return self.discipline.execute(
            dst, protocol, data,
            lambda target: self.fabric.call(
                self.peer_id, target, protocol, data))

    def _serve(self, src: str, protocol: str, data: bytes) -> list[bytes]:
        if not self.limiter.allow(src, protocol):
            raise RateLimited(f"{src} rate-limited on {protocol}")
        handler = self.handlers.get(protocol)
        if handler is None:
            raise RpcError(f"unsupported protocol {protocol}")
        return handler(src, data)


# protocol ids (reference rpc/protocol.rs)
P_STATUS = "/eth2/beacon_chain/req/status/1"
P_GOODBYE = "/eth2/beacon_chain/req/goodbye/1"
P_BLOCKS_BY_RANGE = "/eth2/beacon_chain/req/beacon_blocks_by_range/2"
P_BLOCKS_BY_ROOT = "/eth2/beacon_chain/req/beacon_blocks_by_root/2"
P_BLOBS_BY_RANGE = "/eth2/beacon_chain/req/blob_sidecars_by_range/1"
P_BLOBS_BY_ROOT = "/eth2/beacon_chain/req/blob_sidecars_by_root/1"
P_LC_BOOTSTRAP = "/eth2/beacon_chain/req/light_client_bootstrap/1"
P_LC_UPDATES_BY_RANGE = "/eth2/beacon_chain/req/light_client_updates_by_range/1"
P_LC_OPTIMISTIC = "/eth2/beacon_chain/req/light_client_optimistic_update/1"
P_LC_FINALITY = "/eth2/beacon_chain/req/light_client_finality_update/1"
