"""Network service: one node's full networking stack.

Rebuild of /root/reference/beacon_node/network/src/service.rs:160,432 —
binds a BeaconChain to the gossip fabric, the RPC fabric, the router, the
peer manager and the sync manager.  `NetworkService.connect` performs the
status handshake both ways (the reference's dial + Status exchange).
"""

from __future__ import annotations

from lighthouse_tpu.network.gossip import GossipHub
from lighthouse_tpu.network.peer_manager import PeerManager
from lighthouse_tpu.network.router import Router
from lighthouse_tpu.network.rpc import RpcFabric
from lighthouse_tpu.network.sync import SyncManager


class NetworkFabric:
    """Shared in-process swarm: gossip + rpc hubs (the simulator's
    localhost network, /root/reference/testing/simulator/src/local_network.rs)."""

    def __init__(self):
        self.gossip = GossipHub()
        self.rpc = RpcFabric()


class NetworkService:
    def __init__(self, chain, fabric: NetworkFabric, peer_id: str,
                 scheduled_subnets: bool = False, processor=None):
        from lighthouse_tpu.network.discovery import Discovery, Enr
        from lighthouse_tpu.network.router import fork_digest

        self.chain = chain
        self.fabric = fabric
        self.peer_id = peer_id
        self.peer_manager = PeerManager()
        self.upnp = None                 # UpnpService when NAT mapping is on
        self.gossip_ep = fabric.gossip.join(peer_id)
        self.rpc_ep = fabric.rpc.join(peer_id)
        subnet_service = None
        if scheduled_subnets:
            # production bandwidth sharding: listen on the node's
            # long-lived subnets + short-lived duty subnets only, not
            # all 64 (reference subnet_service)
            import hashlib as _hashlib

            from lighthouse_tpu.network.subnet_service import (
                AttestationSubnetService,
            )

            subnet_service = AttestationSubnetService(
                chain.spec, _hashlib.sha256(peer_id.encode()).digest())
        self.subnet_service = subnet_service
        if subnet_service is not None:
            # the HTTP API's beacon_committee_subscriptions endpoint
            # reaches the scheduler through the chain handle; never
            # clobber an existing scheduler with None
            chain.subnet_service = subnet_service
        self.router = Router(
            chain, self.gossip_ep, self.rpc_ep, self.peer_manager,
            on_unknown_parent=self._on_unknown_parent,
            subnet_service=subnet_service, processor=processor)
        self.sync = SyncManager(chain, self.rpc_ep, self.router,
                                self.peer_manager)
        # the rpc request discipline's quarantine ladder feeds peer
        # scoring: a peer that keeps timing out / erroring until it is
        # quarantined loses standing like any other misbehaver
        discipline = getattr(self.rpc_ep, "discipline", None)
        if discipline is not None:
            discipline.on_quarantine = (
                lambda peer, rung, _pm=self.peer_manager:
                _pm.report(peer, "mid"))
        # gossip fresh light-client updates as the chain mints them
        # (reference --light-client-server gossip publication)
        chain.light_client.on_finality_update = \
            self.router.publish_lc_finality_update
        chain.light_client.on_optimistic_update = \
            self.router.publish_lc_optimistic_update
        # socket fabrics: bind the peer manager to the transport — ban
        # gate at the HELLO door, connection bookkeeping for pruning
        node = getattr(fabric, "node", None)
        if node is not None:
            node.accept_peer = self.peer_manager.accept_connection
            # gossipsub topic scoring feeds the ban gate: a peer whose
            # mesh score crosses the graylist floor is penalized once
            # per crossing (gossipsub_scoring_parameters.rs wires the
            # same signal into libp2p's connection scoring)
            self._graylisted_gossip: set[str] = set()
            node.on_gossip_score = self._on_gossip_score

            def _on_connected(pid, _node=node, _pm=self.peer_manager):
                addr = _node.peer_addr(pid)
                _pm.mark_connected(
                    pid, ip=addr[0] if addr else None,
                    outbound=_node.peer_outbound(pid),
                    agent=_node.peer_agent(pid))

            node.on_peer_connected = _on_connected
            node.on_peer_disconnected = self.peer_manager.mark_disconnected

        # socket fabrics carry discovery over UDP datagrams and advertise
        # a real (host, port); the in-process fabric reuses the rpc seam
        disc_ep = getattr(fabric, "discovery_ep", None) or self.rpc_ep
        enr = Enr(peer_id=peer_id)
        if hasattr(fabric, "listen_port"):
            enr.port = fabric.listen_port
            enr.ip = getattr(fabric.node, "listen_host", "127.0.0.1")
        if node is not None:
            # socket fabric: sign our record so remote nodes accept it
            # (fork digest first — Discovery must not mutate it after
            # signing, or the record self-invalidates)
            enr.fork_digest = fork_digest(chain)
            enr.sign(node.identity)
        self.discovery = Discovery(
            disc_ep, enr, fork_digest=fork_digest(chain))

    def _on_gossip_score(self, peer: str, score: float) -> None:
        from lighthouse_tpu.network.wire.gossipsub import SCORE_GRAYLIST

        if score < SCORE_GRAYLIST:
            if peer not in self._graylisted_gossip:
                self._graylisted_gossip.add(peer)
                self.peer_manager.report(peer, "high", topic="gossipsub")
        else:
            self._graylisted_gossip.discard(peer)

    def on_slot(self, slot: int) -> None:
        """Per-slot tick: chain-health lag gauges, subnet subscription
        deltas + the peer-manager heartbeat (disconnect bad scores,
        prune beyond the target peer count with sole-subnet-provider
        protection, refill the dial deficit from the discovery
        table)."""
        health = getattr(self.chain, "chain_health", None)
        if health is not None:
            try:
                health.on_slot(slot)
            except Exception as e:
                from lighthouse_tpu.common.metrics import record_swallowed

                record_swallowed("network.chain_health_tick", e)
        self.router.update_attestation_subnets(slot)
        node = getattr(self.fabric, "node", None)
        if node is None:
            return
        # both args are callables: the candidate scan and the provider
        # map only run when the heartbeat actually dials or prunes
        self.peer_manager.heartbeat(
            node,
            dial_candidates=lambda: self._dial_candidates(node),
            protected=lambda: self._sole_subnet_providers(node))

    def _sole_subnet_providers(self, node) -> set[str]:
        """Peers that are the ONLY provider of a topic we subscribe —
        pruning them last keeps rare subnets reachable (reference
        prune_excess_peers' subnet protection)."""
        providers: dict[str, list[str]] = {}
        for pid in node.peers:
            for t in node.peer_topics(pid):
                providers.setdefault(t, []).append(pid)
        return {ps[0] for t, ps in providers.items() if len(ps) == 1}

    def _dial_candidates(self, node) -> list:
        """Discovery-table ENRs we are not connected to, as (host, port)
        dial targets (discovery → peer_manager dial flow).  Banned peers
        and banned IPs are skipped — a doomed dial would burn a slot of
        the capped deficit only for our own accept gate to refuse it."""
        connected = set(node.peers)
        pm = self.peer_manager
        banned_ips = pm.banned_ips
        out = []
        for enr in self.discovery.table.closest(
                self.discovery.enr.node_id, n=16):
            if enr.peer_id in connected or enr.peer_id == self.peer_id:
                continue
            if pm.is_banned(enr.peer_id) or enr.ip in banned_ips:
                continue
            if enr.ip and enr.port:
                out.append((enr.ip, enr.port))
        return out

    def connect(self, other: "NetworkService"):
        """Mutual status handshake (dial)."""
        self.sync.status_handshake(other.peer_id)
        other.sync.status_handshake(self.peer_id)

    def discover_and_connect(self, bootnode_peer: str,
                             max_dials: int = 8) -> int:
        """Bootstrap discovery from a bootnode, then status-handshake the
        discovered peers (reference discovery → peer_manager dial flow).
        Returns the number of peers successfully connected."""
        self.discovery.bootstrap(bootnode_peer)
        connected = 0
        for enr in self.discovery.table.closest(
                self.discovery.enr.node_id, n=max_dials):
            if enr.peer_id == self.peer_id:
                continue
            if self.sync.status_handshake(enr.peer_id) is not None:
                connected += 1
        return connected

    def _on_unknown_parent(self, peer: str, block):
        self.sync.lookup_unknown_parent(peer, block)
