"""Pairwise partition bookkeeping shared by the in-process fabrics.

One implementation for both hubs (GossipHub and RpcFabric): the
simulator's ``LocalNetwork.partition``/``heal`` assume gossip and rpc
sever identically, so the semantics must live in exactly one place.
"""

from __future__ import annotations


class PartitionSet:
    """Symmetric blocked-pair set (fault induction for drills/tests)."""

    def __init__(self):
        self._blocked: dict[str, set[str]] = {}

    def disconnect(self, a: str, b: str) -> None:
        self._blocked.setdefault(a, set()).add(b)
        self._blocked.setdefault(b, set()).add(a)

    def reconnect(self, a: str, b: str) -> None:
        self._blocked.get(a, set()).discard(b)
        self._blocked.get(b, set()).discard(a)

    def blocked(self, a: str, b: str) -> bool:
        return b in self._blocked.get(a, ())

    def blocked_for(self, a: str) -> set[str]:
        return self._blocked.get(a, set())
