"""Peer discovery: ENR-style records + XOR-distance routing table.

Rebuild of /root/reference/beacon_node/lighthouse_network/src/discovery/
(discv5 UDP protocol) re-shaped for this framework's transport fabric:
nodes carry signed ENR records (sequence-numbered, fork-digest-scoped),
maintain a k-bucket routing table keyed by XOR distance over sha256 node
ids, and answer PING / FINDNODE queries.  A recursive lookup walks
closer-and-closer buckets exactly like discv5's FINDNODE iteration, and
`BootNode` is the chain-less standalone answerer
(/root/reference/boot_node/).
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field

from lighthouse_tpu.network.rpc import RpcError

P_DISCOVERY_PING = "/discovery/ping/1"
P_DISCOVERY_FINDNODE = "/discovery/findnode/1"

BUCKET_SIZE = 16          # discv5 k
N_BUCKETS = 256
LOOKUP_PARALLELISM = 3    # discv5 alpha
MAX_NODES_RESPONSE = 16


@dataclass
class Enr:
    """Minimal ENR: identity + reachable endpoint + fork digest.

    The reference's ENR is RLP + secp256k1-signed; identity here is the
    sha256 of the node's public identity key (the fabric peer id doubles
    as the key), which preserves the property discovery actually needs:
    node ids uniformly spread over the XOR metric space."""

    peer_id: str
    seq: int = 1
    fork_digest: bytes = b"\x00\x00\x00\x00"
    ip: str = "127.0.0.1"
    port: int = 9000
    identity_pub: bytes = b""     # Ed25519 pub of the record's owner
    sig: bytes = b""              # signature over signed_content()

    @property
    def node_id(self) -> bytes:
        return hashlib.sha256(self.peer_id.encode()).digest()

    def signed_content(self) -> bytes:
        return json.dumps({
            "peer_id": self.peer_id, "seq": self.seq,
            "fork_digest": self.fork_digest.hex(),
            "ip": self.ip, "port": self.port,
            "identity_pub": self.identity_pub.hex(),
        }).encode()

    def sign(self, identity) -> "Enr":
        """Sign in place with an Ed25519 identity key; the record's
        peer_id must be that key's fingerprint for verify() to accept."""
        from lighthouse_tpu.network.wire import noise

        self.identity_pub = noise.identity_pub(identity)
        self.sig = noise.sign_enr(identity, self.signed_content())
        return self

    def verify(self) -> bool:
        """True iff signed by the key whose fingerprint is peer_id —
        an unsigned or forged record fails (discv5 ENRs are signed:
        reference .../discovery/enr.rs)."""
        from lighthouse_tpu.network.wire import noise

        if not self.identity_pub or not self.sig:
            return False
        if self.peer_id != noise.peer_id_of(self.identity_pub):
            return False
        return noise.verify_enr(self.identity_pub, self.signed_content(),
                                self.sig)

    def to_bytes(self) -> bytes:
        return json.dumps({
            "peer_id": self.peer_id, "seq": self.seq,
            "fork_digest": self.fork_digest.hex(),
            "ip": self.ip, "port": self.port,
            "identity_pub": self.identity_pub.hex(),
            "sig": self.sig.hex(),
        }).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "Enr":
        d = json.loads(raw)
        return Enr(peer_id=d["peer_id"], seq=int(d["seq"]),
                   fork_digest=bytes.fromhex(d["fork_digest"]),
                   ip=d["ip"], port=int(d["port"]),
                   identity_pub=bytes.fromhex(d.get("identity_pub", "")),
                   sig=bytes.fromhex(d.get("sig", "")))

    @staticmethod
    def try_from_bytes(raw: bytes) -> "Enr | None":
        """Decode a record a REMOTE handed us, or None when it is
        garbage.  Every byte of a remote's response is attacker- (or
        fault-plane-) controlled: a corrupted record must cost the
        querier one dropped chunk, never a crashed lookup."""
        try:
            return Enr.from_bytes(raw)
        except (ValueError, KeyError, TypeError):
            # json/hex/int decode failures, missing fields, non-dict
            # payloads (UnicodeDecodeError is a ValueError)
            return None


def xor_distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


def log2_distance(a: bytes, b: bytes) -> int:
    """discv5 bucket index: bit length of the XOR distance (0 = self)."""
    return xor_distance(a, b).bit_length()


class RoutingTable:
    """k-buckets by log2 XOR distance from the local node id."""

    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: list[dict[bytes, Enr]] = [
            {} for _ in range(N_BUCKETS + 1)]

    def insert(self, enr: Enr) -> bool:
        nid = enr.node_id
        if nid == self.local_id:
            return False
        bucket = self.buckets[log2_distance(self.local_id, nid)]
        existing = bucket.get(nid)
        if existing is not None:
            if enr.seq >= existing.seq:
                bucket[nid] = enr
            return True
        if len(bucket) >= BUCKET_SIZE:
            return False  # discv5 drops-newest on a full bucket
        bucket[nid] = enr
        return True

    def remove(self, node_id: bytes) -> None:
        self.buckets[log2_distance(self.local_id, node_id)].pop(node_id, None)

    def closest(self, target: bytes, n: int = MAX_NODES_RESPONSE) -> list[Enr]:
        allnodes = [e for b in self.buckets for e in b.values()]
        allnodes.sort(key=lambda e: xor_distance(e.node_id, target))
        return allnodes[:n]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


class Discovery:
    """Discovery endpoint bound to an rpc fabric endpoint."""

    def __init__(self, rpc_ep, enr: Enr,
                 fork_digest: bytes | None = None,
                 require_signed: bool | None = None):
        self.rpc = rpc_ep
        self.enr = enr
        locally_signed = bool(enr.sig)
        if fork_digest is not None:
            self.enr.fork_digest = fork_digest
        # fail CLOSED: a signed local record that no longer verifies
        # (e.g. a field mutated after signing) must not silently turn
        # signature checking off for remote records
        if locally_signed and not self.enr.verify():
            raise ValueError(
                "local ENR signature invalid — was a field mutated "
                "after sign()? re-sign with the current contents")
        # over real sockets every field of a record (including the "src"
        # it claims to be from) is attacker-controlled: only admit ENRs
        # signed by the key whose fingerprint is their peer id, or an
        # attacker fills target buckets with fabricated records and the
        # table serves poison to every FINDNODE querier.  The in-process
        # fabric (trusted, same interpreter) keeps unsigned records.
        if require_signed is None:
            require_signed = locally_signed
        self.require_signed = require_signed
        self.table = RoutingTable(enr.node_id)
        # the table is written from two threads: the RPC server side
        # (_serve_ping admits records on the transport's thread) and
        # the bootstrap/lookup client side
        self._table_lock = threading.Lock()
        rpc_ep.register(P_DISCOVERY_PING, self._serve_ping)
        rpc_ep.register(P_DISCOVERY_FINDNODE, self._serve_findnode)

    def _admissible(self, enr: Enr) -> bool:
        """The one ENR admission rule: on our network, and (over
        sockets) signed by the key its peer id fingerprints."""
        return (enr.fork_digest == self.enr.fork_digest
                and (not self.require_signed or enr.verify()))

    # -- server side --------------------------------------------------------

    def _serve_ping(self, src: str, data: bytes) -> list[bytes]:
        remote = Enr.try_from_bytes(data)
        # only self-describing records on OUR network enter the table
        # (same eth2-field filter as the client side); our reply never
        # depends on the caller's record decoding
        if (remote is not None and remote.peer_id == src
                and self._admissible(remote)):
            with self._table_lock:
                self.table.insert(remote)
        return [self.enr.to_bytes()]

    def _serve_findnode(self, src: str, data: bytes) -> list[bytes]:
        target = data[:32]
        with self._table_lock:
            return [e.to_bytes() for e in self.table.closest(target)]

    # -- client side --------------------------------------------------------

    def ping(self, peer: str) -> Enr | None:
        try:
            chunks = self.rpc.request(
                peer, P_DISCOVERY_PING, self.enr.to_bytes())
        except RpcError:
            with self._table_lock:
                self.table.remove(
                    hashlib.sha256(peer.encode()).digest())
            return None
        if not chunks:
            return None
        remote = Enr.try_from_bytes(chunks[0])
        if remote is None:
            return None
        # only table peers on our network (the eth2 ENR-field filter the
        # reference applies before dialing, discovery/enr_ext.rs)
        if self._admissible(remote):
            with self._table_lock:
                self.table.insert(remote)
        return remote

    def find_node(self, peer: str, target: bytes) -> list[Enr]:
        try:
            chunks = self.rpc.request(peer, P_DISCOVERY_FINDNODE, target)
        except RpcError:
            return []
        # drop chunks a faulted/Byzantine peer mangled — the soak's
        # malformed plane XORs response prefixes, and a real network's
        # FINDNODE answers deserve no more trust
        found = (Enr.try_from_bytes(c) for c in chunks)
        return [e for e in found if e is not None]

    def lookup(self, target: bytes | None = None,
               max_rounds: int = 8) -> list[Enr]:
        """Recursive FINDNODE toward `target` (default: self — the
        discv5 self-lookup that populates the table)."""
        target = target if target is not None else self.enr.node_id
        queried: set[str] = set()
        with self._table_lock:
            candidates = {e.node_id: e for e in self.table.closest(target)}
        for _ in range(max_rounds):
            frontier = sorted(
                (e for e in candidates.values() if e.peer_id not in queried),
                key=lambda e: xor_distance(e.node_id, target),
            )[:LOOKUP_PARALLELISM]
            if not frontier:
                break
            for enr in frontier:
                queried.add(enr.peer_id)
                for found in self.find_node(enr.peer_id, target):
                    if not self._admissible(found):
                        continue
                    with self._table_lock:
                        self.table.insert(found)
                    candidates.setdefault(found.node_id, found)
        with self._table_lock:
            return self.table.closest(target)

    def bootstrap(self, bootnode_peer: str) -> int:
        """Dial a bootnode, then self-lookup to fill the table.  Returns
        the number of known peers after bootstrap."""
        if self.ping(bootnode_peer) is None:
            return len(self.table)
        self.lookup()
        return len(self.table)


class BootNode:
    """Standalone discovery-only node (reference boot_node/): joins the
    fabric, answers PING/FINDNODE, serves no chain data."""

    def __init__(self, fabric, peer_id: str = "boot-node",
                 fork_digest: bytes = b"\x00\x00\x00\x00"):
        node = getattr(fabric, "node", None)
        if node is not None:
            peer_id = node.peer_id        # socket fabric: key-derived id
        self.rpc_ep = (getattr(fabric, "discovery_ep", None)
                       or fabric.rpc.join(peer_id))
        enr = Enr(peer_id=peer_id, fork_digest=fork_digest)
        if node is not None:
            enr.ip = node.listen_host
            enr.port = fabric.listen_port
            enr.sign(node.identity)
        self.discovery = Discovery(self.rpc_ep, enr)

    @property
    def peer_id(self) -> str:
        return self.discovery.enr.peer_id

    def known_peers(self) -> int:
        return len(self.discovery.table)


__all__ = [
    "BootNode",
    "BUCKET_SIZE",
    "Discovery",
    "Enr",
    "RoutingTable",
    "log2_distance",
    "xor_distance",
]
