"""Snappy compression: block format + framing format, from scratch.

The eth2 wire protocol compresses gossip payloads with the snappy BLOCK
format and req/resp payloads with the snappy FRAME format
(/root/reference/beacon_node/lighthouse_network/src/rpc/codec/ssz_snappy.rs:1,
via the `snap` crate).  No snappy library ships in this environment, so
this module implements both:

- the DECOMPRESSOR handles the full tag set (literals + all three copy
  element widths), i.e. it decodes streams from any conformant encoder;
- the COMPRESSOR runs the standard greedy hash-table matcher over
  4-byte anchors (copy1/copy2 emission, skip acceleration on
  incompressible input);
- the frame format carries masked CRC32C checksums per chunk, verified
  on decode (the spec's crc32c(data) mask/rotate), shipping each chunk
  compressed when that wins.
"""

from __future__ import annotations

import struct

MAX_FRAME_DATA = 65536  # max uncompressed bytes per frame chunk
_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"


class SnappyError(ValueError):
    pass


# --- CRC32C (Castagnoli, reflected poly 0x82F63B78) -------------------------

def _make_table():
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- varint ------------------------------------------------------------------

def uvarint_encode(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def uvarint_decode(data: bytes, offset: int = 0) -> tuple[int, int]:
    """Returns (value, next_offset)."""
    shift = 0
    value = 0
    while True:
        if offset >= len(data):
            raise SnappyError("truncated varint")
        b = data[offset]
        offset += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, offset
        shift += 7
        if shift > 63:
            raise SnappyError("varint too long")


# --- block format ------------------------------------------------------------

def _emit_literal(out: bytearray, data: bytes, start: int, end: int):
    while start < end:
        chunk_end = min(end, start + (1 << 24))  # 3-byte length bound
        ln = chunk_end - start - 1
        if ln < 60:
            out.append(ln << 2)
        elif ln < (1 << 8):
            out.append(60 << 2)
            out += struct.pack("<B", ln)
        elif ln < (1 << 16):
            out.append(61 << 2)
            out += struct.pack("<H", ln)
        else:
            out.append(62 << 2)
            out += struct.pack("<I", ln)[:3]
        out += data[start:chunk_end]
        start = chunk_end


def _emit_copy(out: bytearray, offset: int, length: int):
    # copy1 for short near matches (len 4-11, offset < 2048), copy2
    # chunks of <= 64 otherwise (copy2 expresses any length >= 1, so
    # remainders never strand; same offset per chunk keeps overlapping
    # pattern-repeat semantics)
    while length > 0:
        if 4 <= length <= 11 and offset < 2048:
            out.append(((offset >> 8) << 5) | ((length - 4) << 2) | 1)
            out.append(offset & 0xFF)
            return
        step = min(length, 64)
        out.append(((step - 1) << 2) | 2)
        out += struct.pack("<H", offset)
        length -= step


def compress_block(data: bytes) -> bytes:
    """Snappy block compression with hash-table match finding (the
    standard greedy matcher over 4-byte anchors; the decoder is the
    conformance oracle — tests roundtrip both paths)."""
    n = len(data)
    out = bytearray(uvarint_encode(n))
    if n == 0:
        return bytes(out)
    if n < 16:
        _emit_literal(out, data, 0, n)
        return bytes(out)

    table: dict[bytes, int] = {}
    i = 0
    lit_start = 0
    limit = n - 4
    misses = 0          # skip acceleration: incompressible regions stride
    while i <= limit:
        key = data[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF:
            # the dict is keyed by the literal bytes: a hit IS a match
            m = i + 4
            c = cand + 4
            while m < n and data[m] == data[c]:
                m += 1
                c += 1
            if lit_start < i:
                _emit_literal(out, data, lit_start, i)
            _emit_copy(out, i - cand, m - i)
            i = m
            lit_start = m
            misses = 0
        else:
            misses += 1
            i += 1 + (misses >> 5)   # reference snappy's growing stride
    if lit_start < n:
        _emit_literal(out, data, lit_start, n)
    return bytes(out)


def decompress_block(data: bytes, max_len: int | None = None) -> bytes:
    """Full block-format decoder (literals + copy1/2/4)."""
    expected, i = uvarint_decode(data)
    if max_len is not None and expected > max_len:
        raise SnappyError(f"declared length {expected} > limit {max_len}")
    out = bytearray()
    n = len(data)
    while i < n:
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:                      # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if i + extra > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[i:i + extra], "little")
                i += extra
            ln += 1
            if i + ln > n:
                raise SnappyError("truncated literal")
            out += data[i:i + ln]
            i += ln
        else:                              # copy
            if kind == 1:
                if i >= n:
                    raise SnappyError("truncated copy1")
                ln = ((tag >> 2) & 0x7) + 4
                off = ((tag >> 5) << 8) | data[i]
                i += 1
            elif kind == 2:
                if i + 2 > n:
                    raise SnappyError("truncated copy2")
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[i:i + 2], "little")
                i += 2
            else:
                if i + 4 > n:
                    raise SnappyError("truncated copy4")
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[i:i + 4], "little")
                i += 4
            if off == 0 or off > len(out):
                raise SnappyError("copy offset out of range")
            # overlapping copies are defined byte-by-byte
            for _ in range(ln):
                out.append(out[-off])
        if len(out) > expected:
            raise SnappyError("output exceeds declared length")
    if len(out) != expected:
        raise SnappyError(
            f"declared {expected} bytes, produced {len(out)}")
    return bytes(out)


# --- framing format ----------------------------------------------------------

def frame_compress(data: bytes) -> bytes:
    """Snappy framing-format stream: stream id + per-chunk masked
    CRC32C; each ≤65536-byte chunk ships block-compressed (type 0x00)
    when that wins, raw (type 0x01) otherwise."""
    out = bytearray(_STREAM_ID)
    offsets = range(0, len(data), MAX_FRAME_DATA) if data else (0,)
    for i in offsets:
        chunk = data[i:i + MAX_FRAME_DATA]
        crc = struct.pack("<I", _masked_crc(chunk))
        packed = compress_block(chunk)
        if len(packed) < len(chunk):
            ctype, payload = 0x00, packed
        else:
            ctype, payload = 0x01, chunk
        out.append(ctype)
        out += struct.pack("<I", 4 + len(payload))[:3]
        out += crc + payload
    return bytes(out)


def frame_decompress(data: bytes, max_len: int | None = None) -> bytes:
    """Decode a framing-format stream (compressed + uncompressed chunks,
    skippable chunks ignored), verifying each chunk's CRC32C."""
    out = bytearray()
    i = 0
    n = len(data)
    seen_stream_id = False
    while i < n:
        if i + 4 > n:
            raise SnappyError("truncated chunk header")
        ctype = data[i]
        clen = int.from_bytes(data[i + 1:i + 4], "little")
        i += 4
        if i + clen > n:
            raise SnappyError("truncated chunk body")
        body = data[i:i + clen]
        i += clen
        if ctype == 0xFF:
            if body != b"sNaPpY":
                raise SnappyError("bad stream identifier")
            seen_stream_id = True
            continue
        if not seen_stream_id:
            raise SnappyError("chunk before stream identifier")
        if ctype == 0x00 or ctype == 0x01:
            if clen < 4:
                raise SnappyError("chunk too short for checksum")
            want_crc = int.from_bytes(body[:4], "little")
            payload = body[4:]
            if ctype == 0x00:
                payload = decompress_block(payload, max_len=MAX_FRAME_DATA)
            elif len(payload) > MAX_FRAME_DATA:
                # framing format caps uncompressed chunk payloads at 65536
                raise SnappyError("uncompressed chunk exceeds 65536 bytes")
            if _masked_crc(payload) != want_crc:
                raise SnappyError("chunk checksum mismatch")
            out += payload
            if max_len is not None and len(out) > max_len:
                raise SnappyError("frame stream exceeds limit")
        elif 0x80 <= ctype <= 0xFE:
            continue                       # skippable
        else:
            raise SnappyError(f"unknown unskippable chunk 0x{ctype:02x}")
    return bytes(out)
