"""Noise_XX_25519_ChaChaPoly_SHA256 channel security for the wire stack.

The reference authenticates every libp2p connection with the Noise XX
handshake over the node's identity key
(/root/reference/beacon_node/lighthouse_network/src/service/utils.rs:40-56);
this module is the same capability built directly on the Noise spec
(rev 34) with the `cryptography` primitives:

- X25519 ephemeral + static Diffie-Hellman, HKDF-SHA256 key chaining,
  ChaCha20-Poly1305 AEAD with the Noise nonce layout (4 zero bytes +
  64-bit little-endian counter).
- XX pattern:  -> e   <- e, ee, s, es   -> s, se.  Both static keys are
  transmitted encrypted and are mutually authenticated by the `es`/`se`
  DH results; the final handshake hash `h` binds the full transcript.
- libp2p-style identity binding: each node holds an Ed25519 identity
  key; its peer id IS the fingerprint of that public key.  The HELLO
  payload (sent over the encrypted channel) carries the identity public
  key and a signature over the Noise static key, so a peer cannot claim
  an identity whose private key it does not hold — the same binding the
  reference's noise payload makes between the libp2p identity key and
  the Noise static key.

Everything here is host-side session crypto — tiny, latency-bound, and
per-connection — so it stays off the device on purpose; the TPU planes
are for the bulk verification math in ops/.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    CRYPTO_BACKEND = "cryptography"
except ImportError:
    # containers without the wheel still get the identical wire
    # protocol from the RFC-pinned pure-Python fallback (purecrypto
    # docstring); the wheel wins whenever it is importable
    from lighthouse_tpu.network.wire.purecrypto import (
        ChaCha20Poly1305,
        Ed25519PrivateKey,
        Ed25519PublicKey,
        InvalidSignature,
        X25519PrivateKey,
        X25519PublicKey,
    )

    CRYPTO_BACKEND = "purecrypto"

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
DHLEN = 32
TAGLEN = 16
# domain separator for the identity->static-key binding signature
BINDING_PREFIX = b"lighthouse-tpu-noise-static-key:"


class NoiseError(Exception):
    pass


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hkdf(chaining_key: bytes, ikm: bytes, n: int) -> tuple[bytes, ...]:
    """Noise-spec HKDF: HMAC-SHA256 extract + n expand rounds (n in 2,3)."""
    temp = _hmac.new(chaining_key, ikm, hashlib.sha256).digest()
    out1 = _hmac.new(temp, b"\x01", hashlib.sha256).digest()
    out2 = _hmac.new(temp, out1 + b"\x02", hashlib.sha256).digest()
    if n == 2:
        return out1, out2
    out3 = _hmac.new(temp, out2 + b"\x03", hashlib.sha256).digest()
    return out1, out2, out3


class CipherState:
    """AEAD key + nonce counter (Noise spec §5.1); the AEAD object is
    built once per key — this sits on the per-frame transport path."""

    def __init__(self, key: bytes | None = None):
        self.k = key
        self.n = 0
        self._aead = ChaCha20Poly1305(key) if key is not None else None

    def _nonce(self) -> bytes:
        return b"\x00\x00\x00\x00" + self.n.to_bytes(8, "little")

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self._aead is None:
            return plaintext
        if self.n >= (1 << 64) - 1:
            raise NoiseError("nonce exhausted")
        ct = self._aead.encrypt(self._nonce(), plaintext, ad)
        self.n += 1
        return ct

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self._aead is None:
            return ciphertext
        if self.n >= (1 << 64) - 1:
            raise NoiseError("nonce exhausted")
        try:
            pt = self._aead.decrypt(self._nonce(), ciphertext, ad)
        except Exception as e:          # cryptography raises InvalidTag
            raise NoiseError("AEAD authentication failed") from e
        self.n += 1
        return pt


class SymmetricState:
    """Chaining key + handshake hash (Noise spec §5.2)."""

    def __init__(self):
        # len(PROTOCOL_NAME) == 32 == HASHLEN, so h = the name itself
        self.h = PROTOCOL_NAME
        self.ck = PROTOCOL_NAME
        self.cipher = CipherState()

    def mix_key(self, ikm: bytes):
        self.ck, temp_k = _hkdf(self.ck, ikm, 2)
        self.cipher = CipherState(temp_k)

    def mix_hash(self, data: bytes):
        self.h = _sha256(self.h + data)

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple[CipherState, CipherState]:
        k1, k2 = _hkdf(self.ck, b"", 2)
        return CipherState(k1), CipherState(k2)


def _dh(priv: X25519PrivateKey, pub_bytes: bytes) -> bytes:
    return priv.exchange(X25519PublicKey.from_public_bytes(pub_bytes))


def _pub_bytes(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes_raw()


class NoiseXX:
    """One XX handshake; drive with read_message/write_message in pattern
    order, then take (send, recv, handshake_hash, remote_static)."""

    def __init__(self, initiator: bool,
                 static: X25519PrivateKey | None = None):
        self.initiator = initiator
        self.s = static or X25519PrivateKey.generate()
        self.e: X25519PrivateKey | None = None
        self.rs: bytes | None = None     # remote static pub (authenticated)
        self.re: bytes | None = None
        self.ss = SymmetricState()
        self.ss.mix_hash(b"")            # empty prologue
        self._msg = 0

    @property
    def static_pub(self) -> bytes:
        return _pub_bytes(self.s)

    # -- message 1: -> e ----------------------------------------------------

    def write_msg1(self, payload: bytes = b"") -> bytes:
        assert self.initiator and self._msg == 0
        self.e = X25519PrivateKey.generate()
        e_pub = _pub_bytes(self.e)
        self.ss.mix_hash(e_pub)
        out = e_pub + self.ss.encrypt_and_hash(payload)
        self._msg = 1
        return out

    def read_msg1(self, msg: bytes) -> bytes:
        assert not self.initiator and self._msg == 0
        if len(msg) < DHLEN:
            raise NoiseError("short handshake message 1")
        self.re = msg[:DHLEN]
        self.ss.mix_hash(self.re)
        payload = self.ss.decrypt_and_hash(msg[DHLEN:])
        self._msg = 1
        return payload

    # -- message 2: <- e, ee, s, es -----------------------------------------

    def write_msg2(self, payload: bytes = b"") -> bytes:
        assert not self.initiator and self._msg == 1
        self.e = X25519PrivateKey.generate()
        e_pub = _pub_bytes(self.e)
        self.ss.mix_hash(e_pub)
        self.ss.mix_key(_dh(self.e, self.re))            # ee
        s_ct = self.ss.encrypt_and_hash(self.static_pub)  # s
        self.ss.mix_key(_dh(self.s, self.re))            # es (resp: s, re)
        out = e_pub + s_ct + self.ss.encrypt_and_hash(payload)
        self._msg = 2
        return out

    def read_msg2(self, msg: bytes) -> bytes:
        assert self.initiator and self._msg == 1
        if len(msg) < DHLEN + DHLEN + TAGLEN:
            raise NoiseError("short handshake message 2")
        self.re = msg[:DHLEN]
        self.ss.mix_hash(self.re)
        self.ss.mix_key(_dh(self.e, self.re))            # ee
        self.rs = self.ss.decrypt_and_hash(
            msg[DHLEN:DHLEN + DHLEN + TAGLEN])           # s
        self.ss.mix_key(_dh(self.e, self.rs))            # es (init: e, rs)
        payload = self.ss.decrypt_and_hash(msg[DHLEN + DHLEN + TAGLEN:])
        self._msg = 2
        return payload

    # -- message 3: -> s, se --------------------------------------------------

    def write_msg3(self, payload: bytes = b"") -> bytes:
        assert self.initiator and self._msg == 2
        s_ct = self.ss.encrypt_and_hash(self.static_pub)  # s
        self.ss.mix_key(_dh(self.s, self.re))            # se (init: s, re)
        out = s_ct + self.ss.encrypt_and_hash(payload)
        self._msg = 3
        return out

    def read_msg3(self, msg: bytes) -> bytes:
        assert not self.initiator and self._msg == 2
        if len(msg) < DHLEN + TAGLEN:
            raise NoiseError("short handshake message 3")
        self.rs = self.ss.decrypt_and_hash(msg[:DHLEN + TAGLEN])  # s
        self.ss.mix_key(_dh(self.e, self.rs))            # se (resp: e, rs)
        payload = self.ss.decrypt_and_hash(msg[DHLEN + TAGLEN:])
        self._msg = 3
        return payload

    # -- transport ------------------------------------------------------------

    def finalize(self) -> tuple[CipherState, CipherState, bytes]:
        """Returns (send_cipher, recv_cipher, handshake_hash)."""
        if self._msg != 3:
            raise NoiseError("handshake incomplete")
        c1, c2 = self.ss.split()
        if self.initiator:
            return c1, c2, self.ss.h
        return c2, c1, self.ss.h


# --- identity: Ed25519 key, fingerprint peer ids, static-key binding ---------

def generate_identity(seed: bytes | None = None) -> Ed25519PrivateKey:
    """A node identity key; pass a 32-byte seed for deterministic tests."""
    if seed is None:
        return Ed25519PrivateKey.generate()
    if len(seed) != 32:
        seed = _sha256(seed)
    return Ed25519PrivateKey.from_private_bytes(seed)


def identity_pub(identity: Ed25519PrivateKey) -> bytes:
    return identity.public_key().public_bytes_raw()


def peer_id_of(identity_pub_bytes: bytes) -> str:
    """Peer id = fingerprint of the identity public key (libp2p PeerId
    analogue): the only unforgeable name for a node."""
    return _sha256(identity_pub_bytes)[:16].hex()


def sign_static_binding(identity: Ed25519PrivateKey,
                        noise_static_pub: bytes) -> bytes:
    return identity.sign(BINDING_PREFIX + noise_static_pub)


def verify_static_binding(identity_pub_bytes: bytes, noise_static_pub: bytes,
                          signature: bytes) -> bool:
    try:
        Ed25519PublicKey.from_public_bytes(identity_pub_bytes).verify(
            signature, BINDING_PREFIX + noise_static_pub)
        return True
    except (InvalidSignature, ValueError):
        return False


def sign_enr(identity: Ed25519PrivateKey, content: bytes) -> bytes:
    return identity.sign(b"lighthouse-tpu-enr:" + content)


def verify_enr(identity_pub_bytes: bytes, content: bytes,
               signature: bytes) -> bool:
    try:
        Ed25519PublicKey.from_public_bytes(identity_pub_bytes).verify(
            signature, b"lighthouse-tpu-enr:" + content)
        return True
    except (InvalidSignature, ValueError):
        return False


def new_random_static() -> X25519PrivateKey:
    return X25519PrivateKey.generate()
