"""Gossipsub mesh machinery: heartbeat graft/prune, IHAVE/IWANT lazy
gossip, and per-topic peer scoring.

Rebuild of the reference's vendored gossipsub behaviour at this
framework's altitude (/root/reference/beacon_node/lighthouse_network/
gossipsub/src/behaviour.rs:2098 `heartbeat`, and the eth2 scoring
parameters in src/service/gossipsub_scoring_parameters.rs):

- Each subscribed topic keeps a **mesh** — the D peers full messages are
  eagerly pushed to.  A once-per-second heartbeat grafts random eligible
  peers when the mesh is under D_LOW, and prunes the worst-scored peers
  when over D_HIGH (score ties broken randomly, exactly the pressure
  direction the reference applies).
- A windowed **message cache** (mcache) holds recent full messages; the
  heartbeat advances the window and announces the last GOSSIP_WINDOW
  worth of message ids to D_LAZY non-mesh subscribers (IHAVE).  A peer
  missing a message answers with IWANT and receives the full payload —
  the lazy pull path that heals mesh partitions.
- **Per-topic scoring** (P1 time-in-mesh, P2 first-deliveries, P3 mesh
  delivery deficit, P4 invalid messages) aggregates into a peer score;
  negative peers are pruned from meshes and refused GRAFT, and the
  existing peer-manager ban gate consumes the same signal.

The engine is transport-agnostic: `WireNode` feeds it events (peer
connect/disconnect, subscription changes, message arrivals, control
frames) and supplies async send callbacks; all state mutation happens on
the wire node's asyncio loop thread.
"""

from __future__ import annotations

import random
import time
from collections import OrderedDict
from typing import Callable

# gossipsub v1.1 mainnet-ish parameters (behaviour.rs defaults)
D = 8                 # mesh target
D_LOW = 6             # graft below
D_HIGH = 12           # prune above
D_LAZY = 6            # IHAVE fanout per heartbeat
HEARTBEAT_S = 1.0
MCACHE_LEN = 5        # history windows kept
GOSSIP_WINDOW = 3     # windows announced in IHAVE
MAX_IHAVE_IDS = 5000
MAX_IWANT_IDS = 500
IWANT_SERVE_BUDGET = 1000     # full messages served per peer per heartbeat
IWANT_RETRANSMIT = 3          # times one message is re-served to one peer
PRUNE_BACKOFF_S = 60.0
PX_PEERS = 16                 # peer-exchange sample attached to PRUNE
# minimum sender score before px records are DIALED: strictly positive,
# so the pruner must have delivered scored-valid traffic first — a fresh
# (score 0) or negative peer cannot steer our outbound dials
PX_DIAL_SCORE = 1.0
GOSSIP_FACTOR = 0.25          # adaptive IHAVE fanout share of non-mesh
# opportunistic grafting (behaviour.rs:2305): when the mesh's median
# score stagnates below the threshold, graft a couple of better-scored
# outsiders to break a low-quality (or eclipse-captured) mesh
OPPORTUNISTIC_GRAFT_TICKS = 60
OPPORTUNISTIC_GRAFT_PEERS = 2
OPPORTUNISTIC_GRAFT_THRESHOLD = 1.0

# scoring weights (shaped like gossipsub_scoring_parameters.rs, scaled
# to this engine's units)
W_TIME_IN_MESH = 0.01         # per second, capped
TIME_IN_MESH_CAP = 300.0
W_FIRST_DELIVERY = 1.0
FIRST_DELIVERY_CAP = 100.0
W_MESH_DEFICIT = -1.0         # squared deficit vs expected deliveries
# a mesh peer should relay at least this share of the topic's ACTUAL
# traffic while it is in the mesh; tying the expectation to observed
# traffic (not wall clock) keeps quiet topics (a block every 12s, idle
# subnets) from penalizing healthy peers — the same role as the
# reference's mesh_message_deliveries activation/decay parameters
MESH_DELIVERY_SHARE = 0.25
MESH_ACTIVATION_MSGS = 4      # grace: no deficit until this much traffic
MESH_DEFICIT_CAP = 16.0       # bound the per-topic deficit window
W_INVALID = -10.0
SCORE_PRUNE = -4.0            # below: pruned from mesh, GRAFT refused
SCORE_GRAYLIST = -16.0        # below: all gossip from peer ignored


class TopicScore:
    """Per-peer per-topic counters (behaviour.rs peer_score topic stats)."""

    __slots__ = ("mesh_since", "first_deliveries", "mesh_deliveries",
                 "invalid", "topic_msgs_at_join")

    def __init__(self):
        self.mesh_since: float | None = None
        self.first_deliveries = 0.0
        self.mesh_deliveries = 0.0
        self.invalid = 0.0
        self.topic_msgs_at_join = 0

    def value(self, now: float, topic_msgs: int = 0) -> float:
        s = 0.0
        if self.mesh_since is not None:
            s += W_TIME_IN_MESH * min(now - self.mesh_since,
                                      TIME_IN_MESH_CAP)
        s += W_FIRST_DELIVERY * min(self.first_deliveries,
                                    FIRST_DELIVERY_CAP)
        if self.mesh_since is not None:
            # deficit vs the topic's OBSERVED traffic while in mesh
            window = topic_msgs - self.topic_msgs_at_join
            if window > MESH_ACTIVATION_MSGS:
                expected = min(MESH_DELIVERY_SHARE
                               * (window - MESH_ACTIVATION_MSGS),
                               MESH_DEFICIT_CAP)
                deficit = max(0.0, expected - self.mesh_deliveries)
                s += W_MESH_DEFICIT * deficit * deficit
        s += W_INVALID * self.invalid
        return s


class MessageCache:
    """Windowed recent-message store (mcache.rs): put() on arrival,
    shift() each heartbeat, gossip_ids() for IHAVE."""

    def __init__(self, history: int = MCACHE_LEN,
                 gossip_window: int = GOSSIP_WINDOW):
        self.windows: list[list[tuple[str, bytes]]] = [
            [] for _ in range(history)]
        self.msgs: dict[bytes, tuple[str, bytes]] = {}   # id -> (topic, data)
        self.gossip_window = gossip_window

    def put(self, mid: bytes, topic: str, data: bytes):
        if mid in self.msgs:
            return
        self.msgs[mid] = (topic, data)
        self.windows[0].append((topic, mid))

    def get(self, mid: bytes) -> tuple[str, bytes] | None:
        return self.msgs.get(mid)

    def gossip_ids(self, topic: str) -> list[bytes]:
        out = []
        for w in self.windows[:self.gossip_window]:
            out.extend(m for t, m in w if t == topic)
        return out[:MAX_IHAVE_IDS]

    def shift(self):
        dropped = self.windows.pop()
        self.windows.insert(0, [])
        for _, mid in dropped:
            self.msgs.pop(mid, None)


class GossipsubEngine:
    """Mesh + scoring + lazy-gossip state machine.

    The owner wires in:
      send_graft/send_prune/send_ihave/send_iwant/send_msg — async
        callbacks (peer_id, ...) that emit control/data frames;
      peers_on_topic(topic) -> set[str] — connected peers subscribed;
      on_score(peer_id, score) — scoring feed (peer-manager ban gate).
    """

    def __init__(self, local_id: str, rng: random.Random | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.local_id = local_id
        self.clock = clock
        self.rng = rng or random.Random()
        self.mesh: dict[str, set[str]] = {}              # topic -> peers
        self.topic_msgs: dict[str, int] = {}             # topic -> count
        self.scores: dict[str, dict[str, TopicScore]] = {}  # peer->topic->
        self.mcache = MessageCache()
        self.backoff: dict[tuple[str, str], float] = {}  # (peer,topic)->until
        self.iwant_budget: dict[str, int] = {}           # peer -> ids left
        self.iwant_serve: dict[str, int] = {}            # peer -> serves left
        self._retransmits: dict[tuple[str, bytes], int] = {}
        # delivery bookkeeping: which peers already delivered an id
        self._delivered: dict[bytes, set[str]] = {}
        self._delivered_order: OrderedDict[bytes, None] = OrderedDict()
        # owner callbacks (set after construction)
        self.send_graft = None
        self.send_prune = None
        self.send_ihave = None
        self.send_iwant = None
        self.send_msg = None
        self.peers_on_topic: Callable[[str], set[str]] = lambda t: set()
        self.on_score: Callable[[str, float], None] | None = None

    # -- scoring -------------------------------------------------------------

    def _tscore(self, peer: str, topic: str) -> TopicScore:
        return self.scores.setdefault(peer, {}).setdefault(
            topic, TopicScore())

    def score(self, peer: str) -> float:
        now = self.clock()
        return sum(ts.value(now, self.topic_msgs.get(topic, 0))
                   for topic, ts in self.scores.get(peer, {}).items())

    def mark_invalid(self, peer: str, topic: str):
        """Validation failed on a message this peer delivered."""
        self._tscore(peer, topic).invalid += 1.0
        self._push_score(peer)

    def _push_score(self, peer: str):
        if self.on_score is not None:
            self.on_score(peer, self.score(peer))

    def graylisted(self, peer: str) -> bool:
        return self.score(peer) < SCORE_GRAYLIST

    # -- membership ----------------------------------------------------------

    def join(self, topic: str):
        """Local subscribe: build an initial mesh from eligible peers."""
        if topic in self.mesh:
            return
        elig = [p for p in self.peers_on_topic(topic)
                if self.score(p) >= SCORE_PRUNE]
        self.rng.shuffle(elig)
        self.mesh[topic] = set(elig[:D])
        now = self.clock()
        for p in self.mesh[topic]:
            ts = self._tscore(p, topic)
            ts.mesh_since = now
            ts.topic_msgs_at_join = self.topic_msgs.get(topic, 0)
        return list(self.mesh[topic])

    def leave(self, topic: str) -> list[str]:
        """Local unsubscribe: returns peers to PRUNE."""
        peers = list(self.mesh.pop(topic, ()))
        for p in peers:
            ts = self._tscore(p, topic)
            ts.mesh_since = None
        return peers

    def peer_disconnected(self, peer: str):
        for topic, members in self.mesh.items():
            members.discard(peer)
        self.scores.pop(peer, None)
        self.iwant_budget.pop(peer, None)
        self.iwant_serve.pop(peer, None)
        for key in [k for k in self._retransmits if k[0] == peer]:
            del self._retransmits[key]

    # -- inbound control -----------------------------------------------------

    def handle_graft(self, peer: str, topic: str) -> bool:
        """True = accepted; False = caller should PRUNE back."""
        if topic not in self.mesh:
            return False                      # not subscribed
        now = self.clock()
        if self.backoff.get((peer, topic), 0.0) > now:
            return False                      # grafting through backoff
        if self.score(peer) < SCORE_PRUNE:
            return False
        if peer not in self.peers_on_topic(topic):
            return False
        self.mesh[topic].add(peer)
        ts = self._tscore(peer, topic)
        if ts.mesh_since is None:
            ts.mesh_since = now
            ts.topic_msgs_at_join = self.topic_msgs.get(topic, 0)
        return True

    def handle_prune(self, peer: str, topic: str):
        if topic not in self.mesh:
            return             # unknown topic: no state for an attacker
        self.mesh[topic].discard(peer)
        ts = self.scores.get(peer, {}).get(topic)
        if ts is not None:
            ts.mesh_since = None
        self.backoff[(peer, topic)] = self.clock() + PRUNE_BACKOFF_S

    def accept_px(self, peer: str, threshold: float = 0.0) -> bool:
        """Peer-exchange records are only honoured from peers whose score
        clears ``threshold`` (behaviour.rs: px processing gated on the
        prune sender's score) — a peer steering us toward its accomplices
        is the eclipse entry-point.  The transport dials px targets only
        above PX_DIAL_SCORE (strictly positive): every FRESH peer scores
        exactly 0, so a zero threshold would let any just-connected
        stranger direct our dials."""
        return self.score(peer) >= threshold

    def px_for_prune(self, topic: str, exclude: str) -> list[str]:
        """Up to PX_PEERS well-scored topic peers to attach to a PRUNE
        (peer exchange, behaviour.rs:1091,1420): the pruned peer can
        re-mesh elsewhere instead of losing the topic."""
        cands = [p for p in self.peers_on_topic(topic)
                 if p != exclude and p != self.local_id
                 and self.score(p) >= 0.0]
        self.rng.shuffle(cands)
        return cands[:PX_PEERS]

    def handle_ihave(self, peer: str, topic: str,
                     mids: list[bytes],
                     seen: Callable[[bytes], bool]) -> list[bytes]:
        """Returns the ids to IWANT from this peer."""
        if self.graylisted(peer) or topic not in self.mesh:
            return []
        budget = self.iwant_budget.setdefault(peer, MAX_IWANT_IDS)
        want = []
        for mid in mids[:MAX_IHAVE_IDS]:
            if budget <= 0:
                break
            if not seen(mid) and self.mcache.get(mid) is None:
                want.append(mid)
                budget -= 1
        self.iwant_budget[peer] = budget
        return want

    def handle_iwant(self, peer: str,
                     mids: list[bytes]) -> list[tuple[bytes, str, bytes]]:
        """Returns (id, topic, data) for cached messages to send back.

        Bandwidth-amplification guards: a per-peer serve budget per
        heartbeat window, and a cap on how many times one message is
        re-served to the same peer (one small IWANT frame must not be
        able to elicit unbounded full-payload retransmission)."""
        if self.graylisted(peer):
            return []
        budget = self.iwant_serve.setdefault(peer, IWANT_SERVE_BUDGET)
        out = []
        for mid in mids[:MAX_IWANT_IDS]:
            if budget <= 0:
                break
            m = self.mcache.get(mid)
            if m is None:
                continue
            key = (peer, mid)
            sent = self._retransmits.get(key, 0)
            if sent >= IWANT_RETRANSMIT:
                continue
            if len(self._retransmits) > 16384:
                self._retransmits.clear()     # coarse bound; ids expire fast
            self._retransmits[key] = sent + 1
            budget -= 1
            out.append((mid, m[0], m[1]))
        self.iwant_serve[peer] = budget
        return out

    # -- inbound data --------------------------------------------------------

    def on_message(self, src: str | None, topic: str, mid: bytes,
                   data: bytes, first_time: bool):
        """Record a message arrival (src=None for locally published)."""
        self.mcache.put(mid, topic, data)
        if first_time:
            self.topic_msgs[topic] = self.topic_msgs.get(topic, 0) + 1
        if src is None:
            return
        delivered = self._delivered.get(mid)
        if delivered is None:
            delivered = self._delivered[mid] = set()
            self._delivered_order[mid] = None
            while len(self._delivered_order) > 8192:
                old, _ = self._delivered_order.popitem(last=False)
                self._delivered.pop(old, None)
        if src in delivered:
            return
        delivered.add(src)
        ts = self._tscore(src, topic)
        if first_time:
            ts.first_deliveries += 1
        if src in self.mesh.get(topic, ()):
            ts.mesh_deliveries += 1

    def eager_targets(self, topic: str, exclude: set[str]) -> list[str]:
        """Mesh peers to push a full message to (fanout for unsubscribed
        topics: random D from the subscriber set)."""
        members = self.mesh.get(topic)
        if members is None:
            cands = [p for p in self.peers_on_topic(topic)
                     if p not in exclude and not self.graylisted(p)]
            self.rng.shuffle(cands)
            return cands[:D]
        return [p for p in members
                if p not in exclude and not self.graylisted(p)]

    # -- heartbeat -----------------------------------------------------------

    def heartbeat(self) -> dict:
        """One tick: maintain meshes, emit IHAVE plan, advance mcache.

        Returns {"graft": [(peer, topic)], "prune": [(peer, topic)],
                 "ihave": [(peer, topic, [mid, ...])]}.
        """
        now = self.clock()
        self._hb_count = getattr(self, "_hb_count", 0) + 1
        opportunistic = self._hb_count % OPPORTUNISTIC_GRAFT_TICKS == 0
        plan = {"graft": [], "prune": [], "ihave": []}
        # expire backoffs
        for key in [k for k, until in self.backoff.items() if until <= now]:
            del self.backoff[key]
        for topic, members in self.mesh.items():
            on_topic = self.peers_on_topic(topic)
            # lazy gossip FIRST, to the peers outside the mesh as it was
            # when recent messages were (not) pushed — a peer grafted
            # below would otherwise neither have been pushed the message
            # nor hear the IHAVE that lets it IWANT-recover
            mids = self.mcache.gossip_ids(topic)
            if mids:
                lazies = [p for p in on_topic
                          if p not in members and not self.graylisted(p)]
                self.rng.shuffle(lazies)
                # adaptive gossip: fanout grows with the non-mesh
                # population so large topics still hear announcements
                n_lazy = max(D_LAZY, int(GOSSIP_FACTOR * len(lazies)))
                for p in lazies[:n_lazy]:
                    plan["ihave"].append((p, topic, mids))
            # drop peers that fell below the prune threshold or left
            bad = [p for p in members
                   if self.score(p) < SCORE_PRUNE or p not in on_topic]
            for p in bad:
                members.discard(p)
                self._tscore(p, topic).mesh_since = None
                self.backoff[(p, topic)] = now + PRUNE_BACKOFF_S
                if p in on_topic:
                    plan["prune"].append((p, topic))
            # under-populated: graft random eligible non-members
            if len(members) < D_LOW:
                cands = [p for p in on_topic
                         if p not in members
                         and self.score(p) >= SCORE_PRUNE
                         and self.backoff.get((p, topic), 0.0) <= now]
                self.rng.shuffle(cands)
                for p in cands[:D - len(members)]:
                    members.add(p)
                    ts = self._tscore(p, topic)
                    if ts.mesh_since is None:
                        ts.mesh_since = now
                        ts.topic_msgs_at_join = self.topic_msgs.get(topic, 0)
                    plan["graft"].append((p, topic))
            # over-populated: prune worst-scored down to D
            elif len(members) > D_HIGH:
                ranked = sorted(members,
                                key=lambda p: (self.score(p),
                                               self.rng.random()))
                for p in ranked[:len(members) - D]:
                    members.discard(p)
                    self._tscore(p, topic).mesh_since = None
                    self.backoff[(p, topic)] = now + PRUNE_BACKOFF_S
                    plan["prune"].append((p, topic))
            # opportunistic grafting (behaviour.rs:2305-2352): a mesh
            # whose MEDIAN score sits below the threshold is dominated
            # by low-quality (or adversarial) peers that deliver little;
            # periodically graft a couple of outsiders scoring above the
            # median so an eclipse-captured mesh can recover without
            # waiting for every captor to cross the prune floor
            if opportunistic and members:
                med = sorted(self.score(p) for p in members)[
                    len(members) // 2]
                if med < OPPORTUNISTIC_GRAFT_THRESHOLD:
                    cands = [p for p in on_topic
                             if p not in members
                             and self.score(p) > max(med, 0.0)
                             and self.backoff.get((p, topic), 0.0) <= now]
                    self.rng.shuffle(cands)
                    for p in cands[:OPPORTUNISTIC_GRAFT_PEERS]:
                        members.add(p)
                        ts = self._tscore(p, topic)
                        if ts.mesh_since is None:
                            ts.mesh_since = now
                            ts.topic_msgs_at_join = self.topic_msgs.get(
                                topic, 0)
                        plan["graft"].append((p, topic))
        self.mcache.shift()
        # refresh iwant budgets + push scores to the ban gate
        self.iwant_budget.clear()
        self.iwant_serve.clear()
        for peer in list(self.scores):
            self._push_score(peer)
        return plan
