"""Socket transport: asyncio TCP mux + UDP discovery, behind the fabric seams.

`WireFabric` is the drop-in for `network/service.NetworkFabric`: its
`.gossip.join(peer_id)` / `.rpc.join(peer_id)` return endpoints with the
SAME interfaces as the in-process `GossipEndpoint` / `RpcEndpoint`
(subscribe/unsubscribe/publish + register/request), so the router, sync
manager and discovery logic run unchanged over real sockets.  Rebuild of
the reference's libp2p service at this framework's altitude
(/root/reference/beacon_node/lighthouse_network/src/service/mod.rs:112):

- ONE TCP connection per peer pair, length-prefixed binary frames
  multiplexing gossip pushes and RPC request/response streams; RPC
  payloads use the ssz_snappy codec (wire/codec.py), gossip payloads the
  snappy block format — the reference codec's framing
  (rpc/codec/ssz_snappy.rs:1).
- Gossip is real gossipsub (wire/gossipsub.py): per-topic meshes
  maintained by a 1 Hz heartbeat (graft under D_LOW, prune worst-scored
  over D_HIGH), IHAVE/IWANT lazy gossip from a windowed message cache,
  flood-publish for locally-originated messages, and per-topic peer
  scoring feeding the ban gate (.../gossipsub/src/behaviour.rs:2098);
  the seen-cache stops forwarding loops.
- Discovery is ping/findnode over UDP datagrams (discv5's transport
  shape, .../src/discovery/mod.rs:1): `WireDiscoveryEndpoint` speaks the
  same `register/request` protocol as the in-process rpc endpoint, so
  network/discovery.py's Enr + k-bucket + lookup logic is reused as-is;
  peer addresses learned from Enrs feed the TCP dialer.

The asyncio loop runs in a daemon thread; the node's (synchronous)
callers block on futures with timeouts.  Everything here is host-side IO
— no device work — so plain asyncio is the right tool (the TPU data
plane stays in ops/).
"""

from __future__ import annotations

import asyncio
import errno
import json
import secrets
import struct
import threading
import time
from typing import Callable

from lighthouse_tpu.common.logging import Logger
from lighthouse_tpu.common.metrics import record_swallowed
from lighthouse_tpu.network.gossip import _SeenCache, message_id
from lighthouse_tpu.network.rpc import (RateLimiter, RequestDiscipline,
                                        RpcError)
from lighthouse_tpu.network.wire import codec, gossipsub, noise

REQUEST_TIMEOUT_S = 10.0
MAX_FRAME = 16 * 1024 * 1024
HANDSHAKE_TIMEOUT_S = 5.0
MAX_HANDSHAKE_FRAME = 4096
# fixed-port bind collisions (N nodes on one host racing a port range):
# walk this many successive ports, then fall back to an ephemeral bind —
# the caller reads the truth back from .listen_port either way
PORT_BIND_RETRIES = 8

# frame kinds
K_HELLO = 0x01
K_SUBSCRIBE = 0x02
K_UNSUBSCRIBE = 0x03
K_GOSSIP = 0x04
K_RPC_REQ = 0x05
K_RPC_CHUNK = 0x06
K_RPC_END = 0x07
K_RPC_ERR = 0x08
K_GOODBYE = 0x09
K_GRAFT = 0x0A
K_PRUNE = 0x0B       # compat PRUNE: topic honoured, px tail IGNORED
K_IHAVE = 0x0C
K_IWANT = 0x0D
# PRUNE with peer exchange, under its OWN wire identifier (same
# length-prefixed topic + JSON px body as late K_PRUNE frames).  The
# px-bearing format needs a distinct kind so a peer's capability is
# explicit: px records are only ever DIALED when they arrive under
# K_PRUNE_PX, while compat K_PRUNE frames still prune the topic but
# their px tail is dropped — an un-bumped (or downgrade-spoofing) peer
# cannot steer dials.  Old nodes ignore the unknown kind entirely.
K_PRUNE_PX = 0x0E

MSG_ID_LEN = 20          # gossip.message_id output width


def _pack_mids(mids: list[bytes]) -> bytes:
    return struct.pack("<H", len(mids)) + b"".join(mids)


def _unpack_mids(data: bytes, off: int) -> list[bytes]:
    (n,) = struct.unpack_from("<H", data, off)
    off += 2
    if len(data) < off + n * MSG_ID_LEN:
        raise RpcError("malformed message-id list")
    return [data[off + i * MSG_ID_LEN: off + (i + 1) * MSG_ID_LEN]
            for i in range(n)]


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<H", len(b)) + b


def _unpack_str(data: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<H", data, off)
    off += 2
    return data[off:off + n].decode(), off + n


class _Conn:
    """One live TCP connection to a peer."""

    def __init__(self, reader, writer, outbound: bool = False):
        self.reader = reader
        self.writer = writer
        self.peer_id: str | None = None
        self.topics: set[str] = set()
        self.agent: str = ""                       # their HELLO agent string
        self.addr: tuple[str, int] | None = None   # their LISTEN addr
        self.outbound = outbound                   # we initiated the dial
        self.alive = True
        # Noise session state (set by the handshake before any frame flows)
        self.send_cs: noise.CipherState | None = None
        self.recv_cs: noise.CipherState | None = None
        self.remote_static: bytes | None = None    # authenticated X25519 pub


class WireNode:
    """The per-process socket node: TCP listener + dialer + UDP discovery."""

    def __init__(self, identity_seed: "bytes | str | None" = None,
                 listen_port: int = 0,
                 fork_digest: bytes = b"\x00\x00\x00\x00",
                 listen_host: str = "127.0.0.1",
                 transport: str = "tcp"):
        import concurrent.futures

        if transport not in ("tcp", "quic"):
            raise ValueError(f"unknown transport {transport!r}")
        # "quic" = the QUIC-role UDP stream transport (wire/quic.py);
        # the whole protocol stack above (Noise, HELLO, gossip, RPC)
        # is transport-agnostic and runs unchanged over either
        self.transport = transport

        # Node identity: an Ed25519 key; the peer id IS its fingerprint,
        # so identity cannot be claimed without the private key (libp2p
        # PeerId semantics — reference utils.rs:40).  A seed (str/bytes)
        # gives deterministic test identities; production passes None.
        if isinstance(identity_seed, str):
            identity_seed = identity_seed.encode()
        self.identity = noise.generate_identity(identity_seed)
        self.identity_pub = noise.identity_pub(self.identity)
        self.peer_id = noise.peer_id_of(self.identity_pub)
        # per-node Noise static key, bound to the identity by signature
        self._noise_static = noise.new_random_static()
        self._static_binding = noise.sign_static_binding(
            self.identity,
            self._noise_static.public_key().public_bytes_raw())
        self.fork_digest = fork_digest
        self.listen_host = listen_host
        # handlers run OFF the event loop: block import and RPC serving
        # are heavyweight and may issue nested wire requests (parent
        # lookups) — on the loop thread that deadlocks the loop against
        # its own response frames
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="wire-worker")
        self.listen_port = listen_port      # 0 = ephemeral, read back after start
        self.log = Logger("wire")
        self.loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server = None
        self._udp_transport = None
        self._conns: dict[str, _Conn] = {}           # peer_id -> conn
        self._topics: dict[str, Callable] = {}       # local subscriptions
        # subscribe/unsubscribe mutate from the caller's thread while
        # the wire loop iterates the table for HELLO; single-key gets
        # stay bare (GIL-atomic), whole-table iteration takes the lock
        self._topics_lock = threading.Lock()
        self._rpc_handlers: dict[str, Callable] = {}
        self._rpc_limiter = RateLimiter()
        self._streams: dict[int, dict] = {}          # stream id -> state
        self._next_stream = iter(range(1, 1 << 62))
        self._seen = _SeenCache(capacity=8192)
        # gossipsub mesh machinery: graft/prune + IHAVE/IWANT + scoring
        self._gs = gossipsub.GossipsubEngine(self.peer_id)
        self._gs.peers_on_topic = lambda t: {
            pid for pid, c in self._conns.items()
            if t in c.topics and c.alive}
        self._gs.on_score = lambda peer, score: (
            self.on_gossip_score(peer, score)
            if self.on_gossip_score is not None else None)
        self.on_gossip_score: Callable[[str, float], None] | None = None
        self._udp_waiters: dict[bytes, asyncio.Future] = {}
        self._udp_handlers: dict[str, Callable] = {}
        self.on_delivery_result: Callable[[str, str, bool], None] | None = None
        self.on_peer_connected: Callable[[str], None] | None = None
        self.on_peer_disconnected: Callable[[str], None] | None = None
        # ban gate: return False to refuse a peer at the HELLO door
        # (peer_manager.accept_connection when a NetworkService attaches);
        # called with (peer_id, remote_ip) so IP-collated bans apply
        self.accept_peer: Callable[[str, str], bool] | None = None
        # admin partition seam: peers in this set are refused at the
        # HELLO door AND severed if live — the socket-level mirror of
        # network/partition.PartitionSet (both sides of a severed pair
        # carry the other, so neither direction can re-establish)
        self._blocked: frozenset[str] = frozenset()
        # agent string advertised in HELLO (identify protocol analogue)
        from lighthouse_tpu import __version__ as _v

        self.agent = f"lighthouse_tpu/{_v}"
        self._started = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "WireNode":
        self._thread = threading.Thread(
            target=self._run_loop, name="wire-loop", daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("wire node failed to start")
        return self

    def _run_loop(self):
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        self.loop.run_until_complete(self._start_servers())
        self._started.set()
        try:
            self.loop.run_forever()
        finally:
            self.loop.close()

    async def _start_servers(self):
        # fixed-port binds retry across successive ports before falling
        # back to ephemeral: a multi-node-per-host fleet racing a port
        # base must degrade to "a port", never to a dead node (the
        # caller reads the outcome back from .listen_port)
        port = self.listen_port
        for attempt in range(PORT_BIND_RETRIES + 1):
            try:
                await self._bind_servers(port)
                break
            except OSError as e:
                if e.errno != errno.EADDRINUSE or port == 0:
                    raise
                port = 0 if attempt >= PORT_BIND_RETRIES - 1 else port + 1
        self.log.info("listening", tcp=self.listen_port,
                      udp=self.listen_port)
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def _bind_servers(self, port: int):
        if self.transport == "quic":
            from lighthouse_tpu.network.wire import quic

            # stream frames and UDP discovery share ONE socket: quic's
            # endpoint demuxes by magic byte and hands discovery
            # datagrams through the fallback
            self._server = await quic.start_listener(
                self.listen_host, port,
                lambda r, w: asyncio.ensure_future(self._on_inbound(r, w)),
                fallback=self._on_datagram)
            self.listen_port = self._server.port
            self._udp_transport = self._server._transport
        else:
            self._server = await asyncio.start_server(
                self._on_inbound, self.listen_host, port)
            self.listen_port = self._server.sockets[0].getsockname()[1]
            try:
                self._udp_transport, _ = (
                    await self.loop.create_datagram_endpoint(
                        lambda: _UdpProtocol(self),
                        local_addr=(self.listen_host, self.listen_port)))
            except OSError:
                # TCP landed but the matching UDP port is taken: the
                # pair binds together or not at all (discovery and
                # streams advertise ONE port)
                self._server.close()
                await self._server.wait_closed()
                self._server = None
                raise

    def stop(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self.loop is None:
            return

        async def _shutdown():
            if getattr(self, "_hb_task", None) is not None:
                self._hb_task.cancel()
            for conn in list(self._conns.values()):
                # abort, not close: RST hits the OS socket now, so a
                # peer observes the departure even though this loop is
                # about to die (close() only schedules the FIN, and a
                # stopped loop would never flush it)
                try:
                    conn.writer.transport.abort()
                except Exception:
                    try:
                        conn.writer.close()
                    except Exception as e:
                        record_swallowed("wire.shutdown_close", e)
            if self._server is not None:
                self._server.close()
            if self._udp_transport is not None:
                self._udp_transport.close()
            # one breath for the scheduled connection_lost callbacks to
            # actually release the fds before the loop halts
            await asyncio.sleep(0.05)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self.loop)
            self._thread.join(timeout=5)
        except Exception as e:
            record_swallowed("wire.stop", e)

    def _call(self, coro, timeout=REQUEST_TIMEOUT_S):
        """Run a coroutine on the wire loop from a foreign thread."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # -- connections ---------------------------------------------------------

    async def _on_inbound(self, reader, writer):
        conn = _Conn(reader, writer)
        try:
            await asyncio.wait_for(self._handshake(conn),
                                   HANDSHAKE_TIMEOUT_S)
        except Exception as e:
            self.log.warn("inbound handshake failed", err=str(e))
            writer.close()
            return
        await self._serve_conn(conn)

    # -- noise handshake ------------------------------------------------------

    async def _hs_send(self, conn: _Conn, data: bytes):
        conn.writer.write(struct.pack("<I", len(data)) + data)
        await conn.writer.drain()

    async def _hs_recv(self, conn: _Conn) -> bytes:
        hdr = await conn.reader.readexactly(4)
        (n,) = struct.unpack("<I", hdr)
        if n > MAX_HANDSHAKE_FRAME:
            raise noise.NoiseError(f"oversized handshake frame {n}")
        return await conn.reader.readexactly(n)

    async def _handshake(self, conn: _Conn):
        """Noise XX before anything else flows; a peer that cannot
        complete it never reaches the frame loop (fail-closed)."""
        hs = noise.NoiseXX(initiator=conn.outbound,
                           static=self._noise_static)
        if conn.outbound:
            await self._hs_send(conn, hs.write_msg1())
            hs.read_msg2(await self._hs_recv(conn))
            await self._hs_send(conn, hs.write_msg3())
        else:
            hs.read_msg1(await self._hs_recv(conn))
            await self._hs_send(conn, hs.write_msg2())
            hs.read_msg3(await self._hs_recv(conn))
        conn.send_cs, conn.recv_cs, _hs_hash = hs.finalize()
        conn.remote_static = hs.rs

    async def _dial(self, host: str, port: int) -> str:
        """Open a connection; returns the remote peer id."""
        if self.transport == "quic":
            from lighthouse_tpu.network.wire import quic

            reader, writer = await quic.open_connection(host, port)
        else:
            reader, writer = await asyncio.open_connection(host, port)
        conn = _Conn(reader, writer, outbound=True)
        try:
            await asyncio.wait_for(self._handshake(conn),
                                   HANDSHAKE_TIMEOUT_S)
        except Exception as e:
            writer.close()
            raise RpcError(f"noise handshake with {host}:{port} "
                           f"failed: {e}") from e
        await self._send_hello(conn)
        # the serve loop fills in peer_id on receiving their HELLO
        task = asyncio.ensure_future(self._serve_conn(conn, said_hello=True))
        for _ in range(200):
            if conn.peer_id is not None or task.done():
                break
            await asyncio.sleep(0.025)
        if conn.peer_id is None:
            writer.close()
            raise RpcError(f"handshake with {host}:{port} timed out")
        return conn.peer_id

    def connect(self, host: str, port: int) -> str:
        """Dial a peer (sync facade).  Returns the remote peer id."""
        return self._call(self._dial(host, port))

    async def _send_hello(self, conn: _Conn):
        hello = json.dumps({
            "peer_id": self.peer_id,
            "identity_pub": self.identity_pub.hex(),
            "static_sig": self._static_binding.hex(),
            "fork_digest": self.fork_digest.hex(),
            "topics": self._topic_names(),
            "listen_port": self.listen_port,
            "agent": self.agent,
        }).encode()
        await self._send_frame(conn, bytes([K_HELLO]) + hello)

    async def _send_frame(self, conn: _Conn, frame: bytes):
        # encrypt-then-frame; the counter nonce and the write share one
        # synchronous block, so concurrent senders on the loop cannot
        # reorder ciphertexts relative to their nonces
        ct = conn.send_cs.encrypt_with_ad(b"", frame)
        conn.writer.write(struct.pack("<I", len(ct)) + ct)
        await conn.writer.drain()

    async def _serve_conn(self, conn: _Conn, said_hello: bool = False):
        try:
            if not said_hello and conn.outbound:
                await self._send_hello(conn)
            # inbound connections stay silent until the remote's HELLO
            # passes the accept gate (_on_frame replies there): a banned
            # dialer learns nothing — not even our peer id — and its
            # connect() times out instead of reading a success signal.
            # No deadlock: the OUTBOUND side always speaks first.
            while True:
                hdr = await conn.reader.readexactly(4)
                (n,) = struct.unpack("<I", hdr)
                if n > MAX_FRAME:
                    raise RpcError(f"oversized frame {n}")
                ct = await conn.reader.readexactly(n)
                # AEAD failure (tamper / injection / desync) severs the
                # connection: NoiseError propagates to the finally below
                frame = conn.recv_cs.decrypt_with_ad(b"", ct)
                await self._on_frame(conn, frame)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception as e:
            self.log.warn("connection error", peer=conn.peer_id, err=str(e))
        finally:
            conn.alive = False
            try:
                conn.writer.close()
            except Exception as e:
                record_swallowed("wire.conn_close", e)
            if conn.peer_id and self._conns.get(conn.peer_id) is conn:
                del self._conns[conn.peer_id]
                self._gs.peer_disconnected(conn.peer_id)
                if self.on_peer_disconnected:
                    try:
                        self.on_peer_disconnected(conn.peer_id)
                    except Exception as e:
                        record_swallowed("wire.peer_disconnected_cb", e)

    # -- frame handling ------------------------------------------------------

    def _stream_for(self, conn: _Conn, stream: int) -> dict | None:
        """Stream state iff it belongs to THIS connection: response
        frames only resolve requests actually sent to that peer (stream
        ids are sequential, so any connected peer could guess them)."""
        st = self._streams.get(stream)
        if st is None or st.get("conn") is not conn:
            return None
        return st

    async def _on_frame(self, conn: _Conn, frame: bytes):
        kind = frame[0]
        body = frame[1:]
        if kind != K_HELLO and conn.peer_id is None:
            # no frames before the authenticated HELLO: otherwise a peer
            # could skip the identity binding / ban gate entirely and
            # push gossip or RPC anonymously
            raise RpcError("frame before HELLO")
        if kind == K_HELLO:
            d = json.loads(body)
            if bytes.fromhex(d["fork_digest"]) != self.fork_digest:
                raise RpcError("wrong network (fork digest mismatch)")
            pid = d["peer_id"]
            # authenticate the claimed identity: the Ed25519 key must
            # sign the Noise static key the handshake proved possession
            # of, and the peer id must be that key's fingerprint — a
            # mismatch on either is an impersonation attempt
            ipub = bytes.fromhex(d.get("identity_pub", ""))
            sig = bytes.fromhex(d.get("static_sig", ""))
            if not noise.verify_static_binding(
                    ipub, conn.remote_static, sig):
                raise RpcError("identity binding signature invalid")
            if pid != noise.peer_id_of(ipub):
                raise RpcError("peer id does not match identity key")
            peer_host = conn.writer.get_extra_info("peername")[0]
            if pid in self._blocked or (
                    self.accept_peer is not None
                    and not self.accept_peer(pid, peer_host)):
                # refuse BEFORE exposing peer_id: the dialer's connect()
                # polls conn.peer_id as its success signal.  The blocked
                # set rides the same gate — a partitioned peer's redial
                # dies exactly like a banned one's
                conn.alive = False
                conn.writer.close()
                return
            conn.peer_id = pid
            conn.topics = set(d.get("topics", ()))
            conn.agent = str(d.get("agent", ""))
            conn.addr = (peer_host, int(d.get("listen_port", 0)))
            if not conn.outbound:
                # deferred HELLO reply: an inbound peer only hears from
                # us once its HELLO has passed the gate (see _serve_conn).
                # Sent BEFORE the dedup tie-break below — a simultaneous
                # dialer that loses the tie still deserves the reply its
                # (healthy) dial is polling for
                await self._send_hello(conn)
            old = self._conns.get(conn.peer_id)
            if old is not None and old is not conn and old.alive:
                # simultaneous dial: both sides keep the connection the
                # lexicographically smaller PEER initiated — a direction-
                # based rule both ends compute identically (tiebreaking
                # on local arrival order closes opposite connections and
                # strands both peers)
                keep_outbound = self.peer_id < conn.peer_id
                keep, drop = ((conn, old)
                              if conn.outbound == keep_outbound
                              else (old, conn))
                drop.alive = False
                drop.writer.close()
                if keep is old:
                    return
            self._conns[conn.peer_id] = conn
            if self.on_peer_connected:
                try:
                    self.on_peer_connected(conn.peer_id)
                except Exception as e:
                    record_swallowed("wire.peer_connected_cb", e)
        elif kind == K_SUBSCRIBE:
            conn.topics.add(body.decode())
        elif kind == K_UNSUBSCRIBE:
            conn.topics.discard(body.decode())
        elif kind == K_GOSSIP:
            # a malformed payload penalizes the message/peer, it does NOT
            # sever the connection (gossipsub drops invalid messages)
            topic, off = _unpack_str(body, 0)
            try:
                data = codec.decode_gossip(body[off:])
            except codec.CodecError:
                if self.on_delivery_result is not None:
                    try:
                        self.on_delivery_result(conn.peer_id, topic, False)
                    except Exception as e:
                        record_swallowed("wire.delivery_result_cb", e)
                return
            self._on_gossip(conn.peer_id, topic, data)
        elif kind == K_RPC_REQ:
            (stream,) = struct.unpack_from("<Q", body, 0)
            proto, off = _unpack_str(body, 8)
            try:
                payload = codec.decode_payload(body[off:])
            except codec.CodecError as e:
                await self._send_frame(
                    conn, bytes([K_RPC_ERR]) + struct.pack("<Q", stream)
                    + f"bad request payload: {e}".encode())
                return
            asyncio.ensure_future(
                self._serve_rpc(conn, stream, proto, payload))
        elif kind == K_RPC_CHUNK:
            (stream,) = struct.unpack_from("<Q", body, 0)
            st = self._stream_for(conn, stream)
            try:
                result, chunk = codec.decode_response_chunk(body[8:])
            except codec.CodecError as e:
                # fail the waiting request fast instead of letting the
                # malformed chunk tear down the whole peer connection and
                # the caller ride out the full request timeout
                if st is not None:
                    self._streams.pop(stream, None)
                    if not st["future"].done():
                        st["future"].set_exception(
                            RpcError(f"malformed response chunk: {e}"))
                return
            if st is not None:
                if result == codec.RESP_SUCCESS:
                    st["chunks"].append(chunk)
                else:
                    st["error"] = chunk.decode(errors="replace")
        elif kind == K_RPC_END:
            (stream,) = struct.unpack_from("<Q", body, 0)
            st = self._stream_for(conn, stream)
            if st is not None:
                self._streams.pop(stream, None)
                if not st["future"].done():
                    if st.get("error"):
                        st["future"].set_exception(RpcError(st["error"]))
                    else:
                        st["future"].set_result(st["chunks"])
        elif kind == K_RPC_ERR:
            (stream,) = struct.unpack_from("<Q", body, 0)
            st = self._stream_for(conn, stream)
            if st is not None:
                self._streams.pop(stream, None)
                if not st["future"].done():
                    st["future"].set_exception(
                        RpcError(body[8:].decode(errors="replace")))
        elif kind == K_GRAFT:
            topic = body.decode()
            if not self._gs.handle_graft(conn.peer_id, topic):
                await self._send_frame(
                    conn, self._prune_frame(topic, conn.peer_id))
        elif kind == K_PRUNE:
            # compat PRUNE: prior versions sent length-prefixed topic +
            # px JSON under THIS kind, so parse the same layout — but
            # the px tail is deliberately IGNORED here (dialing
            # attacker-supplied addresses from the un-bumped frame is
            # the hole the K_PRUNE_PX identifier closes)
            try:
                topic, _ = _unpack_str(body, 0)
            except (struct.error, UnicodeDecodeError):
                return
            self._gs.handle_prune(conn.peer_id, topic)
        elif kind == K_PRUNE_PX:
            topic, off = _unpack_str(body, 0)
            self._gs.handle_prune(conn.peer_id, topic)
            # peer exchange (behaviour.rs px handling): re-mesh through
            # the pruner's candidates — only from POSITIVELY-scored peers
            # (a fresh peer scores 0 and must not steer our dials),
            # capacity-, count- and address-gated against eclipse steering
            rest = body[off:]
            if rest and self._gs.accept_px(conn.peer_id,
                                           gossipsub.PX_DIAL_SCORE):
                try:
                    px = json.loads(rest.decode())
                except (ValueError, UnicodeDecodeError):
                    px = []
                if not isinstance(px, list):
                    px = []          # tolerate any malformed px payload
                dialed = 0
                for ent in px[:gossipsub.PX_PEERS]:
                    if dialed >= 2:
                        break
                    try:
                        pid, host, port = ent[0], str(ent[1]), int(ent[2])
                    except (TypeError, ValueError, IndexError):
                        continue
                    if pid == self.peer_id or pid in self._conns:
                        continue
                    if not self._px_target_allowed(host, port):
                        continue
                    dialed += 1
                    asyncio.ensure_future(self._dial_quiet(host, port))
        elif kind == K_IHAVE:
            topic, off = _unpack_str(body, 0)
            mids = _unpack_mids(body, off)
            want = self._gs.handle_ihave(
                conn.peer_id, topic, mids,
                seen=lambda mid: mid in self._seen)
            if want:
                await self._send_frame(
                    conn, bytes([K_IWANT]) + _pack_mids(want))
        elif kind == K_IWANT:
            mids = _unpack_mids(body, 0)
            for mid, topic, data in self._gs.handle_iwant(
                    conn.peer_id, mids):
                await self._send_frame(
                    conn, bytes([K_GOSSIP]) + _pack_str(topic)
                    + codec.encode_gossip(data))
        elif kind == K_GOODBYE:
            conn.writer.close()

    # -- gossip --------------------------------------------------------------

    def _on_gossip(self, src: str, topic: str, data: bytes):
        if self._gs.graylisted(src):
            return                        # scoring floor: ignore entirely
        mid = message_id(topic, data)
        first = self._seen.observe(mid)
        self._gs.on_message(src, topic, mid, data, first_time=first)
        if not first:
            return
        handler = self._topics.get(topic)

        async def run():
            ok = True
            if handler is not None:
                try:
                    await self.loop.run_in_executor(
                        self._pool, handler, topic, data, src)
                except Exception as e:
                    # the sender is downscored via mark_invalid below;
                    # the handler error itself is counted
                    record_swallowed("wire.gossip_handler", e)
                    ok = False
            if not ok:
                self._gs.mark_invalid(src, topic)
            if self.on_delivery_result is not None:
                try:
                    self.on_delivery_result(src, topic, ok)
                except Exception as e:
                    record_swallowed("wire.delivery_result_cb", e)
            # forward valid messages to OUR mesh; invalid messages are
            # NOT propagated (gossipsub validation gating)
            if ok:
                await self._fanout(topic, data, exclude={src})

        asyncio.ensure_future(run())

    async def _fanout(self, topic: str, data: bytes, exclude: set[str],
                      flood: bool = False):
        """flood=True (local publish): push to every subscribed peer —
        gossipsub's flood_publish, which closes the window where the
        mesh hasn't converged around a fresh publisher.  flood=False
        (forwarding): push to the topic mesh only."""
        wire = bytes([K_GOSSIP]) + _pack_str(topic) + codec.encode_gossip(data)
        if flood:
            targets = [p for p in self._gs.peers_on_topic(topic)
                       if p not in exclude and not self._gs.graylisted(p)]
        else:
            targets = self._gs.eager_targets(topic, exclude)
        for pid in targets:
            conn = self._conns.get(pid)
            if conn is None or not conn.alive:
                continue
            try:
                await self._send_frame(conn, wire)
            except Exception as e:
                record_swallowed("wire.fanout_send", e)

    def publish(self, topic: str, data: bytes):
        async def run():
            # observe on the loop thread: _SeenCache is mutated only there
            mid = message_id(topic, data)
            self._seen.observe(mid)
            self._gs.on_message(None, topic, mid, data, first_time=True)
            await self._fanout(topic, data, exclude=set(), flood=True)
        asyncio.run_coroutine_threadsafe(run(), self.loop)

    def _topic_names(self) -> list[str]:
        """Sorted snapshot of the local subscriptions, safe against a
        concurrent subscribe() from another thread."""
        with self._topics_lock:
            return sorted(self._topics)

    def subscribe(self, topic: str, handler: Callable):
        with self._topics_lock:
            self._topics[topic] = handler
        self._announce(K_SUBSCRIBE, topic)
        if self.loop is None:
            # pre-start subscribe (supported everywhere else in this
            # file): no peers exist yet, but the mesh entry must, or
            # inbound GRAFT/IHAVE for the topic are refused forever
            self._gs.join(topic)
        else:
            async def _join():
                for p in (self._gs.join(topic) or ()):
                    conn = self._conns.get(p)
                    if conn is not None and conn.alive:
                        try:
                            await self._send_frame(
                                conn, bytes([K_GRAFT]) + topic.encode())
                        except Exception as e:
                            record_swallowed("wire.graft_send", e)
            asyncio.run_coroutine_threadsafe(_join(), self.loop)

    def unsubscribe(self, topic: str):
        with self._topics_lock:
            self._topics.pop(topic, None)
        self._announce(K_UNSUBSCRIBE, topic)
        if self.loop is not None:
            async def _leave():
                for p in self._gs.leave(topic):
                    conn = self._conns.get(p)
                    if conn is not None and conn.alive:
                        try:
                            await self._send_frame(
                                conn, self._prune_frame(topic, p))
                        except Exception as e:
                            record_swallowed("wire.prune_send", e)
            asyncio.run_coroutine_threadsafe(_leave(), self.loop)

    def _announce(self, kind: int, topic: str):
        if self.loop is None:
            return

        async def _do():
            frame = bytes([kind]) + topic.encode()
            for conn in list(self._conns.values()):
                try:
                    await self._send_frame(conn, frame)
                except Exception as e:
                    record_swallowed("wire.announce_send", e)

        asyncio.run_coroutine_threadsafe(_do(), self.loop)

    async def _heartbeat_loop(self):
        """Once-per-second gossipsub heartbeat (behaviour.rs:2098):
        mesh maintenance (graft/prune) + lazy IHAVE gossip."""
        while True:
            await asyncio.sleep(gossipsub.HEARTBEAT_S)
            try:
                plan = self._gs.heartbeat()
            except Exception as e:
                self.log.warn("heartbeat error", err=str(e))
                continue
            for peer, topic in plan["graft"]:
                await self._send_ctrl(peer, bytes([K_GRAFT])
                                      + topic.encode())
            for peer, topic in plan["prune"]:
                await self._send_ctrl(peer, self._prune_frame(topic, peer))
            for peer, topic, mids in plan["ihave"]:
                await self._send_ctrl(peer, bytes([K_IHAVE])
                                      + _pack_str(topic) + _pack_mids(mids))

    def _prune_frame(self, topic: str, pruned_peer: str) -> bytes:
        """PRUNE with peer exchange: attach (id, host, port) records of
        well-scored topic peers so the pruned side can re-mesh.  Sent
        under K_PRUNE_PX, the length-prefixed format's own identifier
        (K_PRUNE stays the legacy raw-topic frame)."""
        px = []
        for pid in self._gs.px_for_prune(topic, exclude=pruned_peer):
            c = self._conns.get(pid)
            if c is not None and c.alive and c.addr is not None:
                px.append([pid, c.addr[0], c.addr[1]])
        return bytes([K_PRUNE_PX]) + _pack_str(topic) + json.dumps(px).encode()

    @staticmethod
    def _is_loopback(host: str) -> bool | None:
        """True/False for a parseable target, None = unparseable/refuse.
        Numeric forms only (px records carry socket addresses), plus the
        literal \"localhost\"; ipaddress handles IPv4-mapped IPv6 and
        rejects exotic spellings (decimal/hex ints) that getaddrinfo
        would quietly resolve to 127.0.0.1."""
        import ipaddress

        if host == "localhost":
            return True
        try:
            ip = ipaddress.ip_address(host)
        except ValueError:
            return None
        mapped = getattr(ip, "ipv4_mapped", None)
        if mapped is not None:
            ip = mapped
        if ip.is_unspecified:
            return None       # 0.0.0.0 / :: connect to localhost
        return ip.is_loopback

    def _px_target_allowed(self, host: str, port: int) -> bool:
        """Address sanity for peer-exchange dials: refuse our own listen
        address (self-dial loops), anything that is not a plain numeric
        address, and loopback targets from a node that is itself
        non-loopback (an external peer has no business pointing us at
        127.0.0.1 — a classic rebind/steering primitive).  Local test
        deployments where WE listen on loopback keep working."""
        if not 0 < port < 65536:
            return False
        loopback = self._is_loopback(host)
        if loopback is None:
            return False
        if host == self.listen_host and port == self.listen_port:
            return False
        if loopback and self._is_loopback(self.listen_host) is not True:
            return False
        return True

    async def _dial_quiet(self, host: str, port: int):
        try:
            await self._dial(host, port)
        except Exception as e:
            record_swallowed("wire.dial_quiet", e)

    async def _send_ctrl(self, peer: str, frame: bytes):
        conn = self._conns.get(peer)
        if conn is None or not conn.alive:
            return
        try:
            await self._send_frame(conn, frame)
        except Exception as e:
            record_swallowed("wire.ctrl_send", e)

    # -- rpc -----------------------------------------------------------------

    def register_rpc(self, protocol: str, handler: Callable):
        self._rpc_handlers[protocol] = handler

    async def _serve_rpc(self, conn: _Conn, stream: int, proto: str,
                         payload: bytes):
        try:
            if not self._rpc_limiter.allow(conn.peer_id or "?", proto):
                raise RpcError(f"rate-limited on {proto}")
            handler = self._rpc_handlers.get(proto)
            if handler is None:
                raise RpcError(f"unsupported protocol {proto}")
            chunks = await self.loop.run_in_executor(
                self._pool, handler, conn.peer_id, payload)
            for c in chunks:
                await self._send_frame(conn, bytes([K_RPC_CHUNK])
                                       + struct.pack("<Q", stream)
                                       + codec.encode_response_chunk(
                                           codec.RESP_SUCCESS, c))
            await self._send_frame(
                conn, bytes([K_RPC_END]) + struct.pack("<Q", stream))
        except Exception as e:
            try:
                await self._send_frame(
                    conn, bytes([K_RPC_ERR]) + struct.pack("<Q", stream)
                    + str(e).encode())
            except Exception as e2:
                record_swallowed("wire.rpc_err_send", e2)

    def request(self, dst_peer: str, protocol: str,
                data: bytes) -> list[bytes]:
        """Sync RPC call over the peer's connection."""
        async def _do():
            conn = self._conns.get(dst_peer)
            if conn is None or not conn.alive:
                raise RpcError(f"not connected to {dst_peer}")
            stream = next(self._next_stream)
            fut = self.loop.create_future()
            self._streams[stream] = {"future": fut, "chunks": [],
                                     "error": None, "conn": conn}
            await self._send_frame(
                conn, bytes([K_RPC_REQ]) + struct.pack("<Q", stream)
                + _pack_str(protocol) + codec.encode_payload(data))
            try:
                return await asyncio.wait_for(fut, REQUEST_TIMEOUT_S)
            finally:
                self._streams.pop(stream, None)

        return self._call(_do(), timeout=REQUEST_TIMEOUT_S + 2)

    # -- udp discovery -------------------------------------------------------

    def register_udp(self, protocol: str, handler: Callable):
        """Serve a discovery protocol over UDP datagrams."""
        self._udp_handlers[protocol] = handler

    def udp_request(self, addr: tuple[str, int], protocol: str,
                    data: bytes, timeout: float = 3.0) -> list[bytes]:
        async def _do():
            nonce = secrets.token_bytes(8)
            fut = self.loop.create_future()
            self._udp_waiters[nonce] = fut
            msg = json.dumps({
                "t": "req", "n": nonce.hex(), "p": protocol,
                "d": data.hex(), "from": self.peer_id,
            }).encode()
            self._udp_transport.sendto(msg, addr)
            try:
                return await asyncio.wait_for(fut, timeout)
            finally:
                self._udp_waiters.pop(nonce, None)

        return self._call(_do(), timeout=timeout + 1)

    def _on_datagram(self, data: bytes, addr):
        try:
            d = json.loads(data)
        except ValueError:
            return
        if d.get("t") == "req":
            handler = self._udp_handlers.get(d.get("p"))
            if handler is None:
                return
            try:
                chunks = handler(d.get("from", "?"),
                                 bytes.fromhex(d.get("d", "")))
            except Exception as e:
                # a failed discovery handler drops the datagram (UDP is
                # best-effort) but must not vanish uncounted
                record_swallowed("wire.udp_handler", e)
                return
            resp = json.dumps({
                "t": "resp", "n": d["n"],
                "c": [c.hex() for c in chunks],
            }).encode()
            self._udp_transport.sendto(resp, addr)
        elif d.get("t") == "resp":
            # asyncio datagram callback: runs on the wire loop, the same
            # thread as every other _udp_waiters access (udp_request's
            # _do is loop-submitted) — lint cannot see protocol-callback
            # threading; there is no second thread here
            fut = self._udp_waiters.pop(  # lhlint: allow(LH1003) — loop-confined: datagram callbacks run on the wire loop
                bytes.fromhex(d.get("n", "")), None)
            if fut is not None and not fut.done():
                fut.set_result([bytes.fromhex(c) for c in d.get("c", ())])

    def disconnect(self, peer_id: str):
        """Drop a peer's connection (scoring/pruning enforcement)."""
        conn = self._conns.get(peer_id)
        if conn is None or self.loop is None:
            return

        async def _close():
            conn.alive = False
            try:
                conn.writer.close()
            except Exception as e:
                record_swallowed("wire.disconnect_close", e)

        asyncio.run_coroutine_threadsafe(_close(), self.loop)

    def set_blocked_peers(self, peers) -> None:
        """Install the admin partition set (PartitionSet semantics over
        sockets): every peer id in ``peers`` is refused at the HELLO
        door and any live connection to it is severed now.  An empty
        set heals.  Symmetry is the caller's job — the fleet admin
        installs each side of a severed pair on BOTH processes."""
        self._blocked = frozenset(str(p) for p in peers)
        for pid in self._blocked:
            self.disconnect(pid)

    @property
    def blocked_peers(self) -> frozenset:
        return self._blocked

    @property
    def peers(self) -> list[str]:
        # _conns is mutated ONLY on the wire loop (single-writer); this
        # sync facade iterates a snapshot taken in one C-level call
        conns = list(self._conns.items())  # lhlint: allow(LH1003) — single-writer dict, GIL-atomic list() snapshot
        return [pid for pid, c in conns if c.alive]

    def peer_addr(self, peer_id: str) -> tuple[str, int] | None:
        conn = self._conns.get(peer_id)
        return conn.addr if conn else None

    def peer_agent(self, peer_id: str) -> str:
        conn = self._conns.get(peer_id)
        return conn.agent if conn else ""

    def peer_outbound(self, peer_id: str) -> bool:
        conn = self._conns.get(peer_id)
        return bool(conn and conn.outbound)

    def peer_topics(self, peer_id: str) -> set[str]:
        conn = self._conns.get(peer_id)
        return set(conn.topics) if conn else set()


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, node: WireNode):
        self.node = node

    def datagram_received(self, data, addr):
        self.node._on_datagram(data, addr)


# --- fabric seams ------------------------------------------------------------


class WireGossipEndpoint:
    """GossipEndpoint seam over the socket node."""

    def __init__(self, node: WireNode):
        self.node = node
        self.peer_id = node.peer_id
        self._handlers: dict[str, Callable] = {}

    @property
    def on_delivery_result(self):
        return self.node.on_delivery_result

    @on_delivery_result.setter
    def on_delivery_result(self, fn):
        self.node.on_delivery_result = fn

    def subscribe(self, topic: str, handler):
        from lighthouse_tpu.network.gossip import GossipMessage

        def _adapt(t, data, src):
            handler(GossipMessage(t, data, src))

        self._handlers[topic] = handler
        self.node.subscribe(topic, _adapt)

    def unsubscribe(self, topic: str):
        self._handlers.pop(topic, None)
        self.node.unsubscribe(topic)

    def publish(self, topic: str, data: bytes):
        self.node.publish(topic, data)


class WireRpcEndpoint:
    """RpcEndpoint seam over the socket node; dials on demand via the
    address book the discovery layer maintains."""

    def __init__(self, node: WireNode, resolve_addr: Callable | None = None):
        self.node = node
        self.peer_id = node.peer_id
        self._resolve_addr = resolve_addr
        # same per-peer deadline/backoff/quarantine + accounting as the
        # in-process endpoint (network/rpc.RequestDiscipline)
        self.discipline = RequestDiscipline()

    def register(self, protocol: str, handler):
        self.node.register_rpc(protocol, handler)

    def request(self, dst: str, protocol: str, data: bytes) -> list[bytes]:
        return self.discipline.execute(dst, protocol, data,
                                       lambda target: self._issue(
                                           target, protocol, data))

    def _issue(self, dst: str, protocol: str, data: bytes) -> list[bytes]:
        if dst not in self.node.peers and self._resolve_addr is not None:
            addr = self._resolve_addr(dst)
            if addr is not None:
                try:
                    self.node.connect(*addr)
                except Exception as e:
                    raise RpcError(f"dial {dst} failed: {e}") from e
        return self.node.request(dst, protocol, data)


class WireDiscoveryEndpoint:
    """The rpc-endpoint seam network/discovery.py binds to, carried over
    UDP datagrams.  Peer ids resolve to (host, port) through the address
    book populated from Enr records seen in responses."""

    def __init__(self, node: WireNode):
        self.node = node
        self.peer_id = node.peer_id
        self.addr_book: dict[str, tuple[str, int]] = {}

    def register(self, protocol: str, handler):
        self.node.register_udp(protocol, handler)

    def _sniff_enrs(self, chunks: list[bytes]):
        from lighthouse_tpu.network.discovery import Enr

        for c in chunks:
            try:
                enr = Enr.from_bytes(c)
            except Exception:  # lhlint: allow(LH902) — probe loop over
                continue       # untrusted datagram bytes: non-Enr chunks
                #                are expected, the verify() below is the
                #                actual trust gate
            # records learned over UDP are untrusted: only admit ENRs
            # signed by the key whose fingerprint is the record's peer id
            if not enr.verify():
                continue
            self.addr_book[enr.peer_id] = (enr.ip, enr.port)

    def resolve(self, peer_id: str) -> tuple[str, int] | None:
        if ":" in peer_id:                      # "host:port" bootstrap form
            host, port = peer_id.rsplit(":", 1)
            return host, int(port)
        return self.addr_book.get(peer_id)

    def request(self, dst: str, protocol: str, data: bytes) -> list[bytes]:
        addr = self.resolve(dst)
        if addr is None:
            raise RpcError(f"no address for {dst}")
        try:
            chunks = self.node.udp_request(addr, protocol, data)
        except (TimeoutError, asyncio.TimeoutError) as e:
            raise RpcError(f"udp request to {dst} timed out") from e
        self._sniff_enrs(chunks)
        return chunks


class WireFabric:
    """Drop-in for service.NetworkFabric backed by sockets.

    One per process; `.gossip.join()` / `.rpc.join()` hand out the seam
    endpoints (join is a no-op rendezvous — the node IS the process)."""

    def __init__(self, identity_seed: "bytes | str | None" = None,
                 listen_port: int = 0,
                 fork_digest: bytes = b"\x00\x00\x00\x00",
                 listen_host: str = "127.0.0.1",
                 transport: str = "tcp"):
        self.node = WireNode(
            identity_seed,
            listen_port=listen_port, fork_digest=fork_digest,
            listen_host=listen_host, transport=transport).start()
        self.discovery_ep = WireDiscoveryEndpoint(self.node)
        self.gossip = _JoinShim(
            lambda pid: WireGossipEndpoint(self.node))
        self.rpc = _JoinShim(
            lambda pid: WireRpcEndpoint(
                self.node, resolve_addr=self.discovery_ep.resolve))

    @property
    def peer_id(self) -> str:
        return self.node.peer_id

    @property
    def listen_port(self) -> int:
        return self.node.listen_port

    def connect(self, host: str, port: int) -> str:
        return self.node.connect(host, port)

    def stop(self):
        self.node.stop()


class _JoinShim:
    def __init__(self, factory):
        self._factory = factory

    def join(self, peer_id: str):
        return self._factory(peer_id)
