"""QUIC-role UDP transport: reliable ordered streams over datagrams.

The reference dials peers over genuine QUIC alongside TCP
(/root/reference/beacon_node/lighthouse_network/src/service/mod.rs:352-390
— libp2p's quic transport) for lower connection latency and userspace
congestion control.  This module fills the same role in this stack's
wire fabric: a UDP transport carrying the node's ordered byte stream,
so the Noise handshake, HELLO exchange, gossip and RPC framing all run
unchanged over it (WireNode's `transport="quic"`).

Honest interop note (see README "wire interoperability"): this is NOT
wire-format QUIC (no TLS 1.3, no varint packet encoding) — like the
rest of the wire stack it is a from-scratch protocol in the same ROLE.
Frame: [magic u8][type u8][cid 8B][seq u32 BE][payload].  Reliability
is per-packet ARQ: cumulative ACKs, fixed-window flow control, RTO
retransmission with exponential backoff.  One ordered stream per
connection — the wire protocol already multiplexes streams above this
layer, which is also why a single stream suffices.

Surface: `start_listener(host, port, on_conn)` mirrors
`asyncio.start_server` (the callback receives (reader, writer));
`open_connection(host, port)` mirrors `asyncio.open_connection`.  The
reader IS an `asyncio.StreamReader`; the writer implements the subset
of `StreamWriter` the wire node uses (write/drain/close/is_closing/
wait_closed/get_extra_info).
"""

from __future__ import annotations

import asyncio
import secrets
import struct
from collections import deque

MAGIC = 0xD7
T_INIT = 1       # open: payload empty; cid chosen by the dialer
T_INIT_ACK = 2   # accept
T_DATA = 3       # seq + stream bytes
T_ACK = 4        # seq = highest in-order DATA delivered
T_FIN = 5        # reliable end-of-stream (carries a seq like DATA)
T_RST = 6        # abort

MAX_PAYLOAD = 1200          # stay under typical MTU
WINDOW_PACKETS = 256        # in-flight cap before drain() blocks
RTO_S = 0.2                 # initial retransmission timeout
MAX_RETRIES = 8             # ~51 s of backoff before the conn errors
HDR = struct.Struct("!BB8sI")


class QuicError(ConnectionError):
    pass


def _pack(ptype: int, cid: bytes, seq: int, payload: bytes = b"") -> bytes:
    return HDR.pack(MAGIC, ptype, cid, seq) + payload


class _QuicConn:
    """One connection's reliability state, shared by both directions."""

    def __init__(self, proto: "_Endpoint", cid: bytes,
                 addr: tuple[str, int]):
        self.proto = proto
        self.cid = cid
        self.addr = addr
        self.reader = asyncio.StreamReader()
        self.established = asyncio.get_event_loop().create_future()
        # send side
        self.next_seq = 0
        self.unacked: dict[int, list] = {}   # seq -> [bytes, deadline, tries]
        # pacing queue: chunks with assigned seqs NOT yet transmitted —
        # released into the wire window as ACKs free slots, so a
        # multi-MB write can never burst thousands of datagrams
        self.pending: "deque[tuple[int, bytes, int]]" = deque()
        self.window_free = asyncio.Event()
        self.window_free.set()
        self.fin_sent = False
        self.closed = False
        self.close_waiter = asyncio.get_event_loop().create_future()
        # receive side
        self.rcv_next = 0
        self.rcv_buf: dict[int, tuple[int, bytes]] = {}  # seq -> (type, data)
        self._retx_task = asyncio.ensure_future(self._retx_loop())

    # -- send path ---------------------------------------------------------

    def _transmit(self, ptype: int, seq: int, payload: bytes) -> None:
        self.proto.sendto(_pack(ptype, self.cid, seq, payload), self.addr)

    def send_segmented(self, data: bytes) -> None:
        """Segment + transmit, paced to the window: at most WINDOW_PACKETS
        in flight; excess chunks queue unsent and are released by ACKs
        (on_packet -> _release_window).  A big write therefore never
        bursts past the window, and retransmits under loss cannot amplify
        an already-oversized flight."""
        for off in range(0, len(data), MAX_PAYLOAD):
            chunk = data[off:off + MAX_PAYLOAD]
            seq = self.next_seq
            self.next_seq += 1
            if self.pending or len(self.unacked) >= WINDOW_PACKETS:
                self.pending.append((seq, chunk, T_DATA))
            else:
                self.unacked[seq] = [
                    chunk, asyncio.get_event_loop().time() + RTO_S, 0,
                    T_DATA]
                self._transmit(T_DATA, seq, chunk)
        if self.pending or len(self.unacked) >= WINDOW_PACKETS:
            self.window_free.clear()

    def _release_window(self) -> None:
        """Move queued chunks into freed window slots (ACK-clocked)."""
        now = asyncio.get_event_loop().time()
        while self.pending and len(self.unacked) < WINDOW_PACKETS:
            seq, chunk, ptype = self.pending.popleft()
            self.unacked[seq] = [chunk, now + RTO_S, 0, ptype]
            self._transmit(ptype, seq, chunk)
        if not self.pending and len(self.unacked) < WINDOW_PACKETS:
            self.window_free.set()

    def send_fin(self) -> None:
        if self.fin_sent or self.closed:
            return
        self.fin_sent = True
        seq = self.next_seq
        self.next_seq += 1
        if self.pending or len(self.unacked) >= WINDOW_PACKETS:
            # FIN rides the pacing queue behind the unsent data; it must
            # also queue at an exactly-full window — transmitted there it
            # would land at rcv_next + WINDOW and the receiver's reorder
            # bound would silently drop it (an RTO-stalled close)
            self.pending.append((seq, b"", T_FIN))
            return
        self.unacked[seq] = [
            b"", asyncio.get_event_loop().time() + RTO_S, 0, T_FIN]
        self._transmit(T_FIN, seq, b"")

    async def _retx_loop(self):
        try:
            while not self.closed:
                await asyncio.sleep(RTO_S / 4)
                now = asyncio.get_event_loop().time()
                for seq, ent in list(self.unacked.items()):
                    chunk, deadline, tries, ptype = ent
                    if now < deadline:
                        continue
                    if tries >= MAX_RETRIES:
                        self._die(QuicError(
                            f"retransmission limit for seq {seq}"))
                        return
                    ent[1] = now + RTO_S * (2 ** (tries + 1))
                    ent[2] = tries + 1
                    self._transmit(ptype, seq, chunk)
        except asyncio.CancelledError:
            pass

    # -- receive path ------------------------------------------------------

    def on_packet(self, ptype: int, seq: int, payload: bytes) -> None:
        if ptype == T_ACK:
            for s in [s for s in self.unacked if s < seq]:
                del self.unacked[s]
            self._release_window()
            if self.fin_sent and not self.unacked and not self.pending:
                self._finish_close()
            return
        if ptype == T_RST:
            self._die(QuicError("connection reset by peer"))
            return
        if ptype in (T_DATA, T_FIN):
            if seq >= self.rcv_next + WINDOW_PACKETS:
                # bound the reorder buffer: connections exist BEFORE the
                # Noise handshake, so an unauthenticated peer spraying
                # far-future seqs must not grow rcv_buf without limit.
                # Silently dropped segments are retransmitted (RTO) once
                # the window advances.
                from lighthouse_tpu.common.metrics import REGISTRY

                REGISTRY.counter(
                    "quic_rx_window_dropped_total",
                    "segments dropped beyond the receive reorder window",
                ).inc()
                return
            if seq >= self.rcv_next and seq not in self.rcv_buf:
                self.rcv_buf[seq] = (ptype, payload)
            # deliver everything now in order
            while self.rcv_next in self.rcv_buf:
                pt, data = self.rcv_buf.pop(self.rcv_next)
                self.rcv_next += 1
                if pt == T_FIN:
                    self.reader.feed_eof()
                elif data:
                    self.reader.feed_data(data)
            # cumulative ACK (covers duplicates and reordering)
            self._transmit(T_ACK, self.rcv_next, b"")

    # -- teardown ----------------------------------------------------------

    def _finish_close(self) -> None:
        if not self.closed:
            self.closed = True
            self._retx_task.cancel()
            if not self.close_waiter.done():
                self.close_waiter.set_result(None)
            self.proto.forget(self)

    def _die(self, exc: Exception) -> None:
        if self.closed:
            return
        self.closed = True
        self._retx_task.cancel()
        self.window_free.set()          # release any blocked drain()
        self.reader.feed_eof()
        if not self.established.done():
            self.established.set_exception(exc)
        if not self.close_waiter.done():
            self.close_waiter.set_result(None)
        self.proto.forget(self)


class _Writer:
    """StreamWriter-shaped facade over a _QuicConn's send side."""

    def __init__(self, conn: _QuicConn):
        self._conn = conn

    def write(self, data: bytes) -> None:
        if self._conn.closed:
            raise QuicError("write on closed quic connection")
        self._conn.send_segmented(bytes(data))

    async def drain(self) -> None:
        await self._conn.window_free.wait()
        if self._conn.closed and (self._conn.unacked or self._conn.pending):
            raise QuicError("quic connection lost")

    def close(self) -> None:
        self._conn.send_fin()
        # a peer that is gone never ACKs the FIN; the retx loop gives up
        # and tears the state down after MAX_RETRIES backoffs

    def is_closing(self) -> bool:
        return self._conn.fin_sent or self._conn.closed

    async def wait_closed(self) -> None:
        await self._conn.close_waiter

    def get_extra_info(self, name: str, default=None):
        if name == "peername":
            return self._conn.addr
        return default


class _Endpoint(asyncio.DatagramProtocol):
    """One UDP socket demuxing many connections by (addr, cid)."""

    def __init__(self, on_conn=None, fallback=None):
        self.on_conn = on_conn          # set on listeners
        # non-MAGIC datagrams hand off here: in quic mode the node's
        # UDP discovery protocol shares this one socket/port
        self.fallback = fallback
        self.transport = None
        self.conns: dict[tuple, _QuicConn] = {}

    def connection_made(self, transport):
        self.transport = transport

    def sendto(self, data: bytes, addr) -> None:
        if self.transport is not None:
            self.transport.sendto(data, addr)

    def forget(self, conn: _QuicConn) -> None:
        self.conns.pop((conn.addr, conn.cid), None)

    def datagram_received(self, data: bytes, addr):
        if len(data) >= HDR.size:
            magic, ptype, cid, seq = HDR.unpack_from(data)
        else:
            magic = None
        if magic != MAGIC:
            if self.fallback is not None:
                self.fallback(data, addr)
            return
        payload = data[HDR.size:]
        key = (addr, cid)
        conn = self.conns.get(key)
        if conn is None:
            if ptype == T_INIT and self.on_conn is not None:
                conn = _QuicConn(self, cid, addr)
                self.conns[key] = conn
                conn._transmit(T_INIT_ACK, 0, b"")
                self.on_conn(conn.reader, _Writer(conn))
            elif ptype == T_INIT_ACK:
                pass  # dialer conns are pre-registered; nothing to do
            elif ptype not in (T_RST, T_ACK):
                # unknown conn: tell the peer to stop retransmitting
                self.sendto(_pack(T_RST, cid, 0), addr)
            return
        if ptype == T_INIT:
            # duplicate INIT (our INIT_ACK was lost): re-accept
            conn._transmit(T_INIT_ACK, 0, b"")
            return
        if ptype == T_INIT_ACK:
            if not conn.established.done():
                conn.established.set_result(None)
            return
        conn.on_packet(ptype, seq, payload)


class QuicListener:
    def __init__(self, transport, endpoint: _Endpoint):
        self._transport = transport
        self.endpoint = endpoint

    @property
    def port(self) -> int:
        return self._transport.get_extra_info("sockname")[1]

    def close(self) -> None:
        for conn in list(self.endpoint.conns.values()):
            conn._die(QuicError("listener closed"))
        self._transport.close()


async def start_listener(host: str, port: int, on_conn,
                         fallback=None) -> QuicListener:
    """`asyncio.start_server` analogue: on_conn(reader, writer) fires per
    accepted connection.  ``fallback(data, addr)`` receives datagrams
    that are not QUIC-role frames (shared-port discovery)."""
    loop = asyncio.get_event_loop()
    transport, endpoint = await loop.create_datagram_endpoint(
        lambda: _Endpoint(on_conn, fallback), local_addr=(host, port))
    return QuicListener(transport, endpoint)


async def open_connection(host: str, port: int, timeout: float = 5.0):
    """`asyncio.open_connection` analogue over the QUIC-role transport."""
    loop = asyncio.get_event_loop()
    transport, endpoint = await loop.create_datagram_endpoint(
        lambda: _Endpoint(None), remote_addr=(host, port))
    cid = secrets.token_bytes(8)
    addr = transport.get_extra_info("peername") or (host, port)
    conn = _QuicConn(endpoint, cid, addr)
    endpoint.conns[(addr, cid)] = conn
    # INIT until accepted (lost-INIT recovery)
    deadline = loop.time() + timeout
    while True:
        conn._transmit(T_INIT, 0, b"")
        try:
            await asyncio.wait_for(
                asyncio.shield(conn.established),
                min(0.25, max(0.01, deadline - loop.time())))
            break
        except asyncio.TimeoutError:
            if loop.time() >= deadline:
                transport.close()
                raise QuicError(f"quic dial to {host}:{port} timed out"
                                ) from None
    writer = _Writer(conn)
    # the dialer owns its socket: close it with the connection
    orig_finish = conn._finish_close

    def finish_and_close():
        orig_finish()
        transport.close()

    conn._finish_close = finish_and_close
    return conn.reader, writer
