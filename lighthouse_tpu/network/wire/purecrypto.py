"""Pure-Python fallback for the ``cryptography`` primitives noise.py uses.

Containers without the ``cryptography`` wheel could not even IMPORT the
wire stack (the process-fleet drills and the wire tests died at
collection).  This module implements the three primitives the Noise
channel needs straight from their RFCs, byte-compatible with the
``cryptography`` API surface noise.py consumes, so the wire protocol is
identical whichever backend loads — a fallback node interoperates with
a wheel-backed node:

- X25519 (RFC 7748): Montgomery-ladder scalar multiplication;
- Ed25519 (RFC 8032): sign/verify over edwards25519;
- ChaCha20-Poly1305 (RFC 8439): the AEAD, one-shot per frame.

Host-side session crypto only (handshakes + small gossip frames on a
drill fleet); the wheel is preferred whenever present — noise.py falls
back here only on ImportError.  Known-answer tests in
tests/test_wire.py pin all three against the RFC vectors.
"""

from __future__ import annotations

import hashlib
import os
import struct


class InvalidSignature(Exception):
    pass


# --- ChaCha20-Poly1305 (RFC 8439) --------------------------------------------

_MASK32 = 0xFFFFFFFF


def _chacha20_block(state16: list, out: bytearray, off: int) -> None:
    x = list(state16)
    for _ in range(10):
        # column rounds
        for a, b, c, d in ((0, 4, 8, 12), (1, 5, 9, 13),
                           (2, 6, 10, 14), (3, 7, 11, 15),
                           (0, 5, 10, 15), (1, 6, 11, 12),
                           (2, 7, 8, 13), (3, 4, 9, 14)):
            xa = (x[a] + x[b]) & _MASK32
            xd = x[d] ^ xa
            xd = ((xd << 16) | (xd >> 16)) & _MASK32
            xc = (x[c] + xd) & _MASK32
            xb = x[b] ^ xc
            xb = ((xb << 12) | (xb >> 20)) & _MASK32
            xa = (xa + xb) & _MASK32
            xd ^= xa
            xd = ((xd << 8) | (xd >> 24)) & _MASK32
            xc = (xc + xd) & _MASK32
            xb ^= xc
            x[a], x[b], x[c], x[d] = (
                xa, ((xb << 7) | (xb >> 25)) & _MASK32, xc, xd)
    struct.pack_into("<16I", out, off,
                     *((x[i] + state16[i]) & _MASK32 for i in range(16)))


def _chacha20_xor(key: bytes, counter: int, nonce: bytes,
                  data: bytes) -> bytes:
    state = [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
             *struct.unpack("<8I", key), counter,
             *struct.unpack("<3I", nonce)]
    n = len(data)
    stream = bytearray((n + 63) & ~63)
    for i in range(0, n, 64):
        _chacha20_block(state, stream, i)
        state[12] = (state[12] + 1) & _MASK32
    return bytes(a ^ b for a, b in zip(data, stream))


def _poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i:i + 16]
        acc = ((acc + int.from_bytes(block, "little")
                + (1 << (8 * len(block)))) * r) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return data + b"\x00" * (16 - rem) if rem else data


class ChaCha20Poly1305:
    """RFC 8439 AEAD construction with the ``cryptography`` call shape
    (12-byte nonce, detached nothing — tag appended to the
    ciphertext)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_xor(self._key, 0, nonce, b"\x00" * 32)
        mac_data = (_pad16(aad) + _pad16(ct)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aad = aad or b""
        ct = _chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aad = aad or b""
        if len(data) < 16:
            raise InvalidSignature("short AEAD ciphertext")
        ct, tag = data[:-16], data[-16:]
        expect = self._tag(nonce, ct, aad)
        # constant-time compare: session keys must not leak via timing
        if not _ct_eq(tag, expect):
            raise InvalidSignature("AEAD tag mismatch")
        return _chacha20_xor(self._key, 1, nonce, ct)


def _ct_eq(a: bytes, b: bytes) -> bool:
    if len(a) != len(b):
        return False
    acc = 0
    for x, y in zip(a, b):
        acc |= x ^ y
    return acc == 0


# --- X25519 (RFC 7748) --------------------------------------------------------

_P = (1 << 255) - 19
_A24 = 121665


def _x25519_scalarmult(k: bytes, u: bytes) -> bytes:
    kn = int.from_bytes(k, "little")
    kn &= ~7
    kn &= (1 << 254) - 1
    kn |= 1 << 254
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (kn >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = (a * a) % _P
        b = (x2 - z2) % _P
        bb = (b * b) % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = (d * a) % _P
        cb = (c * b) % _P
        x3 = (da + cb) % _P
        x3 = (x3 * x3) % _P
        z3 = (da - cb) % _P
        z3 = (x1 * z3 * z3) % _P
        x2 = (aa * bb) % _P
        z2 = (e * (aa + _A24 * e)) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return ((x2 * pow(z2, _P - 2, _P)) % _P).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
        return cls(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, raw: bytes) -> "X25519PrivateKey":
        return cls(raw)

    def public_key(self) -> X25519PublicKey:
        base = (9).to_bytes(32, "little")
        return X25519PublicKey(_x25519_scalarmult(self._raw, base))

    def exchange(self, peer: X25519PublicKey) -> bytes:
        out = _x25519_scalarmult(self._raw, peer.public_bytes_raw())
        if out == b"\x00" * 32:
            raise ValueError("X25519 exchange produced the zero point")
        return out


# --- Ed25519 (RFC 8032) -------------------------------------------------------

_ED_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_ED_L = (1 << 252) + 27742317777372353535851937790883648493
_ED_BY = (4 * pow(5, _P - 2, _P)) % _P
_ED_BX = None  # recovered below
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _ed_recover_x(y: int, sign: int) -> int:
    if y >= _P:
        raise InvalidSignature("point y out of range")
    x2 = ((y * y - 1) * pow(_ED_D * y * y + 1, _P - 2, _P)) % _P
    if x2 == 0:
        if sign:
            raise InvalidSignature("invalid point compression")
        return 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = (x * _SQRT_M1) % _P
    if (x * x - x2) % _P != 0:
        raise InvalidSignature("not a curve point")
    if (x & 1) != sign:
        x = _P - x
    return x


_ED_BX = _ed_recover_x(_ED_BY, 0)
_ED_B = (_ED_BX, _ED_BY, 1, (_ED_BX * _ED_BY) % _P)   # extended coords
_ED_IDENT = (0, 1, 1, 0)


def _ed_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % _P
    b = ((y1 + x1) * (y2 + x2)) % _P
    c = (2 * t1 * t2 * _ED_D) % _P
    d = (2 * z1 * z2) % _P
    e, f, g, h = (b - a) % _P, (d - c) % _P, (d + c) % _P, (b + a) % _P
    return ((e * f) % _P, (g * h) % _P, (f * g) % _P, (e * h) % _P)


def _ed_mul(s: int, p):
    q = _ED_IDENT
    while s > 0:
        if s & 1:
            q = _ed_add(q, p)
        p = _ed_add(p, p)
        s >>= 1
    return q


def _ed_compress(p) -> bytes:
    x, y, z, _ = p
    zi = pow(z, _P - 2, _P)
    x, y = (x * zi) % _P, (y * zi) % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _ed_decompress(raw: bytes):
    if len(raw) != 32:
        raise InvalidSignature("Ed25519 point must be 32 bytes")
    enc = int.from_bytes(raw, "little")
    y = enc & ((1 << 255) - 1)
    x = _ed_recover_x(y, enc >> 255)
    return (x, y, 1, (x * y) % _P)


def _ed_eq(p, q) -> bool:
    # x1/z1 == x2/z2 and y1/z1 == y2/z2, avoided divisions
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return ((x1 * z2 - x2 * z1) % _P == 0
            and (y1 * z2 - y2 * z1) % _P == 0)


def _ed_secret_expand(seed: bytes) -> tuple:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


class Ed25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("Ed25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
        return cls(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if len(signature) != 64:
            raise InvalidSignature("Ed25519 signature must be 64 bytes")
        a = _ed_decompress(self._raw)
        r_raw, s_raw = signature[:32], signature[32:]
        s = int.from_bytes(s_raw, "little")
        if s >= _ED_L:
            raise InvalidSignature("signature scalar out of range")
        r = _ed_decompress(r_raw)
        k = int.from_bytes(
            hashlib.sha512(r_raw + self._raw + data).digest(),
            "little") % _ED_L
        # [s]B == R + [k]A
        if not _ed_eq(_ed_mul(s, _ED_B), _ed_add(r, _ed_mul(k, a))):
            raise InvalidSignature("Ed25519 verification failed")


class Ed25519PrivateKey:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("Ed25519 private key must be 32 bytes")
        self._seed = bytes(seed)
        a, self._prefix = _ed_secret_expand(self._seed)
        self._a = a
        self._pub = _ed_compress(_ed_mul(a, _ED_B))

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
        return cls(seed)

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pub)

    def sign(self, data: bytes) -> bytes:
        r = int.from_bytes(
            hashlib.sha512(self._prefix + data).digest(), "little") % _ED_L
        r_enc = _ed_compress(_ed_mul(r, _ED_B))
        k = int.from_bytes(
            hashlib.sha512(r_enc + self._pub + data).digest(),
            "little") % _ED_L
        s = (r + k * self._a) % _ED_L
        return r_enc + s.to_bytes(32, "little")


__all__ = [
    "ChaCha20Poly1305", "Ed25519PrivateKey", "Ed25519PublicKey",
    "InvalidSignature", "X25519PrivateKey", "X25519PublicKey",
]
