"""ssz_snappy wire codec: the reference's req/resp payload framing.

Request payload  = uvarint(ssz_len) ‖ snappy-frame(ssz_bytes)
Response chunk   = result_byte ‖ uvarint(ssz_len) ‖ snappy-frame(ssz_bytes)
Gossip payload   = snappy-block(ssz_bytes)

(/root/reference/beacon_node/lighthouse_network/src/rpc/codec/ssz_snappy.rs:1
— the varint is of the UNCOMPRESSED length, bounding decompression before
it runs.)
"""

from __future__ import annotations

from lighthouse_tpu.network.wire import snappy

MAX_PAYLOAD = 10 * 1024 * 1024  # spec max_chunk_size ballpark

RESP_SUCCESS = 0x00
RESP_INVALID_REQUEST = 0x01
RESP_SERVER_ERROR = 0x02
RESP_RESOURCE_UNAVAILABLE = 0x03


class CodecError(ValueError):
    pass


def encode_payload(ssz_bytes: bytes) -> bytes:
    return snappy.uvarint_encode(len(ssz_bytes)) + \
        snappy.frame_compress(ssz_bytes)


def decode_payload(data: bytes) -> bytes:
    try:
        declared, off = snappy.uvarint_decode(data)
        if declared > MAX_PAYLOAD:
            raise CodecError(f"declared payload {declared} over limit")
        out = snappy.frame_decompress(data[off:], max_len=declared)
    except snappy.SnappyError as e:
        raise CodecError(str(e)) from e
    if len(out) != declared:
        raise CodecError(
            f"payload length {len(out)} != declared {declared}")
    return out


def encode_response_chunk(result: int, ssz_bytes: bytes) -> bytes:
    return bytes([result]) + encode_payload(ssz_bytes)


def decode_response_chunk(data: bytes) -> tuple[int, bytes]:
    if not data:
        raise CodecError("empty response chunk")
    return data[0], decode_payload(data[1:])


def encode_gossip(ssz_bytes: bytes) -> bytes:
    return snappy.compress_block(ssz_bytes)


def decode_gossip(data: bytes) -> bytes:
    try:
        return snappy.decompress_block(data, max_len=MAX_PAYLOAD)
    except snappy.SnappyError as e:
        raise CodecError(str(e)) from e
