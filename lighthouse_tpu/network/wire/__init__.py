"""Socket wire stack: the bytes-on-the-wire half of the network layer.

The in-process fabric (network/gossip.py, network/rpc.py) defines the
seams — topic pub/sub and protocol req/resp; this package implements the
same seams over real sockets so two OS processes can peer:

- snappy.py: snappy block + frame formats with CRC32C (the reference
  wire compression, lighthouse_network/src/rpc/codec/ssz_snappy.rs)
- codec.py: length-prefixed ssz_snappy request/response framing
- transport.py: asyncio TCP mux (gossip + RPC streams) and the UDP
  discovery datagram endpoint, exposed as `WireFabric`
"""

from lighthouse_tpu.network.wire.transport import WireFabric

__all__ = ["WireFabric"]
