"""Networking layer (L6): gossip pub/sub, Req/Resp RPC, router, sync,
peer management (reference beacon_node/{network,lighthouse_network})."""

from lighthouse_tpu.network.backfill import BackfillSync
from lighthouse_tpu.network.discovery import BootNode, Discovery, Enr
from lighthouse_tpu.network.gossip import GossipHub
from lighthouse_tpu.network.peer_manager import PeerManager
from lighthouse_tpu.network.router import Router
from lighthouse_tpu.network.rpc import RpcFabric
from lighthouse_tpu.network.service import NetworkFabric, NetworkService
from lighthouse_tpu.network.sync import SyncManager

__all__ = [
    "BackfillSync",
    "BootNode",
    "Discovery",
    "Enr",
    "GossipHub",
    "PeerManager",
    "Router",
    "RpcFabric",
    "NetworkFabric",
    "NetworkService",
    "SyncManager",
]
