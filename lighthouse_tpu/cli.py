"""`lighthouse-tpu` CLI: one binary multiplexing every role.

Rebuild of /root/reference/lighthouse/src/main.rs:87,412-414,669-736
(bn / vc / account_manager / validator_manager / database_manager
subcommands) at the flag surface this client consumes.  Run with
``python -m lighthouse_tpu <subcommand>``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lighthouse-tpu",
        description="TPU-native Ethereum consensus client")
    p.add_argument("--network", default="devnet",
                   help="built-in network name (mainnet/minimal/devnet)")
    p.add_argument("--network-config", default=None,
                   help="path to a config.yaml overriding --network")
    p.add_argument("--datadir", default=None,
                   help="persistent DB directory (default: in-memory)")
    sub = p.add_subparsers(dest="command", required=True)

    bn = sub.add_parser("bn", help="run a beacon node")
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--execution-endpoint", default=None)
    bn.add_argument("--execution-jwt", default=None,
                    help="hex JWT secret for the engine API")
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--interop-validators", type=int, default=64,
                    help="interop genesis validator count (dev networks)")
    bn.add_argument("--genesis-fork", default="capella")
    bn.add_argument("--run-seconds", type=float, default=None,
                    help="exit after N seconds (default: run forever)")

    vc = sub.add_parser("vc", help="run a validator client")
    vc.add_argument("--beacon-node", default="http://127.0.0.1:5052")
    vc.add_argument("--validators-dir", default=None,
                    help="directory of EIP-2335 keystores")
    vc.add_argument("--keystore-password", default="")
    vc.add_argument("--interop-range", default=None,
                    help="START:END interop validator indices (dev)")
    vc.add_argument("--run-seconds", type=float, default=None)

    am = sub.add_parser("account-manager",
                        help="wallet + validator key tooling")
    am_sub = am.add_subparsers(dest="am_command", required=True)
    wc = am_sub.add_parser("wallet-create")
    wc.add_argument("--name", required=True)
    wc.add_argument("--password", required=True)
    wc.add_argument("--out", required=True, help="wallet JSON output path")
    vcreate = am_sub.add_parser("validator-create")
    vcreate.add_argument("--wallet", required=True)
    vcreate.add_argument("--wallet-password", required=True)
    vcreate.add_argument("--keystore-password", required=True)
    vcreate.add_argument("--count", type=int, default=1)
    vcreate.add_argument("--out-dir", required=True)

    vm = sub.add_parser("validator-manager",
                        help="bulk import/list validators")
    vm_sub = vm.add_subparsers(dest="vm_command", required=True)
    imp = vm_sub.add_parser("import")
    imp.add_argument("--keystores-dir", required=True)
    imp.add_argument("--password", required=True)
    imp.add_argument("--out", required=True,
                     help="validator_definitions.json output")
    vm_sub.add_parser("list").add_argument("--definitions", required=True)

    db = sub.add_parser("db", help="database inspection/maintenance")
    db_sub = db.add_subparsers(dest="db_command", required=True)
    db_sub.add_parser("inspect")
    db_sub.add_parser("compact")
    prune = db_sub.add_parser("prune-states")
    prune.add_argument("--confirm", action="store_true")
    return p


# -- subcommand drivers ------------------------------------------------------

def _run_bn(args) -> int:
    from lighthouse_tpu.client.builder import ClientBuilder, ClientConfig

    cfg = ClientConfig(
        network=args.network,
        network_config_path=args.network_config,
        datadir=args.datadir,
        http_port=args.http_port,
        execution_endpoint=args.execution_endpoint,
        execution_jwt_hex=args.execution_jwt,
        slasher_enabled=args.slasher,
        n_genesis_validators=args.interop_validators,
        genesis_fork=args.genesis_fork,
    )
    client = ClientBuilder(cfg).build()
    print(json.dumps({
        "running": "bn", "network": client.spec.config_name,
        "http_port": client.http_server.port if client.http_server else None,
        "genesis_root": "0x" + client.chain.genesis_block_root.hex(),
    }), flush=True)
    try:
        deadline = (time.time() + args.run_seconds
                    if args.run_seconds else None)
        while deadline is None or time.time() < deadline:
            if client.executor.exit_event.wait(0.5):
                break
    except KeyboardInterrupt:
        pass
    client.stop()
    return 0


def _run_vc(args) -> int:
    import os

    from lighthouse_tpu.api import BeaconNodeClient
    from lighthouse_tpu.client.network_config import spec_for_network
    from lighthouse_tpu.crypto import keystore as ks
    from lighthouse_tpu.validator import ValidatorStore

    spec = spec_for_network(args.network)
    bn = BeaconNodeClient(args.beacon_node)
    genesis = bn.genesis()
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    store = ValidatorStore(spec, gvr)
    if args.interop_range:
        from lighthouse_tpu.testing import interop_secret_key

        lo, hi = (int(x) for x in args.interop_range.split(":"))
        for i in range(lo, hi):
            store.add_validator(interop_secret_key(i), index=i)
    elif args.validators_dir:
        for name in sorted(os.listdir(args.validators_dir)):
            if not name.endswith(".json"):
                continue
            with open(os.path.join(args.validators_dir, name)) as f:
                store.import_keystore(json.load(f), args.keystore_password)
    print(json.dumps({
        "running": "vc", "validators": len(store.voting_pubkeys()),
        "beacon_node": args.beacon_node,
    }), flush=True)
    # duty loop over the HTTP API is driven by the in-process
    # ValidatorClient when embedded; standalone mode polls the BN health
    deadline = time.time() + args.run_seconds if args.run_seconds else None
    while deadline is None or time.time() < deadline:
        time.sleep(0.5)
    return 0


def _run_account_manager(args) -> int:
    from lighthouse_tpu.crypto.wallet import Wallet

    if args.am_command == "wallet-create":
        w = Wallet.create(args.name, args.password)
        with open(args.out, "w") as f:
            json.dump(w.data, f)
        print(json.dumps({"wallet": args.name, "path": args.out}))
        return 0
    if args.am_command == "validator-create":
        import os

        with open(args.wallet) as f:
            w = Wallet(json.load(f))
        os.makedirs(args.out_dir, exist_ok=True)
        created = []
        for _ in range(args.count):
            keystore, _sk = w.next_validator(
                args.wallet_password, args.keystore_password)
            path = os.path.join(
                args.out_dir, f"keystore-{keystore['pubkey'][:16]}.json")
            with open(path, "w") as f:
                json.dump(keystore, f)
            created.append(keystore["pubkey"])
        with open(args.wallet, "w") as f:
            json.dump(w.data, f)  # persist nextaccount
        print(json.dumps({"created": created}))
        return 0
    raise SystemExit(f"unknown account-manager command {args.am_command}")


def _run_validator_manager(args) -> int:
    import os

    if args.vm_command == "import":
        from lighthouse_tpu.crypto import keystore as ks

        defs = []
        for name in sorted(os.listdir(args.keystores_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(args.keystores_dir, name)
            with open(path) as f:
                keystore = json.load(f)
            ks.decrypt(keystore, args.password)  # validate the password
            defs.append({
                "enabled": True,
                "voting_public_key": "0x" + keystore["pubkey"],
                "type": "local_keystore",
                "voting_keystore_path": path,
            })
        with open(args.out, "w") as f:
            json.dump(defs, f, indent=2)
        print(json.dumps({"imported": len(defs)}))
        return 0
    if args.vm_command == "list":
        with open(args.definitions) as f:
            defs = json.load(f)
        for d in defs:
            print(d["voting_public_key"])
        return 0
    raise SystemExit(f"unknown validator-manager command {args.vm_command}")


def _run_db(args) -> int:
    import os

    from lighthouse_tpu.store import NativeKVStore

    if not args.datadir:
        raise SystemExit("db commands need --datadir")
    out = {}
    for name in ("hot.db", "cold.db"):
        path = os.path.join(args.datadir, name)
        if not os.path.exists(path):
            continue
        store = NativeKVStore(path)
        if args.db_command == "compact":
            store.compact()
        out[name] = {"keys": len(store),
                     "bytes": os.path.getsize(path)}
        store.close()
    if args.db_command == "prune-states" and not args.confirm:
        raise SystemExit("prune-states is destructive; pass --confirm")
    print(json.dumps({args.db_command: out}))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    return {
        "bn": _run_bn,
        "vc": _run_vc,
        "account-manager": _run_account_manager,
        "validator-manager": _run_validator_manager,
        "db": _run_db,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
