"""`lighthouse-tpu` CLI: one binary multiplexing every role.

Rebuild of /root/reference/lighthouse/src/main.rs:87,412-414,669-736
(bn / vc / account_manager / validator_manager / database_manager
subcommands) at the flag surface this client consumes.  Run with
``python -m lighthouse_tpu <subcommand>``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _positive_int(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError("must be >= 1")
    return n


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lighthouse-tpu",
        description="TPU-native Ethereum consensus client")
    p.add_argument("--network", default="devnet",
                   help="built-in network name (mainnet/minimal/devnet)")
    p.add_argument("--network-config", default=None,
                   help="path to a config.yaml overriding --network")
    p.add_argument("--datadir", default=None,
                   help="persistent DB directory (default: in-memory)")
    sub = p.add_subparsers(dest="command", required=True)

    bn = sub.add_parser("bn", help="run a beacon node")
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--execution-endpoint", default=None)
    bn.add_argument("--execution-jwt", default=None,
                    help="hex JWT secret for the engine API")
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--wire-transport", default="tcp",
                    choices=("tcp", "quic"),
                    help="stream transport for gossip/RPC "
                         "(quic = the UDP stream transport)")
    bn.add_argument("--disable-upnp", action="store_true",
                    help="skip UPnP gateway port mapping (reference flag)")
    bn.add_argument("--slasher-backend", default="native",
                    choices=("memory", "native", "sqlite"),
                    help="slasher DB engine (reference --slasher-backend)")
    bn.add_argument("--interop-validators", type=int, default=64,
                    help="interop genesis validator count (dev networks)")
    bn.add_argument("--genesis-fork", default="capella")
    bn.add_argument("--genesis-time", type=int, default=None,
                    help="interop genesis time (default: now); nodes "
                         "sharing a devnet must pass the same value")
    bn.add_argument("--run-seconds", type=float, default=None,
                    help="exit after N seconds (default: run forever)")
    bn.add_argument("--bls-backend", default="auto",
                    choices=["auto", "tpu", "reference", "fake"],
                    help="BLS data plane: auto = device pipeline when a "
                         "TPU is attached, pure-Python reference otherwise")
    bn.add_argument("--listen-port", type=int, default=None,
                    help="TCP+UDP wire port (0 = ephemeral); omit to run "
                         "without the socket network stack")
    bn.add_argument("--seconds-per-slot", type=int, default=None,
                    help="dev-only slot pacing override (process-fleet "
                         "devnets walk fast slots; None = the spec's)")
    bn.add_argument("--identity-seed", default=None,
                    help="deterministic wire identity seed: the node "
                         "keeps its peer id across restarts (fleets); "
                         "None = random per start")
    bn.add_argument("--interop-vc", default=None, metavar="LO:HI",
                    help="run an in-process duty loop for interop "
                         "validators [LO, HI) — the process-fleet "
                         "analogue of the simulator's validator split")
    bn.add_argument("--boot-nodes", default=None,
                    help="comma-separated host:port discovery bootstrap "
                         "addresses")
    bn.add_argument("--builder", default=None,
                    help="external block-builder (MEV) endpoint URL")
    bn.add_argument("--trusted-setup", default=None,
                    help="path to the KZG ceremony trusted_setup.json "
                         "(consensus-specs format)")
    bn.add_argument("--monitoring-endpoint", default=None,
                    help="remote monitoring service URL to POST "
                         "node/system metrics to every 60s")

    vc = sub.add_parser("vc", help="run a validator client")
    vc.add_argument("--beacon-node", default="http://127.0.0.1:5052")
    vc.add_argument("--validators-dir", default=None,
                    help="directory of EIP-2335 keystores")
    vc.add_argument("--keystore-password", default="")
    vc.add_argument("--builder-blocks", action="store_true",
                    help="propose via the blinded (builder) round trip")
    vc.add_argument("--interop-range", default=None,
                    help="START:END interop validator indices (dev)")
    vc.add_argument("--run-seconds", type=float, default=None)
    vc.add_argument("--monitoring-endpoint", default=None,
                    help="remote monitoring service URL to POST "
                         "validator/system metrics to every 60s")

    am = sub.add_parser("account-manager",
                        help="wallet + validator key tooling")
    am_sub = am.add_subparsers(dest="am_command", required=True)
    wc = am_sub.add_parser("wallet-create")
    wc.add_argument("--name", required=True)
    wc.add_argument("--password", required=True)
    wc.add_argument("--out", required=True, help="wallet JSON output path")
    wr = am_sub.add_parser("wallet-recover",
                           help="recover a wallet from a BIP-39 mnemonic")
    wr.add_argument("--name", required=True)
    wr.add_argument("--password", required=True)
    wr.add_argument("--mnemonic", required=True)
    wr.add_argument("--passphrase", default="")
    wr.add_argument("--out", required=True)
    vexit = am_sub.add_parser(
        "validator-exit", help="sign + publish a voluntary exit")
    vexit.add_argument("--keystore", required=True)
    vexit.add_argument("--password", required=True)
    vexit.add_argument("--validator-index", type=int, required=True)
    vexit.add_argument("--epoch", type=int, required=True)
    vexit.add_argument("--beacon-node", default="http://127.0.0.1:5052")
    vcreate = am_sub.add_parser("validator-create")
    vcreate.add_argument("--wallet", required=True)
    vcreate.add_argument("--wallet-password", required=True)
    vcreate.add_argument("--keystore-password", required=True)
    vcreate.add_argument("--count", type=int, default=1)
    vcreate.add_argument("--out-dir", required=True)

    vm = sub.add_parser("validator-manager",
                        help="bulk import/list validators")
    vm_sub = vm.add_subparsers(dest="vm_command", required=True)
    imp = vm_sub.add_parser("import")
    imp.add_argument("--keystores-dir", required=True)
    imp.add_argument("--password", required=True)
    imp.add_argument("--out", required=True,
                     help="validator_definitions.json output")
    vm_sub.add_parser("list").add_argument("--definitions", required=True)
    mv = vm_sub.add_parser(
        "move", help="move validators between VCs via their keymanager "
                     "APIs (delete+export from source, import to dest)")
    mv.add_argument("--src-url", required=True)
    mv.add_argument("--src-token", required=True)
    mv.add_argument("--dest-url", required=True)
    mv.add_argument("--dest-token", required=True)
    mv.add_argument("--pubkeys", required=True, nargs="+")
    mv.add_argument("--password", required=True,
                    help="transport password the moved keystores are "
                         "re-encrypted under")

    db = sub.add_parser("db", help="database inspection/maintenance")
    db_sub = db.add_subparsers(dest="db_command", required=True)
    db_sub.add_parser("inspect")
    db_sub.add_parser("compact")
    db_sub.add_parser("version")
    mig = db_sub.add_parser("migrate")
    mig.add_argument("--to", type=int, default=None,
                     help="target schema version (default: current)")
    prune = db_sub.add_parser("prune-states")
    prune.add_argument("--confirm", action="store_true")

    # lcli-equivalent dev tooling (reference lcli/src/{transition_blocks,
    # skip_slots,parse_ssz}.rs): timed state-transition runs over SSZ
    # fixtures — the CPU-baseline measuring stick.
    dev = sub.add_parser("dev", help="dev/benchmark tooling")
    dev_sub = dev.add_subparsers(dest="dev_command", required=True)
    tb = dev_sub.add_parser("transition-blocks",
                            help="apply block(s) to a pre-state, timed")
    tb.add_argument("--pre", required=True, help="pre-state SSZ path")
    tb.add_argument("--blocks", required=True, nargs="+",
                    help="signed-block SSZ path(s), in order")
    tb.add_argument("--fork", default="capella")
    tb.add_argument("--runs", type=_positive_int, default=1)
    tb.add_argument("--no-signature-verification", action="store_true")
    tb.add_argument("--post-out", default=None,
                    help="write the post-state SSZ here")
    sk = dev_sub.add_parser("skip-slots",
                            help="advance a pre-state N slots, timed")
    sk.add_argument("--pre", required=True)
    sk.add_argument("--slots", type=int, required=True)
    sk.add_argument("--fork", default="capella")
    sk.add_argument("--runs", type=_positive_int, default=1)
    sr = dev_sub.add_parser("state-root", help="hash_tree_root a state, timed")
    sr.add_argument("--state", required=True)
    sr.add_argument("--fork", default="capella")
    sr.add_argument("--runs", type=_positive_int, default=1)
    pz = dev_sub.add_parser("parse-ssz", help="decode an SSZ object to JSON")
    pz.add_argument("--type", required=True,
                    help="container name, e.g. SignedBeaconBlock:capella")
    pz.add_argument("path")
    return p


# -- subcommand drivers ------------------------------------------------------

def _run_bn(args) -> int:
    from lighthouse_tpu.client.builder import ClientBuilder, ClientConfig

    # one-shot routing calibration: measure host-vs-device pair-hash
    # rates and pick the merkle device thresholds for THIS host (the
    # static defaults assume a real TPU; an XLA-CPU fallback node would
    # route mid-sized trees to the slower path).  LHTPU_SHA_DEVICE_MIN
    # pins the threshold and skips the measurement.  Fake-crypto nodes
    # (process-fleet drills) skip it entirely: they never route device
    # work, and a fleet paying N calibration warmups serially would
    # blow its launch deadline
    if args.bls_backend != "fake":
        try:
            from lighthouse_tpu.ops import sha256 as _sha_ops

            _sha_ops.calibrate_device_thresholds()
        except Exception as e:
            # never block node startup on a calibration failure
            from lighthouse_tpu.common.metrics import record_swallowed

            record_swallowed("cli.sha_calibration", e)

    cfg = ClientConfig(
        network=args.network,
        network_config_path=args.network_config,
        datadir=args.datadir,
        http_port=args.http_port,
        execution_endpoint=args.execution_endpoint,
        execution_jwt_hex=args.execution_jwt,
        slasher_enabled=args.slasher,
        upnp_enabled=not args.disable_upnp and args.listen_port is not None,
        wire_transport=args.wire_transport,
        slasher_backend=args.slasher_backend,
        n_genesis_validators=args.interop_validators,
        genesis_fork=args.genesis_fork,
        genesis_time=args.genesis_time,
        bls_backend=args.bls_backend,
        listen_port=args.listen_port,
        boot_nodes=tuple(a.strip() for a in args.boot_nodes.split(",")
                         if a.strip()) if args.boot_nodes else (),
        builder_url=args.builder,
        trusted_setup_path=args.trusted_setup,
        monitoring_endpoint=args.monitoring_endpoint,
        seconds_per_slot=args.seconds_per_slot,
        identity_seed=args.identity_seed,
        interop_vc_range=(tuple(int(x) for x in args.interop_vc.split(":"))
                          if args.interop_vc else None),
    )

    # SIGTERM/SIGINT run the ORDERLY path — persist-frame + store close
    # + clean dirty-marker — so a fleet's stop() (SIGTERM) and kill()
    # (SIGKILL) have genuinely distinct on-disk semantics.  Installed
    # before the build: a TERM racing a slow assembly still lands
    import signal

    _stop_requested = threading.Event()
    _client_box: list = [None]

    def _graceful(signum, frame):
        _stop_requested.set()
        c = _client_box[0]
        if c is not None:
            c.executor.exit_event.set()

    for _sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(_sig, _graceful)
        except ValueError:
            # not the main thread (embedded use) — the KeyboardInterrupt
            # fallback below still covers interactive ^C
            break

    client = ClientBuilder(cfg).build()
    _client_box[0] = client
    if _stop_requested.is_set():
        client.executor.exit_event.set()
    wire = client.services.get("wire")
    print(json.dumps({
        "running": "bn", "network": client.spec.config_name,
        "http_port": client.http_server.port if client.http_server else None,
        "genesis_root": "0x" + client.chain.genesis_block_root.hex(),
        "wire_port": wire.listen_port if wire else None,
        "peer_id": wire.peer_id if wire else None,
    }), flush=True)
    try:
        deadline = (time.time() + args.run_seconds
                    if args.run_seconds else None)
        while deadline is None or time.time() < deadline:
            if client.executor.exit_event.wait(0.5):
                break
    except KeyboardInterrupt:
        pass
    client.stop()
    return 0


def _run_vc(args) -> int:
    import os

    from lighthouse_tpu.api import BeaconNodeClient
    from lighthouse_tpu.client.network_config import spec_for_network
    from lighthouse_tpu.crypto import keystore as ks
    from lighthouse_tpu.validator import ValidatorStore

    spec = spec_for_network(args.network)
    bn = BeaconNodeClient(args.beacon_node)
    genesis = bn.genesis()
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    store = ValidatorStore(spec, gvr)
    if args.interop_range:
        from lighthouse_tpu.testing import interop_secret_key

        lo, hi = (int(x) for x in args.interop_range.split(":"))
        for i in range(lo, hi):
            store.add_validator(interop_secret_key(i), index=i)
    elif args.validators_dir:
        for name in sorted(os.listdir(args.validators_dir)):
            if not name.endswith(".json"):
                continue
            with open(os.path.join(args.validators_dir, name)) as f:
                store.import_keystore(json.load(f), args.keystore_password)
    print(json.dumps({
        "running": "vc", "validators": len(store.voting_pubkeys()),
        "beacon_node": args.beacon_node,
    }), flush=True)
    # standalone duty loop: the remote VC drives propose/attest per slot
    # over the standard HTTP API (validator/remote_client.py)
    from lighthouse_tpu.validator.remote_client import RemoteValidatorClient

    rvc = RemoteValidatorClient(bn, store, spec,
                                builder_blocks=args.builder_blocks)
    rvc.resolve_indices()
    mon = None
    mon_next = 0.0
    mon_thread = None
    if args.monitoring_endpoint:
        from lighthouse_tpu.common.system_health import MonitoringHttpClient

        mon = MonitoringHttpClient(args.monitoring_endpoint,
                                   validator_store=store)
    genesis_time = int(genesis["genesis_time"])
    deadline = time.time() + args.run_seconds if args.run_seconds else None
    last_slot = None
    while deadline is None or time.time() < deadline:
        now = time.time()
        if mon is not None and now >= mon_next and not (
                mon_thread is not None and mon_thread.is_alive()):
            # post off-thread: a dead endpoint's 5s timeout must never
            # delay slot duties (the bn path gets this from the
            # executor).  Runs pre-genesis too — operators want the VC
            # visible while it waits.
            import threading as _threading

            mon_thread = _threading.Thread(
                target=mon.send_metrics, args=(("validator", "system"),),
                daemon=True)
            mon_thread.start()
            mon_next = now + mon.update_period_s
        if now < genesis_time:
            # pre-genesis: wait without consuming slot 0, so slot-0
            # duties run when genesis actually arrives
            time.sleep(min(0.25, genesis_time - now))
            continue
        slot = int((now - genesis_time) // spec.seconds_per_slot)
        if slot != last_slot:
            last_slot = slot
            try:
                summary = rvc.run_slot(slot)
                print(json.dumps({
                    "slot": slot,
                    "proposed": summary.blocks_proposed,
                    "attested": summary.attestations_published,
                }), flush=True)
            except Exception as e:
                print(json.dumps({"slot": slot, "error": str(e)}),
                      flush=True)
        time.sleep(0.25)
    return 0


def _run_account_manager(args) -> int:
    from lighthouse_tpu.crypto.wallet import Wallet

    if args.am_command == "wallet-create":
        w = Wallet.create(args.name, args.password)
        with open(args.out, "w") as f:
            json.dump(w.data, f)
        print(json.dumps({"wallet": args.name, "path": args.out}))
        return 0
    if args.am_command == "wallet-recover":
        w = Wallet.recover(args.name, args.password, args.mnemonic,
                           args.passphrase)
        with open(args.out, "w") as f:
            json.dump(w.data, f)
        print(json.dumps({"wallet": args.name, "path": args.out,
                          "recovered": True}))
        return 0
    if args.am_command == "validator-exit":
        from lighthouse_tpu.api import BeaconNodeClient
        from lighthouse_tpu.client.network_config import spec_for_network
        from lighthouse_tpu.crypto import bls, keystore as ks
        from lighthouse_tpu import types as T
        from lighthouse_tpu.state_transition import misc

        with open(args.keystore) as f:
            keystore = json.load(f)
        sk = bls.SecretKey.from_bytes(ks.decrypt(keystore, args.password))
        bn = BeaconNodeClient(args.beacon_node)
        genesis = bn.genesis()
        gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
        spec = spec_for_network(args.network)
        exit_msg = T.VoluntaryExit(
            epoch=args.epoch, validator_index=args.validator_index)
        # the NODE verifies with the domain rule for ITS current fork
        # (signature_sets.voluntary_exit_set), so the signer must key off
        # the chain head, not the exit's epoch
        head = bn.header("head")
        head_slot = int(head["header"]["message"]["slot"])
        fork_now = spec.fork_at_epoch(
            spec.compute_epoch_at_slot(head_slot))
        if T.ChainSpec.fork_at_least(fork_now, "deneb"):
            version = spec.fork_version("capella")  # EIP-7044
        elif args.epoch < spec.fork_epoch(fork_now):
            # server get_domain: previous fork version for pre-boundary
            # epochs
            from lighthouse_tpu.types.spec import FORKS

            prev = FORKS[max(FORKS.index(fork_now) - 1, 0)]
            version = spec.fork_version(prev)
        else:
            version = spec.fork_version(fork_now)
        domain = misc.compute_domain(
            spec.domain_voluntary_exit, version, gvr)
        root = misc.compute_signing_root(exit_msg.hash_tree_root(), domain)
        signed = T.SignedVoluntaryExit(
            message=exit_msg, signature=sk.sign(root).to_bytes())
        bn._call("POST", "/eth/v1/beacon/pool/voluntary_exits",
                 {"ssz_hex": signed.serialize().hex()})
        print(json.dumps({"exit_published": args.validator_index,
                          "epoch": args.epoch}))
        return 0
    if args.am_command == "validator-create":
        import os

        with open(args.wallet) as f:
            w = Wallet(json.load(f))
        os.makedirs(args.out_dir, exist_ok=True)
        created = []
        for _ in range(args.count):
            keystore, _sk = w.next_validator(
                args.wallet_password, args.keystore_password)
            path = os.path.join(
                args.out_dir, f"keystore-{keystore['pubkey'][:16]}.json")
            with open(path, "w") as f:
                json.dump(keystore, f)
            created.append(keystore["pubkey"])
        with open(args.wallet, "w") as f:
            json.dump(w.data, f)  # persist nextaccount
        print(json.dumps({"created": created}))
        return 0
    raise SystemExit(f"unknown account-manager command {args.am_command}")


def _run_validator_manager(args) -> int:
    import os

    if args.vm_command == "import":
        from lighthouse_tpu.crypto import keystore as ks

        defs = []
        for name in sorted(os.listdir(args.keystores_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(args.keystores_dir, name)
            with open(path) as f:
                keystore = json.load(f)
            ks.decrypt(keystore, args.password)  # validate the password
            defs.append({
                "enabled": True,
                "voting_public_key": "0x" + keystore["pubkey"],
                "type": "local_keystore",
                "voting_keystore_path": path,
            })
        with open(args.out, "w") as f:
            json.dump(defs, f, indent=2)
        print(json.dumps({"imported": len(defs)}))
        return 0
    if args.vm_command == "move":
        import urllib.request

        def call(url, token, method, path, body=None):
            req = urllib.request.Request(
                url + path, method=method,
                data=json.dumps(body).encode() if body else None,
                headers={"Authorization": f"Bearer {token}",
                         "Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        # 1. export from the source VC (keys re-encrypted + EIP-3076);
        # keep (pubkey, keystore) ALIGNED — a missing key must not shift
        # later pairings
        exported = call(args.src_url, args.src_token, "POST",
                        "/lighthouse/validators/export",
                        {"pubkeys": args.pubkeys,
                         "password": args.password})
        pairs = [(pk, k) for pk, k in zip(args.pubkeys, exported["data"])
                 if k is not None]
        if not pairs:
            raise SystemExit("no requested keys exist on the source VC")
        # 2. import to the destination VC with the slashing history
        imported = call(args.dest_url, args.dest_token, "POST",
                        "/eth/v1/keystores",
                        {"keystores": [k for _, k in pairs],
                         "passwords": [args.password] * len(pairs),
                         "slashing_protection":
                             exported["slashing_protection"]})
        # 3. delete from the source ONLY the keys the destination
        # confirmed — a failed import must never orphan a key
        confirmed = [pk for (pk, _), st_ in
                     zip(pairs, imported["data"])
                     if st_["status"] == "imported"]
        deleted = {"data": []}
        if confirmed:
            deleted = call(args.src_url, args.src_token, "DELETE",
                           "/eth/v1/keystores", {"pubkeys": confirmed})
        print(json.dumps({
            "moved": len(confirmed),
            "deleted": sum(1 for s_ in deleted["data"]
                           if s_["status"] == "deleted"),
            "failed": [st_ for st_ in imported["data"]
                       if st_["status"] != "imported"],
        }))
        return 0
    if args.vm_command == "list":
        with open(args.definitions) as f:
            defs = json.load(f)
        for d in defs:
            print(d["voting_public_key"])
        return 0
    raise SystemExit(f"unknown validator-manager command {args.vm_command}")


def _run_db(args) -> int:
    import os

    from lighthouse_tpu.store import NativeKVStore

    if not args.datadir:
        raise SystemExit("db commands need --datadir")

    if args.db_command in ("version", "migrate"):
        # open the raw KV only — HotColdDB would auto-migrate on open,
        # making 'version' destructive and 'migrate --to' uncontrollable
        from lighthouse_tpu.store import migrate_schema, read_schema_version

        hot_path = os.path.join(args.datadir, "hot.db")
        if not os.path.exists(hot_path):
            raise SystemExit(f"no database at {hot_path}")
        hot = NativeKVStore(hot_path)

        class _RawDB:  # the shim migrate_schema/read_schema_version need
            def __init__(self):
                self.hot = hot
                # prefer the DB's own recorded config; fall back to the
                # --network preset only for pre-v2 DBs that never stored
                # one (the operator must pass the right --network then)
                from lighthouse_tpu.store.migrations import read_db_config

                cfg = read_db_config(self)
                if cfg and "slots_per_restore_point" in cfg:
                    self.slots_per_restore_point = cfg[
                        "slots_per_restore_point"]
                else:
                    from lighthouse_tpu.client.network_config import (
                        spec_for_network,
                    )

                    spec = spec_for_network(args.network)
                    self.slots_per_restore_point = 2 * spec.slots_per_epoch

        db = _RawDB()
        if args.db_command == "migrate":
            v = migrate_schema(db, target=args.to)
        else:
            v = read_schema_version(db)
        hot.close()
        print(json.dumps({"schema_version": v}))
        return 0

    out = {}
    for name in ("hot.db", "cold.db"):
        path = os.path.join(args.datadir, name)
        if not os.path.exists(path):
            continue
        store = NativeKVStore(path)
        if args.db_command == "compact":
            store.compact()
        out[name] = {"keys": len(store),
                     "bytes": os.path.getsize(path)}
        store.close()
    if args.db_command == "prune-states" and not args.confirm:
        raise SystemExit("prune-states is destructive; pass --confirm")
    print(json.dumps({args.db_command: out}))
    return 0


def _run_dev(args) -> int:
    """lcli-equivalent timed tools (reference lcli/src/transition_blocks.rs
    :1-30 run/timing structure, skip_slots.rs)."""
    from lighthouse_tpu import types as T
    from lighthouse_tpu.client.network_config import spec_for_network

    spec = spec_for_network(args.network)
    t = T.make_types(spec.preset)

    def load_state(path, fork):
        with open(path, "rb") as f:
            return t.beacon_state_class(fork).deserialize(f.read())

    if args.dev_command == "parse-ssz":
        name, _, fork = args.type.partition(":")
        cls = (t.signed_beacon_block_class(fork or "capella")
               if name == "SignedBeaconBlock"
               else t.beacon_state_class(fork or "capella")
               if name == "BeaconState"
               else getattr(T, name))
        with open(args.path, "rb") as f:
            obj = cls.deserialize(f.read())
        root = obj.hash_tree_root()
        print(json.dumps({"type": args.type,
                          "hash_tree_root": "0x" + root.hex()}))
        return 0

    if args.dev_command == "state-root":
        state = load_state(args.state, args.fork)
        times = []
        for _ in range(args.runs):
            state_copy = state.copy()
            t0 = time.perf_counter()
            root = state_copy.hash_tree_root()
            times.append(time.perf_counter() - t0)
        print(json.dumps({
            "state_root": "0x" + root.hex(),
            "slot": int(state.slot),
            "ms_per_run": round(min(times) * 1000, 3)}))
        return 0

    if args.dev_command == "skip-slots":
        from lighthouse_tpu.state_transition import state_advance

        state = load_state(args.pre, args.fork)
        target = int(state.slot) + args.slots
        times = []
        for _ in range(args.runs):
            st = state.copy()
            t0 = time.perf_counter()
            state_advance(st, spec, target)
            times.append(time.perf_counter() - t0)
        print(json.dumps({
            "slots": args.slots,
            "post_root": "0x" + st.hash_tree_root().hex(),
            "ms_per_run": round(min(times) * 1000, 3)}))
        return 0

    if args.dev_command == "transition-blocks":
        from lighthouse_tpu.state_transition import (
            SignatureStrategy,
            state_transition,
        )

        state = load_state(args.pre, args.fork)
        blocks = []
        for path in args.blocks:
            with open(path, "rb") as f:
                blocks.append(
                    t.signed_beacon_block_class(args.fork).deserialize(
                        f.read()))
        strategy = (SignatureStrategy.NO_VERIFICATION
                    if args.no_signature_verification
                    else SignatureStrategy.VERIFY_BULK)
        times = []
        for _ in range(args.runs):
            st = state.copy()
            t0 = time.perf_counter()
            for blk in blocks:
                state_transition(st, spec, blk, strategy,
                                 validate_result=False)
            times.append(time.perf_counter() - t0)
        if args.post_out:
            with open(args.post_out, "wb") as f:
                f.write(st.serialize())
        print(json.dumps({
            "blocks": len(blocks),
            "post_root": "0x" + st.hash_tree_root().hex(),
            "ms_per_run": round(min(times) * 1000, 3)}))
        return 0
    raise SystemExit(f"unknown dev command {args.dev_command}")


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    return {
        "bn": _run_bn,
        "vc": _run_vc,
        "account-manager": _run_account_manager,
        "validator-manager": _run_validator_manager,
        "db": _run_db,
        "dev": _run_dev,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
