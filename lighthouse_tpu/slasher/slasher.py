"""Slasher: double-vote + surround-vote detection over batched queues.

Rebuild of /root/reference/slasher/src/{lib,attestation_queue,
block_queue}.rs + slasher/service: gossip-verified attestations and
block headers queue up and are processed in per-epoch batches; detected
offences yield AttesterSlashing / ProposerSlashing containers that the
service submits to the operation pool.  Detection state is the columnar
SurroundArray plus an indexed-attestation store keyed by
(target_epoch, data_root), persisted through the embedded KV engine
(the reference swaps LMDB/MDBX/redb behind one interface; here the
C++ log-structured store or the in-memory store serve the same role).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.common.metrics import record_swallowed
from lighthouse_tpu.slasher.array import SurroundArray
from lighthouse_tpu.store.kv import KeyValueOp, MemoryStore

P_ATT = b"sa:"      # (target, data_root) -> indexed attestation ssz
P_ATT_REF = b"sr:"  # (validator, target) -> data_root
P_BLOCK = b"sb:"    # (proposer, slot) -> signed header ssz


@dataclass
class SlasherConfig:
    history_length: int = 4096
    # flush dirty min/max chunks to the KV store after every batch
    # (reference: chunks write back to the slasher DB per update)
    chunk_persist: bool = True
    # "memory" | "native" | "sqlite" — the reference swaps MDBX/LMDB/redb
    # behind one interface (slasher/src/config.rs DEFAULT_BACKEND); the
    # equivalent seam here picks the KeyValueStore implementation
    backend: str = "memory"
    db_path: str | None = None


def open_slasher_db(config: SlasherConfig):
    """Backend seam: build the KeyValueStore named by the config
    (reference DatabaseBackend::{Mdbx,Lmdb,Redb} selection)."""
    if config.backend == "memory":
        return MemoryStore()
    if config.db_path is None:
        raise ValueError(f"backend {config.backend!r} needs db_path")
    if config.backend == "native":
        from lighthouse_tpu.store.kv import NativeKVStore

        return NativeKVStore(config.db_path)
    if config.backend == "sqlite":
        from lighthouse_tpu.store.kv import SqliteStore

        return SqliteStore(config.db_path)
    raise ValueError(f"unknown slasher backend {config.backend!r}")


@dataclass
class SlashingsFound:
    attester: list = field(default_factory=list)
    proposer: list = field(default_factory=list)


class Slasher:
    def __init__(self, spec, t, db=None, config: SlasherConfig | None = None,
                 n_validators: int = 0):
        self.spec = spec
        self.t = t
        self.config = config or SlasherConfig()
        self.db = db if db is not None else open_slasher_db(self.config)
        # resume the min/max planes from a prior run's chunk blobs
        # (reference: the arrays ARE the DB; here they load from it)
        self.array = SurroundArray.load(
            self.db, self.config.history_length) or SurroundArray(
            n_validators, self.config.history_length)
        self._att_queue: list = []
        self._block_queue: list = []

    # -- ingest (called from gossip pipelines) ----------------------------

    def accept_attestation(self, indexed_attestation) -> None:
        self._att_queue.append(indexed_attestation)

    def accept_block_header(self, signed_header) -> None:
        self._block_queue.append(signed_header)

    # -- batch processing (reference: per-epoch batches) ------------------

    def process_queued(self, current_epoch: int) -> SlashingsFound:
        found = SlashingsFound()
        atts, self._att_queue = self._att_queue, []
        blocks, self._block_queue = self._block_queue, []

        # group by (source, target, data_root): one columnar update per
        # distinct vote (reference groups per chunk; grouping per vote is
        # the natural columnar unit)
        groups: dict[tuple, tuple] = {}
        for att in atts:
            s = int(att.data.source.epoch)
            t_ = int(att.data.target.epoch)
            root = att.data.hash_tree_root()
            key = (s, t_, root)
            if key in groups:
                prev = groups[key][1]
                merged = np.union1d(
                    prev, np.asarray(att.attesting_indices, np.int64))
                groups[key] = (att, merged)
            else:
                groups[key] = (att, np.asarray(
                    att.attesting_indices, np.int64))

        for (s, t_, root), (att, indices) in sorted(groups.items()):
            if t_ + self.config.history_length <= current_epoch:
                continue  # beyond the detection window
            self._detect_double_votes(att, indices, t_, root, found)
            self._detect_surrounds(att, indices, s, t_, root, found)
            self._store_attestation(att, indices, t_, root)

        for header in blocks:
            self._detect_double_proposal(header, found)
        if self.config.chunk_persist and groups:
            self.array.save(self.db)  # incremental: dirty chunks only
        return found

    # -- double votes -----------------------------------------------------

    def _att_ref_key(self, validator: int, target: int) -> bytes:
        return P_ATT_REF + int(validator).to_bytes(8, "little") + \
            int(target).to_bytes(8, "little")

    def _detect_double_votes(self, att, indices, target, root, found):
        for v in indices:
            prior_root = self.db.get(self._att_ref_key(v, target))
            if prior_root is None or prior_root == root:
                continue
            prior = self._load_attestation(target, prior_root)
            if prior is None:
                continue
            found.attester.append(self.t.AttesterSlashing(
                attestation_1=prior, attestation_2=att))
            break  # one slashing proves the offence for this vote

    def _detect_surrounds(self, att, indices, s, t_, root, found):
        surrounds, surrounded = self.array.check_and_insert(indices, s, t_)
        offenders = set(np.asarray(indices)[surrounds | surrounded])
        for v in offenders:
            counter = self._find_countervote(int(v), s, t_)
            if counter is not None:
                found.attester.append(self.t.AttesterSlashing(
                    attestation_1=counter, attestation_2=att))
                break

    def _find_countervote(self, validator: int, s: int, t_: int):
        """Locate a stored attestation by `validator` in surround relation
        with (s, t_)."""
        for e, mn, mx in self.array.lookup_source_epochs(
                validator, max(0, t_ - self.config.history_length),
                t_ + self.config.history_length):
            for target in (mn, mx):
                if e == s and target == t_:
                    continue
                if not ((e < s and target > t_) or (e > s and target < t_)):
                    continue
                ref = self.db.get(self._att_ref_key(validator, target))
                if ref is None:
                    continue
                prior = self._load_attestation(target, ref)
                if prior is not None:
                    return prior
        return None

    # -- storage ----------------------------------------------------------

    def _store_attestation(self, att, indices, target, root):
        ops = [KeyValueOp(
            P_ATT + int(target).to_bytes(8, "little") + root,
            att.serialize())]
        for v in indices:
            ops.append(KeyValueOp(self._att_ref_key(v, target), root))
        self.db.do_atomically(ops)

    def _load_attestation(self, target, root):
        raw = self.db.get(P_ATT + int(target).to_bytes(8, "little") + root)
        if raw is None:
            return None
        return self.t.IndexedAttestation.deserialize(raw)

    # -- proposer double votes --------------------------------------------

    def _detect_double_proposal(self, signed_header, found):
        from lighthouse_tpu.types.containers import (
            ProposerSlashing,
            SignedBeaconBlockHeader,
        )

        h = signed_header.message
        key = (P_BLOCK + int(h.proposer_index).to_bytes(8, "little")
               + int(h.slot).to_bytes(8, "little"))
        prior_raw = self.db.get(key)
        if prior_raw is not None:
            prior = SignedBeaconBlockHeader.deserialize(prior_raw)
            if prior.message.hash_tree_root() != h.hash_tree_root():
                found.proposer.append(ProposerSlashing(
                    signed_header_1=prior, signed_header_2=signed_header))
                return
        self.db.put(key, signed_header.serialize())

    # -- pruning ----------------------------------------------------------

    def prune(self, current_epoch: int) -> None:
        """Drop attestation records older than the history window."""
        cutoff = max(0, current_epoch - self.config.history_length)
        dead = []
        for key, _ in self.db.iter_prefix(P_ATT):
            target = int.from_bytes(key[len(P_ATT):len(P_ATT) + 8], "little")
            if target < cutoff:
                dead.append(key)
        for key in dead:
            self.db.delete(key)


class SlasherService:
    """Wires the slasher into a chain: ingest gossip-verified material,
    run batches on epoch ticks, feed slashings to the op pool
    (reference slasher/service)."""

    def __init__(self, chain, slasher: Slasher | None = None):
        self.chain = chain
        self.slasher = slasher or Slasher(
            chain.spec, chain.t, n_validators=len(
                chain.head_state.validators))
        self._last_batch_epoch = -1

    def on_verified_attestation(self, indexed_attestation) -> None:
        self.slasher.accept_attestation(indexed_attestation)

    def on_block(self, signed_block) -> None:
        from lighthouse_tpu.types.containers import (
            BeaconBlockHeader,
            SignedBeaconBlockHeader,
        )

        msg = signed_block.message
        self.slasher.accept_block_header(SignedBeaconBlockHeader(
            message=BeaconBlockHeader(
                slot=msg.slot, proposer_index=msg.proposer_index,
                parent_root=msg.parent_root, state_root=msg.state_root,
                body_root=msg.body.hash_tree_root()),
            signature=bytes(signed_block.signature)))

    def tick(self, current_slot: int) -> SlashingsFound:
        epoch = self.chain.spec.compute_epoch_at_slot(current_slot)
        found = self.slasher.process_queued(epoch)
        for sl in found.attester:
            try:
                self.chain.op_pool.insert_attester_slashing(sl)
            except Exception as e:
                record_swallowed("slasher.insert_attester", e)
        for sl in found.proposer:
            try:
                self.chain.op_pool.insert_proposer_slashing(sl)
            except Exception as e:
                record_swallowed("slasher.insert_proposer", e)
        if epoch > self._last_batch_epoch:
            self.slasher.prune(epoch)
            self._last_batch_epoch = epoch
        return found
