"""Slasher (reference /root/reference/slasher): surround/double-vote
detection over columnar (validator × epoch) planes."""

from lighthouse_tpu.slasher.array import SurroundArray
from lighthouse_tpu.slasher.slasher import (
    Slasher,
    SlasherConfig,
    SlasherService,
    SlashingsFound,
)

__all__ = [
    "Slasher",
    "SlasherConfig",
    "SlasherService",
    "SlashingsFound",
    "SurroundArray",
]
