"""Columnar surround-vote detection engine.

Rebuild of /root/reference/slasher/src/array.rs, redesigned columnar:
the reference keeps chunked (validator × epoch) u16 min/max-target-
distance arrays with per-chunk disk pages and lazy running extremes;
here the whole window lives as two numpy (validator × history) planes
and every check/update is a vectorized slice over the attesting
committee — one numpy reduction per (source, target) group instead of
per-validator chunk walks.

min_plane[v, e % H] = min attestation target by v with source epoch e
max_plane[v, e % H] = max target likewise (NOVAL sentinels when empty).

For a new attestation (s, t) by committee V:
  * it SURROUNDS an earlier vote  iff min over e in (s, t) of
    min_plane[V, e] is < t        (victim has s' > s, t' < t)
  * it is SURROUNDED by one       iff max over e in (max(0, t-H), s) of
    max_plane[V, e] is > t        (attacker has s' < s, t' > t)

Epoch indices wrap modulo the history length; advancing the current
epoch clears the recycled columns (the reference's chunk pruning).
"""

from __future__ import annotations

import numpy as np

MIN_NOVAL = np.uint32(0xFFFFFFFF)
MAX_NOVAL = np.uint32(0)


class SurroundArray:
    def __init__(self, n_validators: int, history_length: int = 4096):
        self.H = int(history_length)
        self.n = int(n_validators)
        self.min_plane = np.full((self.n, self.H), MIN_NOVAL, np.uint32)
        self.max_plane = np.full((self.n, self.H), MAX_NOVAL, np.uint32)
        # absolute source epoch stored in each column, NONE = -1
        self.col_epoch = np.full(self.H, -1, np.int64)

    def _ensure_validators(self, max_index: int) -> None:
        if max_index < self.n:
            return
        grow = max(self.n * 2, max_index + 1, 64)
        for name, noval in (("min_plane", MIN_NOVAL),
                            ("max_plane", MAX_NOVAL)):
            old = getattr(self, name)
            new = np.full((grow, self.H), noval, old.dtype)
            new[: self.n] = old
            setattr(self, name, new)
        self.n = grow

    def _column(self, epoch: int) -> int:
        """Map an absolute epoch to its column, recycling stale ones."""
        col = epoch % self.H
        if self.col_epoch[col] != epoch:
            self.min_plane[:, col] = MIN_NOVAL
            self.max_plane[:, col] = MAX_NOVAL
            self.col_epoch[col] = epoch
        return col

    def _columns_range(self, lo: int, hi: int) -> np.ndarray:
        """Valid columns holding sources in [lo, hi) (absolute epochs)."""
        if hi <= lo:
            return np.zeros(0, np.int64)
        epochs = np.arange(max(lo, 0), hi, dtype=np.int64)
        cols = epochs % self.H
        live = self.col_epoch[cols] == epochs
        return cols[live]

    def check_and_insert(
        self, indices: np.ndarray, source: int, target: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Process one (source, target) group for a whole committee.

        Returns (surrounds_mask, surrounded_mask) over `indices`: which
        attesters' NEW vote surrounds an older one / is surrounded by an
        older one.  The vote is recorded either way (the slashing is the
        caller's to build from the indexed-attestation DB).
        """
        indices = np.asarray(indices, np.int64)
        if indices.size:
            self._ensure_validators(int(indices.max()))
        s, t = int(source), int(target)

        # victims of the new vote: sources strictly inside (s, t)
        cols_in = self._columns_range(s + 1, t)
        if cols_in.size and indices.size:
            window = self.min_plane[np.ix_(indices, cols_in)]
            surrounds = window.min(axis=1) < np.uint32(t)
        else:
            surrounds = np.zeros(indices.shape[0], bool)

        # attackers of the new vote: sources strictly before s, targets > t
        cols_before = self._columns_range(t - self.H + 1, s)
        if cols_before.size and indices.size:
            window = self.max_plane[np.ix_(indices, cols_before)]
            surrounded = window.max(axis=1) > np.uint32(t)
        else:
            surrounded = np.zeros(indices.shape[0], bool)

        col = self._column(s)
        cur_min = self.min_plane[indices, col]
        cur_max = self.max_plane[indices, col]
        self.min_plane[indices, col] = np.minimum(cur_min, np.uint32(t))
        self.max_plane[indices, col] = np.maximum(cur_max, np.uint32(t))
        return surrounds, surrounded

    def lookup_source_epochs(self, validator: int, lo: int, hi: int
                             ) -> list[tuple[int, int, int]]:
        """(source, min_target, max_target) entries for one validator with
        source in [lo, hi) — used to locate the countervote when building
        a slashing."""
        out = []
        for e in range(max(lo, 0), hi):
            col = e % self.H
            if self.col_epoch[col] != e:
                continue
            mn = int(self.min_plane[validator, col])
            mx = int(self.max_plane[validator, col])
            if mn != int(MIN_NOVAL):
                out.append((e, mn, mx))
        return out
