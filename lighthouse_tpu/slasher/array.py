"""Columnar surround-vote detection engine.

Rebuild of /root/reference/slasher/src/array.rs, redesigned columnar:
the reference keeps chunked (validator × epoch) u16 min/max-target-
distance arrays with per-chunk disk pages and lazy running extremes;
here the whole window lives as two numpy (validator × history) uint16
DISTANCE planes and every check/update is a vectorized slice over the
attesting committee — one numpy reduction per (source, target) group
instead of per-validator chunk walks.

Encoding (matches the reference's u16 distance choice,
slasher/src/array.rs): for a column holding source epoch e,

  min_plane[v, e % H] = min (target - e) over v's attestations with
                        source epoch e          (0xFFFF when empty)
  max_plane[v, e % H] = max (target - e) likewise  (0 when empty)

Distances within the detection window are <= H + 1 << 0xFFFE, so u16
never saturates in reachable states; uint16 halves resident memory vs
a target-epoch encoding (16 MB per 1k validators at H=4096 -> 8 MB,
and zlib compresses the NOVAL-dominated planes ~100x on disk).

For a new attestation (s, t) by committee V:
  * it SURROUNDS an earlier vote   iff  min_plane[V, e] < t - e for
    some column e in (s, t)         (victim has s' > s, t' < t)
  * it is SURROUNDED by one        iff  max_plane[V, e] > t - e for
    some column e in (max(0, t-H), s)  (attacker has s' < s, t' > t)

Epoch indices wrap modulo the history length; advancing the current
epoch clears the recycled columns (the reference's chunk pruning).

Persistence (reference array.rs chunked zlib pages): the planes save
to any KeyValueStore as per-(validator-chunk × epoch-chunk) zlib blobs
— 256 validators × 16 columns per blob, the reference's
DEFAULT_VALIDATOR_CHUNK_SIZE × DEFAULT_CHUNK_SIZE — with each blob
carrying its own column-epoch snapshot so stale blobs self-invalidate
on load.  Only dirty chunks rewrite (save() after each batch is an
incremental flush, not a full dump).
"""

from __future__ import annotations

import zlib

import numpy as np

from lighthouse_tpu.store.kv import KeyValueOp, KeyValueStore

MIN_NOVAL = np.uint16(0xFFFF)
MAX_NOVAL = np.uint16(0)

CHUNK_V = 256   # validators per persisted blob (ref validator_chunk_size)
CHUNK_E = 16    # columns per persisted blob (ref chunk_size)

P_CHUNK = b"sc:"   # (vchunk, echunk) -> zlib(col_epochs || min || max)
P_META = b"sce:"   # global column-epoch array + validator count


class SurroundArray:
    def __init__(self, n_validators: int, history_length: int = 4096):
        self.H = int(history_length)
        self.n = int(n_validators)
        self.min_plane = np.full((self.n, self.H), MIN_NOVAL, np.uint16)
        self.max_plane = np.full((self.n, self.H), MAX_NOVAL, np.uint16)
        # absolute source epoch stored in each column, NONE = -1
        self.col_epoch = np.full(self.H, -1, np.int64)
        self._dirty: set[tuple[int, int]] = set()

    def _ensure_validators(self, max_index: int) -> None:
        if max_index < self.n:
            return
        grow = max(self.n * 2, max_index + 1, 64)
        for name, noval in (("min_plane", MIN_NOVAL),
                            ("max_plane", MAX_NOVAL)):
            old = getattr(self, name)
            new = np.full((grow, self.H), noval, old.dtype)
            new[: self.n] = old
            setattr(self, name, new)
        self.n = grow

    def _column(self, epoch: int) -> int:
        """Map an absolute epoch to its column, recycling stale ones."""
        col = epoch % self.H
        if self.col_epoch[col] != epoch:
            self.min_plane[:, col] = MIN_NOVAL
            self.max_plane[:, col] = MAX_NOVAL
            self.col_epoch[col] = epoch
        return col

    def _columns_range(self, lo: int, hi: int
                       ) -> tuple[np.ndarray, np.ndarray]:
        """(columns, their absolute epochs) holding sources in [lo, hi)."""
        if hi <= lo:
            z = np.zeros(0, np.int64)
            return z, z
        epochs = np.arange(max(lo, 0), hi, dtype=np.int64)
        cols = epochs % self.H
        live = self.col_epoch[cols] == epochs
        return cols[live], epochs[live]

    def check_and_insert(
        self, indices: np.ndarray, source: int, target: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Process one (source, target) group for a whole committee.

        Returns (surrounds_mask, surrounded_mask) over `indices`: which
        attesters' NEW vote surrounds an older one / is surrounded by an
        older one.  The vote is recorded either way (the slashing is the
        caller's to build from the indexed-attestation DB).
        """
        indices = np.asarray(indices, np.int64)
        if indices.size:
            self._ensure_validators(int(indices.max()))
        s, t = int(source), int(target)

        # victims of the new vote: sources strictly inside (s, t); the
        # per-column threshold is the new vote's distance from THAT column
        cols_in, eps_in = self._columns_range(s + 1, t)
        if cols_in.size and indices.size:
            window = self.min_plane[np.ix_(indices, cols_in)]
            thresh = (t - eps_in).astype(np.uint16)  # in (0, H)
            surrounds = (window < thresh[None, :]).any(axis=1)
        else:
            surrounds = np.zeros(indices.shape[0], bool)

        # attackers of the new vote: sources strictly before s, targets > t
        cols_before, eps_before = self._columns_range(t - self.H + 1, s)
        if cols_before.size and indices.size:
            window = self.max_plane[np.ix_(indices, cols_before)]
            thresh = np.minimum(t - eps_before, 0xFFFE).astype(np.uint16)
            surrounded = (window > thresh[None, :]).any(axis=1)
        else:
            surrounded = np.zeros(indices.shape[0], bool)

        col = self._column(s)
        d = np.uint16(min(t - s, 0xFFFE))  # unreachable clip, belt only
        cur_min = self.min_plane[indices, col]
        cur_max = self.max_plane[indices, col]
        self.min_plane[indices, col] = np.minimum(cur_min, d)
        self.max_plane[indices, col] = np.maximum(cur_max, d)
        ec = col // CHUNK_E
        for vc in np.unique(indices // CHUNK_V):
            self._dirty.add((int(vc), ec))
        return surrounds, surrounded

    def lookup_source_epochs(self, validator: int, lo: int, hi: int
                             ) -> list[tuple[int, int, int]]:
        """(source, min_target, max_target) entries for one validator with
        source in [lo, hi) — used to locate the countervote when building
        a slashing.  One vectorized pass over the live columns (an
        8k-epoch window scanned per offender was the profile's hottest
        python loop)."""
        cols, eps = self._columns_range(lo, hi)
        if cols.size == 0:
            return []
        mn = self.min_plane[validator, cols]
        has = mn != MIN_NOVAL
        mx = self.max_plane[validator, cols]
        return [(int(e), int(e) + int(a), int(e) + int(b))
                for e, a, b in zip(eps[has], mn[has], mx[has])]

    # -- chunked persistence ----------------------------------------------

    def _chunk_key(self, vc: int, ec: int) -> bytes:
        return P_CHUNK + int(vc).to_bytes(4, "little") + \
            int(ec).to_bytes(4, "little")

    def save(self, db: KeyValueStore, full: bool = False) -> int:
        """Flush dirty (or all non-empty, when ``full``) chunks as zlib
        blobs + the global column-epoch metadata.  Returns the number of
        chunk blobs written."""
        if full:
            todo = {(vc, ec)
                    for vc in range((self.n + CHUNK_V - 1) // CHUNK_V)
                    for ec in range((self.H + CHUNK_E - 1) // CHUNK_E)}
        else:
            todo = set(self._dirty)
        ops = []
        for vc, ec in sorted(todo):
            v0, v1 = vc * CHUNK_V, min((vc + 1) * CHUNK_V, self.n)
            c0, c1 = ec * CHUNK_E, min((ec + 1) * CHUNK_E, self.H)
            if v0 >= self.n or c0 >= self.H:
                continue
            mn = self.min_plane[v0:v1, c0:c1]
            mx = self.max_plane[v0:v1, c0:c1]
            if full and (mn == MIN_NOVAL).all() and (mx == MAX_NOVAL).all():
                continue  # nothing recorded; skip the empty blob
            raw = (self.col_epoch[c0:c1].tobytes()
                   + np.ascontiguousarray(mn).tobytes()
                   + np.ascontiguousarray(mx).tobytes())
            ops.append(KeyValueOp(self._chunk_key(vc, ec),
                                  zlib.compress(raw)))
        meta = (int(self.n).to_bytes(8, "little")
                + int(self.H).to_bytes(8, "little")
                + self.col_epoch.tobytes())
        ops.append(KeyValueOp(P_META, zlib.compress(meta)))
        db.do_atomically(ops)
        self._dirty.clear()
        return len(ops) - 1

    @classmethod
    def load(cls, db: KeyValueStore,
             history_length: int = 4096) -> "SurroundArray | None":
        """Rebuild from chunk blobs; None when the store holds no array.

        Each blob self-invalidates per column: rows whose embedded
        column epoch disagrees with the global metadata (the column was
        recycled after that blob's last write) reset to NOVAL."""
        raw_meta = db.get(P_META)
        if raw_meta is None:
            return None
        meta = zlib.decompress(raw_meta)
        n = int.from_bytes(meta[:8], "little")
        h = int.from_bytes(meta[8:16], "little")
        if h != history_length:
            raise ValueError(
                f"stored history_length {h} != configured {history_length}")
        arr = cls(n, h)
        arr.col_epoch = np.frombuffer(meta[16:], np.int64).copy()
        for key, blob in db.iter_prefix(P_CHUNK):
            vc = int.from_bytes(key[len(P_CHUNK):len(P_CHUNK) + 4], "little")
            ec = int.from_bytes(key[len(P_CHUNK) + 4:len(P_CHUNK) + 8],
                                "little")
            v0, v1 = vc * CHUNK_V, min((vc + 1) * CHUNK_V, n)
            c0, c1 = ec * CHUNK_E, min((ec + 1) * CHUNK_E, h)
            if v0 >= n or c0 >= h:
                continue
            raw = zlib.decompress(blob)
            rows, cols = v1 - v0, c1 - c0
            eb = cols * 8
            blk = rows * cols * 2
            blob_eps = np.frombuffer(raw[:eb], np.int64)
            mn = np.frombuffer(raw[eb:eb + blk], np.uint16).reshape(
                rows, cols)
            mx = np.frombuffer(raw[eb + blk:eb + 2 * blk],
                               np.uint16).reshape(rows, cols)
            live = blob_eps == arr.col_epoch[c0:c1]
            mn = np.where(live[None, :], mn, MIN_NOVAL)
            mx = np.where(live[None, :], mx, MAX_NOVAL)
            arr.min_plane[v0:v1, c0:c1] = mn
            arr.max_plane[v0:v1, c0:c1] = mx
        return arr
