"""Device-resident validator pubkey plane for the attestation firehose.

The registry's pubkey column lives ON DEVICE as an affine Montgomery
limb table; committee aggregate pubkeys for the ingest lane's
(slot, committee index, beacon_block_root) groups become a gather +
G1 MSM in one fused dispatch (ops/pubkey_kernels) instead of per-set
host point additions in ``SignatureSet.aggregate_pubkey`` /
``pre_aggregation._fold_group`` — the per-set host cost ISSUE 14's
profile names as the post-decode firehose ceiling.

Rungs, mirroring the epoch/BLS supervisor shape (PR 4 breaker):

- ``device``  — the fused gather+MSM kernel over the resident table;
- ``sharded`` — same kernel, lanes partitioned over the device mesh
  (parallel/msm_sharded; LHTPU_MSM_SHARDED=0 drops the auto-pick);
- ``reference`` — host point adds (one ``g1_mul`` per unique
  (group, pubkey) after scalar-sum collapse), the authoritative
  terminal rung.

Faults on a device rung recover on reference, count
``pubkey_plane_faults_total``, and trip a consecutive-fault breaker
(shared LHTPU_SUPERVISOR_* knobs); successes close it.  The breaker
transitions emit flight events like the other planes.

Table refresh/invalidation discipline: validator pubkeys are
append-only and immutable per index (consensus invariant), so a table
covering rows [0, T) stays valid for any registry that grew from the
same prefix.  The plane fingerprints the registry's pubkey column
(sha256) at build; a registry object it has not seen yet is verified
against the prefix fingerprint before reuse and the check result is
cached on the object — a MISMATCH rebuilds from scratch (all-or-nothing
swap: the new table is fully built before the old one is replaced, a
mid-build fault leaves the old table serving).  The PR 6 epoch
bridge's write-back calls :func:`notify_registry` after registry
updates so growth refreshes eagerly instead of on first use.

``LHTPU_PUBKEY_PLANE=0`` is the kill switch: the plane always answers
with the reference rung and never touches jax.
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed

_BACKENDS = ("device", "sharded", "reference")
_DEVICE_MIN_DEFAULT = 256

_BREAKER = {"fails": 0, "open_until": 0.0, "backoff": 0.0}
_BREAKER_LOCK = threading.Lock()
_AUTO_RUNG: str | None = None


def enabled() -> bool:
    return envreg.get_bool("LHTPU_PUBKEY_PLANE", True)


def reset_pubkey_plane() -> None:
    """Close the breaker, drop the memoized auto rung and the table
    (tests / operator reset)."""
    global _AUTO_RUNG, _PLANE
    with _BREAKER_LOCK:
        _BREAKER.update(fails=0, open_until=0.0, backoff=0.0)
    _AUTO_RUNG = None
    _PLANE = PubkeyPlane()


def resolve_pubkey_backend(n_lanes: int) -> str:
    """Which rung folds an ``n_lanes`` batch: kill switch first, then
    LHTPU_PUBKEY_BACKEND force, the breaker, then auto (device only on
    a real TPU at or above LHTPU_PUBKEY_DEVICE_MIN lanes — XLA-CPU
    defaults to reference: first-dispatch compiles dominate short
    processes; operators can force the device rung on long-lived
    fallback nodes).  Small batches never import jax."""
    if not enabled():
        return "reference"
    forced = envreg.get_choice("LHTPU_PUBKEY_BACKEND", _BACKENDS)
    if forced:
        return forced
    with _BREAKER_LOCK:
        open_until = _BREAKER["open_until"]
    if open_until > time.monotonic():
        return "reference"
    device_min = envreg.get_int("LHTPU_PUBKEY_DEVICE_MIN",
                                _DEVICE_MIN_DEFAULT)
    if n_lanes < max(device_min, 1):
        return "reference"
    global _AUTO_RUNG
    if _AUTO_RUNG is None:
        import jax

        if jax.devices()[0].platform != "tpu":
            _AUTO_RUNG = "reference"
        elif (len(jax.devices()) > 1
                and envreg.get_bool("LHTPU_MSM_SHARDED", True)):
            _AUTO_RUNG = "sharded"
        else:
            _AUTO_RUNG = "device"
    return _AUTO_RUNG


def _breaker_ok() -> None:
    was_tripped = False
    with _BREAKER_LOCK:
        was_tripped = _BREAKER["open_until"] > 0.0
        _BREAKER["fails"] = 0
        _BREAKER["backoff"] = 0.0
        _BREAKER["open_until"] = 0.0
    if was_tripped:
        from lighthouse_tpu.common import flight_recorder as flight

        flight.emit("breaker", plane="pubkey", old="open", new="closed")


def _breaker_fault() -> None:
    threshold = envreg.get_int("LHTPU_SUPERVISOR_FAILS", 1) or 1
    backoff_init = float(
        envreg.get_float("LHTPU_SUPERVISOR_BACKOFF_S", 1.0) or 1.0)
    ceiling = float(
        envreg.get_float("LHTPU_SUPERVISOR_BACKOFF_MAX_S", 60.0) or 60.0)
    opened = False
    with _BREAKER_LOCK:
        fails = _BREAKER["fails"] = _BREAKER["fails"] + 1
        if fails >= threshold:
            backoff = _BREAKER["backoff"] or backoff_init
            _BREAKER["open_until"] = time.monotonic() + backoff
            _BREAKER["backoff"] = min(backoff * 2, ceiling)
            _BREAKER["fails"] = 0
            opened = True
    from lighthouse_tpu.common import flight_recorder as flight

    flight.emit("breaker", plane="pubkey", old="closed",
                new="open" if opened else "counting", fails=fails)


def record_fold(backend: str, seconds: float, n_groups: int) -> None:
    try:
        REGISTRY.counter(
            "pubkey_plane_batches_total",
            "aggregate-pubkey fold batches by executing backend",
        ).labels(backend=backend).inc()
        REGISTRY.histogram(
            "pubkey_plane_fold_seconds",
            "aggregate-pubkey fold wall time by backend",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                     5.0, 60.0),
        ).labels(backend=backend).observe(seconds)
        REGISTRY.counter(
            "pubkey_plane_groups_total",
            "merged (slot, committee index, beacon_block_root) lanes "
            "folded").inc(n_groups)
    except Exception as e:
        record_swallowed("pubkey_plane.record_fold", e)


def record_plane_fault(backend: str, kind: str) -> None:
    try:
        REGISTRY.counter(
            "pubkey_plane_faults_total",
            "device pubkey-plane faults recovered on the reference rung",
        ).labels(backend=backend, kind=kind).inc()
    except Exception as e:
        record_swallowed("pubkey_plane.record_fault", e)


class _TableUnavailable(RuntimeError):
    """ensure_table failed — the fault and breaker step were already
    recorded there; fold() must not account them a second time."""


class PubkeyPlane:
    """The resident table + fold entry point (module singleton via
    :func:`get_plane`; a fresh instance per reset keeps tests
    hermetic)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table = None          # (tx, ty) device arrays
        self._table_rows = 0        # valid rows in the table
        self._rows = None           # host (x, y) limb rows for [0, table_rows)
        self._prefix_sha = b""      # sha256 of pubkey rows [0, table_rows)
        # verified registry objects, id -> STRONG ref (a live ref can't
        # have its id() recycled by a different registry — the memo can
        # never alias; bounded, newest-wins)
        self._seen: dict[int, object] = {}

    # -- table discipline --------------------------------------------------

    def _column_sha(self, validators, n: int) -> bytes:
        return hashlib.sha256(
            np.ascontiguousarray(validators.pubkeys[:n]).tobytes()).digest()

    def _registry_matches(self, validators) -> bool:
        """True when the resident table is a prefix of this registry
        (append-only discipline); memoized per registry object."""
        if self._table_rows == 0:
            return False
        if len(validators) < self._table_rows:
            return False
        if id(validators) in self._seen:
            return True
        ok = self._column_sha(validators, self._table_rows) == \
            self._prefix_sha
        if ok:
            if len(self._seen) >= 4:
                self._seen.pop(next(iter(self._seen)))
            self._seen[id(validators)] = validators
        return ok

    def ensure_table(self, validators) -> bool:
        """Make the device table cover this registry — incremental
        append when the prefix matches (only the NEW rows decompress
        and limb-convert; the resident rows' host limbs are cached),
        full rebuild otherwise.  A registry SHORTER than the table is
        served as-is: the registry is append-only (deposits apply in
        deposit-index order on every branch — the same argument that
        lets the fold read the head registry), so the resident table
        already covers any prefix; rebuilding here would shrink the
        table and pay a full-registry rebuild under this lock on every
        epoch replay of an older state.  The swap is all-or-nothing:
        the new (tx, ty) pair is fully built before it replaces the
        old one, so a mid-build fault leaves the previous table
        intact.  Returns False on failure (callers fall back to the
        reference rung)."""
        from lighthouse_tpu.ops import pubkey_kernels

        n = len(validators)
        with self._lock:
            if self._registry_matches(validators) and self._table_rows >= n:
                return True
            if 0 < n < self._table_rows:
                return True         # prefix registry: already covered
            try:
                if self._registry_matches(validators):
                    start = self._table_rows       # append-only growth
                    rows_x, rows_y = self._rows
                else:
                    start, rows_x, rows_y = 0, None, None
                new_x, new_y = pubkey_kernels.mont_rows(
                    self._decompress_rows(validators, start, n))
                if start:
                    rows_x = np.concatenate([rows_x, new_x])
                    rows_y = np.concatenate([rows_y, new_y])
                else:
                    rows_x, rows_y = new_x, new_y
                table = pubkey_kernels.table_from_rows(rows_x, rows_y)
                sha = self._column_sha(validators, n)
            except Exception as e:
                record_plane_fault("device", "table_" + type(e).__name__)
                _breaker_fault()
                return False
            self._table = table
            self._table_rows = n
            self._rows = (rows_x, rows_y)
            self._prefix_sha = sha
            self._seen = {id(validators): validators}
            try:
                REGISTRY.counter(
                    "pubkey_plane_refreshes_total",
                    "device pubkey-table refreshes by kind",
                ).labels(kind="append" if start else "rebuild").inc()
                REGISTRY.gauge(
                    "pubkey_plane_table_rows",
                    "validator rows resident in the device pubkey table",
                ).set(n)
            except Exception as e:
                record_swallowed("pubkey_plane.refresh_metric", e)
            return True

    @staticmethod
    def _decompress(pk_bytes: bytes):
        from lighthouse_tpu.crypto import bls

        return bls.PublicKey.interned(pk_bytes).point

    @staticmethod
    def _decompress_rows(validators, start: int, n: int) -> list:
        """Affine points for registry rows [start, n): ONE native
        batched decompress + [r]P membership sweep when available
        (~0.5 ms/key vs ~6 ms python per key — the difference between
        minutes and tens of minutes on a mainnet-scale rebuild), the
        interned python path otherwise.  A row that fails either step
        raises exactly like the python path — the caller's table-build
        fault accounting is unchanged."""
        from lighthouse_tpu.crypto import bls

        rows = [validators.pubkeys[i].tobytes() for i in range(start, n)]
        try:
            from lighthouse_tpu.ops import native_bls

            if native_bls.available():
                pts = native_bls.g1_decompress_batch(rows)
                if pts is not None:
                    bad = [i for i, p in enumerate(pts)
                           if p is None or p == native_bls.G1_INF]
                    if bad:
                        raise bls.BlsError(
                            f"pubkey row {start + bad[0]} undecompressable")
                    verdicts = native_bls.g1_in_subgroup_batch(pts)
                    if verdicts is not None:
                        if any(v != 1 for v in verdicts):
                            i = next(i for i, v in enumerate(verdicts)
                                     if v != 1)
                            raise bls.BlsError(
                                f"pubkey row {start + i} not in G1 "
                                "subgroup")
                        return pts
        except bls.BlsError:
            raise
        except Exception as e:
            record_swallowed("pubkey_plane.decompress_rows_native", e)
        return [PubkeyPlane._decompress(pk) for pk in rows]

    # -- the fold ----------------------------------------------------------

    def fold(self, validators, indices: np.ndarray, scalars: np.ndarray,
             groups: np.ndarray, n_groups: int) -> list:
        """Blinded committee-aggregate pubkeys: out[g] = Σ_{i: groups[i]
        == g} scalars[i]·pubkey(indices[i]) as host affine points (None
        for an identity aggregate — such a merged set can never
        verify).  Routed device → reference per the breaker ladder;
        device faults recover on reference within this call."""
        backend = resolve_pubkey_backend(len(indices))
        t0 = time.perf_counter()
        if backend in ("device", "sharded"):
            try:
                out = self._fold_device(validators, indices, scalars,
                                        groups, n_groups, backend)
                _breaker_ok()
                record_fold(backend, time.perf_counter() - t0, n_groups)
                return out
            except _TableUnavailable:
                pass    # ensure_table already counted fault + breaker step
            except Exception as exc:   # device fault: recover on host
                record_plane_fault(backend, type(exc).__name__)
                _breaker_fault()
        out = self._fold_host(validators, indices, scalars, groups,
                              n_groups)
        record_fold("reference", time.perf_counter() - t0, n_groups)
        return out

    def _fold_device(self, validators, indices, scalars, groups,
                     n_groups: int, backend: str) -> list:
        from lighthouse_tpu.ops import bigint as bi
        from lighthouse_tpu.ops import pubkey_kernels

        if not self.ensure_table(validators):
            raise _TableUnavailable("pubkey table unavailable")
        with self._lock:
            # snapshot: a concurrent refresh swaps the whole (tx, ty)
            # tuple (tables only grow — ensure_table never shrinks),
            # so one read under the lock keeps this fold consistent
            table = self._table
        if backend == "sharded":
            from lighthouse_tpu.parallel import msm_sharded

            xa, ya, inf = msm_sharded.gather_fold_sharded(
                table, np.asarray(indices, np.int64),
                np.asarray(scalars, np.uint64),
                np.asarray(groups, np.int64), n_groups)
        else:
            xa, ya, inf = pubkey_kernels.gather_fold(
                table, np.asarray(indices, np.int64),
                np.asarray(scalars, np.uint64),
                np.asarray(groups, np.int64), n_groups)
        out: list = []
        for g in range(n_groups):
            if bool(inf[g]):
                out.append(None)
                continue
            out.append((int(bi.from_mont(xa[g])), int(bi.from_mont(ya[g]))))
        return out

    def _fold_host(self, validators, indices, scalars, groups,
                   n_groups: int) -> list:
        """Reference rung: scalar-sum collapse per (group, pubkey) —
        r₁·pk + r₂·pk = (r₁+r₂)·pk, sound regardless of which sets the
        blinders came from — then ONE native segment-MSM over the
        unique pairs (ops/native_bls.g1_lincomb_groups, ~100 µs/point;
        host g1_mul + point adds when the native layer is unavailable).
        This IS the old per-set host aggregation, minus the redundant
        multiplications for repeated keys."""
        from lighthouse_tpu.crypto.bls import curve as cv
        from lighthouse_tpu.crypto.bls.fields import R as _R

        sums: dict[tuple[int, bytes], int] = {}
        for i in range(len(indices)):
            key = (int(groups[i]),
                   validators.pubkeys[int(indices[i])].tobytes())
            sums[key] = (sums.get(key, 0) + int(scalars[i])) % _R
        entries = [(g, pk_bytes, s) for (g, pk_bytes), s in sums.items()
                   if s != 0]
        try:
            from lighthouse_tpu.ops import native_bls

            if native_bls.available():
                res = native_bls.g1_lincomb_groups(
                    [self._decompress(pk) for _g, pk, _s in entries],
                    [s for _g, _pk, s in entries],
                    [g for g, _pk, _s in entries], n_groups)
                if res is not None:
                    return res
        except Exception as e:
            record_swallowed("pubkey_plane.fold_host_native", e)
        acc: list = [cv.INF] * n_groups
        for g, pk_bytes, s in entries:
            pt = self._decompress(pk_bytes)
            acc[g] = cv.g1_add(acc[g], cv.g1_mul(pt, s))
        return [None if pt is cv.INF else pt for pt in acc]


_PLANE = PubkeyPlane()


def get_plane() -> PubkeyPlane:
    return _PLANE


def notify_registry(validators) -> None:
    """Registry write-back hook (PR 6 epoch bridge / deposit
    processing): refresh the device copy eagerly when a device rung is
    armed.  Never raises — a failed refresh is a counted fault and the
    next fold recovers on reference."""
    try:
        if resolve_pubkey_backend(
                envreg.get_int("LHTPU_PUBKEY_DEVICE_MIN",
                               _DEVICE_MIN_DEFAULT)) == "reference":
            return
        get_plane().ensure_table(validators)
    except Exception as e:
        record_swallowed("pubkey_plane.notify_registry", e)


__all__ = [
    "PubkeyPlane",
    "enabled",
    "get_plane",
    "notify_registry",
    "record_fold",
    "record_plane_fault",
    "reset_pubkey_plane",
    "resolve_pubkey_backend",
]
