"""Per-validator observability (reference beacon_chain/src/
validator_monitor.rs, 2,173 LoC): registered validators get per-epoch
hit/miss accounting for attestations (with inclusion delay), block
proposals, and sync-committee participation, surfaced as metrics and
log-friendly summaries.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.common.metrics import REGISTRY


@dataclass
class ValidatorEpochSummary:
    attestation_hits: int = 0
    attestation_misses: int = 0
    inclusion_delays: list = field(default_factory=list)
    blocks_proposed: int = 0
    blocks_missed: int = 0
    sync_signatures: int = 0
    # sync-committee signatures of this validator INCLUDED in blocks'
    # sync aggregates (distinct from gossip sightings)
    sync_aggregate_inclusions: int = 0
    # gossip-level sightings (seen on the wire before inclusion — the
    # reference distinguishes "seen" from "included")
    attestations_seen: int = 0
    aggregates_seen: int = 0
    # lifecycle events observed on chain this epoch
    slashed: bool = False
    exited: bool = False
    # balance tracking at the epoch boundary
    balance_gwei: int = 0
    balance_delta_gwei: int = 0
    # on-chain participation truth, read from the NEXT epoch's state
    # (previous_epoch_participation): the reference's per-flag
    # attestation_{source,target,head}_hit metrics.  None = not yet
    # finalized into the participation registry
    source_hit: bool | None = None
    target_hit: bool | None = None
    head_hit: bool | None = None
    # per-flag reward attribution (api/rewards attestation-rewards calc):
    # actual gwei earned per component + the ideal for this validator's
    # effective-balance tier (reference validator_monitor.rs
    # attestations_rewards family)
    reward_source_gwei: int = 0
    reward_target_gwei: int = 0
    reward_head_gwei: int = 0
    reward_inactivity_gwei: int = 0
    ideal_reward_gwei: int = 0


class ValidatorMonitor:
    def __init__(self, auto_register: bool = False):
        self.auto_register = auto_register
        self.registered: set[int] = set()
        # epoch -> validator -> summary
        self._epochs: dict[int, dict[int, ValidatorEpochSummary]] = {}
        # epoch -> balances snapshot (numpy; presence == recorded, so a
        # legitimate 0 balance still yields a delta)
        self._balances: dict[int, np.ndarray] = {}
        # epoch -> FINAL participation-flag array for that epoch (numpy;
        # per-validator flags materialize lazily in epoch_summary) and
        # the matching duty-eligibility mask (active & unslashed)
        self._participation: dict[int, np.ndarray] = {}
        self._part_eligible: dict[int, np.ndarray] = {}
        self._att_hits = REGISTRY.counter(
            "validator_monitor_attestation_hits_total",
            "attestations by monitored validators seen on chain")
        self._blocks = REGISTRY.counter(
            "validator_monitor_blocks_total",
            "blocks proposed by monitored validators")
        self._att_misses = REGISTRY.counter(
            "validator_monitor_attestation_misses_total",
            "epochs where a monitored validator missed the target vote")
        self._delay_hist = REGISTRY.histogram(
            "validator_monitor_inclusion_distance_slots",
            "slots between attestation and its including block",
            buckets=(1, 2, 3, 4, 8, 16, 32))
        self._slashings = REGISTRY.counter(
            "validator_monitor_slashings_total",
            "slashings of monitored validators observed on chain")

    def register(self, *indices: int) -> None:
        self.registered.update(int(i) for i in indices)

    def _summary(self, epoch: int, validator: int) -> ValidatorEpochSummary:
        per = self._epochs.setdefault(int(epoch), {})
        s = per.get(int(validator))
        if s is None:
            s = per[int(validator)] = ValidatorEpochSummary()
        return s

    def _monitored(self, index: int) -> bool:
        return self.auto_register or int(index) in self.registered

    # -- feed points (called from the chain) ------------------------------

    def on_block_imported(self, block, spec) -> None:
        proposer = int(block.proposer_index)
        epoch = spec.compute_epoch_at_slot(int(block.slot))
        if self._monitored(proposer):
            self._summary(epoch, proposer).blocks_proposed += 1
            self._blocks.inc()

    def on_attestation_included(self, indices: np.ndarray, data,
                                block_slot: int, spec) -> None:
        epoch = int(data.target.epoch)
        delay = max(int(block_slot) - int(data.slot), 1)
        for v in np.asarray(indices).tolist():
            if not self._monitored(v):
                continue
            s = self._summary(epoch, v)
            s.attestation_hits += 1
            s.inclusion_delays.append(delay)
            self._att_hits.inc()
            self._delay_hist.observe(delay)

    def on_sync_signature(self, validator: int, slot: int, spec) -> None:
        if self._monitored(validator):
            epoch = spec.compute_epoch_at_slot(int(slot))
            self._summary(epoch, validator).sync_signatures += 1

    def on_gossip_attestation(self, indices, data, spec) -> None:
        """Unaggregated attestations seen on gossip (pre-inclusion) —
        the reference's register_gossip_unaggregated_attestation."""
        epoch = int(data.target.epoch)
        for v in np.asarray(indices).reshape(-1).tolist():
            if self._monitored(v):
                self._summary(epoch, v).attestations_seen += 1

    def on_gossip_aggregate(self, aggregator_index: int, data, spec) -> None:
        epoch = int(data.target.epoch)
        if self._monitored(aggregator_index):
            self._summary(epoch, aggregator_index).aggregates_seen += 1

    def on_sync_aggregate_included(self, indices, slot: int, spec) -> None:
        """Monitored validators whose sync signature made a block's
        sync aggregate (reference register_sync_aggregate_in_block)."""
        epoch = spec.compute_epoch_at_slot(int(slot))
        for v in indices:
            if self._monitored(v):
                self._summary(epoch, v).sync_aggregate_inclusions += 1

    def on_attester_slashing(self, indices, epoch: int) -> None:
        """A block carried an attester slashing covering monitored
        validators (reference register_attester_slashing) — the highest-
        severity signal the monitor emits."""
        for v in np.asarray(indices).reshape(-1).tolist():
            if self._monitored(v):
                self._summary(epoch, int(v)).slashed = True
                self._slashings.inc()

    def on_proposer_slashing(self, proposer: int, epoch: int) -> None:
        if self._monitored(proposer):
            self._summary(epoch, int(proposer)).slashed = True
            self._slashings.inc()

    def on_exit(self, validator: int, epoch: int) -> None:
        """A voluntary exit for a monitored validator was included on
        chain (reference register_block_voluntary_exit)."""
        if self._monitored(validator):
            self._summary(epoch, int(validator)).exited = True

    def on_block_missed(self, slot: int, expected_proposer: int,
                        spec) -> None:
        """An empty slot whose duty belonged to a monitored validator
        (the reference's missed-block tracking)."""
        if self._monitored(expected_proposer):
            epoch = spec.compute_epoch_at_slot(int(slot))
            self._summary(epoch, expected_proposer).blocks_missed += 1

    def on_epoch_boundary(self, epoch: int, state, spec,
                          prev_state=None) -> None:
        """Snapshot the balances array (one vectorized copy — this runs
        on the head-update path, a per-validator Python loop at registry
        scale would stall imports).  Per-validator balance/delta fields
        are filled lazily on read (epoch_summary / log_lines).

        Also reads the on-chain participation truth out of
        previous_epoch_participation (altair+): per-flag hit/miss — the
        reference's authoritative missed-attestation detection
        (validator_monitor.rs process_validator_statuses).

        FINALITY: an epoch's flags keep accumulating through the NEXT
        epoch (late inclusions), so the read must come from a state LATE
        in the following epoch.  `prev_state` — the head state the chain
        held just before crossing the boundary, i.e. the last head of
        the previous epoch — provides exactly that: its
        previous_epoch_participation is the FINAL record for the epoch
        before it.  Reading the fresh boundary state instead would mark
        false misses for every attestation included late.  The epoch the
        flags belong to is derived from the participation state's own
        slot, so skipped epochs can never mislabel."""
        epoch = int(epoch)
        self._balances[epoch] = np.asarray(state.balances).copy()
        if not (self.auto_register or self.registered):
            return
        part_state = prev_state if prev_state is not None else state
        part = getattr(part_state, "previous_epoch_participation", None)
        if part is None:       # phase0 state: no participation registry
            return
        part = np.asarray(part).copy()
        rec_epoch = int(part_state.slot) // spec.slots_per_epoch - 1
        if rec_epoch < 0:
            return
        # only active-unslashed validators had attestation duties in
        # rec_epoch; zero flags on a pending/exited validator are not
        # misses (reference process_validator_statuses eligibility)
        v = part_state.validators
        eligible = (np.asarray(v.activation_epoch) <= rec_epoch) \
            & (np.asarray(v.exit_epoch) > rec_epoch) \
            & ~np.asarray(v.slashed)
        # keep the raw arrays; flags materialize lazily on read so the
        # auto_register path stays vectorized at registry scale
        self._participation[rec_epoch] = part
        self._part_eligible[rec_epoch] = eligible
        # eager miss counting for the explicit watch list only (small);
        # epoch_summary answers for the rest
        for i in [i for i in self.registered if i < len(part)]:
            if eligible[i] and not (int(part[i]) & 0b010):  # target unset
                s = self._summary(rec_epoch, int(i))
                if s.attestation_misses == 0:
                    s.attestation_misses += 1
                    self._att_misses.inc()

    def record_rewards(self, chain, epoch: int) -> None:
        """Per-validator reward attribution for `epoch` via the same
        calculator that serves the standard attestation-rewards API
        (api/rewards.compute_attestation_rewards; reference
        validator_monitor.rs attestations reward logging).  Called for
        registered sets only — the calc is vectorized over the whole
        registry, so cost is one rewards pass per epoch."""
        if not self.registered:
            return
        from lighthouse_tpu.api.rewards import compute_attestation_rewards

        epoch = int(epoch)
        idxs = sorted(self.registered)
        try:
            data = compute_attestation_rewards(
                chain, epoch, idxs, include_effective_balance=True)
        except Exception:
            return                       # pre-altair / state unavailable
        ideal_by_eb = {int(r["effective_balance"]): r
                       for r in data.get("ideal_rewards", [])}
        for row in data.get("total_rewards", []):
            v = int(row["validator_index"])
            s = self._summary(epoch, v)
            s.reward_source_gwei = int(row["source"])
            s.reward_target_gwei = int(row["target"])
            s.reward_head_gwei = int(row["head"])
            s.reward_inactivity_gwei = int(row.get("inactivity", 0))
            # tier keyed on the EB the calc itself used (replayed state)
            ideal = ideal_by_eb.get(int(row.get("effective_balance", -1)))
            if ideal is not None:
                s.ideal_reward_gwei = (int(ideal["source"])
                                       + int(ideal["target"])
                                       + int(ideal["head"]))

    def note_misses(self, epoch: int, expected: list[int]) -> None:
        """Called at epoch end with the validators that SHOULD have
        attested; anyone with zero hits is a miss."""
        per = self._epochs.get(int(epoch), {})
        for v in expected:
            if not self._monitored(v):
                continue
            s = per.get(int(v))
            if s is None or s.attestation_hits == 0:
                self._summary(epoch, v).attestation_misses += 1

    # -- reads ------------------------------------------------------------

    def epoch_summary(self, epoch: int) -> dict[int, ValidatorEpochSummary]:
        epoch = int(epoch)
        out = dict(self._epochs.get(epoch, {}))
        bal = self._balances.get(epoch)
        part = self._participation.get(epoch)
        elig = self._part_eligible.get(epoch)
        n = max(len(bal) if bal is not None else 0,
                len(part) if part is not None else 0)
        targets = (range(n) if self.auto_register
                   else [i for i in self.registered if i < n])
        prev = self._balances.get(epoch - 1)
        for v in targets:
            s = out.get(int(v))
            if s is None:
                s = out[int(v)] = ValidatorEpochSummary()
            if bal is not None and v < len(bal):
                s.balance_gwei = int(bal[v])
                if prev is not None and v < len(prev):
                    s.balance_delta_gwei = int(bal[v]) - int(prev[v])
            if part is not None and v < len(part) and (
                    elig is None or (v < len(elig) and elig[v])):
                bits = int(part[v])
                s.source_hit = bool(bits & 0b001)   # TIMELY_SOURCE
                s.target_hit = bool(bits & 0b010)   # TIMELY_TARGET
                s.head_hit = bool(bits & 0b100)     # TIMELY_HEAD
        return out

    def log_lines(self, epoch: int) -> list[str]:
        """Operator-readable per-validator epoch digests (the reference's
        'Previous epoch attestation(s) success' log family)."""
        out = []
        for v, s in sorted(self.epoch_summary(epoch).items()):
            delay = (sum(s.inclusion_delays) / len(s.inclusion_delays)
                     if s.inclusion_delays else 0.0)
            flags = "".join(
                "-" if hit is None else ("Y" if hit else "n")
                for hit in (s.source_hit, s.target_hit, s.head_hit))
            # attestation reward vs its like-for-like ideal; the
            # inactivity-leak penalty is reported separately (the ideal
            # table has no inactivity component by construction)
            reward = (s.reward_source_gwei + s.reward_target_gwei
                      + s.reward_head_gwei)
            leak = (f" leak={s.reward_inactivity_gwei}"
                    if s.reward_inactivity_gwei else "")
            events = ("" + (" SLASHED" if s.slashed else "")
                      + (" exited" if s.exited else ""))
            out.append(
                f"validator {v} epoch {epoch}: "
                f"att hit={s.attestation_hits} miss={s.attestation_misses} "
                f"sth={flags} "
                f"seen={s.attestations_seen} delay={delay:.2f} "
                f"blocks={s.blocks_proposed} missed={s.blocks_missed} "
                f"sync={s.sync_signatures}/{s.sync_aggregate_inclusions} "
                f"reward={reward:+d}/{s.ideal_reward_gwei}{leak} "
                f"balance={s.balance_gwei} Δ={s.balance_delta_gwei:+d}"
                f"{events}")
        return out

    def prune_below(self, epoch: int) -> None:
        for e in [e for e in self._epochs if e < epoch]:
            del self._epochs[e]
        for e in [e for e in self._balances if e < epoch - 1]:
            del self._balances[e]
        for e in [e for e in self._participation if e < epoch - 1]:
            del self._participation[e]
        for e in [e for e in self._part_eligible if e < epoch - 1]:
            del self._part_eligible[e]
