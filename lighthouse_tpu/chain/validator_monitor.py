"""Per-validator observability (reference beacon_chain/src/
validator_monitor.rs, 2,173 LoC): registered validators get per-epoch
hit/miss accounting for attestations (with inclusion delay), block
proposals, and sync-committee participation, surfaced as metrics and
log-friendly summaries.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.common.metrics import REGISTRY


@dataclass
class ValidatorEpochSummary:
    attestation_hits: int = 0
    attestation_misses: int = 0
    inclusion_delays: list = field(default_factory=list)
    blocks_proposed: int = 0
    blocks_missed: int = 0
    sync_signatures: int = 0
    # gossip-level sightings (seen on the wire before inclusion — the
    # reference distinguishes "seen" from "included")
    attestations_seen: int = 0
    aggregates_seen: int = 0
    # balance tracking at the epoch boundary
    balance_gwei: int = 0
    balance_delta_gwei: int = 0


class ValidatorMonitor:
    def __init__(self, auto_register: bool = False):
        self.auto_register = auto_register
        self.registered: set[int] = set()
        # epoch -> validator -> summary
        self._epochs: dict[int, dict[int, ValidatorEpochSummary]] = {}
        # epoch -> balances snapshot (numpy; presence == recorded, so a
        # legitimate 0 balance still yields a delta)
        self._balances: dict[int, np.ndarray] = {}
        self._att_hits = REGISTRY.counter(
            "validator_monitor_attestation_hits_total",
            "attestations by monitored validators seen on chain")
        self._blocks = REGISTRY.counter(
            "validator_monitor_blocks_total",
            "blocks proposed by monitored validators")

    def register(self, *indices: int) -> None:
        self.registered.update(int(i) for i in indices)

    def _summary(self, epoch: int, validator: int) -> ValidatorEpochSummary:
        per = self._epochs.setdefault(int(epoch), {})
        s = per.get(int(validator))
        if s is None:
            s = per[int(validator)] = ValidatorEpochSummary()
        return s

    def _monitored(self, index: int) -> bool:
        return self.auto_register or int(index) in self.registered

    # -- feed points (called from the chain) ------------------------------

    def on_block_imported(self, block, spec) -> None:
        proposer = int(block.proposer_index)
        epoch = spec.compute_epoch_at_slot(int(block.slot))
        if self._monitored(proposer):
            self._summary(epoch, proposer).blocks_proposed += 1
            self._blocks.inc()

    def on_attestation_included(self, indices: np.ndarray, data,
                                block_slot: int, spec) -> None:
        epoch = int(data.target.epoch)
        delay = max(int(block_slot) - int(data.slot), 1)
        for v in np.asarray(indices).tolist():
            if not self._monitored(v):
                continue
            s = self._summary(epoch, v)
            s.attestation_hits += 1
            s.inclusion_delays.append(delay)
            self._att_hits.inc()

    def on_sync_signature(self, validator: int, slot: int, spec) -> None:
        if self._monitored(validator):
            epoch = spec.compute_epoch_at_slot(int(slot))
            self._summary(epoch, validator).sync_signatures += 1

    def on_gossip_attestation(self, indices, data, spec) -> None:
        """Unaggregated attestations seen on gossip (pre-inclusion) —
        the reference's register_gossip_unaggregated_attestation."""
        epoch = int(data.target.epoch)
        for v in np.asarray(indices).reshape(-1).tolist():
            if self._monitored(v):
                self._summary(epoch, v).attestations_seen += 1

    def on_gossip_aggregate(self, aggregator_index: int, data, spec) -> None:
        epoch = int(data.target.epoch)
        if self._monitored(aggregator_index):
            self._summary(epoch, aggregator_index).aggregates_seen += 1

    def on_block_missed(self, slot: int, expected_proposer: int,
                        spec) -> None:
        """An empty slot whose duty belonged to a monitored validator
        (the reference's missed-block tracking)."""
        if self._monitored(expected_proposer):
            epoch = spec.compute_epoch_at_slot(int(slot))
            self._summary(epoch, expected_proposer).blocks_missed += 1

    def on_epoch_boundary(self, epoch: int, state, spec) -> None:
        """Snapshot the balances array (one vectorized copy — this runs
        on the head-update path, a per-validator Python loop at registry
        scale would stall imports).  Per-validator balance/delta fields
        are filled lazily on read (epoch_summary / log_lines)."""
        self._balances[int(epoch)] = np.asarray(state.balances).copy()

    def note_misses(self, epoch: int, expected: list[int]) -> None:
        """Called at epoch end with the validators that SHOULD have
        attested; anyone with zero hits is a miss."""
        per = self._epochs.get(int(epoch), {})
        for v in expected:
            if not self._monitored(v):
                continue
            s = per.get(int(v))
            if s is None or s.attestation_hits == 0:
                self._summary(epoch, v).attestation_misses += 1

    # -- reads ------------------------------------------------------------

    def epoch_summary(self, epoch: int) -> dict[int, ValidatorEpochSummary]:
        epoch = int(epoch)
        out = dict(self._epochs.get(epoch, {}))
        bal = self._balances.get(epoch)
        if bal is not None:
            prev = self._balances.get(epoch - 1)
            targets = (range(len(bal)) if self.auto_register
                       else [i for i in self.registered if i < len(bal)])
            for v in targets:
                s = out.get(int(v))
                if s is None:
                    s = out[int(v)] = ValidatorEpochSummary()
                s.balance_gwei = int(bal[v])
                if prev is not None and v < len(prev):
                    s.balance_delta_gwei = int(bal[v]) - int(prev[v])
        return out

    def log_lines(self, epoch: int) -> list[str]:
        """Operator-readable per-validator epoch digests (the reference's
        'Previous epoch attestation(s) success' log family)."""
        out = []
        for v, s in sorted(self.epoch_summary(epoch).items()):
            delay = (sum(s.inclusion_delays) / len(s.inclusion_delays)
                     if s.inclusion_delays else 0.0)
            out.append(
                f"validator {v} epoch {epoch}: "
                f"att hit={s.attestation_hits} miss={s.attestation_misses} "
                f"seen={s.attestations_seen} delay={delay:.2f} "
                f"blocks={s.blocks_proposed} missed={s.blocks_missed} "
                f"sync={s.sync_signatures} "
                f"balance={s.balance_gwei} Δ={s.balance_delta_gwei:+d}")
        return out

    def prune_below(self, epoch: int) -> None:
        for e in [e for e in self._epochs if e < epoch]:
            del self._epochs[e]
        for e in [e for e in self._balances if e < epoch - 1]:
            del self._balances[e]
