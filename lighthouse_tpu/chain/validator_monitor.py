"""Per-validator observability (reference beacon_chain/src/
validator_monitor.rs, 2,173 LoC): registered validators get per-epoch
hit/miss accounting for attestations (with inclusion delay), block
proposals, and sync-committee participation, surfaced as metrics and
log-friendly summaries.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.common.metrics import REGISTRY


@dataclass
class ValidatorEpochSummary:
    attestation_hits: int = 0
    attestation_misses: int = 0
    inclusion_delays: list = field(default_factory=list)
    blocks_proposed: int = 0
    sync_signatures: int = 0


class ValidatorMonitor:
    def __init__(self, auto_register: bool = False):
        self.auto_register = auto_register
        self.registered: set[int] = set()
        # epoch -> validator -> summary
        self._epochs: dict[int, dict[int, ValidatorEpochSummary]] = {}
        self._att_hits = REGISTRY.counter(
            "validator_monitor_attestation_hits_total",
            "attestations by monitored validators seen on chain")
        self._blocks = REGISTRY.counter(
            "validator_monitor_blocks_total",
            "blocks proposed by monitored validators")

    def register(self, *indices: int) -> None:
        self.registered.update(int(i) for i in indices)

    def _summary(self, epoch: int, validator: int) -> ValidatorEpochSummary:
        per = self._epochs.setdefault(int(epoch), {})
        s = per.get(int(validator))
        if s is None:
            s = per[int(validator)] = ValidatorEpochSummary()
        return s

    def _monitored(self, index: int) -> bool:
        return self.auto_register or int(index) in self.registered

    # -- feed points (called from the chain) ------------------------------

    def on_block_imported(self, block, spec) -> None:
        proposer = int(block.proposer_index)
        epoch = spec.compute_epoch_at_slot(int(block.slot))
        if self._monitored(proposer):
            self._summary(epoch, proposer).blocks_proposed += 1
            self._blocks.inc()

    def on_attestation_included(self, indices: np.ndarray, data,
                                block_slot: int, spec) -> None:
        epoch = int(data.target.epoch)
        delay = max(int(block_slot) - int(data.slot), 1)
        for v in np.asarray(indices).tolist():
            if not self._monitored(v):
                continue
            s = self._summary(epoch, v)
            s.attestation_hits += 1
            s.inclusion_delays.append(delay)
            self._att_hits.inc()

    def on_sync_signature(self, validator: int, slot: int, spec) -> None:
        if self._monitored(validator):
            epoch = spec.compute_epoch_at_slot(int(slot))
            self._summary(epoch, validator).sync_signatures += 1

    def note_misses(self, epoch: int, expected: list[int]) -> None:
        """Called at epoch end with the validators that SHOULD have
        attested; anyone with zero hits is a miss."""
        per = self._epochs.get(int(epoch), {})
        for v in expected:
            if not self._monitored(v):
                continue
            s = per.get(int(v))
            if s is None or s.attestation_hits == 0:
                self._summary(epoch, v).attestation_misses += 1

    # -- reads ------------------------------------------------------------

    def epoch_summary(self, epoch: int) -> dict[int, ValidatorEpochSummary]:
        return dict(self._epochs.get(int(epoch), {}))

    def prune_below(self, epoch: int) -> None:
        for e in [e for e in self._epochs if e < epoch]:
            del self._epochs[e]
