"""Blob sidecar verification (Deneb).

Rebuild of /root/reference/beacon_node/beacon_chain/src/blob_verification.rs
(gossip checks + the KZG batch at :380) and kzg_utils.rs:23-35
(validate_blobs -> verify_blob_kzg_proof_batch): structural/timing checks
per sidecar, the commitment inclusion proof against the block header's
body root, the proposer's header signature, then ONE batched KZG proof
verification riding the device multi-pairing.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from lighthouse_tpu.crypto import bls, kzg
from lighthouse_tpu.state_transition.misc import is_valid_merkle_branch

# deneb BeaconBlockBody: 12 fields, blob_kzg_commitments is field 11
_BODY_FIELDS = 16  # padded to next power of two
_BODY_DEPTH = 4
_COMMITMENTS_FIELD_INDEX = 11


class BlobError(ValueError):
    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


def _inclusion_depth(spec) -> int:
    list_depth = max(spec.preset.max_blob_commitments_per_block - 1, 1).bit_length()
    return _BODY_DEPTH + 1 + list_depth


def _commitment_leaf(commitment: bytes) -> bytes:
    # Bytes48 hash_tree_root: chunk0 = bytes[0:32], chunk1 = bytes[32:48]+pad
    return hashlib.sha256(commitment + b"\x00" * 16).digest()


def _list_subtree_nodes(commitments: list[bytes], depth: int) -> list[list[bytes]]:
    """Levels of the (padded) commitments chunk tree, leaves first."""
    zero = [b"\x00" * 32]
    for _ in range(depth):
        zero.append(hashlib.sha256(zero[-1] * 2).digest())
    level = [_commitment_leaf(c) for c in commitments]
    levels = []
    for d in range(depth):
        width = 1 << (depth - d)
        levels.append(level)
        nxt = []
        for i in range(0, max(len(level), 2), 2):
            left = level[i] if i < len(level) else zero[d]
            right = level[i + 1] if i + 1 < len(level) else zero[d]
            nxt.append(hashlib.sha256(left + right).digest())
        level = nxt
    levels.append(level)  # the chunks root
    return levels


def compute_kzg_inclusion_proof(body, index: int, spec) -> list[bytes]:
    """Merkle branch proving body.blob_kzg_commitments[index] under the
    body root (depth 4 + 1 + log2(max commitments), reference
    KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)."""
    commitments = [bytes(c) for c in body.blob_kzg_commitments]
    list_depth = _inclusion_depth(spec) - _BODY_DEPTH - 1

    levels = _list_subtree_nodes(commitments, list_depth)
    branch = []
    idx = index
    for d in range(list_depth):
        sib = idx ^ 1
        level = levels[d]
        if sib < len(level):
            branch.append(level[sib])
        else:
            zero = b"\x00" * 32
            for _ in range(d):
                zero = hashlib.sha256(zero * 2).digest()
            branch.append(zero)
        idx >>= 1

    # length mix-in: sibling is the little-endian list length
    branch.append(len(commitments).to_bytes(32, "little"))

    # body field tree: siblings of field 11 at depth 4
    field_roots = []
    for fname, ftype in type(body).fields.items():
        field_roots.append(ftype.hash_tree_root(getattr(body, fname)))
    while len(field_roots) < _BODY_FIELDS:
        field_roots.append(b"\x00" * 32)
    nodes = field_roots
    idx = _COMMITMENTS_FIELD_INDEX
    for _ in range(_BODY_DEPTH):
        branch.append(nodes[idx ^ 1])
        nodes = [hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
                 for i in range(0, len(nodes), 2)]
        idx >>= 1
    return branch


def verify_kzg_inclusion_proof(sidecar, spec) -> bool:
    depth = _inclusion_depth(spec)
    list_depth = depth - _BODY_DEPTH - 1
    index = (int(sidecar.index)
             | (_COMMITMENTS_FIELD_INDEX << (list_depth + 1)))
    return is_valid_merkle_branch(
        _commitment_leaf(bytes(sidecar.kzg_commitment)),
        [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof],
        depth, index,
        bytes(sidecar.signed_block_header.message.body_root))


@dataclass
class VerifiedBlob:
    sidecar: object
    block_root: bytes


def verify_blob_sidecar_for_gossip(chain, sidecar, settings: kzg.KzgSettings
                                   ) -> VerifiedBlob:
    """Gossip-level checks for one sidecar (reference GossipVerifiedBlob).
    KZG proof itself is verified in batch via `validate_blobs`."""
    spec = chain.spec
    header = sidecar.signed_block_header.message
    slot = int(header.slot)
    epoch = spec.compute_epoch_at_slot(slot)
    if int(sidecar.index) >= spec.preset.max_blobs_per_block:
        raise BlobError("invalid_subnet_index")
    if slot > chain.current_slot():
        raise BlobError("future_slot")
    if epoch < chain.fork_choice.finalized.epoch:
        raise BlobError("past_finalized_slot")
    parent_root = bytes(header.parent_root)
    if parent_root not in chain.fork_choice.proto:
        raise BlobError("unknown_parent")
    block_root = header.hash_tree_root()
    digest = block_root + int(sidecar.index).to_bytes(8, "little")
    if chain.observed_blob_sidecars.is_seen(epoch, digest):
        raise BlobError("repeat_blob")
    if not verify_kzg_inclusion_proof(sidecar, spec):
        raise BlobError("invalid_inclusion_proof")
    if not check_expected_proposer(chain, header):
        raise BlobError("invalid_proposer")

    # proposer header signature against the parent's post-state
    if chain.verify_signatures:
        state = chain.state_for_block(parent_root)
        if state is None:
            raise BlobError("parent_state_unavailable")
        from lighthouse_tpu.state_transition import misc

        proposer = int(header.proposer_index)
        domain = misc.get_domain(state, spec, spec.domain_beacon_proposer, epoch)
        root = misc.compute_signing_root(header.hash_tree_root(), domain)
        pk = chain.pubkey_cache.get(proposer)
        if pk is None:
            raise BlobError("unknown_proposer")
        sset = bls.SignatureSet(
            bls.Signature(bytes(sidecar.signed_block_header.signature)),
            [pk], root)
        if not bls.verify_signature_sets([sset]):
            raise BlobError("invalid_proposer_signature")
    # NOTE: the dup cache is marked by the CALLER after the KZG proof
    # checks out (blob bytes aren't covered by the header signature, so
    # observing here would let a corrupted copy block the honest one)
    return VerifiedBlob(sidecar, block_root)


def check_expected_proposer(chain, header) -> bool:
    """header.proposer_index must be the slot's actual proposer — else any
    validator key could flood the DA checker with self-signed sidecars
    under fresh bogus block roots (reference checks this via shuffling)."""
    from lighthouse_tpu.state_transition import misc, state_advance

    state = chain.state_for_block(bytes(header.parent_root))
    if state is None:
        return False
    slot = int(header.slot)
    st = state
    if int(state.slot) < slot:
        st = state.copy()
        state_advance(st, chain.spec, slot)
    expected = misc.get_beacon_proposer_index(st, chain.spec)
    return int(header.proposer_index) == expected


def validate_blobs(settings: kzg.KzgSettings, commitments, blobs, proofs) -> bool:
    """Batched KZG verification for a block's blobs (kzg_utils.rs:23-35)."""
    if not blobs:
        return True
    return kzg.verify_blob_kzg_proof_batch(
        [bytes(b) for b in blobs],
        [bytes(c) for c in commitments],
        [bytes(p) for p in proofs],
        settings)
