"""BeaconChain: verification pipelines, import, canonical head.

Rebuild of /root/reference/beacon_node/beacon_chain/src/beacon_chain.rs
(the BeaconChain god-object) at the altitude this framework needs: the
gossip → signature → execution typestate pipeline feeding fork choice and
the hot/cold store, batch attestation verification on the pluggable BLS
backend, canonical-head recompute (canonical_head.rs:495), and block
production (beacon_chain.rs:4224).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from lighthouse_tpu import types as T
from lighthouse_tpu.chain import attestation_verification as att_verify
from lighthouse_tpu.chain import sync_committee_verification as sync_verify
from lighthouse_tpu.chain.block_verification import (
    BlockError,
    ExecutionPendingBlock,
    execute_block,
    verify_block_for_gossip,
    verify_block_signatures,
)
from lighthouse_tpu.chain.caches import (
    BlockTimesCache,
    EpochIndexedSeen,
    ObservedDigests,
    ShufflingCache,
    SlotIndexedSeen,
    StateCache,
    ValidatorPubkeyCache,
)
from lighthouse_tpu.chain.data_availability import DataAvailabilityChecker
from lighthouse_tpu.common import tracing
from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed
from lighthouse_tpu.common.slot_clock import ManualSlotClock, SlotClock
from lighthouse_tpu.fork_choice import ForkChoice
from lighthouse_tpu.store import HotColdDB


class BeaconChain:
    def __init__(
        self,
        spec: T.ChainSpec,
        genesis_state,
        store: HotColdDB | None = None,
        slot_clock: SlotClock | None = None,
        verify_signatures: bool = True,
        kzg_settings=None,
        execution_layer=None,
    ):
        self.spec = spec
        self.t = T.make_types(spec.preset)
        # import serialization: gossip/RPC/HTTP callers arrive on
        # different threads (wire worker pool, beacon processor, API
        # server) but chain mutation is single-writer by design — the
        # reference's equivalent is the per-chain write lock
        # (beacon_chain.rs canonical_head write lock)
        self._import_lock = threading.RLock()
        # per-slot SLO scoring rides the tracer's root-span sink; the
        # process engine is shared, install is idempotent
        from lighthouse_tpu.chain import slo as _slo

        self.slo = _slo.install()
        self.store = store if store is not None else HotColdDB(spec)
        self.slot_clock = slot_clock or ManualSlotClock(
            int(genesis_state.genesis_time), spec.seconds_per_slot)
        self.verify_signatures = verify_signatures

        from lighthouse_tpu.ssz.tree_cache import enable_tree_cache

        enable_tree_cache(genesis_state)
        genesis_root = self._anchor_block_root(genesis_state)
        state_root = genesis_state.hash_tree_root()
        self.genesis_block_root = genesis_root
        self.store.store_anchor_state(state_root, genesis_state)

        self.fork_choice = ForkChoice(
            spec, genesis_root, genesis_state,
            balances_fn=self._balances_for_checkpoint)
        self._anchor_state_root = state_root

        self.head_root = genesis_root
        self.head_state = genesis_state
        self.state_cache = StateCache(capacity=8)
        self.state_cache.insert(state_root, genesis_state)
        # block root -> state root (for state_for_block); the store also
        # resolves this via block records, this is the hot fast path
        self._state_root_of_block: dict[bytes, bytes] = {
            genesis_root: state_root}

        self.shuffling_cache = ShufflingCache()
        self.pubkey_cache = ValidatorPubkeyCache()
        self.pubkey_cache.import_new(genesis_state.validators)
        self.observed_attesters = EpochIndexedSeen()
        self.observed_aggregators = EpochIndexedSeen()
        self.observed_aggregates = ObservedDigests()
        self.observed_blob_sidecars = ObservedDigests()
        self.observed_block_producers = SlotIndexedSeen()
        self.da_checker = DataAvailabilityChecker(spec)
        self.kzg_settings = kzg_settings
        self.execution_layer = execution_layer
        # external builder (MEV) client + the payload book for the
        # blinded round trip: block_hash -> ("local"|"builder", payload)
        self.builder_client = None
        self._blinded_payloads: dict[bytes, tuple[str, object]] = {}
        self.slasher = None  # attach a SlasherService to enable slashing detection
        self.eth1_service = None  # attach an Eth1Service for eth1data voting
        self.state_advance_timer = None  # StateAdvanceTimer.install()
        from lighthouse_tpu.chain.chain_health import ChainHealthMonitor
        from lighthouse_tpu.chain.events import EventStream
        from lighthouse_tpu.chain.light_client import LightClientServerCache
        from lighthouse_tpu.chain.validator_monitor import ValidatorMonitor

        self.events = EventStream()
        # reorg forensics + head/finality lag tracking; every head move
        # in recompute_head runs through its common-ancestor classifier
        self.chain_health = ChainHealthMonitor(self)
        self.validator_monitor = ValidatorMonitor()
        self.light_client = LightClientServerCache(self)
        self._pending_executed: dict[bytes, object] = {}
        from lighthouse_tpu.pool import NaiveAggregationPool, OperationPool
        from lighthouse_tpu.pool.sync_contribution import SyncContributionPool

        self.op_pool = OperationPool()
        self.naive_pool = NaiveAggregationPool()
        self.sync_pool = SyncContributionPool()
        self.observed_sync_contributors = SlotIndexedSeen()
        self.observed_sync_aggregators = SlotIndexedSeen()
        self.observed_contributions = ObservedDigests(retained_epochs=64)
        self._sync_rows_cache: dict[bytes, np.ndarray] = {}
        self.block_times = BlockTimesCache()
        self.metrics: dict[str, float] = {}
        self._migrated_finalized_epoch = self.fork_choice.finalized.epoch
        self._advanced_states: dict[bytes, object] = {}
        # how the last try_resume concluded: "fresh" | "snapshot" | "rebuilt"
        self.resume_mode = "fresh"

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _anchor_block_root(state) -> bytes:
        from lighthouse_tpu.store.hot_cold import anchor_block_root

        return anchor_block_root(state)

    def current_slot(self) -> int:
        return self.slot_clock.current_slot()

    def _balances_for_checkpoint(self, block_root: bytes) -> np.ndarray:
        st = self.state_for_block(block_root)
        if st is None:
            st = self.head_state
        epoch = self.spec.compute_epoch_at_slot(int(st.slot))
        eb = np.asarray(st.validators.effective_balance, np.int64).copy()
        eb[~st.validators.is_active(epoch)] = 0
        return eb

    def committee_shuffle(self, state, epoch: int):
        """Cached committee shuffle for (epoch, seed, active-count) — the
        seed pins the randao mix, so equal keys give equal shuffles across
        branches (reference shuffling_cache keyed by decision root)."""
        from lighthouse_tpu.state_transition import misc

        seed = misc.get_seed(state, self.spec, epoch,
                             self.spec.domain_beacon_attester)
        # active count per (epoch, registry len, slot) is stable: exits/
        # activations only take effect at future epochs, so the O(n)
        # is_active scan runs once per state, not once per attestation
        memo = state.__dict__.setdefault("_active_count_memo", {})
        mkey = (epoch, len(state.validators), int(state.slot))
        n_active = memo.get(mkey)
        if n_active is None:
            n_active = int(state.validators.is_active(epoch).sum())
            if len(memo) > 8:   # prev/current epochs interleave: keep both
                memo.clear()
            memo[mkey] = n_active
        key = seed + n_active.to_bytes(8, "little")
        shuffle = self.shuffling_cache.get(epoch, key)
        if shuffle is None:
            shuffle = misc.compute_committee_shuffle(state, self.spec, epoch)
            self.shuffling_cache.insert(epoch, key, shuffle)
        return shuffle

    def state_for_block(self, block_root: bytes):
        """Post-state of `block_root`: hot cache first, then store replay."""
        state_root = self._state_root_of_block.get(block_root)
        if state_root is None:
            blk = self.store.get_block(block_root)
            if blk is None:
                if block_root == self.genesis_block_root:
                    state_root = self._anchor_state_root
                else:
                    return None
            else:
                state_root = bytes(blk.message.state_root)
            self._state_root_of_block[block_root] = state_root
        cached = self.state_cache.get(state_root)
        if cached is not None:
            return cached
        st = self.store.get_hot_state(state_root)
        if st is not None:
            self.state_cache.insert(state_root, st)
        return st

    # -- block import pipeline --------------------------------------------

    def process_block(self, signed_block, blobs_ssz: bytes | None = None,
                      source: str = "gossip") -> bytes | None:
        """Full pipeline: gossip-verify → batch-signature-verify → execute
        → availability gate → import (reference chain.process_block,
        beacon_chain.rs:3089).  source="rpc" for sync-fetched blocks
        (skips gossip-only checks).  Returns None when the block carries
        blob commitments whose sidecars have not all arrived yet — it
        waits in the DA checker and imports when they do.

        Locking contract (lhlint LH102): the import lock is held for the
        gossip stage (state/dup-cache reads + the 1-set proposer-sig
        check that authenticates the dup-cache mark) and for the
        execute/import stage — the full-block BLS signature batch, the
        single heaviest device dispatch on this path, runs UNLOCKED
        between the two holds, same contract as the attestation
        pipelines below."""
        t_start = time.perf_counter()
        slot = int(signed_block.message.slot)
        # the per-slot timeline root (Lighthouse block-delay analogue):
        # gossip arrival -> verified -> executed -> head updated; served
        # by GET /lighthouse/tracing/{slot}
        with tracing.span("block_import", slot=slot, source=source):
            with self._import_lock:
                with tracing.span("gossip_verify"):
                    gossip = verify_block_for_gossip(
                        self, signed_block, source)
            # pure crypto over already-extracted sets, no chain state
            # touched: block imports on other threads proceed while the
            # device grinds this block's signature batch
            with tracing.span("signature_verify"):
                sigv = verify_block_signatures(self, gossip)
            with self._import_lock:
                root = self._execute_and_import_locked(
                    sigv, signed_block, blobs_ssz)
        total = time.perf_counter() - t_start
        if root is not None:
            self.block_times.record(root, "total", total)
            REGISTRY.histogram(
                "block_import_seconds",
                "full block import pipeline wall time, by source",
            ).labels(source=source).observe(total)
        return root

    def _execute_and_import_locked(self, sigv, signed_block, blobs_ssz):
        # re-check the dup gate under THIS hold: a concurrent copy of the
        # same block (two sync workers racing an RPC fetch) can pass the
        # gossip stage before either imports, because the BLS batch now
        # runs between the two lock holds.  Exactly the pre-split
        # semantics: the loser fails with "duplicate".
        if self.store.block_exists(sigv.block_root):
            raise BlockError("duplicate")
        # payload verification runs CONCURRENTLY with the state
        # transition (reference block_verification.rs:1342-1415 payload
        # future; SURVEY §2.9-5 pipeline overlap), joined below
        payload_future = self._spawn_payload_verification(signed_block)
        with tracing.span("state_transition"):
            pending = execute_block(self, sigv)
        with tracing.span("payload_join"):
            pending.execution_status = self._join_payload_verification(
                payload_future)

        # Deneb data-availability gate (data_availability_checker.rs:32).
        # Callers that ALREADY hold the block's blob data (RPC/backfill
        # sync, which verifies sidecars out-of-band) pass blobs_ssz and
        # import directly — only gossip blocks wait on gossip sidecars.
        commitments = getattr(signed_block.message.body,
                              "blob_kzg_commitments", None)
        if (commitments is not None and len(commitments) > 0
                and blobs_ssz is None):
            self._pending_executed[pending.block_root] = pending
            while len(self._pending_executed) > self.da_checker.capacity:
                # stay in lockstep with the DA checker's LRU bound
                oldest = next(iter(self._pending_executed))
                del self._pending_executed[oldest]
            availability = self.da_checker.put_pending_executed_block(
                pending.block_root, pending.signed_block)
            if not availability.is_available:
                return None
            # sidecars all arrived already: the import completes in
            # THIS call, so it must hit the timing sinks in the caller
            # too — post-Deneb every gossip block takes this branch
            return self._import_available(availability)
        # direct import (no DA wait): drop any copy of this block parked
        # awaiting sidecars under the SAME hold, or late-arriving gossip
        # sidecars would complete availability and re-import the root
        self._pending_executed.pop(pending.block_root, None)
        return self.import_block(pending, blobs_ssz)

    def process_gossip_blob(self, sidecar) -> bytes | None:
        """Verify one gossip blob sidecar and import its block if that
        completes availability (blob_verification.rs + DA checker).

        Locking contract (lhlint LH102): gossip checks (state + dup-cache
        reads, header-signature authentication) hold the import lock; the
        KZG proof verification — a device multi-pairing — runs UNLOCKED;
        the dup-cache mark + DA-checker commit re-acquire the lock.  The
        mark lands only after the FULL verification (incl. KZG) passed,
        so a corrupted copy cannot block the honest sidecar, and marks
        are claimed atomically under the commit hold, so concurrent
        copies of one sidecar cannot both commit."""
        from lighthouse_tpu.chain.blob_verification import (
            BlobError,
            validate_blobs,
            verify_blob_sidecar_for_gossip,
        )

        with self._import_lock:
            verified = verify_blob_sidecar_for_gossip(self, sidecar,
                                                      self.kzg_settings)
        if not validate_blobs(
                self.kzg_settings, [sidecar.kzg_commitment],
                [sidecar.blob], [sidecar.kzg_proof]):
            raise BlobError("invalid_kzg_proof")
        with self._import_lock:
            epoch = self.spec.compute_epoch_at_slot(
                int(sidecar.signed_block_header.message.slot))
            if self.observed_blob_sidecars.observe(
                    epoch,
                    verified.block_root
                    + int(sidecar.index).to_bytes(8, "little")):
                # a concurrent copy of this sidecar won the commit race
                # while our KZG check ran unlocked — only the first mark
                # may feed the DA checker (a second put could recreate a
                # ghost pending entry for an already-imported block)
                return None
            availability = self.da_checker.put_verified_blobs(
                verified.block_root, [verified])
            if availability.is_available:
                return self._import_available(availability)
        return None

    def _import_available(self, availability) -> bytes | None:
        pending = self._pending_executed.pop(availability.block_root, None)
        if pending is None:
            return None  # block arrived via another path already
        blobs_ssz = b"".join(s.serialize() for s in (availability.blobs or []))
        return self.import_block(pending, blobs_ssz or None)

    def _spawn_payload_verification(self, signed_block):
        """newPayload future when an EL is wired and the block carries a
        payload; None otherwise."""
        if self.execution_layer is None:
            return None
        payload = getattr(signed_block.message.body, "execution_payload",
                          None)
        if payload is None:
            return None
        fork = self.spec.fork_at_epoch(self.spec.compute_epoch_at_slot(
            int(signed_block.message.slot)))
        version = {"bellatrix": 1, "capella": 2}.get(fork, 3)
        if version < 3:
            return self.execution_layer.notify_new_payload_async(
                payload, version=version)
        # Deneb+: the EL cross-checks blob versioned hashes and the parent
        # beacon block root against the payload
        import hashlib

        commitments = getattr(signed_block.message.body,
                              "blob_kzg_commitments", [])
        hashes = [b"\x01" + hashlib.sha256(bytes(c)).digest()[1:]
                  for c in commitments]
        return self.execution_layer.notify_new_payload_async(
            payload, version=version, versioned_hashes=hashes,
            parent_beacon_block_root=bytes(signed_block.message.parent_root))

    def _join_payload_verification(self, future) -> int:
        from lighthouse_tpu.fork_choice.proto_array import (
            EXEC_IRRELEVANT,
            EXEC_OPTIMISTIC,
            EXEC_VALID,
        )

        if future is None:
            return EXEC_IRRELEVANT
        try:
            status = future.result()
        except Exception:
            # engine offline: import optimistically, as the reference does
            return EXEC_OPTIMISTIC
        if status.is_invalid:
            raise BlockError(
                f"payload_invalid: {status.validation_error or status.status}")
        return EXEC_VALID if status.is_valid else EXEC_OPTIMISTIC

    def import_block(self, pending: ExecutionPendingBlock,
                     blobs_ssz: bytes | None = None) -> bytes:
        """Fork choice + atomic DB write + head recompute
        (reference chain.import_block, beacon_chain.rs:3449)."""
        block = pending.signed_block.message
        # nests under the block_import root on the direct path; on the
        # blob-availability path this IS the slot-timeline root
        with tracing.span("import_block", slot=int(block.slot)):
            return self._import_block_spanned(pending, blobs_ssz)

    def _import_block_spanned(self, pending: ExecutionPendingBlock,
                              blobs_ssz: bytes | None = None) -> bytes:
        block = pending.signed_block.message
        root = pending.block_root
        state = pending.post_state
        current_slot = max(self.current_slot(), int(block.slot))

        with tracing.span("fork_choice"):
            is_timely = (
                int(block.slot) == self.slot_clock.current_slot()
                and self.slot_clock.is_timely_for_boost())
            self.fork_choice.on_block(
                current_slot, block, root, state, is_timely=is_timely,
                execution_status=getattr(pending, "execution_status", 0))

            # apply the block's attestations/slashings to fork choice
            # (block_verification.rs:1654-1688)
            from lighthouse_tpu.state_transition.block_processing import (
                get_attesting_indices,
            )
            for att in block.body.attestations:
                try:
                    shuffle = self.committee_shuffle(
                        state, int(att.data.target.epoch))
                    indices = get_attesting_indices(
                        state, self.spec, att, shuffle)
                    self.validator_monitor.on_attestation_included(
                        indices, att.data, int(block.slot), self.spec)
                    self.fork_choice.on_attestation(
                        current_slot, indices,
                        bytes(att.data.beacon_block_root),
                        int(att.data.target.epoch), int(att.data.slot),
                        is_from_block=True)
                except Exception as e:
                    # invalid-for-fork-choice attestations skippable
                    record_swallowed("chain.block_att_fork_choice", e)
            block_epoch = self.spec.compute_epoch_at_slot(int(block.slot))
            for slashing in block.body.attester_slashings:
                a1 = set(int(i)
                         for i in slashing.attestation_1.attesting_indices)
                a2 = set(int(i)
                         for i in slashing.attestation_2.attesting_indices)
                both = np.array(sorted(a1 & a2), np.int64)
                if both.size:
                    self.fork_choice.on_attester_slashing(both)
                    self.validator_monitor.on_attester_slashing(
                        both, block_epoch)
            for ps in block.body.proposer_slashings:
                self.validator_monitor.on_proposer_slashing(
                    int(ps.signed_header_1.message.proposer_index),
                    block_epoch)
            for ex in block.body.voluntary_exits:
                self.validator_monitor.on_exit(
                    int(ex.message.validator_index), block_epoch)
            self._note_sync_aggregate(block, state)

        if self.slasher is not None:
            self.slasher.on_block(pending.signed_block)
        with tracing.span("store_import"):
            self.store.import_block(root, pending.signed_block, state,
                                    pending.state_root, blobs_ssz)
            self._state_root_of_block[root] = pending.state_root
            self.state_cache.insert(pending.state_root, state)
            self.pubkey_cache.import_new(state.validators)
        self.validator_monitor.on_block_imported(block, self.spec)
        self._note_missed_proposals(block, state)
        try:
            self.light_client.on_block_imported(pending.signed_block)
        except Exception as e:
            # LC serving is best-effort, never blocks import
            record_swallowed("chain.light_client_update", e)
        self.events.publish("block", {
            "slot": str(int(block.slot)), "block": "0x" + root.hex(),
            "execution_optimistic": pending.execution_status == 1})
        with tracing.span("head_update"):
            self.recompute_head()
        return root

    def _note_sync_aggregate(self, block, state) -> None:
        """Attribute a block's sync-aggregate bits to validator indices
        for the monitor (reference register_sync_aggregate_in_block).
        Pays the committee-row + pubkey-index lookups only when someone
        is monitored; altair- blocks have no aggregate."""
        vm = self.validator_monitor
        if not (vm.auto_register or vm.registered):
            return
        agg = getattr(block.body, "sync_aggregate", None)
        if agg is None:
            return
        try:
            rows = self.sync_committee_rows(state, int(block.slot))
            included = []
            for i, bit in enumerate(agg.sync_committee_bits):
                if not bit:
                    continue
                idx = self.pubkey_cache.index_of(rows[i].tobytes())
                if idx is not None:
                    included.append(idx)
            vm.on_sync_aggregate_included(
                included, int(block.slot), self.spec)
        except Exception as e:
            # observability only, never blocks import
            record_swallowed("chain.sync_aggregate_monitor", e)

    def _note_missed_proposals(self, block, post_state) -> None:
        """Feed skipped slots between a block and its parent to the
        monitor (reference missed-block tracking).  Only pays the parent
        lookup + proposer shuffles when someone is actually monitored."""
        vm = self.validator_monitor
        if not (vm.auto_register or vm.registered):
            return
        parent = self.store.get_block(bytes(block.parent_root))
        if parent is None:
            return
        from lighthouse_tpu.state_transition import misc

        epoch = self.spec.compute_epoch_at_slot(int(block.slot))
        for slot in range(int(parent.message.slot) + 1, int(block.slot)):
            if self.spec.compute_epoch_at_slot(slot) != epoch:
                continue  # proposer shuffle differs across the boundary
            try:
                proposer = misc.get_beacon_proposer_index(
                    post_state, self.spec, slot)
            except Exception:
                continue
            vm.on_block_missed(slot, int(proposer), self.spec)

    def recompute_head(self) -> bytes:
        """Fork-choice get_head + head snapshot update + finality pruning
        (reference recompute_head_at_slot, canonical_head.rs:495)."""
        head = self.fork_choice.get_head(self.current_slot())
        if head != self.head_root:
            st = self.state_for_block(head)
            if st is not None:
                old_head_root = self.head_root
                old_head_state = self.head_state
                self.head_root = head
                self.head_state = st
                self.store.persist_head(head)
                try:
                    # extension-vs-reorg classification, chain_reorg SSE,
                    # deep_reorg trip — never blocks the head update
                    self.chain_health.on_head_update(old_head_root, head)
                except Exception as e:
                    record_swallowed("chain.chain_health", e)
                self.events.publish("head", {
                    "slot": str(int(st.slot)), "block": "0x" + head.hex(),
                    "state": "0x" + bytes(
                        self._state_root_of_block.get(head, b"")).hex(),
                    "epoch_transition": int(st.slot)
                    % self.spec.slots_per_epoch == 0})
                epoch = self.spec.compute_epoch_at_slot(int(st.slot))
                if epoch > getattr(self, "_monitor_epoch", -1):
                    self._monitor_epoch = epoch
                    # old_head_state (the last head of the finished
                    # epoch) carries the FINAL participation flags for
                    # the epoch before it — see on_epoch_boundary
                    self.validator_monitor.on_epoch_boundary(
                        epoch, st, self.spec, prev_state=old_head_state)
                    # operator digest for the newest COMPLETE epoch:
                    # epoch-2's flags and rewards are final here, while
                    # epoch-1 attestations can still be included
                    # (registered validators only — auto_register at
                    # registry scale would flood the log)
                    if self.validator_monitor.registered and epoch >= 2:
                        from lighthouse_tpu.common.logging import Logger

                        self.validator_monitor.record_rewards(
                            self, epoch - 2)
                        log = Logger("validator_monitor")
                        for line in self.validator_monitor.log_lines(
                                epoch - 2):
                            log.info(line)
                self._notify_forkchoice_updated(st)
        if self.fork_choice.finalized.epoch > self._migrated_finalized_epoch:
            self._on_finalized()
        return self.head_root

    def _notify_forkchoice_updated(self, head_state) -> None:
        """Push the new head to the EL (reference forkchoiceUpdated on head
        change).  Best-effort: an offline EL must not stall the chain."""
        if self.execution_layer is None:
            return
        header = getattr(head_state, "latest_execution_payload_header", None)
        if header is None or bytes(header.block_hash) == b"\x00" * 32:
            return
        # finalized payload hash from the stored BLOCK (a few KB) — not the
        # finalized state, which would be a multi-MB load per head change
        fin_hash = b"\x00" * 32
        fin_block = self.store.get_block(self.fork_choice.finalized.root)
        if fin_block is not None:
            fin_payload = getattr(
                fin_block.message.body, "execution_payload", None)
            if fin_payload is not None:
                fin_hash = bytes(fin_payload.block_hash)
        try:
            self.execution_layer.notify_forkchoice_updated(
                bytes(header.block_hash), fin_hash, fin_hash)
        except Exception as e:
            record_swallowed("chain.forkchoice_notify", e)

    def persist(self) -> None:
        """Snapshot fork choice + head for restart resume (reference
        PersistedForkChoice written on shutdown/finalization).  One
        atomic frame: a crash can never pair the head of one snapshot
        with the fork choice of another."""
        self.store.persist_frame(
            fork_choice=self.fork_choice.to_bytes(), head=self.head_root)

    def try_resume(self) -> bool:
        """Restore fork choice + head from a previous run's snapshot;
        when the snapshot is missing, corrupt, or incoherent but the
        store still holds blocks, fall back to rebuilding fork choice
        from them.  Returns True when a prior run's chain was adopted.
        ``resume_mode`` records how: "snapshot" | "rebuilt" | "fresh"."""
        from lighthouse_tpu.fork_choice.fork_choice import ForkChoice
        from lighthouse_tpu.store import StoreCorruptionError

        self.resume_mode = "fresh"
        try:
            blob = self.store.load_fork_choice()
            head = self.store.load_head()
        except StoreCorruptionError:
            # detected (not silently deserialized); the startup sweep
            # normally drops these — reaching here means the sweep was
            # disabled, so treat it exactly like a missing snapshot
            blob = head = None
        if blob is not None and head is not None:
            try:
                fc = ForkChoice.from_bytes(
                    self.spec, blob,
                    balances_fn=self._balances_for_checkpoint)
                if head in fc.proto:
                    head_state = self.state_for_block(head)
                    if head_state is not None:
                        self.fork_choice = fc
                        self.head_root = head
                        self.head_state = head_state
                        # finalization migration already ran for the
                        # persisted epoch; a stale marker would re-migrate
                        # (and re-prune) on the very first head recompute
                        # after every restart
                        self._migrated_finalized_epoch = fc.finalized.epoch
                        self.resume_mode = "snapshot"
                        return True
            except Exception as e:
                # torn/incoherent snapshot: rebuild from blocks — but
                # leave a signal distinguishing "snapshot corrupt" from
                # "resume code broken"
                record_swallowed("chain.try_resume", e)
        return self.rebuild_fork_choice()

    def rebuild_fork_choice(self) -> bool:
        """Repair path: reconstruct fork choice by replaying every
        stored hot block into a fresh instance (reference fork_revert /
        reset_fork_choice tooling, here automatic).  Anchored at genesis
        pre-finality, else at the finalization boundary state the prune
        keeps (store.anchor_at_split)."""
        from lighthouse_tpu.fork_choice.fork_choice import (
            ForkChoice,
            ForkChoiceError,
        )

        store = self.store
        blocks = sorted(
            ((int(blk.message.slot), root, blk)
             for root, blk in store.iter_hot_blocks()),
            key=lambda x: x[0])
        if not any(root != self.genesis_block_root for _, root, _ in blocks):
            return False  # nothing to rebuild from (fresh store)
        if store.split_slot > 0:
            anchor = store.anchor_at_split()
            if anchor is None:
                return False
            anchor_state_root, anchor_root = anchor
            anchor_state = store.get_hot_state(anchor_state_root)
        else:
            anchor_root = self.genesis_block_root
            anchor_state_root = self._anchor_state_root
            anchor_state = self.state_cache.get(anchor_state_root)
            if anchor_state is None:
                anchor_state = store.get_hot_state(anchor_state_root)
        if anchor_state is None:
            return False
        fc = ForkChoice(self.spec, anchor_root, anchor_state,
                        balances_fn=self._balances_for_checkpoint)
        top = max(slot for slot, _, _ in blocks)
        applied = 0
        for slot, root, blk in blocks:
            if root in fc.proto:
                continue
            state = self.state_for_block(root)
            if state is None:
                continue  # torn import: block landed, state didn't
            try:
                fc.on_block(top, blk.message, root, state)
                applied += 1
            except ForkChoiceError:
                continue  # pruned parent / pre-anchor block: skip
        head = fc.get_head(top)
        head_state = (anchor_state if head == anchor_root
                      else self.state_for_block(head))
        if head_state is None:
            return False
        self.fork_choice = fc
        self.head_root = head
        self.head_state = head_state
        self._migrated_finalized_epoch = fc.finalized.epoch
        self.persist()  # re-snapshot the rebuilt instance atomically
        self.resume_mode = "rebuilt"
        REGISTRY.counter(
            "store_recovery_fork_choice_rebuilds_total",
            "fork-choice instances rebuilt from stored blocks").inc()
        with tracing.span("store.fork_choice_rebuild", blocks=applied,
                          head_slot=int(head_state.slot)):
            pass
        return True

    def _on_finalized(self):
        """Prune fork choice + migrate the store (reference migrate.rs)."""
        fin = self.fork_choice.finalized
        fin_block = self.store.get_block(fin.root)
        if fin_block is None:
            return  # retry at the next head recompute
        self.fork_choice.prune()
        self.store.migrate_to_finalized(
            bytes(fin_block.message.state_root), fin.root)
        self.persist()
        self._migrated_finalized_epoch = fin.epoch
        fin_slot = self.spec.compute_start_slot_at_epoch(fin.epoch)
        self.da_checker.prune_finalized(fin_slot)
        self._pending_executed = {
            r: p for r, p in self._pending_executed.items()
            if int(p.signed_block.message.slot) >= fin_slot}
        self.op_pool.prune(self.head_state, self.spec)
        self.naive_pool.prune_below(fin_slot)
        self.sync_pool.prune_below(fin_slot)
        self.validator_monitor.prune_below(max(fin.epoch - 2, 0))
        self.events.publish("finalized_checkpoint", {
            "epoch": str(fin.epoch), "block": "0x" + fin.root.hex()})

    # -- attestation pipelines --------------------------------------------

    def verify_attestations_for_gossip(self, attestations: list):
        """Batch-verify unaggregated gossip attestations
        (reference batch_verify_unaggregated_attestations,
        beacon_chain.rs:1961 + batch.rs:133).  Returns
        (verified, rejects) — verified items are already applied to fork
        choice.

        Locking contract (dispatch-pipeline PR): the import lock is held
        only for the prepare phase (state/cache reads) and the commit
        phase (dup-cache marks, fork choice, pools).  The BLS batch
        verification — seconds of device work for a full sweep — runs
        UNLOCKED, so block imports and head updates proceed while the
        device grinds; cross-batch duplicates are still caught because
        observation marks are claimed atomically under the commit lock."""

        def insert(v):
            # feed the naive aggregation pool; its aggregates in turn
            # feed block packing via the operation pool
            self.naive_pool.insert(v.attestation)
            self.validator_monitor.on_gossip_attestation(
                v.indexed_indices, v.attestation.data, self.spec)

        return self._batch_pipeline(
            attestations, att_verify.verify_unaggregated_for_gossip,
            on_verified=insert)

    def verify_aggregates_for_gossip(self, aggregates: list):
        """Batch-verify SignedAggregateAndProofs (3 sets each,
        batch.rs:62-102).  Same locking contract as
        verify_attestations_for_gossip: BLS runs outside the import lock."""
        from lighthouse_tpu.state_transition.misc import (
            attestation_committee_index,
        )

        def insert(v):
            att = v.attestation
            self.validator_monitor.on_gossip_aggregate(
                int(v.item.message.aggregator_index), att.data, self.spec)
            self.op_pool.insert_attestation(
                att.data, np.asarray(att.aggregation_bits, bool),
                bytes(att.signature),
                committee_index=attestation_committee_index(att))

        return self._batch_pipeline(
            aggregates, att_verify.verify_aggregated_for_gossip,
            on_verified=insert)

    def _batch_pipeline(self, items, verify_fn, on_verified=None):
        candidates, rejects = self._prepare_batch(items, verify_fn)
        # signature verification OUTSIDE the import lock: pure crypto
        # over already-extracted sets, no chain state touched
        if self.verify_signatures:
            att_verify.batch_verify(self, candidates)
        else:
            for c in candidates:
                c.ok = True
        with self._import_lock:
            verified = self._commit_batch(candidates, rejects)
            # pool/monitor inserts ride the SAME lock hold as the commit:
            # a finalization pruning the pools must not interleave between
            # a batch's fork-choice commit and its pool inserts
            if on_verified is not None:
                for v in verified:
                    on_verified(v)
        return verified, rejects

    def _prepare_batch(self, items, verify_fn):
        """Gossip checks + signature-set extraction, under the import
        lock (reads states, shuffles and dup caches)."""
        candidates, rejects = [], []
        with self._import_lock:
            for item in items:
                state = self._attestation_state(item)
                try:
                    candidates.append(verify_fn(self, item, state))
                except att_verify.AttestationError as e:
                    rejects.append((item, e.reason))
        return candidates, rejects

    def _commit_batch(self, candidates, rejects):
        """Claim dup-cache marks and apply survivors to fork choice /
        slasher.  Caller holds the import lock: observation marks are
        claimed atomically here, so batches whose BLS ran concurrently
        (unlocked) still reject cross-batch duplicates."""
        verified = []
        for c in candidates:
            if not c.ok:
                rejects.append((c.item, "invalid_signature"))
                continue
            if not att_verify.commit_observations(self, c):
                rejects.append((c.item, "duplicate_in_batch"))
                continue
            verified.append(c)
            if self.slasher is not None:
                self.slasher.on_verified_attestation(att_verify._as_indexed(
                    self, c.attestation, c.indexed_indices))
            try:
                self.fork_choice.on_attestation(
                    self.current_slot(), c.indexed_indices,
                    bytes(c.attestation.data.beacon_block_root),
                    int(c.attestation.data.target.epoch),
                    int(c.attestation.data.slot))
            except Exception as e:
                record_swallowed("chain.batch_att_fork_choice", e)
        return verified

    # -- sync-committee pipelines -------------------------------------------

    def sync_committee_rows(self, state, slot: int) -> np.ndarray:
        """Cached uint8[size, 48] pubkey rows of the committee at `slot`."""
        period = self.spec.sync_committee_period_at_slot(int(slot))
        committee = (
            state.current_sync_committee
            if period == self.spec.sync_committee_period_at_slot(
                int(state.slot))
            else state.next_sync_committee)
        key = bytes(committee.aggregate_pubkey)
        rows = self._sync_rows_cache.get(key)
        if rows is None:
            rows = np.frombuffer(
                b"".join(bytes(pk) for pk in committee.pubkeys),
                dtype=np.uint8,
            ).reshape(self.spec.preset.sync_committee_size, 48)
            if len(self._sync_rows_cache) > 4:
                self._sync_rows_cache.clear()
            self._sync_rows_cache[key] = rows
        return rows

    def verify_sync_messages_for_gossip(self, messages: list):
        """Batch-verify (message, subnet_id) pairs and fold the valid ones
        into the sync-contribution pool (reference
        sync_committee_verification.rs:670 batch path)."""
        state = self.head_state
        candidates, rejects = [], []
        for message, subnet in messages:
            try:
                candidates.append(sync_verify.verify_sync_message_for_gossip(
                    self, message, subnet, state))
            except sync_verify.SyncCommitteeError as e:
                rejects.append(((message, subnet), e.reason))
        verified = self._finish_sync_batch(candidates, rejects)
        for v in verified:
            self.sync_pool.insert_message(v.item, v.positions, self.spec)
        return verified, rejects

    def verify_contributions_for_gossip(self, signed_contributions: list):
        """Batch-verify SignedContributionAndProofs (3 sets each)."""
        state = self.head_state
        candidates, rejects = [], []
        for signed in signed_contributions:
            try:
                candidates.append(sync_verify.verify_contribution_for_gossip(
                    self, signed, state))
            except sync_verify.SyncCommitteeError as e:
                rejects.append((signed, e.reason))
        verified = self._finish_sync_batch(candidates, rejects)
        for v in verified:
            self.sync_pool.insert_contribution(v.item.message.contribution)
        return verified, rejects

    def _finish_sync_batch(self, candidates, rejects):
        if self.verify_signatures:
            sync_verify.batch_verify(self, candidates)
        else:
            for c in candidates:
                c.ok = True
        verified = []
        for c in candidates:
            if not c.ok:
                rejects.append((c.item, "invalid_signature"))
            elif not sync_verify.commit_observations(self, c):
                rejects.append((c.item, "duplicate_in_batch"))
            else:
                verified.append(c)
        return verified

    def _attestation_state(self, item):
        """State to validate an attestation against: the target block's
        post-state, advanced to the attestation's target epoch when stale
        (committees come from the target-epoch shuffle, so an old state
        would compute the wrong committee)."""
        from lighthouse_tpu.state_transition import state_advance

        att = getattr(getattr(item, "message", item), "aggregate", None)
        att = att if att is not None else getattr(item, "message", item)
        data = att.data if hasattr(att, "data") else att
        root = bytes(data.beacon_block_root)
        st = self.state_for_block(root)
        if st is None:
            st = self.head_state
        target_epoch = int(data.target.epoch)
        spec = self.spec
        if spec.compute_epoch_at_slot(int(st.slot)) < target_epoch:
            key = root + target_epoch.to_bytes(8, "little")
            cached = self._advanced_states.get(key)
            if cached is None:
                cached = st.copy()
                state_advance(cached, spec,
                              spec.compute_start_slot_at_epoch(target_epoch))
                if len(self._advanced_states) > 8:
                    self._advanced_states.clear()
                self._advanced_states[key] = cached
            st = cached
        return st

    # -- block production --------------------------------------------------

    def _produce_payload(self, pre, slot: int, fork: str,
                         proposer_index: int | None = None):
        """Build the block's payload via the EL (reference
        execution_layer.get_payload in produce_partial_beacon_block).
        The proposer's prepared fee recipient (prepare_beacon_proposer
        route) overrides the EL default."""
        from lighthouse_tpu.state_transition import misc
        from lighthouse_tpu.state_transition.block_processing import (
            get_expected_withdrawals,
        )

        spec = self.spec
        parent_hash = bytes(
            pre.latest_execution_payload_header.block_hash)
        timestamp = int(pre.genesis_time) + slot * spec.seconds_per_slot
        epoch = spec.compute_epoch_at_slot(slot)
        prev_randao = bytes(misc.get_randao_mix(pre, spec, epoch))
        withdrawals = None
        version = {"bellatrix": 1, "capella": 2}.get(fork, 3)
        if fork in ("capella", "deneb", "electra"):
            withdrawals = get_expected_withdrawals(pre, spec)
        fee_recipient = None
        if proposer_index is not None:
            fee_recipient = getattr(self, "prepared_proposers", {}).get(
                int(proposer_index))
        payload_id = self.execution_layer.prepare_payload(
            parent_hash, timestamp, prev_randao, withdrawals,
            fee_recipient=fee_recipient, version=version,
            parent_beacon_block_root=self.get_proposer_head(slot))
        if payload_id is None:
            raise BlockError("el_did_not_return_payload_id")
        payload_cls = getattr(
            self.t, f"ExecutionPayload{fork.capitalize()}")
        return self.execution_layer.get_payload(
            payload_id, payload_cls, version=version)

    def produce_block_on(self, slot: int, randao_reveal: bytes,
                         graffiti: bytes = b"", attestations: list | None = None,
                         sync_aggregate=None, execution_payload=None):
        """Produce an unsigned block on the current head
        (reference produce_block_with_verification, beacon_chain.rs:4224).
        The caller (validator client) signs it.  With attestations=None,
        the operation pool packs them (max-cover) along with slashings,
        exits and BLS changes (produce_partial_beacon_block,
        beacon_chain.rs:4930)."""
        from lighthouse_tpu.state_transition import (
            SignatureStrategy,
            misc,
            process_block,
            state_advance,
        )

        spec = self.spec
        fork = spec.fork_at_epoch(spec.compute_epoch_at_slot(slot))
        head_root = self.get_proposer_head(slot)
        pre = None
        if self.state_advance_timer is not None:
            cached = self.state_advance_timer.get(head_root, slot)
            if cached is not None:
                pre = cached.copy()
        if pre is None:
            pre = self.state_for_block(head_root).copy()
            if int(pre.slot) < slot:
                state_advance(pre, spec, slot)
        proposer = misc.get_beacon_proposer_index(pre, spec, slot)

        pool_kw = {}
        if attestations is None:
            # fold the naive pool's current aggregates in before packing
            for data, bits, sig, ci in self.naive_pool.iter_aggregates():
                self.op_pool.insert_attestation(
                    data, bits, sig, committee_index=ci)
            attestations = self.op_pool.get_attestations(
                pre, spec, lambda e: self.committee_shuffle(pre, e), t=self.t)
            prop_sl, att_sl = self.op_pool.get_slashings(pre, spec)
            pool_kw = dict(
                proposer_slashings=prop_sl,
                attester_slashings=att_sl,
                voluntary_exits=self.op_pool.get_voluntary_exits(pre, spec),
            )
            if T.ChainSpec.fork_at_least(fork, "capella"):
                pool_kw["bls_to_execution_changes"] = (
                    self.op_pool.get_bls_to_execution_changes(pre, spec))

        eth1_data = pre.eth1_data
        deposits = []
        if self.eth1_service is not None:
            eth1_data = self.eth1_service.get_eth1_vote(pre)
            # the transition applies process_eth1_data BEFORE the deposit
            # count check, so deposits must match the POST-vote eth1_data:
            # mirror the majority condition here
            period_slots = (spec.preset.epochs_per_eth1_voting_period
                            * spec.preset.slots_per_epoch)
            n_equal = 1 + sum(
                1 for v in pre.eth1_data_votes if v == eth1_data)
            effective = (eth1_data if n_equal * 2 > period_slots
                         else pre.eth1_data)
            if int(pre.eth1_deposit_index) < int(effective.deposit_count):
                deposits = self.eth1_service.deposits_for_inclusion(
                    pre, spec.preset.max_deposits, eth1_data=effective)
        body_kw = dict(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=graffiti.ljust(32, b"\x00")[:32],
            attestations=list(attestations),
            deposits=deposits,
            **pool_kw,
        )
        if fork != "phase0":
            if sync_aggregate is None:
                # contributions for the parent root at the previous slot
                # (reference get_sync_aggregate in block production)
                sync_aggregate = self.sync_pool.produce_sync_aggregate(
                    slot - 1, head_root, spec, self.t)
            body_kw["sync_aggregate"] = sync_aggregate
        if T.ChainSpec.fork_at_least(fork, "bellatrix"):
            if execution_payload is None and self.execution_layer is not None:
                execution_payload = self._produce_payload(
                    pre, slot, fork, proposer_index=proposer)
            if execution_payload is None and hasattr(self, "mock_payload"):
                # dev/sim nodes without an EL self-build payloads
                execution_payload = self.mock_payload(slot)
            if execution_payload is None:
                raise BlockError("execution_payload_required")
            body_kw["execution_payload"] = execution_payload

        body = self.t.beacon_block_body_class(fork)(**body_kw)
        block = self.t.beacon_block_class(fork)(
            slot=slot, proposer_index=proposer,
            parent_root=head_root, state_root=b"\x00" * 32, body=body)
        trial = pre.copy()
        signed_cls = self.t.signed_beacon_block_class(fork)
        process_block(trial, spec, signed_cls(
            message=block, signature=b"\x00" * 96),
            SignatureStrategy.NO_VERIFICATION)
        block.state_root = trial.hash_tree_root()
        return block, proposer

    def produce_blinded_block_on(self, slot: int, randao_reveal: bytes,
                                 graffiti: bytes = b""):
        """Blinded production for the builder round trip: race the
        builder's bid against the local payload, build the full block on
        the winner, return its BLINDED form + the payload source.  The
        payload book remembers how to unblind on submission
        (reference http_api produce_blinded_block + execution_layer
        get_payload builder/local race)."""
        from lighthouse_tpu.chain.block_verification import BlockError
        from lighthouse_tpu.execution.blinded import blind_block
        from lighthouse_tpu.execution.builder_api import choose_payload

        spec = self.spec
        fork = spec.fork_at_epoch(spec.compute_epoch_at_slot(slot))
        if fork in ("phase0", "altair"):
            raise BlockError(
                f"blinded production needs an execution fork, slot {slot} "
                f"is {fork}")
        payload, source = choose_payload(
            self, slot, self.builder_client, local_payload=None)
        block, proposer = self.produce_block_on(
            slot, randao_reveal, graffiti=graffiti,
            execution_payload=payload)
        used = block.body.execution_payload
        self._blinded_payloads[bytes(used.block_hash)] = (source, used)
        while len(self._blinded_payloads) > 8:
            self._blinded_payloads.pop(next(iter(self._blinded_payloads)))
        return blind_block(self.t, fork, block), proposer, source

    def submit_blinded_block(self, signed_blinded):
        """Unblind a signed blinded block and import it: local payloads
        come from the payload book, builder payloads are revealed by
        POSTing the signed block to the builder.  A builder that fails
        to reveal loses the proposal (the signature commits to ITS
        payload header; nothing else can be substituted)."""
        from lighthouse_tpu.chain.block_verification import BlockError
        from lighthouse_tpu.execution.blinded import (
            UnblindError,
            unblind_block,
        )
        from lighthouse_tpu.execution.builder_api import BuilderError

        blinded = signed_blinded.message
        spec = self.spec
        fork = spec.fork_at_epoch(
            spec.compute_epoch_at_slot(int(blinded.slot)))
        header = blinded.body.execution_payload_header
        entry = self._blinded_payloads.get(bytes(header.block_hash))
        if entry is None:
            raise BlockError("unknown blinded payload (not produced here)")
        source, payload = entry
        if source == "builder":
            if self.builder_client is None:
                raise BlockError("builder payload but no builder client")
            try:
                raw = self.builder_client.submit_blinded_block(
                    signed_blinded.serialize())
                payload = type(payload).deserialize(raw)
            except (BuilderError, KeyError, ValueError) as e:
                # same fault class the bid path tolerates: transport
                # errors AND malformed 200 bodies (missing keys, bad hex,
                # undecodable SSZ) are all "the builder failed us"
                raise BlockError(f"builder failed to reveal: {e}") from e
        try:
            full = unblind_block(self.t, fork, signed_blinded, payload)
        except UnblindError as e:
            raise BlockError(str(e)) from e
        return self.process_block(full), full

    def get_proposer_head(self, slot: int) -> bytes:
        """Head to build on, with the late-block re-org rule
        (reference get_proposer_head, fork_choice.rs:516)."""
        return self.fork_choice.get_proposer_head(self.head_root, slot)

    # -- queries ----------------------------------------------------------

    def block_root_at_slot(self, slot: int) -> bytes | None:
        if slot < self.store.split_slot:
            return self.store.cold_block_root_at_slot(slot)
        st = self.head_state
        sphr = self.spec.preset.slots_per_historical_root
        if slot == int(st.slot):
            return self.head_root
        if slot < int(st.slot) <= slot + sphr:
            return bytes(st.block_roots[slot % sphr].tobytes())
        return None

    def finalized_checkpoint(self):
        return self.fork_choice.finalized

    def justified_checkpoint(self):
        return self.fork_choice.justified
