"""Fork-choice revert after EL invalidation (reference beacon_chain/src/
fork_revert.rs): when the execution layer declares the head's payload
chain invalid, rebuild fork choice from the finalized (or anchor) state
and replay the still-valid stored blocks, leaving invalidated branches
out.
"""

from __future__ import annotations


def revert_to_fork_boundary(chain, invalid_root: bytes):
    """Mark `invalid_root` and its descendants invalid; if the current
    head is affected, recompute.  When the whole tree above finality is
    poisoned, rebuild fork choice from the finalized state."""
    from lighthouse_tpu.fork_choice import ForkChoice

    proto = chain.fork_choice.proto
    if invalid_root in proto:
        proto.set_execution_invalid(invalid_root)
        new_head = chain.recompute_head()
        if new_head != invalid_root:
            return new_head

    # head stuck on an invalid branch: rebuild from the finalized state
    fin = chain.fork_choice.finalized
    fin_block = chain.store.get_block(fin.root)
    fin_state = chain.state_for_block(fin.root)
    if fin_block is None or fin_state is None:
        raise RuntimeError(
            "cannot revert: finalized block/state unavailable")
    chain.fork_choice = ForkChoice(
        chain.spec, fin.root, fin_state,
        balances_fn=chain._balances_for_checkpoint)
    # replay stored non-finalized blocks that do not descend from the
    # invalid root
    replayable = []
    for root, block in chain.store.iter_hot_blocks():
        if int(block.message.slot) <= int(fin_state.slot):
            continue
        replayable.append((int(block.message.slot), root, block))
    skipped = {bytes(invalid_root)}
    for slot, root, block in sorted(replayable):
        parent = bytes(block.message.parent_root)
        if parent in skipped or root == bytes(invalid_root):
            skipped.add(root)
            continue
        state = chain.state_for_block(root)
        if state is None:
            skipped.add(root)
            continue
        try:
            chain.fork_choice.on_block(
                max(chain.current_slot(), slot), block.message, root, state)
        except Exception:
            skipped.add(root)
    chain.head_root = chain.fork_choice.get_head(chain.current_slot())
    st = chain.state_for_block(chain.head_root)
    if st is not None:
        chain.head_state = st
    return chain.head_root
