"""Light-client server: bootstrap/optimistic/finality updates.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
light_client_server_cache.rs (+ the LC types from consensus/types): the
chain keeps the latest sync-aggregate-attested header and serves

  * LightClientBootstrap   — header + current sync committee (+ proof)
  * LightClientOptimisticUpdate — attested header + sync aggregate
  * LightClientFinalityUpdate   — + finalized header + finality proof

Merkle proofs ride the generalized-index machinery over the state's
field roots (altair state: current_sync_committee gindex 54, next 55,
finalized_checkpoint.root gindex 105 — depth 5/6 over the 2^5-padded
field tree; computed generically below instead of hardcoding offsets).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from lighthouse_tpu.common.metrics import record_swallowed


def _field_proof(state, field_name: str) -> tuple[bytes, list[bytes], int]:
    """(leaf_root, branch, generalized_index) for a top-level state field
    against state.hash_tree_root().

    Field roots ride the state's incremental tree cache when present —
    this runs on the block-import hot path, so a from-scratch registry
    rehash here would undo the cache's whole point."""
    cls = type(state)
    names = list(cls.fields)
    idx = names.index(field_name)
    cache = getattr(state, "_tree_cache", None)
    if cache is not None:
        leaves = [cache.field_root(fn, ft, getattr(state, fn))
                  for fn, ft in cls.fields.items()]
    else:
        leaves = [ft.hash_tree_root(getattr(state, fn))
                  for fn, ft in cls.fields.items()]
    width = 1
    while width < len(leaves):
        width *= 2
    padded = leaves + [b"\x00" * 32] * (width - len(leaves))
    branch = []
    pos = idx
    level = padded
    while len(level) > 1:
        sibling = pos ^ 1
        branch.append(level[sibling])
        level = [hashlib.sha256(level[i] + level[i + 1]).digest()
                 for i in range(0, len(level), 2)]
        pos //= 2
    gindex = width + idx
    return leaves[idx], branch, gindex


@dataclass
class LightClientHeader:
    slot: int
    proposer_index: int
    parent_root: bytes
    state_root: bytes
    body_root: bytes

    def to_json(self) -> dict:
        return {"beacon": {
            "slot": str(self.slot),
            "proposer_index": str(self.proposer_index),
            "parent_root": "0x" + self.parent_root.hex(),
            "state_root": "0x" + self.state_root.hex(),
            "body_root": "0x" + self.body_root.hex(),
        }}


@dataclass
class LightClientBootstrap:
    header: LightClientHeader
    current_sync_committee: object
    current_sync_committee_branch: list


@dataclass
class LightClientOptimisticUpdate:
    attested_header: LightClientHeader
    sync_aggregate: object
    signature_slot: int

    def to_json(self) -> dict:
        return {
            "attested_header": self.attested_header.to_json(),
            "sync_aggregate": sync_aggregate_json(self.sync_aggregate),
            "signature_slot": str(self.signature_slot),
        }


@dataclass
class LightClientFinalityUpdate:
    attested_header: LightClientHeader
    finalized_header: LightClientHeader | None
    finality_branch: list
    sync_aggregate: object
    signature_slot: int

    def to_json(self) -> dict:
        return {
            "attested_header": self.attested_header.to_json(),
            "finalized_header": (self.finalized_header.to_json()
                                 if self.finalized_header else None),
            "finality_branch": [
                "0x" + b.hex() for b in self.finality_branch],
            "sync_aggregate": sync_aggregate_json(self.sync_aggregate),
            "signature_slot": str(self.signature_slot),
        }


def _header_for(chain, root: bytes) -> LightClientHeader | None:
    blk = chain.store.get_block(root)
    if blk is None:
        return None
    m = blk.message
    return LightClientHeader(
        int(m.slot), int(m.proposer_index), bytes(m.parent_root),
        bytes(m.state_root), m.body.hash_tree_root())


def sync_aggregate_json(agg) -> dict:
    """THE wire serialization of a SyncAggregate (packed SSZ bitvector)
    — shared by the chain-layer update JSON and the HTTP API so the two
    formats cannot diverge."""
    import numpy as np

    bits = np.asarray(agg.sync_committee_bits, bool)
    return {
        "sync_committee_bits":
            "0x" + np.packbits(bits, bitorder="little").tobytes().hex(),
        "sync_committee_signature":
            "0x" + bytes(agg.sync_committee_signature).hex(),
    }


def sync_committee_json(committee) -> dict:
    return {
        "aggregate_pubkey":
            "0x" + bytes(committee.aggregate_pubkey).hex(),
        "pubkeys": ["0x" + bytes(pk).hex() for pk in committee.pubkeys],
    }


@dataclass
class LightClientUpdate:
    """Full period update: the attested header plus the NEXT sync
    committee under proof — what a light client needs to advance one
    sync-committee period (reference light_client_update.rs)."""

    attested_header: LightClientHeader
    next_sync_committee: object
    next_sync_committee_branch: list[bytes]
    finalized_header: LightClientHeader | None
    finality_branch: list[bytes]
    sync_aggregate: object
    signature_slot: int

    def to_json(self) -> dict:
        return {
            "attested_header": self.attested_header.to_json(),
            "next_sync_committee": sync_committee_json(
                self.next_sync_committee),
            "next_sync_committee_branch": [
                "0x" + b.hex() for b in self.next_sync_committee_branch],
            "finalized_header": (self.finalized_header.to_json()
                                 if self.finalized_header else None),
            "finality_branch": [
                "0x" + b.hex() for b in self.finality_branch],
            "sync_aggregate": sync_aggregate_json(self.sync_aggregate),
            "signature_slot": str(self.signature_slot),
        }


def _update_rank(spec, participation: int, committee_size: int,
                 attested_slot: int, signature_slot: int,
                 finalized_slot: int | None) -> tuple:
    """Spec `is_better_update` ordering for per-period best updates
    (sync-protocol.md), encoded as a sortable tuple (bigger wins),
    field for field: supermajority; participation when neither side has
    supermajority (the spec compares it early only in that branch — a
    zero placeholder keeps supermajority pairs falling through);
    relevance (attested period == signature period); finality presence;
    sync-committee finality (finalized period == attested period); raw
    participation; then OLDER attested header and OLDER signature slot
    (earlier proof of the same committee is strictly more useful)."""
    _period_at = spec.sync_committee_period_at_slot
    supermajority = participation * 3 >= committee_size * 2
    relevant = _period_at(attested_slot) == _period_at(signature_slot)
    has_finality = finalized_slot is not None
    sync_committee_finality = has_finality and (
        _period_at(finalized_slot) == _period_at(attested_slot))
    return (supermajority,
            0 if supermajority else participation,
            relevant, has_finality, sync_committee_finality,
            participation, -int(attested_slot), -int(signature_slot))


class LightClientServerCache:
    """Tracks the best sync-aggregate-attested header per slot."""

    MAX_STORED_PERIODS = 128

    def __init__(self, chain):
        self.chain = chain
        self.latest_optimistic: LightClientOptimisticUpdate | None = None
        self.latest_finality: LightClientFinalityUpdate | None = None
        # sync-committee period -> (rank tuple, best update) — ranked by
        # the spec's is_better_update ordering, not bare participation
        self._updates: dict[int, tuple[tuple, LightClientUpdate]] = {}
        # NetworkService hooks these to gossip fresh updates to the
        # light_client_{finality,optimistic}_update topics (the
        # reference's --light-client-server gossip publication)
        self.on_finality_update = None
        self.on_optimistic_update = None

    def on_block_imported(self, signed_block) -> None:
        """Feed each imported block: its sync aggregate attests the
        parent."""
        chain = self.chain
        body = signed_block.message.body
        agg = getattr(body, "sync_aggregate", None)
        if agg is None or not any(agg.sync_committee_bits):
            return
        attested_root = bytes(signed_block.message.parent_root)
        attested = _header_for(chain, attested_root)
        if attested is None:
            return
        sig_slot = int(signed_block.message.slot)
        self.latest_optimistic = LightClientOptimisticUpdate(
            attested, agg, sig_slot)
        # to_json costs packbits + hex over the committee bits; only pay
        # it when an SSE subscriber is actually listening
        if chain.events.has_subscribers("light_client_optimistic_update"):
            chain.events.publish("light_client_optimistic_update",
                                 self.latest_optimistic.to_json())
        if self.on_optimistic_update is not None:
            try:
                self.on_optimistic_update(self.latest_optimistic)
            except Exception as e:
                record_swallowed("light_client.optimistic_cb", e)

        state = chain.state_for_block(attested_root)
        if state is None:
            return
        fin_root = bytes(state.finalized_checkpoint.root)
        fin_header = (_header_for(chain, fin_root)
                      if fin_root != b"\x00" * 32 else None)
        # finality proof: finalized_checkpoint field root -> state root,
        # then checkpoint.root inside (epoch, root) 2-leaf subtree
        leaf, branch, _ = _field_proof(state, "finalized_checkpoint")
        epoch_leaf = int(state.finalized_checkpoint.epoch).to_bytes(
            32, "little")
        finality_branch = [epoch_leaf] + branch
        self.latest_finality = LightClientFinalityUpdate(
            attested, fin_header, finality_branch, agg, sig_slot)
        if chain.events.has_subscribers("light_client_finality_update"):
            chain.events.publish("light_client_finality_update",
                                 self.latest_finality.to_json())
        if self.on_finality_update is not None:
            try:
                self.on_finality_update(self.latest_finality)
            except Exception as e:
                record_swallowed("light_client.finality_cb", e)

        # period update: prove the attested state's NEXT sync committee;
        # keep the spec-ranked best update per period (is_better_update)
        if hasattr(state, "next_sync_committee"):
            spec = chain.spec
            period = spec.sync_committee_period_at_slot(attested.slot)
            participation = sum(
                1 for b in agg.sync_committee_bits if b)
            rank = _update_rank(
                spec, participation, spec.preset.sync_committee_size,
                attested.slot, sig_slot,
                fin_header.slot if fin_header is not None else None)
            best = self._updates.get(period)
            if best is None or rank > best[0]:
                _, nsc_branch, _ = _field_proof(
                    state, "next_sync_committee")
                self._updates[period] = (rank, LightClientUpdate(
                    attested, state.next_sync_committee, nsc_branch,
                    fin_header, finality_branch, agg, sig_slot))
                while len(self._updates) > self.MAX_STORED_PERIODS:
                    self._updates.pop(min(self._updates))

    def updates_by_range(self, start_period: int,
                         count: int) -> list[LightClientUpdate]:
        """Best update per sync-committee period in [start, start+count)
        (reference light_client_updates_by_range RPC + API)."""
        out = []
        for period in range(int(start_period),
                            int(start_period) + min(int(count), 128)):
            hit = self._updates.get(period)
            if hit is not None:
                out.append(hit[1])
        return out

    def bootstrap(self, block_root: bytes) -> LightClientBootstrap | None:
        chain = self.chain
        header = _header_for(chain, block_root)
        state = chain.state_for_block(bytes(block_root))
        if header is None or state is None:
            return None
        if not hasattr(state, "current_sync_committee"):
            return None
        _, branch, _ = _field_proof(state, "current_sync_committee")
        return LightClientBootstrap(
            header, state.current_sync_committee, branch)
