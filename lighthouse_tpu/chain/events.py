"""Server-sent event stream (reference beacon_chain/src/events.rs +
http_api events endpoint): chain milestones fan out to subscribers.
"""

from __future__ import annotations

import json
import queue
import threading


class EventStream:
    """Bounded fan-out of chain events to SSE subscribers."""

    TOPICS = ("head", "block", "attestation", "finalized_checkpoint",
              "chain_reorg", "voluntary_exit", "contribution_and_proof",
              "light_client_finality_update",
              "light_client_optimistic_update")

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._subs: list[tuple[set, queue.Queue]] = []
        self._lock = threading.Lock()

    def subscribe(self, topics: list[str] | None = None) -> queue.Queue:
        topic_set = set(topics or self.TOPICS)
        unknown = topic_set - set(self.TOPICS)
        if unknown:
            raise ValueError(f"unknown event topics: {sorted(unknown)}")
        q: queue.Queue = queue.Queue(self.capacity)
        with self._lock:
            self._subs.append((topic_set, q))
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            self._subs = [(t, s) for t, s in self._subs if s is not q]

    def has_subscribers(self, topic: str) -> bool:
        """Producers with non-trivial serialization cost gate on this so
        the import hot path never serializes into the void."""
        with self._lock:
            return any(topic in topics for topics, _ in self._subs)

    def publish(self, topic: str, data: dict) -> None:
        with self._lock:
            subs = list(self._subs)
        for topics, q in subs:
            if topic not in topics:
                continue
            try:
                q.put_nowait((topic, data))
            except queue.Full:
                pass  # slow consumer: drop (reference lagged-receiver drop)

    @staticmethod
    def format_sse(topic: str, data: dict) -> str:
        return f"event: {topic}\ndata: {json.dumps(data)}\n\n"
