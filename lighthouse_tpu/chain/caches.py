"""Chain-level caches: shuffling, decompressed pubkeys, observed-dup sets.

Reference equivalents in /root/reference/beacon_node/beacon_chain/src/:
shuffling_cache.rs, validator_pubkey_cache.rs, observed_attesters.rs,
observed_aggregates.rs, observed_block_producers.rs.

TPU-first data layout: observed-attester sets are epoch-keyed boolean
numpy columns over validator index (one vectorized gather/scatter per
batch instead of per-item set probes), matching the columnar vote tracker
in fork choice.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np


class ShufflingCache:
    """Committee shuffles keyed by (epoch, shuffling decision root)
    (reference shuffling_cache.rs).  The decision root is the block root at
    the last slot of the epoch two before the shuffling epoch — states on
    the same chain share shuffles."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._d: OrderedDict[tuple[int, bytes], np.ndarray] = OrderedDict()

    def get(self, epoch: int, decision_root: bytes) -> np.ndarray | None:
        key = (epoch, decision_root)
        shuffle = self._d.get(key)
        if shuffle is not None:
            self._d.move_to_end(key)
        return shuffle

    def insert(self, epoch: int, decision_root: bytes, shuffle: np.ndarray):
        self._d[(epoch, decision_root)] = shuffle
        self._d.move_to_end((epoch, decision_root))
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def get_or_compute(self, state, spec, epoch: int, decision_root: bytes):
        from lighthouse_tpu.state_transition import misc

        shuffle = self.get(epoch, decision_root)
        if shuffle is None:
            shuffle = misc.compute_committee_shuffle(state, spec, epoch)
            self.insert(epoch, decision_root, shuffle)
        return shuffle


def shuffling_decision_root(state, spec, epoch: int, head_block_root: bytes) -> bytes:
    """Block root at the last slot before the shuffling's randao seed was
    fixed (reference: proto-array shuffling_id).  Falls back to the head
    block root when the chain is too young."""
    from lighthouse_tpu.state_transition import misc

    decision_slot = spec.compute_start_slot_at_epoch(max(epoch - 1, 0))
    if decision_slot == 0 or decision_slot >= int(state.slot):
        return head_block_root
    try:
        return misc.get_block_root_at_slot(state, spec, decision_slot - 1)
    except ValueError:
        return head_block_root


class ValidatorPubkeyCache:
    """Decompressed G1 pubkey points by validator index (reference
    validator_pubkey_cache.rs) — decompression costs a sqrt in Fp, so it is
    paid once per validator, not once per signature."""

    def __init__(self):
        from lighthouse_tpu.crypto import bls

        self._bls = bls
        self._keys: list = []
        self._index: dict[bytes, int] = {}   # pubkey bytes -> validator idx

    def import_new(self, validators) -> None:
        """Extend with any registry entries beyond the cache length."""
        pubkeys = validators.pubkeys
        n = pubkeys.shape[0] if hasattr(pubkeys, "shape") else len(pubkeys)
        for i in range(len(self._keys), n):
            pk_bytes = bytes(pubkeys[i].tobytes()
                             if hasattr(pubkeys[i], "tobytes") else pubkeys[i])
            self._keys.append(self._bls.PublicKey.interned(pk_bytes))
            self._index[pk_bytes] = i

    def get(self, index: int):
        if 0 <= index < len(self._keys):
            return self._keys[index]
        return None

    def index_of(self, pubkey_bytes: bytes) -> int | None:
        """Validator index for a compressed pubkey (reference
        validator_pubkey_cache.rs get_index — sync-aggregate attribution
        maps committee pubkeys back to indices through this)."""
        return self._index.get(bytes(pubkey_bytes))

    def __len__(self):
        return len(self._keys)


class EpochIndexedSeen:
    """Epoch-keyed seen-bitmaps over validator index (reference
    observed_attesters.rs ObservedAttesters): `check_and_observe` a whole
    batch vectorized."""

    def __init__(self, retained_epochs: int = 4):
        self.retained = retained_epochs
        self._by_epoch: dict[int, np.ndarray] = {}

    def _bitmap(self, epoch: int, n: int) -> np.ndarray:
        bm = self._by_epoch.get(epoch)
        if bm is None:
            bm = np.zeros(max(n, 1024), bool)
            self._by_epoch[epoch] = bm
            self._prune(epoch)
        elif bm.shape[0] < n:
            bm = np.concatenate([bm, np.zeros(n - bm.shape[0], bool)])
            self._by_epoch[epoch] = bm
        return bm

    def _prune(self, current_epoch: int):
        for e in [e for e in self._by_epoch if e + self.retained < current_epoch]:
            del self._by_epoch[e]

    def observe_batch(self, epoch: int, indices: np.ndarray) -> np.ndarray:
        """Mark indices seen; returns mask of indices that were ALREADY seen."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0:
            return np.zeros(0, bool)
        bm = self._bitmap(epoch, int(idx.max()) + 1)
        already = bm[idx].copy()
        bm[idx] = True
        return already

    def seen_mask(self, epoch: int, indices: np.ndarray) -> np.ndarray:
        """Read-only: which of `indices` are already seen (no mutation) —
        dup checks run BEFORE signature verification, marking happens only
        after it succeeds (unauthenticated input must not poison the
        cache)."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0:
            return np.zeros(0, bool)
        bm = self._by_epoch.get(epoch)
        if bm is None:
            return np.zeros(idx.shape[0], bool)
        out = np.zeros(idx.shape[0], bool)
        inb = idx < bm.shape[0]
        out[inb] = bm[idx[inb]]
        return out

    def is_seen(self, epoch: int, index: int) -> bool:
        bm = self._by_epoch.get(epoch)
        return bool(bm[index]) if bm is not None and index < bm.shape[0] else False


class SlotIndexedSeen:
    """Slot-keyed variant (observed block producers / sync contributions)."""

    def __init__(self, retained_slots: int = 64):
        self.retained = retained_slots
        self._by_slot: dict[int, set[int]] = {}

    def observe(self, slot: int, index: int) -> bool:
        """Returns True if (slot, index) was already seen."""
        s = self._by_slot.setdefault(slot, set())
        for old in [x for x in self._by_slot if x + self.retained < slot]:
            del self._by_slot[old]
        if index in s:
            return True
        s.add(index)
        return False

    def is_seen(self, slot: int, index: int) -> bool:
        """Read-only probe (no marking) for pre-signature dup checks."""
        return index in self._by_slot.get(slot, ())


class ObservedDigests:
    """Epoch-keyed digests of seen objects (reference
    observed_aggregates.rs: dedup identical aggregates/sync contributions)."""

    def __init__(self, retained_epochs: int = 4):
        self.retained = retained_epochs
        self._by_epoch: dict[int, set[bytes]] = {}

    def observe(self, epoch: int, data: bytes) -> bool:
        """Returns True if already seen."""
        d = hashlib.sha256(data).digest()
        s = self._by_epoch.setdefault(epoch, set())
        for old in [e for e in self._by_epoch if e + self.retained < epoch]:
            del self._by_epoch[old]
        if d in s:
            return True
        s.add(d)
        return False

    def is_seen(self, epoch: int, data: bytes) -> bool:
        """Read-only probe for pre-signature dup checks."""
        return hashlib.sha256(data).digest() in self._by_epoch.get(epoch, ())


class StateCache:
    """Small LRU of recent post-states by state root (reference: the
    snapshot cache / state LRU feeding block verification)."""

    def __init__(self, capacity: int = 8):
        self.capacity = capacity
        self._d: OrderedDict[bytes, object] = OrderedDict()

    def get(self, state_root: bytes):
        st = self._d.get(state_root)
        if st is not None:
            self._d.move_to_end(state_root)
        return st

    def insert(self, state_root: bytes, state):
        self._d[state_root] = state
        self._d.move_to_end(state_root)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)


class BlockTimesCache:
    """Wall-clock import timeline per block (reference block_times_cache.rs)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._d: OrderedDict[bytes, dict] = OrderedDict()

    def record(self, block_root: bytes, event: str, t: float):
        entry = self._d.setdefault(block_root, {})
        entry[event] = t
        self._d.move_to_end(block_root)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def get(self, block_root: bytes) -> dict:
        return dict(self._d.get(block_root, {}))
