"""Gossip sync-committee message + contribution verification.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
sync_committee_verification.rs (batch verify at :670): timing/membership/
duplicate checks produce SignatureSets that ride the same batched BLS
bridge as attestations, with log-depth bisection fallback on batch
failure.  Committee membership is resolved columnar (pubkey rows compared
vectorized), not via per-validator dict walks.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_transition import signature_sets as sigs

TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16


class SyncCommitteeError(ValueError):
    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


@dataclass
class VerifiedSyncItem:
    item: object
    sets: list
    observations: list = field(default_factory=list)
    ok: bool = False
    # for messages: subnet positions for pool insertion
    positions: list = field(default_factory=list)


def is_sync_aggregator(spec, selection_proof: bytes) -> bool:
    """Spec is_sync_committee_aggregator (selection-proof hash election)."""
    modulo = max(1, spec.preset.sync_committee_size
                 // spec.sync_committee_subnet_count
                 // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


def committee_positions(pubkey_rows: np.ndarray, pubkey: bytes) -> np.ndarray:
    """All positions of `pubkey` in the committee (vectorized row match)."""
    target = np.frombuffer(pubkey, dtype=np.uint8)
    return np.nonzero((pubkey_rows == target).all(axis=1))[0]


def subnet_positions(spec, positions: np.ndarray) -> dict[int, list[int]]:
    """committee positions -> {subnet: [position within subcommittee]}."""
    sub_size = (spec.preset.sync_committee_size
                // spec.sync_committee_subnet_count)
    out: dict[int, list[int]] = {}
    for p in positions:
        out.setdefault(int(p) // sub_size, []).append(int(p) % sub_size)
    return out


def _check_slot(chain, slot: int) -> None:
    current = chain.current_slot()
    # one slot of clock disparity, as the reference's gossip window
    if not (current - 1 <= slot <= current):
        raise SyncCommitteeError("slot_not_current")


def verify_sync_message_for_gossip(
    chain, message, subnet_id: int, state
) -> VerifiedSyncItem:
    spec = chain.spec
    slot = int(message.slot)
    _check_slot(chain, slot)
    vindex = int(message.validator_index)
    if vindex >= len(state.validators):
        raise SyncCommitteeError("unknown_validator")
    rows = chain.sync_committee_rows(state, slot)
    pubkey = state.validators.pubkeys[vindex].tobytes()
    positions = committee_positions(rows, pubkey)
    by_subnet = subnet_positions(spec, positions)
    if subnet_id not in by_subnet:
        raise SyncCommitteeError("validator_not_on_subnet")
    key = vindex * spec.sync_committee_subnet_count + int(subnet_id)
    if chain.observed_sync_contributors.is_seen(slot, key):
        raise SyncCommitteeError("prior_message_known")
    sset = sigs.sync_committee_message_set(state, spec, message)
    return VerifiedSyncItem(
        message, [sset],
        observations=[("contributor", slot, key)],
        positions=[(subnet_id, p) for p in by_subnet[subnet_id]])


def verify_contribution_for_gossip(chain, signed, state) -> VerifiedSyncItem:
    spec = chain.spec
    msg = signed.message
    contribution = msg.contribution
    slot = int(contribution.slot)
    _check_slot(chain, slot)
    subnet = int(contribution.subcommittee_index)
    if subnet >= spec.sync_committee_subnet_count:
        raise SyncCommitteeError("invalid_subcommittee_index")
    if not any(contribution.aggregation_bits):
        raise SyncCommitteeError("empty_aggregation_bits")
    aggregator = int(msg.aggregator_index)
    if aggregator >= len(state.validators):
        raise SyncCommitteeError("unknown_aggregator")
    rows = chain.sync_committee_rows(state, slot)
    pubkey = state.validators.pubkeys[aggregator].tobytes()
    by_subnet = subnet_positions(
        spec, committee_positions(rows, pubkey))
    if subnet not in by_subnet:
        raise SyncCommitteeError("aggregator_not_in_subcommittee")
    if not is_sync_aggregator(spec, bytes(msg.selection_proof)):
        raise SyncCommitteeError("invalid_selection_proof_not_aggregator")
    agg_key = aggregator * spec.sync_committee_subnet_count + subnet
    if chain.observed_sync_aggregators.is_seen(slot, agg_key):
        raise SyncCommitteeError("aggregator_already_known")
    digest = (contribution.beacon_block_root
              + bytes([subnet])
              + bytes(np.packbits(np.asarray(contribution.aggregation_bits))))
    if chain.observed_contributions.is_seen(slot, digest):
        raise SyncCommitteeError("contribution_already_known")

    sub_size = (spec.preset.sync_committee_size
                // spec.sync_committee_subnet_count)
    sub_pubkeys = [rows[subnet * sub_size + i].tobytes()
                   for i in range(sub_size)]
    sets = [
        sigs.sync_selection_proof_set(
            state, spec, slot, subnet, aggregator,
            bytes(msg.selection_proof)),
        sigs.contribution_and_proof_set(state, spec, signed),
        sigs.sync_committee_contribution_set(
            state, spec, contribution, sub_pubkeys),
    ]
    return VerifiedSyncItem(
        signed, sets,
        observations=[("aggregator", slot, agg_key),
                      ("contribution", slot, digest)])


def commit_observations(chain, verified: VerifiedSyncItem) -> bool:
    ok = True
    for kind, slot, payload in verified.observations:
        if kind == "contributor":
            if chain.observed_sync_contributors.observe(slot, payload):
                ok = False
        elif kind == "aggregator":
            if chain.observed_sync_aggregators.observe(slot, payload):
                ok = False
        elif kind == "contribution":
            if chain.observed_contributions.observe(slot, payload):
                ok = False
    return ok


def batch_verify(chain, candidates: list[VerifiedSyncItem]
                 ) -> list[VerifiedSyncItem]:
    """Shared batched-BLS path (duck-typed with attestation batching)."""
    from lighthouse_tpu.chain.attestation_verification import (
        verify_signature_sets_with_bisection,
    )

    all_sets, spans = [], []
    for c in candidates:
        spans.append((len(all_sets), len(all_sets) + len(c.sets)))
        all_sets.extend(c.sets)
    if not all_sets:
        return candidates
    if bls.verify_signature_sets(all_sets):
        for c in candidates:
            c.ok = True
        return candidates
    mask = verify_signature_sets_with_bisection(all_sets)
    for c, (lo, hi) in zip(candidates, spans):
        c.ok = bool(mask[lo:hi].all())
    return candidates
