"""Typestate block verification pipeline.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
block_verification.rs: a block moves through

    SignedBeaconBlock
      → GossipVerifiedBlock      (structure, slot, proposer sig only)
      → SignatureVerifiedBlock   (ALL signatures in one batch)
      → ExecutionPendingBlock    (state transition + state-root check)
      → imported                 (fork choice + atomic DB write)

(diagram at block_verification.rs:24-44).  Each stage is a class holding
what later stages need, so a block can never reach import without passing
every prior stage — the typestate discipline the reference encodes in Rust
types.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_transition import (
    SignatureStrategy,
    misc,
    process_block,
    signature_sets as sigs,
    state_advance,
)
class BlockError(ValueError):
    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


@dataclass
class GossipVerifiedBlock:
    """Structure + slot + proposer-signature-verified
    (reference GossipVerifiedBlock::new, block_verification.rs:793)."""

    signed_block: object
    block_root: bytes
    parent_state: object  # parent post-state advanced to the block's slot


@dataclass
class SignatureVerifiedBlock:
    """Every signature in the block batch-verified
    (reference SignatureVerifiedBlock, block_verification.rs:1117)."""

    signed_block: object
    block_root: bytes
    parent_state: object


@dataclass
class ExecutionPendingBlock:
    """State transition applied; post-state root validated
    (reference ExecutionPendingBlock, block_verification.rs:1286)."""

    signed_block: object
    block_root: bytes
    post_state: object
    state_root: bytes
    timings: dict = field(default_factory=dict)
    execution_status: int = 0  # proto_array EXEC_* (set by the chain)


def verify_block_for_gossip(chain, signed_block,
                            source: str = "gossip") -> GossipVerifiedBlock:
    """source="rpc" skips gossip-only equivocation checks so competing
    fork blocks fetched by sync can still import (reference: rpc blocks
    enter at SignatureVerifiedBlock, not GossipVerifiedBlock)."""
    spec = chain.spec
    block = signed_block.message
    slot = int(block.slot)
    current_slot = chain.current_slot()
    if slot > current_slot:
        raise BlockError("future_slot")
    fin_slot = spec.compute_start_slot_at_epoch(chain.fork_choice.finalized.epoch)
    if slot <= fin_slot:
        raise BlockError("finalized_slot")
    block_root = block.hash_tree_root()
    if chain.store.block_exists(block_root):
        raise BlockError("duplicate")
    proposer = int(block.proposer_index)
    # read-only dup probe here; the slot is only MARKED seen after the
    # proposer signature verifies, so unauthenticated garbage cannot block
    # the real proposal (reference observes post-signature too)
    if (source == "gossip"
            and chain.observed_block_producers.is_seen(slot, proposer)):
        raise BlockError("repeat_proposal")

    parent_root = bytes(block.parent_root)
    if parent_root not in chain.fork_choice.proto:
        raise BlockError("unknown_parent")
    parent_state = chain.state_for_block(parent_root)
    if parent_state is None:
        raise BlockError("parent_state_unavailable")
    # cheap advance to the block slot to obtain proposer/committees
    # (reference cheap_state_advance_to_obtain_committees, :2062)
    if int(parent_state.slot) < slot:
        parent_state = parent_state.copy()
        state_advance(parent_state, spec, slot)
    expected_proposer = misc.get_beacon_proposer_index(parent_state, spec, slot)
    if proposer != expected_proposer:
        raise BlockError("incorrect_proposer")
    # proposer-signature-only verification (:2140)
    if chain.verify_signatures:
        sset = sigs.block_proposal_set(
            parent_state, spec, signed_block, block_root)
        if not bls.verify_signature_sets([sset]):
            raise BlockError("proposer_signature_invalid")
    if chain.observed_block_producers.observe(slot, proposer) and source == "gossip":
        raise BlockError("repeat_proposal")
    return GossipVerifiedBlock(signed_block, block_root, parent_state)


def verify_block_signatures(chain, gossip_block: GossipVerifiedBlock) -> SignatureVerifiedBlock:
    """Accumulate every signature in the block and verify in ONE batch
    (reference BlockSignatureVerifier::include_all_signatures →
    verify_signature_sets, block_signature_verifier.rs:141-176,396-419).
    The batch rides the active BLS backend — this is the TPU offload seam.
    """
    if chain.verify_signatures:
        from lighthouse_tpu.common import tracing

        try:
            # the proposal signature already passed at the gossip stage —
            # don't pay that pairing twice (reference:
            # include_all_signatures_except_proposal).  The extraction is
            # the block path's pre-BLS stage in the slot SLO timeline.
            with tracing.span("pre_bls"):
                sets = sigs.include_all_signatures(
                    gossip_block.parent_state, chain.spec,
                    gossip_block.signed_block, gossip_block.block_root,
                    include_proposal=False)
        except ValueError as e:
            raise BlockError(f"invalid_signature_structure: {e}")
        if sets and not bls.verify_signature_sets(sets):
            raise BlockError("batch_signature_invalid")
    return SignatureVerifiedBlock(
        gossip_block.signed_block, gossip_block.block_root,
        gossip_block.parent_state)


def execute_block(chain, sig_block: SignatureVerifiedBlock) -> ExecutionPendingBlock:
    """Run the state transition and validate the claimed state root
    (reference ExecutionPendingBlock::from_signature_verified_components,
    block_verification.rs:1286: catch-up slots :1472, per_block_processing
    :1599, state-root check :1632)."""
    t0 = time.perf_counter()
    spec = chain.spec
    state = sig_block.parent_state.copy()
    block = sig_block.signed_block.message
    if int(state.slot) < int(block.slot):
        state_advance(state, spec, int(block.slot))
    process_block(state, spec, sig_block.signed_block,
                  SignatureStrategy.NO_VERIFICATION)
    t1 = time.perf_counter()
    state_root = state.hash_tree_root()
    if state_root != bytes(block.state_root):
        raise BlockError("state_root_mismatch")
    t2 = time.perf_counter()
    return ExecutionPendingBlock(
        sig_block.signed_block, sig_block.block_root, state, state_root,
        timings={"core": t1 - t0, "state_root": t2 - t1},
    )
