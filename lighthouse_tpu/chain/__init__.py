"""Chain orchestration: verification pipelines, caches, canonical head.

Reference: /root/reference/beacon_node/beacon_chain.
"""

from lighthouse_tpu.chain.attestation_verification import (
    AttestationError,
    VerifiedAttestation,
    verify_signature_sets_with_bisection,
)
from lighthouse_tpu.chain.beacon_chain import BeaconChain
from lighthouse_tpu.chain.block_verification import (
    BlockError,
    ExecutionPendingBlock,
    GossipVerifiedBlock,
    SignatureVerifiedBlock,
    execute_block,
    verify_block_for_gossip,
    verify_block_signatures,
)
from lighthouse_tpu.chain.caches import (
    BlockTimesCache,
    EpochIndexedSeen,
    ObservedDigests,
    ShufflingCache,
    SlotIndexedSeen,
    StateCache,
    ValidatorPubkeyCache,
)
from lighthouse_tpu.chain.chain_health import ChainHealthMonitor

__all__ = [
    "BeaconChain",
    "BlockError",
    "ChainHealthMonitor",
    "AttestationError",
    "VerifiedAttestation",
    "GossipVerifiedBlock",
    "SignatureVerifiedBlock",
    "ExecutionPendingBlock",
    "verify_block_for_gossip",
    "verify_block_signatures",
    "execute_block",
    "verify_signature_sets_with_bisection",
    "ShufflingCache",
    "ValidatorPubkeyCache",
    "EpochIndexedSeen",
    "SlotIndexedSeen",
    "ObservedDigests",
    "StateCache",
    "BlockTimesCache",
]
