"""Per-slot SLO engine: score every slot's causal timeline.

The tracing ring (PR 1) records WHAT happened inside a slot; this module
decides whether it happened FAST ENOUGH.  It rides the tracer's
root-span sink: finished ``block_import`` / ``import_block`` roots are
stitched into a per-slot causal timeline

    gossip arrival -> admission (gossip checks) -> pre_bls (signature-set
    extraction/coalesce) -> verify (the BLS batch) -> import (state
    transition + payload join + store) -> fork_choice -> head

by mapping span names to protocol stages, and once the ``head`` stage
lands the slot is SCORED against its deadline budget:

- ``LHTPU_SLO_BUDGET_MS`` is the full gossip-to-head budget (default
  4000 ms — a block must be in fork choice well before the 4 s
  attestation deadline inside a 12 s slot);
- each stage's budget is a fixed fraction of it (:data:`STAGE_FRACTIONS`,
  summing > 1 deliberately: stages overlap and a single slow stage
  inside an on-time slot is still worth flagging);
- a stage over budget increments ``slo_violations_total{stage}`` and
  files an ``slo_violation`` flight-recorder event; every scored slot
  lands in ``slo_slots_total{outcome}``.

Latency distributions are exposed two ways: labeled
``slo_stage_seconds{stage}`` histograms (Prometheus surface) and exact
p50/p99/p999 from bounded per-stage reservoirs (:func:`quantiles`, the
``GET /lighthouse/observatory/slo`` payload — the chaos-soak liveness
assertion reads p999 here).  Both structures are hard-bounded
(``LHTPU_SLO_RING`` slots, ``LHTPU_SLO_RESERVOIR`` samples per stage,
newest-wins; evictions counted in ``tracing_evicted_total``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common import flight_recorder as flight
from lighthouse_tpu.common.metrics import (
    REGISTRY,
    record_evicted,
    record_swallowed,
)
from lighthouse_tpu.common.tracing import TRACER

#: protocol stages in causal order (``total`` is the whole root span)
STAGES = ("admission", "pre_bls", "verify", "import", "fork_choice",
          "head", "total")

#: per-stage budget as a fraction of LHTPU_SLO_BUDGET_MS.  Sums past
#: 1.0 on purpose: the stage budgets flag a *locally* slow stage even
#: when pipeline overlap keeps the slot total inside its deadline.
STAGE_FRACTIONS = {
    "admission": 0.10,
    "pre_bls": 0.10,
    "verify": 0.40,
    "import": 0.35,
    "fork_choice": 0.15,
    "head": 0.15,
    "total": 1.00,
}

#: span name -> stage (the stitch map; spans outside it are ignored)
SPAN_STAGES = {
    "gossip_verify": "admission",
    "pre_bls": "pre_bls",
    "signature_verify": "verify",
    "state_transition": "import",
    "payload_join": "import",
    "store_import": "import",
    "fork_choice": "fork_choice",
    "head_update": "head",
}

#: roots the engine stitches (anything else in the ring is not part of
#: the block pipeline)
_ROOT_NAMES = ("block_import", "import_block")

_SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.0, 4.0, 8.0, 12.0)


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class SloEngine:
    """Stage accumulation + scoring; install on a tracer with
    :func:`install` (idempotent)."""

    def __init__(self, budget_ms: float | None = None,
                 ring: int | None = None, reservoir: int | None = None):
        self.budget_ms = (budget_ms if budget_ms is not None
                          else envreg.get_float("LHTPU_SLO_BUDGET_MS",
                                                4000.0) or 4000.0)
        self.ring = max(8, ring if ring is not None
                        else envreg.get_int("LHTPU_SLO_RING", 128) or 128)
        self.reservoir = max(
            32, reservoir if reservoir is not None
            else envreg.get_int("LHTPU_SLO_RESERVOIR", 1024) or 1024)
        self._lock = threading.Lock()
        # slot -> {"stages": {stage: seconds}, "scored": bool}
        self._slots: OrderedDict[int, dict] = OrderedDict()
        # stage -> bounded sample deque (seconds, newest-wins)
        self._samples: dict[str, deque] = {
            s: deque(maxlen=self.reservoir) for s in STAGES}
        self.scored = 0
        self.violations: dict[str, int] = {}
        self._hist_memo: dict = {}
        self._viol_memo: dict = {}

    # -- feeding -------------------------------------------------------------

    def sink(self, root, slot) -> None:
        """Tracer root-span sink: stitch a finished pipeline root into
        its slot's timeline; score once the head stage lands."""
        if not flight.RECORDER.enabled:
            return
        if root.name not in _ROOT_NAMES or slot is None or slot < 0:
            return
        stages: dict[str, float] = {}
        saw_head = False

        def walk(sp):
            nonlocal saw_head
            stage = SPAN_STAGES.get(sp.name)
            if stage is not None:
                stages[stage] = (stages.get(stage, 0.0)
                                 + sp.duration_ms() / 1000.0)
                if stage == "head":
                    saw_head = True
            for c in sp.children:
                walk(c)

        for c in root.children:
            walk(c)
        if root.name == "block_import":
            stages["total"] = (stages.get("total", 0.0)
                               + root.duration_ms() / 1000.0)
        self._merge(int(slot), stages, saw_head)

    def observe_stage(self, slot: int, stage: str, seconds: float,
                      final: bool = False) -> None:
        """Manual stage feed for work that reports outside the span
        tree; ``final=True`` scores the slot immediately."""
        self._merge(int(slot), {stage: seconds}, final)

    def _merge(self, slot: int, stages: dict, score_now: bool) -> None:
        to_score = None
        with self._lock:
            row = self._slots.get(slot)
            if row is None:
                row = self._slots[slot] = {"stages": {}, "scored": False}
                while len(self._slots) > self.ring:
                    self._slots.popitem(last=False)
                    record_evicted("slo_slot")
            else:
                self._slots.move_to_end(slot)
            for stage, secs in stages.items():
                row["stages"][stage] = row["stages"].get(stage, 0.0) + secs
            if score_now and not row["scored"]:
                row["scored"] = True
                to_score = dict(row["stages"])
        if to_score is not None:
            self._score(slot, to_score)

    # -- scoring -------------------------------------------------------------

    def stage_budget_s(self, stage: str) -> float:
        return self.budget_ms / 1000.0 * STAGE_FRACTIONS.get(stage, 1.0)

    def _score(self, slot: int, stages: dict) -> None:
        over: dict[str, float] = {}
        for stage, secs in stages.items():
            # reservoir mutation under the lock: quantiles() iterates
            # these deques under the same lock, and an unlocked append
            # would fault a concurrent scrape mid-sort
            with self._lock:
                if len(self._samples[stage]) == \
                        self._samples[stage].maxlen:
                    record_evicted("slo_sample")
                self._samples[stage].append(secs)
            hist = self._hist_memo.get(stage)
            if hist is None:
                hist = self._hist_memo[stage] = REGISTRY.histogram(
                    "slo_stage_seconds",
                    "scored per-slot protocol-stage wall time",
                    buckets=_SECONDS_BUCKETS).labels(stage=stage)
            hist.observe(secs)
            if secs > self.stage_budget_s(stage):
                over[stage] = secs
                child = self._viol_memo.get(stage)
                if child is None:
                    child = self._viol_memo[stage] = REGISTRY.counter(
                        "slo_violations_total",
                        "scored slots whose stage exceeded its deadline "
                        "budget, by stage").labels(stage=stage)
                child.inc()
        with self._lock:
            self.scored += 1
            for stage in over:
                self.violations[stage] = self.violations.get(stage, 0) + 1
        try:
            REGISTRY.counter(
                "slo_slots_total",
                "slots scored by the SLO engine, by outcome",
            ).labels(outcome="violated" if over else "ok").inc()
        except Exception as e:
            record_swallowed("slo.slot_counter", e)
        if over:
            flight.emit(
                "slo_violation", slot=slot,
                stages={s: round(v * 1000.0, 1) for s, v in over.items()},
                budget_ms=self.budget_ms)

    # -- surfaces ------------------------------------------------------------

    def quantiles(self) -> dict[str, dict]:
        """Exact p50/p99/p999 over each stage's bounded reservoir."""
        out: dict[str, dict] = {}
        with self._lock:
            sampled = {s: sorted(d) for s, d in self._samples.items() if d}
        for stage, vals in sampled.items():
            out[stage] = {
                "n": len(vals),
                "p50_ms": round(_percentile(vals, 0.50) * 1000.0, 3),
                "p99_ms": round(_percentile(vals, 0.99) * 1000.0, 3),
                "p999_ms": round(_percentile(vals, 0.999) * 1000.0, 3),
                "budget_ms": round(self.stage_budget_s(stage) * 1000.0, 1),
            }
        return out

    def report(self) -> dict:
        """The GET /lighthouse/observatory/slo payload."""
        with self._lock:
            violations = dict(self.violations)
            scored = self.scored
            tracked = len(self._slots)
        return {
            "budget_ms": self.budget_ms,
            "stage_fractions": dict(STAGE_FRACTIONS),
            "slots_scored": scored,
            "slots_tracked": tracked,
            "violations": violations,
            "stages": self.quantiles(),
        }

    def reset(self) -> None:
        with self._lock:
            self._slots.clear()
            for d in self._samples.values():
                d.clear()
            self.scored = 0
            self.violations.clear()


ENGINE = SloEngine()
_INSTALLED = False


def install(tracer=None) -> SloEngine:
    """Hook the process engine onto the tracer (idempotent); the chain
    constructor calls this so every node scores its slots."""
    global _INSTALLED
    t = tracer if tracer is not None else TRACER
    t.add_sink(ENGINE.sink)
    _INSTALLED = True
    return ENGINE


def reconfigure() -> SloEngine:
    """Rebuild the process engine from the LHTPU_SLO_* knobs (tests);
    keeps the tracer hook pointed at the fresh state."""
    global ENGINE
    old = ENGINE
    TRACER.remove_sink(old.sink)
    ENGINE = SloEngine()
    if _INSTALLED:
        TRACER.add_sink(ENGINE.sink)
    return ENGINE
