"""Per-node chain-health detector: reorg forensics + lag tracking.

Ten PRs of hardening gave every *subsystem* books and breakers, but the
protocol-level outcomes a consensus node is judged on — does the head
track the slot clock, does finality advance, how often and how deeply
does the canonical chain rewrite itself — were unmeasured: the
simulator's health checks were a bare ``heads_agree()`` bool and a
``min(finalized)``.  This module is the per-node half of the fleet
observatory (the fleet half is :class:`simulator.FleetObserver`):

- **Head-move classification.**  Every head update runs a
  common-ancestor walk in the proto-array
  (:meth:`ProtoArray.common_ancestor`): ``extension`` when the old head
  is an ancestor of the new one, ``reorg`` otherwise — with the exact
  ``depth`` (slots from the old head back to the fork point, the
  reference ChainReorg semantics), ``distance`` (slots from the fork
  point forward to the new head) and abandoned/adopted block counts.
  Reorgs count into ``reorg_events_total{node,depth_bucket}``, publish
  a reference-shaped ``chain_reorg`` SSE event (slot, depth, old/new
  head block+state roots, epoch) and file a flight-recorder event.
- **Lag gauges against the slot clock.**  ``head_lag_slots{node}`` and
  ``finality_lag_epochs{node}`` update on every slot tick, plus an
  effective-balance-weighted ``chain_participation_rate{node}`` gauge
  for each completed epoch (altair+ previous-epoch TIMELY_TARGET
  flags — the quantity justification actually weighs).
- **Trip conditions.**  A reorg of depth >= ``LHTPU_REORG_TRIP_DEPTH``
  fires the ``deep_reorg`` flight trip; a finality lag of
  >= ``LHTPU_FINALITY_STALL_EPOCHS`` epochs fires ``finality_stall``
  ONCE per stall episode (the state machine re-arms when finality
  advances again, with a ``finality_recovered`` event marking the
  edge).  Both dumps are served with the rest of the black box at
  ``GET /lighthouse/observatory/flight``; the live detector state is
  ``GET /lighthouse/observatory/chain``.

``LHTPU_OBS_ARMED=0`` disarms the detector with the rest of the
observatory plane (the overhead A/B knob).  Every hook is wrapped by
the caller (`BeaconChain.recompute_head`, `NetworkService.on_slot`) so
a detector fault can never block import or the slot tick.

Multi-node processes (the in-process simulator) share one metrics
registry and one flight recorder, so every series and event carries a
``node`` label — :class:`simulator.LocalNetwork` names its chains, a
production process keeps the default.
"""

from __future__ import annotations

import threading

import numpy as np

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common import flight_recorder as flight
from lighthouse_tpu.common.metrics import REGISTRY

#: EventStream topic for reorg notifications (reference
#: beacon_chain/src/events.rs ChainReorg SSE)
CHAIN_REORG_TOPIC = "chain_reorg"


def _depth_bucket(depth: int) -> str:
    if depth <= 1:
        return "1"
    if depth == 2:
        return "2"
    if depth <= 4:
        return "3-4"
    if depth <= 8:
        return "5-8"
    return "9+"


class ChainHealthMonitor:
    """One beacon chain's health plane: reorg classification, lag
    gauges, stall/trip state.

    Thread model: ``on_head_update`` runs under the chain's import lock
    (head updates are single-writer); ``on_slot`` may race it from the
    network tick, so the small mutable aggregates are guarded by one
    short lock.
    """

    def __init__(self, chain, name: str = "node"):
        self.chain = chain
        self.name = name
        self._lock = threading.Lock()
        self.reconfigure()
        # finality-stall state machine: "ok" | "stalled"; transitions
        # emit flight events (lhlint LH605 enforces this)
        self.state = "ok"
        self.head_moves = 0
        self.extensions = 0
        self.reorg_count = 0
        self.max_reorg_depth = 0
        self.reorgs_by_bucket: dict[str, int] = {}
        self.last_reorg: dict | None = None
        self.reorg_log: list[dict] = []   # newest-last, bounded
        self.head_lag_slots = 0
        self.finality_lag_epochs = 0
        self.participation_rate: float | None = None
        self.participation_epoch: int | None = None
        self._part_key: tuple | None = None
        self._label_memo: dict = {}
        # the pull observatory's per-node roll-up seq: strictly
        # monotonic per process-lifetime of this monitor, so a scraper
        # can order scrapes and detect duplicates/regressions
        self.snapshot_seq = 0

    def next_snapshot_seq(self) -> int:
        """Monotonic roll-up sequence for GET /lighthouse/observatory/
        node: every composed snapshot gets the next integer."""
        with self._lock:
            self.snapshot_seq += 1
            return self.snapshot_seq

    # -- labeled-series plumbing (literal registrations so the lhlint
    #    metric discipline sees every family; children memoized so the
    #    per-tick cost is one inc()/set()) --------------------------------

    def _reorg_counter(self, bucket: str):
        key = ("reorg", bucket)
        child = self._label_memo.get(key)
        if child is None:
            child = REGISTRY.counter(
                "reorg_events_total",
                "canonical head rewrites, by node and reorg-depth bucket",
            ).labels(node=self.name, depth_bucket=bucket)
            self._label_memo[key] = child
        return child

    def _head_lag_gauge(self):
        child = self._label_memo.get("head_lag")
        if child is None:
            child = REGISTRY.gauge(
                "head_lag_slots",
                "slots between the clock and the canonical head, by node",
            ).labels(node=self.name)
            self._label_memo["head_lag"] = child
        return child

    def _finality_lag_gauge(self):
        child = self._label_memo.get("finality_lag")
        if child is None:
            child = REGISTRY.gauge(
                "finality_lag_epochs",
                "epochs between the clock and the finalized checkpoint, "
                "by node",
            ).labels(node=self.name)
            self._label_memo["finality_lag"] = child
        return child

    def _participation_gauge(self):
        child = self._label_memo.get("participation")
        if child is None:
            child = REGISTRY.gauge(
                "chain_participation_rate",
                "effective-balance-weighted TIMELY_TARGET participation "
                "of the newest completed epoch, by node",
            ).labels(node=self.name)
            self._label_memo["participation"] = child
        return child

    # -- head-move classification -------------------------------------------

    def classify(self, old_root: bytes, new_root: bytes) -> dict | None:
        """Classify one head move via the proto-array common-ancestor
        walk.  Returns None when either root is unknown (a pruned-away
        branch) or the move is a no-op."""
        chain = self.chain
        if old_root == new_root:
            return None
        proto = chain.fork_choice.proto
        ancestor = proto.common_ancestor(old_root, new_root)
        if ancestor is None:
            return None
        old_i = proto.indices[old_root]
        new_i = proto.indices[new_root]
        anc_i = proto.indices[ancestor]
        old_slot = int(proto.slots[old_i])
        new_slot = int(proto.slots[new_i])
        anc_slot = int(proto.slots[anc_i])
        # block counts along each side of the fork (the hand-walkable
        # ancestor chains the property tests pin against)
        abandoned = 0
        i = old_i
        while i != anc_i:
            abandoned += 1
            i = int(proto.parents[i])
        adopted = 0
        i = new_i
        while i != anc_i:
            adopted += 1
            i = int(proto.parents[i])
        kind = "extension" if ancestor == old_root else "reorg"
        return {
            "kind": kind,
            # reference ChainReorg depth: slots from the old head back
            # to the fork point (0 for a pure extension)
            "depth": old_slot - anc_slot,
            "distance": new_slot - anc_slot,
            "abandoned_blocks": abandoned,
            "adopted_blocks": adopted,
            "ancestor": ancestor,
            "old_head": old_root,
            "new_head": new_root,
            "old_slot": old_slot,
            "new_slot": new_slot,
        }

    def on_head_update(self, old_root: bytes, new_root: bytes) -> dict | None:
        """Hook run by ``BeaconChain.recompute_head`` on every head
        change (under the import lock).  Classifies the move, updates
        the reorg books, publishes the ``chain_reorg`` SSE event and
        files/trips the flight recorder."""
        if not self.enabled:
            return None
        move = self.classify(old_root, new_root)
        if move is None:
            return None
        chain = self.chain
        with self._lock:
            self.head_moves += 1
            if move["kind"] == "extension":
                self.extensions += 1
                return move
            bucket = _depth_bucket(move["depth"])
            self.reorg_count += 1
            self.max_reorg_depth = max(self.max_reorg_depth, move["depth"])
            self.reorgs_by_bucket[bucket] = (
                self.reorgs_by_bucket.get(bucket, 0) + 1)
            self.last_reorg = move
            self.reorg_log.append(move)
            del self.reorg_log[:-64]
        self._reorg_counter(bucket).inc()
        self._publish_reorg(chain, move)
        flight.emit("chain_reorg", node=self.name, slot=move["new_slot"],
                    depth=move["depth"], distance=move["distance"],
                    old_head=move["old_head"], new_head=move["new_head"])
        if move["depth"] >= self.trip_depth:
            flight.trip("deep_reorg", node=self.name, depth=move["depth"],
                        distance=move["distance"],
                        old_head=move["old_head"],
                        new_head=move["new_head"])
        return move

    def _publish_reorg(self, chain, move: dict) -> None:
        """Reference-shaped ChainReorg SSE payload (events.rs)."""
        if chain is None:
            return
        state_roots = getattr(chain, "_state_root_of_block", {})
        epoch = chain.spec.compute_epoch_at_slot(move["new_slot"])
        chain.events.publish(CHAIN_REORG_TOPIC, {
            "slot": str(move["new_slot"]),
            "depth": str(move["depth"]),
            "old_head_block": "0x" + move["old_head"].hex(),
            "new_head_block": "0x" + move["new_head"].hex(),
            "old_head_state": "0x" + bytes(
                state_roots.get(move["old_head"], b"")).hex(),
            "new_head_state": "0x" + bytes(
                state_roots.get(move["new_head"], b"")).hex(),
            "epoch": str(epoch),
            "execution_optimistic": False,
        })

    # -- slot-clock tracking -------------------------------------------------

    def on_slot(self, slot: int) -> None:
        """Per-slot tick: lag gauges + the finality-stall machine +
        per-epoch participation.  Idempotent — multiple ticks for one
        slot re-set the same gauges and the stall machine is
        edge-triggered."""
        if not self.enabled:
            return
        chain = self.chain
        spec = chain.spec
        head_slot = int(chain.head_state.slot)
        fin_epoch = int(chain.fork_choice.finalized.epoch)
        epoch = spec.compute_epoch_at_slot(int(slot))
        head_lag = max(int(slot) - head_slot, 0)
        fin_lag = max(epoch - fin_epoch, 0)
        with self._lock:
            self.head_lag_slots = head_lag
            self.finality_lag_epochs = fin_lag
        self._head_lag_gauge().set(head_lag)
        self._finality_lag_gauge().set(fin_lag)
        if fin_lag >= self.stall_epochs:
            self._enter_stall(fin_lag, epoch)
        else:
            self._clear_stall(fin_lag, epoch)
        self._update_participation(chain)

    def _enter_stall(self, lag: int, epoch: int) -> None:
        """Edge-triggered: the trip fires once per stall episode."""
        with self._lock:
            if self.state == "stalled":
                return
            self.state = "stalled"
        flight.trip("finality_stall", node=self.name, lag_epochs=lag,
                    epoch=epoch, threshold=self.stall_epochs)

    def _clear_stall(self, lag: int, epoch: int) -> None:
        """Finality advanced again: re-arm the trip."""
        with self._lock:
            if self.state == "ok":
                return
            self.state = "ok"
        flight.emit("finality_recovered", node=self.name, lag_epochs=lag,
                    epoch=epoch)

    def _update_participation(self, chain) -> None:
        """Effective-balance-weighted previous-epoch TIMELY_TARGET
        participation of the head state (altair+; phase0 states carry
        no flags).  Recomputed whenever the head advances — flags for
        epoch E-1 keep accruing from late-included attestations all
        through epoch E (exactly the post-heal recovery window), so a
        once-per-epoch latch would systematically under-report.  One
        vectorized sweep per new head slot; duplicate ticks for the
        same head are skipped."""
        from lighthouse_tpu.state_transition.epoch_processing import (
            TIMELY_TARGET_FLAG_INDEX,
            has_flag,
        )

        state = chain.head_state
        flags = getattr(state, "previous_epoch_participation", None)
        if flags is None:
            return
        head_epoch = chain.spec.compute_epoch_at_slot(int(state.slot))
        if head_epoch < 1:
            return
        key = (int(state.slot), head_epoch)
        if self._part_key == key:
            return
        self._part_key = key
        part = np.asarray(flags, np.uint8)
        active = state.validators.is_active(head_epoch - 1)
        eb = np.asarray(state.validators.effective_balance, np.int64)
        n = min(part.shape[0], active.shape[0])
        hit = has_flag(part[:n], TIMELY_TARGET_FLAG_INDEX) & active[:n]
        total = int(eb[:n][active[:n]].sum())
        rate = (int(eb[:n][hit].sum()) / total) if total else 0.0
        with self._lock:
            self.participation_rate = rate
            self.participation_epoch = head_epoch - 1
        self._participation_gauge().set(rate)

    # -- surfaces ------------------------------------------------------------

    def status(self) -> dict:
        """The ``GET /lighthouse/observatory/chain`` payload."""
        with self._lock:
            last = dict(self.last_reorg) if self.last_reorg else None
            if last:
                for k in ("ancestor", "old_head", "new_head"):
                    last[k] = "0x" + last[k].hex()
            return {
                "node": self.name,
                "armed": self.enabled,
                "state": self.state,
                "head_lag_slots": self.head_lag_slots,
                "finality_lag_epochs": self.finality_lag_epochs,
                "participation_rate": self.participation_rate,
                "participation_epoch": self.participation_epoch,
                "head_moves": self.head_moves,
                "extensions": self.extensions,
                "reorgs": {
                    "count": self.reorg_count,
                    "max_depth": self.max_reorg_depth,
                    "by_depth_bucket": dict(self.reorgs_by_bucket),
                    "last": last,
                },
                "trip_thresholds": {
                    "deep_reorg_depth": self.trip_depth,
                    "finality_stall_epochs": self.stall_epochs,
                },
            }

    def set_name(self, name: str) -> None:
        """Label this node's series/events (the in-process simulator
        shares one registry across N nodes).  Drops memoized children —
        call before the first slot, not mid-flight."""
        self.name = name
        self._label_memo.clear()

    def reconfigure(self) -> None:
        """Re-read the LHTPU_* knobs (tests/drills mutate os.environ
        after construction)."""
        self.enabled = envreg.get_bool("LHTPU_OBS_ARMED", True) is not False
        self.trip_depth = max(
            1, envreg.get_int("LHTPU_REORG_TRIP_DEPTH", 3) or 3)
        self.stall_epochs = max(
            1, envreg.get_int("LHTPU_FINALITY_STALL_EPOCHS", 4) or 4)
