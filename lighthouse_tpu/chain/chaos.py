"""Seed-keyed chaos scheduler: every fault plane composed on one clock.

Ten PRs built four orthogonal deterministic fault planes (offload
``FaultPlan``, store ``CrashPointStore``, ingest ``IngestPlan``, peer
``PeerFaultPlan``) plus partition induction and a node stop/crash/
restart cycle — each drilled in isolation.  Production failures do not
arrive in isolation: committee-based-consensus measurements (PAPERS.md,
"Performance of EdDSA and BLS Signatures in Committee-Based Consensus")
show finality latency is governed by the *composition* of crypto load,
network faults and restarts.  This module is the composer:

- :func:`build_plan` maps a seed to a :class:`ChaosPlan` — a fixed
  schedule of slot windows, each arming one fault plane against one
  target (``same seed => byte-identical schedule``, pinned by
  :meth:`ChaosPlan.digest`).  The tail of the horizon (the *quiet
  tail*) is kept chaos-free so finality can resume INSIDE the window
  the headline gauge measures.
- :class:`ChaosController` applies the plan to a live
  ``simulator.LocalNetwork`` slot by slot, through each plane's real
  seam: ``partition``/``heal``, ``kill``/``restart`` (mid-commit store
  deaths at chosen commit ordinals), ``ops.faults.install_plan`` /
  ``install_peer_plans`` / ``install_ingest_plan``.  Every armed and
  disarmed edge emits a flight event and counts into the ``chaos_*``
  metric family, so a soak's black box reads causally: which plane was
  blowing when a gate degraded.

``bench.py --child-chaossoak`` drives the acceptance scenario (README
"Chaos soak"); knobs ride ``LHTPU_CHAOS_*`` (common/env.py).

Stdlib-only by design (no jax, no numpy): the scheduler must be
importable from the bench driver and the lint fixtures without the
device stack.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common import flight_recorder as flight
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.ops import faults

#: the fault planes a plan can compose, in deterministic build order
PLANES = ("partition", "crash", "wedge", "ingest", "offload", "peer")


@dataclass(frozen=True)
class ChaosAction:
    """One scheduled fault window: arm ``plane`` against ``node`` at
    ``at_slot``, disarm (and for the crash plane: restart) at
    ``until_slot``.  ``params`` is a sorted tuple of (key, value) pairs
    so actions hash/compare bytewise."""

    plane: str
    at_slot: int
    until_slot: int
    node: str | None
    params: tuple

    def describe(self) -> str:
        return (f"{self.plane}@{self.at_slot}-{self.until_slot}"
                f":{self.node or '*'}:{self.params!r}")

    def param(self, key, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


@dataclass(frozen=True)
class ChaosPlan:
    """A deterministic schedule over ``[start_slot, start_slot +
    horizon)``.  ``quiet_tail`` slots at the end carry no armed
    window — finality recovers inside the measured phase."""

    seed: int
    nodes: tuple
    start_slot: int
    horizon: int
    quiet_tail: int
    actions: tuple

    def digest(self) -> str:
        """Byte-stable fingerprint: equal seeds/inputs give equal
        digests (the determinism pin the soak asserts)."""
        h = hashlib.sha256()
        h.update(f"{self.seed}|{','.join(self.nodes)}|"
                 f"{self.start_slot}|{self.horizon}".encode())
        for a in self.actions:
            h.update(a.describe().encode())
        return h.hexdigest()

    def by_plane(self, plane: str) -> list[ChaosAction]:
        return [a for a in self.actions if a.plane == plane]


def _overlaps(at: int, until: int, windows) -> bool:
    return any(at < w_until and w_at < until for w_at, w_until in windows)


def build_plan(seed: int | None = None, nodes=(), start_slot: int = 0,
               horizon: int | None = None, kill_every: int | None = None,
               planes=PLANES) -> ChaosPlan:
    """Derive a :class:`ChaosPlan` purely from ``seed`` (default
    ``LHTPU_CHAOS_SEED``) and the explicit inputs — no wall clock, no
    ambient state, so the same call is byte-identical across runs and
    machines.  Planes are generated in :data:`PLANES` order from one
    ``random.Random(seed)`` stream; windows that share a process-wide
    seam (wedge/ingest, the peer-plan slot) are kept disjoint so a
    later arm never silently clobbers an earlier one."""
    if seed is None:
        # no falsy-zero remap: seed 0 is a valid seed and must produce
        # the same schedule here as through an explicit seed=0 call
        seed = envreg.get_int("LHTPU_CHAOS_SEED", 1337)
    nodes = tuple(nodes)
    if horizon is None:
        horizon = envreg.get_int("LHTPU_CHAOS_SLOTS", 44) or 44
    if kill_every is None:
        kill_every = envreg.get_int("LHTPU_CHAOS_KILL_EVERY", 10) or 10
    kill_every = max(4, int(kill_every))
    rng = random.Random(seed)
    quiet = max(8, horizon // 4)
    end = start_slot + horizon - quiet   # last slot any window may reach
    actions: list[ChaosAction] = []

    if "partition" in planes and len(nodes) >= 2 and horizon >= 24:
        at = start_slot + rng.randrange(2, max(3, horizon // 3))
        hold = rng.randrange(4, 7)
        # groups carry node NAMES (like every other plane's target):
        # a plan built over a subset of the fleet partitions exactly
        # the named nodes, never positional aliases
        split = list(nodes)
        rng.shuffle(split)
        half = len(split) // 2
        groups = (tuple(sorted(split[:half])), tuple(sorted(split[half:])))
        until = min(at + hold, end)
        if until > at:
            actions.append(ChaosAction(
                "partition", at, until, None, (("groups", groups),)))

    if "crash" in planes and len(nodes) >= 3:
        # staggered kills (never two nodes down at once: the fleet must
        # keep >2/3 attesting weight so the soak's finality gate stays
        # reachable), victims cycle a seed-shuffled node order
        order = list(nodes)
        rng.shuffle(order)
        at = start_slot + kill_every
        k = 0
        while True:
            down = rng.randrange(3, 6)
            if at + down >= end:
                break
            mode = rng.choice(("crash", "drop"))
            actions.append(ChaosAction(
                "crash", at, at + down, order[k % len(order)],
                (("mode", mode), ("offset", rng.randrange(0, 2)),
                 ("op", rng.randrange(0, 2) if mode == "drop" else 0))))
            k += 1
            at = at + down + max(2, kill_every - down)

    # the wedge and ingest planes share the process-wide ingest seam:
    # build their windows from one disjoint pool
    seam_windows: list[tuple[int, int]] = []
    if "wedge" in planes:
        at = start_slot + rng.randrange(1, max(2, horizon // 2))
        until = min(at + rng.randrange(2, 4), end)
        if until > at:
            seam_windows.append((at, until))
            actions.append(ChaosAction(
                "wedge", at, until, None,
                (("stall_s", rng.choice((0.01, 0.02))),)))
    if "ingest" in planes:
        for _ in range(2):
            at = start_slot + rng.randrange(1, max(2, horizon - quiet - 3))
            until = min(at + rng.randrange(2, 5), end)
            if until <= at or _overlaps(at, until, seam_windows):
                continue
            seam_windows.append((at, until))
            actions.append(ChaosAction(
                "ingest", at, until, None,
                (("factor", float(rng.randrange(2, 5))),
                 ("mode", rng.choice(("burst", "dup", "invalid"))))))

    if "offload" in planes:
        at = start_slot + rng.randrange(1, max(2, horizon // 2))
        until = min(at + rng.randrange(3, 6), end)
        if until > at:
            actions.append(ChaosAction(
                "offload", at, until, None,
                (("mode", rng.choice(("raise", "corrupt", "compile"))),
                 ("sites", ("chunk", "tpu")))))

    if "peer" in planes and nodes:
        # Byzantine service: requests TO the victim node get faulted at
        # the requester's discipline seam (bounded fires so a rejoining
        # node is slowed, never starved).  Windows are ALIGNED with the
        # crash restarts — the rejoin's handshakes and range sync are
        # exactly when requests fly, so the plane provably injects
        # instead of arming into a quiet wire
        crash_actions = [a for a in actions if a.plane == "crash"]
        peer_windows: list[tuple[int, int]] = []
        for k in range(2):
            if k < len(crash_actions):
                ca = crash_actions[k]
                # armed one slot BEFORE the restart edge (same-slot
                # edges process in plan order, crash first)
                at = max(ca.at_slot + 1, ca.until_slot - 1)
                victim = rng.choice([n for n in nodes if n != ca.node]
                                    or list(nodes))
            else:
                at = start_slot + rng.randrange(
                    1, max(2, horizon - quiet - 3))
                victim = rng.choice(list(nodes))
            until = min(at + rng.randrange(3, 6), end)
            if until <= at or _overlaps(at, until, peer_windows):
                continue
            peer_windows.append((at, until))
            actions.append(ChaosAction(
                "peer", at, until, victim,
                (("max_fires", rng.randrange(3, 7)),
                 ("mode", rng.choice(("empty", "malformed", "flap"))))))

    actions.sort(key=lambda a: (a.at_slot, PLANES.index(a.plane),
                                a.until_slot, a.node or ""))
    return ChaosPlan(seed=seed, nodes=nodes, start_slot=start_slot,
                     horizon=horizon, quiet_tail=quiet,
                     actions=tuple(actions))


@dataclass
class _ActionRecord:
    action: ChaosAction
    state: str = "pending"       # pending -> armed -> done


class ChaosController:
    """Applies a :class:`ChaosPlan` to a live ``LocalNetwork``.

    Call :meth:`on_slot` once per slot BEFORE the network runs it; call
    :meth:`quiesce` at the end of the phase to disarm anything still
    open (restarting any node still down).  Every edge is a flight
    event (``chaos_edge``) and a ``chaos_actions_total{plane,edge}``
    count; ``chaos_armed_actions`` gauges the composition depth."""

    def __init__(self, net, plan: ChaosPlan):
        self.net = net
        self.plan = plan
        self._records = [_ActionRecord(a) for a in plan.actions]
        self.killed: list[str] = []      # kill order (drill assertions)
        self.restarted: list[tuple[str, str]] = []   # (node, resume_mode)
        # injection evidence per plan-carrying plane, captured at each
        # disarm edge (honest reporting: an armed plane whose consumer
        # never dispatched — e.g. offload under fake BLS — shows 0)
        self.plane_fires: dict[str, int] = {}
        self._armed = 0
        self._counter = REGISTRY.counter(
            "chaos_actions_total",
            "chaos-plan fault windows by plane and edge "
            "(armed/disarmed)")
        self._gauge = REGISTRY.gauge(
            "chaos_armed_actions",
            "fault windows currently armed by the chaos controller "
            "(the composition depth)")

    # -- the clock -----------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        for rec in self._records:
            if rec.state == "pending" and slot >= rec.action.at_slot:
                self._arm(rec, slot)
            elif rec.state == "armed" and slot >= rec.action.until_slot:
                self._disarm(rec, slot)

    def quiesce(self, slot: int) -> None:
        """Disarm every still-open window (end of phase): heal, restart
        downed nodes, clear every process-wide plan."""
        for rec in self._records:
            if rec.state == "armed":
                self._disarm(rec, slot)
        faults.clear_all_plans()

    def armed_planes(self) -> set[str]:
        return {r.action.plane for r in self._records if r.state == "armed"}

    # -- edges ---------------------------------------------------------------

    def _edge(self, action: ChaosAction, edge: str, slot: int) -> None:
        self._counter.labels(plane=action.plane, edge=edge).inc()
        self._gauge.set(self._armed)
        flight.emit("chaos_edge", plane=action.plane, edge=edge,
                    slot=int(slot), node=action.node,
                    window=[action.at_slot, action.until_slot],
                    params=dict(action.params))

    def _arm(self, rec: _ActionRecord, slot: int) -> None:
        a = rec.action
        if a.plane == "partition":
            by_name = {n.name: i for i, n in enumerate(self.net.nodes)}
            self.net.partition(*[[by_name[name] for name in g]
                                 for g in a.param("groups")])
        elif a.plane == "crash":
            self.net.kill(a.node, mode=a.param("mode"),
                          op=a.param("op", 0), offset=a.param("offset", 0))
            self.killed.append(a.node)
        elif a.plane == "wedge":
            faults.install_ingest_plan(faults.IngestPlan(
                "stall", stall_s=a.param("stall_s", 0.01)))
        elif a.plane == "ingest":
            faults.install_ingest_plan(faults.IngestPlan(
                a.param("mode"), factor=a.param("factor", 4.0)))
        elif a.plane == "offload":
            faults.install_plan(faults.FaultPlan(
                a.param("mode"), sites=frozenset(a.param("sites", ()))))
        elif a.plane == "peer":
            faults.install_peer_plans([faults.PeerFaultPlan(
                a.param("mode"), peers=frozenset({a.node}),
                max_fires=a.param("max_fires", 4))])
        rec.state = "armed"
        self._armed += 1
        self._edge(a, "armed", slot)

    def _disarm(self, rec: _ActionRecord, slot: int) -> None:
        a = rec.action
        if a.plane == "partition":
            self.net.heal()
        elif a.plane == "crash":
            node = self.net.restart(a.node)
            self.restarted.append((a.node, node.chain.resume_mode))
        elif a.plane in ("wedge", "ingest"):
            faults.install_ingest_plan(None)
        elif a.plane == "offload":
            active = faults.active_plan()
            if active is not None:
                self.plane_fires["offload"] = (
                    self.plane_fires.get("offload", 0) + active.fires)
            faults.install_plan(None)
        elif a.plane == "peer":
            self.plane_fires["peer"] = (
                self.plane_fires.get("peer", 0)
                + sum(p.fires for p in faults.active_peer_plans()))
            faults.install_peer_plans(())
        rec.state = "done"
        self._armed -= 1
        self._edge(a, "disarmed", slot)


__all__ = ["PLANES", "ChaosAction", "ChaosController", "ChaosPlan",
           "build_plan"]
