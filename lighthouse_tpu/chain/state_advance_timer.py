"""Tail-of-slot head-state pre-advance (reference beacon_chain/src/
state_advance_timer.rs:1-15): in the quiet tail of slot N, advance a copy
of the head state to slot N+1 so the next block's verification and
production find the expensive per-slot work (epoch transitions included)
already done.
"""

from __future__ import annotations


class StateAdvanceTimer:
    def __init__(self, chain):
        self.chain = chain
        # (head_root, slot) -> advanced state
        self._cache: dict[tuple[bytes, int], object] = {}

    def pre_advance(self, for_slot: int | None = None) -> bool:
        """Advance the current head state to `for_slot` (default: next
        slot).  Returns True when a new pre-advanced state was cached."""
        from lighthouse_tpu.state_transition import state_advance

        chain = self.chain
        head_root = chain.head_root
        target = (chain.current_slot() + 1 if for_slot is None
                  else int(for_slot))
        key = (head_root, target)
        if key in self._cache:
            return False
        head = chain.head_state
        if int(head.slot) >= target:
            return False
        st = head.copy()
        state_advance(st, chain.spec, target)
        self._cache.clear()  # only the latest pre-advance is useful
        self._cache[key] = st
        return True

    def get(self, head_root: bytes, slot: int):
        """The pre-advanced state for (head_root, slot), or None."""
        return self._cache.get((bytes(head_root), int(slot)))

    def install(self) -> None:
        """Hook into the chain so block production/verification use the
        pre-advanced state instead of re-advancing."""
        self.chain.state_advance_timer = self
