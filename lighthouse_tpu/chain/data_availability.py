"""Data-availability checker (Deneb).

Rebuild of /root/reference/beacon_node/beacon_chain/src/
data_availability_checker.rs (:32,:61) + its overflow LRU cache: pending
block/blob components are held per block root until every commitment the
block carries has a verified sidecar — only then does import proceed.
Capacity-bounded; finalization prunes.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field


@dataclass
class PendingComponents:
    block: object | None = None
    blobs: dict[int, object] = field(default_factory=dict)  # index -> sidecar

    def num_expected(self) -> int | None:
        if self.block is None:
            return None
        body = self.block.message.body
        commitments = getattr(body, "blob_kzg_commitments", None)
        return 0 if commitments is None else len(commitments)


@dataclass
class Availability:
    """Either available (block + ordered blobs) or missing components."""

    block_root: bytes
    block: object | None = None
    blobs: list | None = None

    @property
    def is_available(self) -> bool:
        return self.block is not None


class DataAvailabilityChecker:
    def __init__(self, spec, capacity: int = 64):
        self.spec = spec
        self._pending: OrderedDict[bytes, PendingComponents] = OrderedDict()
        self.capacity = capacity

    def _entry(self, block_root: bytes) -> PendingComponents:
        entry = self._pending.get(block_root)
        if entry is None:
            entry = self._pending[block_root] = PendingComponents()
            while len(self._pending) > self.capacity:
                self._pending.popitem(last=False)  # LRU overflow
        else:
            self._pending.move_to_end(block_root)
        return entry

    def _check(self, block_root: bytes) -> Availability:
        entry = self._pending.get(block_root)
        if entry is None:
            return Availability(block_root)
        expected = entry.num_expected()
        if expected is None or len(entry.blobs) < expected:
            return Availability(block_root)
        blobs = [entry.blobs[i] for i in sorted(entry.blobs)][:expected]
        self._pending.pop(block_root, None)
        return Availability(block_root, entry.block, blobs)

    def put_verified_blobs(self, block_root: bytes, verified_blobs) -> Availability:
        """Record gossip/RPC-verified sidecars; returns availability."""
        entry = self._entry(block_root)
        for vb in verified_blobs:
            sidecar = getattr(vb, "sidecar", vb)
            entry.blobs[int(sidecar.index)] = sidecar
        return self._check(block_root)

    def put_pending_executed_block(self, block_root: bytes, block) -> Availability:
        """Record a fully-verified block awaiting its blobs."""
        entry = self._entry(block_root)
        entry.block = block
        return self._check(block_root)

    def has_block(self, block_root: bytes) -> bool:
        e = self._pending.get(block_root)
        return e is not None and e.block is not None

    def missing_blob_indices(self, block_root: bytes) -> list[int] | None:
        e = self._pending.get(block_root)
        if e is None or e.block is None:
            return None
        expected = e.num_expected() or 0
        return [i for i in range(expected) if i not in e.blobs]

    def prune_finalized(self, finalized_slot: int):
        for root in list(self._pending):
            e = self._pending[root]
            if e.block is not None and int(e.block.message.slot) < finalized_slot:
                del self._pending[root]

    def __len__(self) -> int:
        return len(self._pending)
