"""Columnar attestation ingest: wire columns -> fork choice/pools.

The vectorized twin of ``BeaconChain.verify_attestations_for_gossip``
for the single-bit gossip firehose (PAPER.md §L5 batch formation).
Where the scalar pipeline pays Python per MESSAGE — container
materialization, an AttestationData hash, a committee lookup, a
signature-set object — this lane pays per GROUP (one distinct
(slot, committee index, beacon_block_root, committee_bits) lane) plus
numpy per row:

- timing/structure checks run as vector masks over the decoded columns;
- signing root, domain, committee and fork-choice ancestry resolve
  once per group;
- attester indices come from the aggregation-bit column + committee
  array, dup suppression from one ``seen_mask`` sweep per group;
- the pre-BLS stage folds each signing-root lane into ONE blinded
  merged set: signature side Σ rᵢ·sigᵢ on host (collapsed per unique
  signature), pubkey side through the chain/pubkey_plane gather+MSM
  (device rung when armed, host point adds otherwise) — the
  ``aggregate_pubkey`` host cost ISSUE 14 profiles;
- full containers are materialized LAZILY, only for rows that survive
  and feed the naive-aggregation pool / slasher.

Semantics parity with the scalar path (property-pinned in
tests/test_columnar.py): same reject vocabulary, dup caches read
before signature verification and claimed under the commit lock after
it, failed fast-path falls back to bisection over the ORIGINAL
per-row sets so attribution is unchanged, and a group whose fold
resists merging (undecompressable signature, identity aggregate,
fake-crypto bytes) passes through UNMERGED — coalescing can remove
redundant pairings, never change a verdict.

Rows the lane cannot handle exactly (electra multi-committee bits,
nonzero electra data.index) are returned as ``fallback_rows`` for the
scalar pipeline rather than approximated."""

from __future__ import annotations

import secrets
import threading
import time

import numpy as np

from lighthouse_tpu.common import tracing
from lighthouse_tpu.common.metrics import record_swallowed
from lighthouse_tpu.crypto import bls

#: 2^62: slots/epochs beyond this are adversarial counters that would
#: overflow the int64 vector math; the scalar path rejects them on the
#: slot-window check, this lane pre-rejects identically.
_SANE = np.int64(1) << np.int64(62)

_STAGE_LOCK = threading.Lock()
_STAGE_SECONDS: dict[str, float] = {}
_STAGE_COUNTS: dict[str, int] = {}


def _stage(key: str, seconds: float, count: int = 0) -> None:
    with _STAGE_LOCK:
        _STAGE_SECONDS[key] = _STAGE_SECONDS.get(key, 0.0) + seconds
        if count:
            _STAGE_COUNTS[key] = _STAGE_COUNTS.get(key, 0) + count


def stage_snapshot() -> dict:
    """Cumulative per-stage wall time + counts (the bench's
    stages.firehose.decode_ms/pubkey_gather_ms source)."""
    with _STAGE_LOCK:
        return {"seconds": dict(_STAGE_SECONDS),
                "counts": dict(_STAGE_COUNTS)}


def reset_stages() -> None:
    with _STAGE_LOCK:
        _STAGE_SECONDS.clear()
        _STAGE_COUNTS.clear()


class WireBatchResult:
    """Outcome of one wire-level batch (indices name the caller's
    ``entries`` list, not columnar rows)."""

    __slots__ = ("n", "verified", "rejects")

    def __init__(self, n: int):
        self.n = n
        self.verified = 0
        #: (entry index, reason) — scalar reject vocabulary plus
        #: ``decode_error`` for blobs the scalar deserialize refused
        self.rejects: list[tuple[int, str]] = []


def process_wire_batch(chain, entries: list[tuple[bytes, bool]]
                       ) -> WireBatchResult:
    """THE wire seam shared by Router's processor batch handler and the
    firehose bench: ``entries`` is one admission batch of
    ``(blob, electra)`` pairs.  Blobs are strided-decoded per layout
    class (one parse per class, not one per message), the columnar lane
    verifies and commits survivors, and exactly the rows the lane
    cannot handle — strided-parse rejects and explicit fallback rows
    (electra multi-committee bits, out-of-registry indices) — pay the
    scalar per-object pipeline.  Reject reasons keep the scalar
    vocabulary; a blob the scalar deserialize refuses rejects as
    ``decode_error`` (the fan-in ledger's delivery-time accounting is
    the CALLER's job — the router counts at delivery, this seam never
    double-counts)."""
    from lighthouse_tpu.ssz import columnar

    out = WireBatchResult(len(entries))
    scalar_items: list[tuple[int, object]] = []
    for electra in (False, True):
        idxs = [i for i, (_b, e) in enumerate(entries)
                if bool(e) == electra]
        if not idxs:
            continue
        layout = columnar.layout_for(chain.spec.preset, electra)
        cls = (chain.t.AttestationElectra if electra
               else chain.t.Attestation)
        t0 = time.perf_counter()
        cols, malformed = columnar.decode_batch(
            [entries[i][0] for i in idxs], layout, cls=cls)
        _stage("decode", time.perf_counter() - t0, len(idxs))
        columnar.record_fallback_rows(len(malformed))
        if malformed:
            t0 = time.perf_counter()
            n_ok = 0
            for j in malformed:
                try:
                    scalar_items.append((idxs[j], cls.deserialize(
                        entries[idxs[j]][0])))
                    n_ok += 1
                except Exception:
                    out.rejects.append((idxs[j], "decode_error"))
            columnar.record_decode(
                "scalar", time.perf_counter() - t0, n_ok)
        outcome = ingest_attestation_columns(chain, cols)
        out.verified += len(outcome.verified_rows)
        for row, reason in outcome.rejects:
            out.rejects.append((idxs[int(cols.row_index[row])], reason))
        if outcome.fallback_rows:
            t0 = time.perf_counter()
            for row in outcome.fallback_rows:
                scalar_items.append(
                    (idxs[int(cols.row_index[row])], cols.materialize(row)))
            columnar.record_decode(
                "scalar", time.perf_counter() - t0,
                len(outcome.fallback_rows))
    if scalar_items:
        objs = [obj for _i, obj in scalar_items]
        entry_of = {id(obj): i for i, obj in scalar_items}
        verified, rejects = chain.verify_attestations_for_gossip(objs)
        out.verified += len(verified)
        for item, reason in rejects:
            out.rejects.append((entry_of.get(id(item), -1), reason))
    return out


class _Group:
    __slots__ = ("gid", "rows", "data", "data_root", "signing_root",
                 "committee", "committee_index", "epoch", "slot")

    def __init__(self, gid):
        self.gid = gid
        self.rows = None
        self.data = None
        self.data_root = b""
        self.signing_root = b""
        self.committee = None
        self.committee_index = 0
        self.epoch = 0
        self.slot = 0


class IngestOutcome:
    """Per-row outcomes of one columnar sweep (row ids index the
    ColumnarAttestations batch, NOT the caller's blob list)."""

    __slots__ = ("n", "verified_rows", "rejects", "fallback_rows")

    def __init__(self, n):
        self.n = n
        self.verified_rows: list[int] = []
        self.rejects: list[tuple[int, str]] = []
        self.fallback_rows: list[int] = []


def ingest_attestation_columns(chain, cols) -> IngestOutcome:
    """Run one decoded batch through checks -> BLS -> commit.  Locking
    contract identical to ``_batch_pipeline``: prepare and commit hold
    the import lock, the BLS work runs unlocked."""
    out = IngestOutcome(cols.n)
    reasons: dict[int, str] = {}
    t0 = time.perf_counter()
    with tracing.span("ingest.columnar_prepare", n=cols.n):
        with chain._import_lock:
            prep = _prepare(chain, cols, reasons, out.fallback_rows)
    _stage("prepare", time.perf_counter() - t0, cols.n)
    verdict_of_set = None
    if chain.verify_signatures and prep["n_sets"]:
        with tracing.span("ingest.columnar_bls", sets=prep["n_sets"]):
            verdict_of_set = _verify_sets(chain, prep)
    t0 = time.perf_counter()
    with tracing.span("ingest.columnar_commit"):
        with chain._import_lock:
            _commit(chain, cols, prep, reasons, verdict_of_set, out)
    _stage("commit", time.perf_counter() - t0)
    out.rejects = sorted(reasons.items())
    return out


# -- prepare ------------------------------------------------------------------


def _prepare(chain, cols, reasons, fallback_rows):
    spec = chain.spec
    n = cols.n
    alive = np.ones(n, bool)

    def kill(mask, reason):
        hit = mask & alive
        for r in np.nonzero(hit)[0]:
            reasons[int(r)] = reason
        alive[hit] = False

    slot64 = cols.slot.astype(np.int64, copy=False)
    target64 = cols.target_epoch.astype(np.int64, copy=False)
    # reason parity with the scalar path: an insane slot IS a future
    # slot, but an insane target epoch on a sane slot passes the
    # slot-window checks and fails the epoch compare — exactly like
    # _gossip_checks with python ints
    insane_slot = cols.slot > np.uint64(_SANE)
    insane_tgt = cols.target_epoch > np.uint64(_SANE)
    kill(insane_slot, "future_slot")
    slot64 = np.where(insane_slot, 0, slot64)
    cur = chain.current_slot()
    kill(slot64 > cur, "future_slot")
    kill(slot64 + spec.slots_per_epoch < cur, "past_slot")
    kill(insane_tgt | (target64 != slot64 // spec.slots_per_epoch),
         "target_epoch_mismatch")
    # NOTE: empty_aggregation_bits / not_unaggregated are decided inside
    # the per-group stage AFTER the head/target root checks — scalar
    # _gossip_checks order.  Deciding them here would downscore senders
    # the scalar path treats as benign (unknown_head_block outranks).
    if cols.electra:
        cb = cols.committee_bits
        one_hot = (cb != 0) & ((cb & (cb - np.uint64(1))) == 0)
        odd = alive & (~one_hot | (cols.index != 0))
        for r in np.nonzero(odd)[0]:
            fallback_rows.append(int(r))
        alive[odd] = False

    group_of_row, first_rows = cols.group_keys()
    groups: list[_Group] = []
    attester = np.full(n, -1, np.int64)
    proto = chain.fork_choice.proto
    from lighthouse_tpu.types.containers import AttestationData

    for gid, first in enumerate(first_rows):
        rows = np.nonzero((group_of_row == gid) & alive)[0]
        if rows.size == 0:
            continue
        g = _Group(gid)
        g.rows = rows
        g.slot = int(slot64[rows[0]])
        g.epoch = int(target64[rows[0]])
        head_root = cols.beacon_block_root[rows[0]].tobytes()
        target_root = cols.target_root[rows[0]].tobytes()
        if head_root not in proto:
            kill_rows(reasons, alive, rows, "unknown_head_block")
            continue
        if target_root not in proto:
            kill_rows(reasons, alive, rows, "unknown_target_root")
            continue
        expected = proto.get_ancestor(
            head_root, spec.compute_start_slot_at_epoch(g.epoch))
        if expected != target_root:
            kill_rows(reasons, alive, rows, "invalid_target_root")
            continue
        g.data = AttestationData.deserialize(
            cols.data_raw[rows[0]].tobytes())
        try:
            shim = _DataShim(g.data)
            state = chain._attestation_state(shim)
            shuffle = chain.committee_shuffle(state, g.epoch)
            if cols.electra:
                g.committee_index = int(
                    cols.committee_bits[rows[0]]).bit_length() - 1
            else:
                g.committee_index = int(cols.index[rows[0]])
            from lighthouse_tpu.state_transition.misc import (
                get_beacon_committee,
            )

            g.committee = get_beacon_committee(
                state, spec, g.slot, g.committee_index, shuffle)
        except (ValueError, KeyError) as e:
            record_swallowed("columnar_ingest.committee", e)
            kill_rows(reasons, alive, rows, "invalid_committee")
            continue
        bad_len = rows[cols.bit_count[rows] != g.committee.shape[0]]
        kill_rows(reasons, alive, bad_len, "aggregation_bits_length")
        rows = rows[cols.bit_count[rows] == g.committee.shape[0]]
        if rows.size == 0:
            continue
        kill_rows(reasons, alive, rows[cols.set_bits[rows] == 0],
                  "empty_aggregation_bits")
        kill_rows(reasons, alive, rows[cols.set_bits[rows] > 1],
                  "not_unaggregated")
        rows = rows[cols.set_bits[rows] == 1]
        if rows.size == 0:
            continue
        attester[rows] = g.committee[cols.first_bit[rows]]
        # pubkey rows below gather from the HEAD registry (validator
        # index -> pubkey is fork-independent: deposits apply in
        # deposit-index order on every branch) — an index the head
        # registry does not cover yet (side-branch state with more
        # deposits) rides the scalar pipeline instead
        n_reg = len(chain.head_state.validators)
        oob = rows[attester[rows] >= n_reg]
        if oob.size:
            fallback_rows.extend(int(r) for r in oob)
            alive[oob] = False
            rows = rows[attester[rows] < n_reg]
            if rows.size == 0:
                continue
        seen = chain.observed_attesters.seen_mask(g.epoch, attester[rows])
        kill_rows(reasons, alive, rows[seen], "prior_attestation_known")
        rows = rows[~seen]
        if rows.size == 0:
            continue
        g.rows = rows
        g.data_root = g.data.hash_tree_root()
        from lighthouse_tpu.state_transition import misc

        domain = misc.get_domain(
            state, spec, spec.domain_beacon_attester, g.epoch)
        g.signing_root = misc.compute_signing_root(g.data_root, domain)
        groups.append(g)

    # unique signature sets: (group, attester PUBKEY bytes, signature
    # bytes) — byte-identical sets verify once (the dedup stage);
    # different validators sharing one key (interop fixtures) share a
    # set exactly like pre_aggregation.dedup_sets
    live_rows = np.concatenate([g.rows for g in groups]) if groups else \
        np.zeros(0, np.int64)
    group_of_live = np.concatenate(
        [np.full(g.rows.size, i, np.int64) for i, g in enumerate(groups)]
    ) if groups else np.zeros(0, np.int64)
    n_sets = 0
    set_of_live = np.zeros(0, np.int64)
    set_first = np.zeros(0, np.int64)
    pk_rows = np.zeros((0, 48), np.uint8)
    cols_sig = np.zeros((0, 96), np.uint8)
    if live_rows.size:
        validators = chain.head_state.validators
        pk_rows = np.asarray(
            validators.pubkeys[attester[live_rows]], np.uint8)
        cols_sig = cols.signature[live_rows]
        key = np.empty((live_rows.size, 8 + 48 + 96), np.uint8)
        key[:, :8] = group_of_live.view(np.uint8).reshape(-1, 8)
        key[:, 8:56] = pk_rows
        key[:, 56:] = cols_sig
        view = np.ascontiguousarray(key).view([("k", "V152")]).ravel()
        _, set_first, set_of_live = np.unique(
            view, return_index=True, return_inverse=True)
        n_sets = set_first.size
    return {
        "groups": groups, "attester": attester, "live_rows": live_rows,
        "group_of_live": group_of_live, "set_of_live": set_of_live,
        "set_first": set_first, "n_sets": n_sets, "pk_rows": pk_rows,
        "cols_sig": cols_sig,
    }


class _DataShim:
    """Duck-typed item for chain._attestation_state (wants .data)."""

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data


def kill_rows(reasons, alive, rows, reason: str) -> None:
    for r in rows:
        reasons[int(r)] = reason
    alive[rows] = False


# -- BLS ----------------------------------------------------------------------


def _unique_set(chain, prep, u: int):
    """Materialize unique set ``u`` as a plain SignatureSet (bisection
    attribution / unmergeable pass-through)."""
    i = int(prep["set_first"][u])
    g = prep["groups"][int(prep["group_of_live"][i])]
    sig = bls.Signature.interned(bytes(prep["sig_bytes"][u]))
    pk = bls.PublicKey.interned(prep["pk_rows"][i].tobytes())
    return bls.SignatureSet(sig, [pk], g.signing_root)


def _should_premerge() -> bool:
    """Merged host folds are redundant when the fused device pipeline
    serves verification — it groups same-message lanes internally
    (ops/bls_backend._chunk_layout), so pre-merging would pay host
    point math for nothing.  Honor the pre-BLS kill switch too."""
    from lighthouse_tpu.pool import pre_aggregation

    if not pre_aggregation.enabled():
        return False
    try:
        from lighthouse_tpu.crypto.bls import api as bls_api

        name = bls_api.get_backend()
        if name == "auto":
            name = bls_api.resolve_auto_backend()
        return name not in ("tpu", "sharded")
    except Exception as e:
        record_swallowed("columnar_ingest.backend_probe", e)
        return True


def _verify_sets(chain, prep) -> np.ndarray:
    """Verdict per unique set: merged fast path + bisection fallback."""
    groups = prep["groups"]
    n_sets = prep["n_sets"]
    set_first = prep["set_first"]
    group_of_live = prep["group_of_live"]
    cols_sig = prep["cols_sig"]

    prep["sig_bytes"] = [cols_sig[int(set_first[u])].tobytes()
                         for u in range(n_sets)]

    verdict = np.zeros(n_sets, bool)
    # merge lanes keyed by SIGNING ROOT (electra committees of one slot
    # share the message, so their sets legally fold together)
    lane_of_root: dict[bytes, int] = {}
    lane_sets: list[list[int]] = []
    for u in range(n_sets):
        g = groups[int(group_of_live[int(set_first[u])])]
        lane = lane_of_root.setdefault(g.signing_root, len(lane_sets))
        if lane == len(lane_sets):
            lane_sets.append([])
        lane_sets[lane].append(u)

    merged: list = []
    singles: list[int] = []
    t_fold0 = time.perf_counter()
    n_folded = 0
    if _should_premerge():
        merged, singles, n_folded = _fold_lanes(chain, prep, lane_sets)
    else:
        singles = list(range(n_sets))
    _stage("pubkey_fold", time.perf_counter() - t_fold0, n_folded)

    t0 = time.perf_counter()
    verify_list = merged + [_unique_set(chain, prep, u) for u in singles]
    ok = bls.verify_signature_sets(verify_list) if verify_list else True
    if ok:
        verdict[:] = True
    else:
        # attribution unchanged: bisect the ORIGINAL per-row sets
        from lighthouse_tpu.chain.attestation_verification import (
            verify_signature_sets_with_bisection,
        )

        originals = [_unique_set(chain, prep, u) for u in range(n_sets)]
        mask = verify_signature_sets_with_bisection(originals)
        verdict[:] = mask
    _stage("verify", time.perf_counter() - t0, len(verify_list))
    return verdict


def _fold_lanes(chain, prep, lane_sets: list[list[int]]
                ) -> tuple[list, list[int], int]:
    """Blinded merged sets for every multi-member signing-root lane.

    Signature side: Σ rᵢ·sigᵢ on host, collapsed per unique signature
    bytes first (r₁·sig + r₂·sig = (r₁+r₂)·sig — one g2_mul per
    distinct signature, the honest-duplication case).  Pubkey side: ONE
    pubkey_plane.fold call over every mergeable lane (the gather+MSM
    batches across lanes).  A lane whose fold resists (bad decompress,
    infinity signature, identity aggregate) passes through UNMERGED —
    mirrors pre_aggregation._fold_group's conservative contract."""
    from lighthouse_tpu.chain import pubkey_plane
    from lighthouse_tpu.crypto.bls import curve as cv
    from lighthouse_tpu.crypto.bls.fields import R as _R

    set_first = prep["set_first"]
    group_of_live = prep["group_of_live"]
    live_rows = prep["live_rows"]
    attester = prep["attester"]
    groups = prep["groups"]

    singles: list[int] = []
    cand: list[dict] = []       # lanes whose sig side folded
    fold_idx: list[int] = []    # plane lanes: validator index
    fold_r: list[int] = []      # plane lanes: blinder
    fold_lane: list[int] = []   # plane lanes: candidate id
    # ONE batched decompress + G2 membership test across every lane's
    # constituents (native, ~150 µs/sig vs ~1.6 ms for the host ψ
    # check) — the per-lane fold below and the reference verifier's
    # per-signature .point path then only re-check signatures that
    # failed here (attack traffic), keeping attribution per lane
    sig_bytes = prep["sig_bytes"]
    every = sorted({u for m in lane_sets for u in m})
    if every:
        batch_sigs = [bls.Signature.interned(sig_bytes[u]) for u in every]
        # decompress result deliberately ignored: one malformed
        # signature must not disable the batched membership test for
        # the whole sweep (the check skips undecompressable entries;
        # their lanes fail per-lane with attribution)
        bls.Signature.decompress_batch(batch_sigs)
        bls.Signature.subgroup_check_batch(batch_sigs)
    for members in lane_sets:
        if len(members) == 1:
            singles.append(members[0])
            continue
        lane = _fold_sig_side(prep, members, cv, _R)
        if lane is None:
            singles.extend(members)     # unmergeable pass-through
            continue
        lane_id = len(cand)
        cand.append(lane)
        for u, r in zip(members, lane["blinders"]):
            pos = int(set_first[u])
            fold_idx.append(int(attester[int(live_rows[pos])]))
            fold_r.append(r)
            fold_lane.append(lane_id)
    merged: list = []
    n_folded = 0
    if cand:
        plane = pubkey_plane.get_plane()
        try:
            pk_pts = plane.fold(
                chain.head_state.validators,
                np.array(fold_idx, np.int64),
                np.array(fold_r, np.uint64),
                np.array(fold_lane, np.int64), len(cand))
        except Exception as e:      # never poison the batch: unmerged
            record_swallowed("columnar_ingest.fold", e)
            pk_pts = [None] * len(cand)
        sig_accs = _sig_accs(cand, cv)
        for lane_id, lane in enumerate(cand):
            pk_pt = pk_pts[lane_id]
            sig_acc = sig_accs[lane_id]
            if pk_pt is None or sig_acc is None:
                singles.extend(lane["members"])
                continue
            g0 = groups[int(group_of_live[int(
                set_first[lane["members"][0]])])]
            merged.append(bls.SignatureSet(
                bls.Signature(cv.g2_to_bytes(sig_acc), sig_acc),
                [bls.PublicKey(cv.g1_to_bytes(pk_pt), pk_pt)],
                g0.signing_root))
            n_folded += len(lane["members"])
    return merged, singles, n_folded


def _fold_sig_side(prep, members: list[int], cv, R: int):
    """Collapsed blinded sig-side terms (Σ rᵢ per unique signature) for
    one lane, or None when a constituent resists.  The scalar muls
    themselves run in ONE native segment-MSM across every lane
    (:func:`_sig_accs`) instead of a ~2.5 ms python g2_mul per term."""
    sig_bytes = prep["sig_bytes"]
    try:
        sigs = [bls.Signature.interned(sig_bytes[u]) for u in members]
        if not bls.Signature.decompress_batch(sigs):
            return None
        blinders: list[int] = []
        sig_sums: dict[bytes, tuple[int, object]] = {}
        for u, sig in zip(members, sigs):
            pt = sig.point_unchecked()
            if pt is cv.INF:
                return None
            # the merged Signature is built with a preset point, which
            # the verifiers trust as subgroup-checked — complete the G2
            # membership test HERE or an on-curve small-subgroup forgery
            # could fold into sig_acc unchecked (the _fold_lanes batch
            # pre-pass marks honest signatures; this per-signature host
            # check only fires for traffic that failed it)
            if not sig.subgroup_checked():
                if not cv.g2_in_subgroup_fast(pt):
                    return None
                sig.mark_subgroup_checked()
            r = 0
            while r == 0:
                r = secrets.randbits(64)
            blinders.append(r)
            key = sig_bytes[u]
            prev = sig_sums.get(key)
            sig_sums[key] = ((prev[0] + r) % R if prev else r, pt)
        terms = [(pt, s) for s, pt in sig_sums.values() if s]
        if not terms:
            return None
        return {"members": members, "blinders": blinders,
                "terms": terms}
    except (bls.BlsError, ValueError, TypeError) as e:
        record_swallowed("columnar_ingest.fold_sig", e)
        return None


def _sig_accs(cand: list[dict], cv) -> list:
    """Σ rᵢ·sigᵢ per candidate lane: one native segment-MSM across all
    lanes (ops/native_bls.g2_lincomb_groups), host point math when the
    native layer is unavailable.  None = identity accumulator (such a
    merged set can never verify — the lane passes through unmerged)."""
    pts: list = []
    scalars: list[int] = []
    gids: list[int] = []
    for lane_id, lane in enumerate(cand):
        for pt, s in lane["terms"]:
            pts.append(pt)
            scalars.append(s)
            gids.append(lane_id)
    try:
        from lighthouse_tpu.ops import native_bls

        if native_bls.available():
            res = native_bls.g2_lincomb_groups(
                pts, scalars, gids, len(cand))
            if res is not None:
                return [None if v is None else
                        (cv.Fq2(v[0][0], v[0][1]),
                         cv.Fq2(v[1][0], v[1][1])) for v in res]
    except Exception as e:
        record_swallowed("columnar_ingest.sig_lincomb", e)
    out: list = []
    for lane in cand:
        acc = cv.INF
        for pt, s in lane["terms"]:
            acc = cv.g2_add(acc, cv.g2_mul(pt, s))
        out.append(None if acc is cv.INF else acc)
    return out


# -- commit -------------------------------------------------------------------


def _commit(chain, cols, prep, reasons, verdict_of_set, out) -> None:
    from lighthouse_tpu.chain import attestation_verification as att_verify

    groups = prep["groups"]
    live_rows = prep["live_rows"]
    set_of_live = prep["set_of_live"]
    attester = prep["attester"]
    if live_rows.size == 0:
        return
    ok_live = (np.ones(live_rows.size, bool) if verdict_of_set is None
               else np.asarray(verdict_of_set)[set_of_live])
    live_pos_of_row = {int(r): i for i, r in enumerate(live_rows)}
    spec = chain.spec
    for gi, g in enumerate(groups):
        rows = g.rows
        pos = np.array([live_pos_of_row[int(r)] for r in rows], np.int64)
        ok_rows = ok_live[pos]
        for r in rows[~ok_rows]:
            reasons[int(r)] = "invalid_signature"
        rows = rows[ok_rows]
        if rows.size == 0:
            continue
        idx = attester[rows]
        # claim dup marks atomically under the commit lock: intra-batch
        # duplicate indices first (order wins), then the cache claim
        order = np.argsort(rows, kind="stable")
        rows_o, idx_o = rows[order], idx[order]
        _uniq, first_pos = np.unique(idx_o, return_index=True)
        keep = np.zeros(rows_o.size, bool)
        keep[first_pos] = True
        for r in rows_o[~keep]:
            reasons[int(r)] = "duplicate_in_batch"
        rows_o, idx_o = rows_o[keep], idx_o[keep]
        already = chain.observed_attesters.observe_batch(g.epoch, idx_o)
        for r in rows_o[already]:
            reasons[int(r)] = "duplicate_in_batch"
        rows_o, idx_o = rows_o[~already], idx_o[~already]
        if rows_o.size == 0:
            continue
        try:
            chain.fork_choice.on_attestation(
                chain.current_slot(), idx_o,
                cols.beacon_block_root[rows_o[0]].tobytes(),
                g.epoch, g.slot)
        except Exception as e:
            record_swallowed("chain.batch_att_fork_choice", e)
        committee_len = int(g.committee.shape[0])
        for r in rows_o:
            chain.naive_pool.insert_single_bit(
                g.data, g.data_root, g.committee_index, committee_len,
                int(cols.first_bit[r]), cols.signature[r].tobytes())
        chain.validator_monitor.on_gossip_attestation(
            idx_o, g.data, spec)
        if chain.slasher is not None:
            for r, vi in zip(rows_o, idx_o):
                try:
                    att = cols.materialize(int(r))
                    chain.slasher.on_verified_attestation(
                        att_verify._as_indexed(
                            chain, att, np.array([vi])))
                except Exception as e:
                    record_swallowed("columnar_ingest.slasher", e)
        out.verified_rows.extend(int(r) for r in rows_o)


__all__ = [
    "IngestOutcome",
    "WireBatchResult",
    "ingest_attestation_columns",
    "process_wire_batch",
    "reset_stages",
    "stage_snapshot",
]
