"""Gossip attestation/aggregate verification with batched BLS.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
attestation_verification.rs and attestation_verification/batch.rs: gossip
checks (slot window, committee membership, dup detection) per item, then
ONE batched `verify_signature_sets` call for the whole batch.

Two deliberate deltas from the reference:
- Poisoned-batch fallback is recursive bisection (log-depth) instead of
  linear per-item re-verification (batch.rs:104-127) — a 64k-lane device
  batch with k bad items costs O(k·log n) re-verifies (SURVEY.md §7 #6).
- Dup caches are only READ before signature verification and written
  after it succeeds, so unauthenticated garbage cannot suppress honest
  validators' later messages.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_transition import signature_sets as sigs
from lighthouse_tpu.state_transition.block_processing import (
    get_attesting_indices,
)
from lighthouse_tpu.state_transition.misc import get_beacon_committee


def is_aggregator(spec, committee_len: int, selection_proof: bytes) -> bool:
    """Spec is_aggregator: the selection proof elects ~TARGET_AGGREGATORS
    members per committee (reference attestation_verification.rs
    InvalidSelectionProof rejection)."""
    import hashlib

    modulo = max(1, committee_len // spec.target_aggregators_per_committee)
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


class AttestationError(ValueError):
    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


@dataclass
class VerifiedAttestation:
    item: object            # what the caller submitted
    attestation: object     # the (inner) Attestation
    indexed_indices: np.ndarray
    sets: list
    observations: list = field(default_factory=list)  # deferred cache marks
    ok: bool = False


def verify_signature_sets_with_bisection(
    sets: Sequence[bls.SignatureSet], *, backend: str | None = None
) -> np.ndarray:
    """Per-set validity mask via batch verify + bisection fallback."""
    n = len(sets)
    out = np.zeros(n, bool)

    def rec(lo: int, hi: int, known_failed: bool):
        if lo >= hi:
            return
        if not known_failed and bls.verify_signature_sets(
                sets[lo:hi], backend=backend):
            out[lo:hi] = True
            return
        if hi - lo == 1:
            out[lo] = False
            return
        mid = (lo + hi) // 2
        rec(lo, mid, False)
        rec(mid, hi, False)

    # callers reach this after a failed whole-batch verify: skip re-checking
    # the root span
    rec(0, n, True)
    return out


def _gossip_checks(chain, attestation, state) -> np.ndarray:
    """Structure/timing checks; returns attesting validator indices."""
    spec = chain.spec
    data = attestation.data
    att_slot = int(data.slot)
    current_slot = chain.current_slot()
    # propagation window: [att_slot, att_slot + ATTESTATION_PROPAGATION_SLOT_RANGE]
    if att_slot > current_slot:
        raise AttestationError("future_slot")
    if att_slot + spec.slots_per_epoch < current_slot:
        raise AttestationError("past_slot")
    target_epoch = int(data.target.epoch)
    if target_epoch != spec.compute_epoch_at_slot(att_slot):
        raise AttestationError("target_epoch_mismatch")
    head_root = bytes(data.beacon_block_root)
    if head_root not in chain.fork_choice.proto:
        raise AttestationError("unknown_head_block")
    # target consistency (reference verify_attestation_target_root): the
    # target must be a known block AND the epoch-boundary ancestor of the
    # LMD vote, else validly-signed attestations with inconsistent targets
    # would be counted in fork choice
    target_root = bytes(data.target.root)
    if target_root not in chain.fork_choice.proto:
        raise AttestationError("unknown_target_root")
    expected_target = chain.fork_choice.proto.get_ancestor(
        head_root, spec.compute_start_slot_at_epoch(target_epoch))
    if expected_target != target_root:
        raise AttestationError("invalid_target_root")
    shuffle = chain.committee_shuffle(state, target_epoch)
    indices = get_attesting_indices(state, spec, attestation, shuffle)
    if indices.size == 0:
        raise AttestationError("empty_aggregation_bits")
    return indices


def verify_unaggregated_for_gossip(chain, attestation, state) -> VerifiedAttestation:
    """Checks for a single-bit gossip attestation (reference
    IndexedUnaggregatedAttestation::verify).  Dup checks are read-only;
    marking is deferred to post-signature commit."""
    indices = _gossip_checks(chain, attestation, state)
    if indices.size != 1:
        raise AttestationError("not_unaggregated")
    epoch = int(attestation.data.target.epoch)
    if chain.observed_attesters.seen_mask(epoch, indices).any():
        raise AttestationError("prior_attestation_known")
    sset = sigs.indexed_attestation_set(state, chain.spec, _as_indexed(
        chain, attestation, indices))
    return VerifiedAttestation(
        attestation, attestation, indices, [sset],
        observations=[("attesters", epoch, indices)])


def verify_aggregated_for_gossip(chain, signed_aggregate, state) -> VerifiedAttestation:
    """Checks for a SignedAggregateAndProof (reference
    IndexedAggregatedAttestation::verify): 3 signature sets — selection
    proof, aggregator signature, aggregate (batch.rs:62-102)."""
    msg = signed_aggregate.message
    aggregate = msg.aggregate
    indices = _gossip_checks(chain, aggregate, state)
    epoch = int(aggregate.data.target.epoch)
    aggregator = int(msg.aggregator_index)
    if chain.observed_aggregators.is_seen(epoch, aggregator):
        raise AttestationError("aggregator_already_known")
    agg_digest = (aggregate.data.hash_tree_root()
                  + bytes(np.packbits(np.asarray(aggregate.aggregation_bits))))
    if chain.observed_aggregates.is_seen(epoch, agg_digest):
        raise AttestationError("aggregate_already_known")
    if aggregator not in set(int(i) for i in indices):
        raise AttestationError("aggregator_not_in_committee")
    from lighthouse_tpu.state_transition.misc import (
        attestation_committee_index,
    )

    slot = int(aggregate.data.slot)
    committee = get_beacon_committee(
        state, chain.spec, slot, attestation_committee_index(aggregate),
        chain.committee_shuffle(state, epoch))
    if not is_aggregator(
            chain.spec, committee.shape[0], bytes(msg.selection_proof)):
        raise AttestationError("invalid_selection_proof_not_aggregator")
    sets = [
        sigs.selection_proof_set(
            state, chain.spec, slot, aggregator, bytes(msg.selection_proof)),
        sigs.aggregate_and_proof_set(state, chain.spec, signed_aggregate),
        sigs.indexed_attestation_set(
            state, chain.spec, _as_indexed(chain, aggregate, indices)),
    ]
    return VerifiedAttestation(
        signed_aggregate, aggregate, indices, sets,
        observations=[
            ("aggregators", epoch, np.array([aggregator])),
            ("aggregates", epoch, agg_digest),
        ])


def _as_indexed(chain, attestation, indices: np.ndarray):
    t = chain.t
    cls = (t.IndexedAttestationElectra
           if hasattr(attestation, "committee_bits")
           else t.IndexedAttestation)
    return cls(
        attesting_indices=[int(i) for i in np.sort(indices)],
        data=attestation.data,
        signature=attestation.signature,
    )


def commit_observations(chain, verified: VerifiedAttestation) -> bool:
    """Mark dup caches for a signature-verified item.  Returns False if a
    concurrent in-batch duplicate already claimed a mark (item rejected)."""
    ok = True
    for kind, epoch, payload in verified.observations:
        if kind == "attesters":
            if chain.observed_attesters.observe_batch(epoch, payload).any():
                ok = False
        elif kind == "aggregators":
            if chain.observed_aggregators.observe_batch(epoch, payload).any():
                ok = False
        elif kind == "aggregates":
            if chain.observed_aggregates.observe(epoch, payload):
                ok = False
    return ok


def batch_verify(
    chain, candidates: list[VerifiedAttestation]
) -> list[VerifiedAttestation]:
    """One device-sized batch verification over all candidates' sets, with
    bisection fallback attributing failures to items
    (reference batch_verify_unaggregated_attestations, batch.rs:133).

    The batch first passes through the pre-BLS coalescing stage
    (pool/pre_aggregation): exact duplicates verify once and
    same-message sets fold into blinded merges, so a mainnet-width
    attestation sweep pays one pairing lane per (slot, committee,
    beacon_block_root) instead of one per validator.  The fast path
    verifies the COALESCED batch; on failure, bisection runs over the
    ORIGINAL per-candidate sets so attribution is unchanged."""
    from lighthouse_tpu.pool.pre_aggregation import coalesce_sets

    all_sets: list[bls.SignatureSet] = []
    spans: list[tuple[int, int]] = []
    for c in candidates:
        spans.append((len(all_sets), len(all_sets) + len(c.sets)))
        all_sets.extend(c.sets)
    if not all_sets:
        return candidates
    coalesced, _stats = coalesce_sets(all_sets)
    if bls.verify_signature_sets(coalesced):
        for c in candidates:
            c.ok = True
        return candidates
    mask = verify_signature_sets_with_bisection(all_sets)
    for c, (lo, hi) in zip(candidates, spans):
        c.ok = bool(mask[lo:hi].all())
    return candidates
