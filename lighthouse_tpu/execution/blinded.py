"""Blinded-block plumbing: payload↔header conversion, blind/unblind.

The builder (MEV) round trip signs a block that carries only the
execution payload HEADER; the builder reveals the payload after seeing
the signature.  Because an ExecutionPayloadHeader is exactly the
payload's field-root vector, the blinded block's hash_tree_root — hence
its signing root — equals the full block's, so one signature covers both
forms (reference consensus/types/src/beacon_block_body.rs blinded
variants + execution_layer/src/lib.rs propose_blinded_beacon_block).
"""

from __future__ import annotations

_ROOT_FIELDS = {
    "transactions_root": "transactions",
    "withdrawals_root": "withdrawals",
    "deposit_requests_root": "deposit_requests",
    "withdrawal_requests_root": "withdrawal_requests",
}


class UnblindError(ValueError):
    pass


def payload_to_header(t, fork: str, payload):
    """ExecutionPayload -> ExecutionPayloadHeader (field roots for the
    variable-size fields, verbatim copies for the rest)."""
    header_cls = t.execution_payload_header_class(fork)
    pf = type(payload).fields
    kwargs = {}
    for name in header_cls.fields:
        src = _ROOT_FIELDS.get(name)
        if src is not None:
            kwargs[name] = pf[src].hash_tree_root(getattr(payload, src))
        else:
            kwargs[name] = getattr(payload, name)
    return header_cls(**kwargs)


def blind_block(t, fork: str, block):
    """Full BeaconBlock -> BlindedBeaconBlock (same hash_tree_root)."""
    blinded_cls = t.blinded_beacon_block_class(fork)
    body_cls = blinded_cls.fields["body"].cls
    body_kwargs = {}
    for name in body_cls.fields:
        if name == "execution_payload_header":
            body_kwargs[name] = payload_to_header(
                t, fork, block.body.execution_payload)
        else:
            body_kwargs[name] = getattr(block.body, name)
    return blinded_cls(
        slot=block.slot, proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body=body_cls(**body_kwargs))


def unblind_block(t, fork: str, signed_blinded, payload):
    """SignedBlindedBeaconBlock + revealed payload -> SignedBeaconBlock.

    Raises UnblindError unless the payload matches the header the
    proposer signed (the trust boundary: a builder cannot swap payloads,
    execution_layer/src/lib.rs header equality check)."""
    blinded = signed_blinded.message
    want = blinded.body.execution_payload_header
    got = payload_to_header(t, fork, payload)
    if want.hash_tree_root() != got.hash_tree_root():
        raise UnblindError("revealed payload does not match signed header")
    block_cls = t.beacon_block_class(fork)
    body_cls = t.beacon_block_body_class(fork)
    body_kwargs = {}
    for name in body_cls.fields:
        if name == "execution_payload":
            body_kwargs[name] = payload
        else:
            body_kwargs[name] = getattr(blinded.body, name)
    full = block_cls(
        slot=blinded.slot, proposer_index=blinded.proposer_index,
        parent_root=bytes(blinded.parent_root),
        state_root=bytes(blinded.state_root),
        body=body_cls(**body_kwargs))
    signed_cls = t.signed_beacon_block_class(fork)
    out = signed_cls(message=full,
                     signature=bytes(signed_blinded.signature))
    # invariant: one signature covers both forms
    assert full.hash_tree_root() == blinded.hash_tree_root()
    return out


def decode_signed_blinded_block(t, raw: bytes):
    """Decode a SignedBlindedBeaconBlock of unknown fork (newest-first,
    like decode_signed_block)."""
    for fork in ("electra", "deneb", "capella", "bellatrix"):
        try:
            return fork, t.signed_blinded_beacon_block_class(
                fork).deserialize(raw)
        except Exception:
            continue
    return None, None
