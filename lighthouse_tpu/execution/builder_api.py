"""External block-builder (MEV) API: client + mock builder.

Rebuild of /root/reference/beacon_node/builder_client (the eth
builder-specs surface the reference drives) and
execution_layer/src/test_utils/mock_builder.rs: the proposer registers
its fee recipient, asks the builder for a bid (header + value) at a
slot, and the production path RACES the builder bid against the local
payload, falling back locally on any builder fault — a failing relay
must never cost a proposal (the reference's builder-fallback rule).

The full blinded-block round trip (sign header, reveal payload) is
collapsed to bid + payload fetch here: the seam (get_header /
get_payload per slot, local fallback) matches, which is what the
production path and tests exercise.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class BuilderError(RuntimeError):
    pass


@dataclass
class BuilderBid:
    slot: int
    value_wei: int          # bid value; higher wins vs local
    payload_ssz: bytes      # the payload the builder commits to
    fork: str


class BuilderApiClient:
    """HTTP client for a builder endpoint (builder-specs shaped)."""

    def __init__(self, base_url: str, timeout: float = 3.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, path: str, body=None):
        req = urllib.request.Request(
            self.base_url + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            raise BuilderError(str(e)) from None

    def register_validator(self, pubkey: bytes, fee_recipient: bytes,
                           gas_limit: int = 30_000_000) -> None:
        self._call("POST", "/eth/v1/builder/validators", [{
            "message": {
                "pubkey": "0x" + pubkey.hex(),
                "fee_recipient": "0x" + fee_recipient.hex(),
                "gas_limit": str(gas_limit),
            }}])

    def get_bid(self, slot: int, parent_hash: bytes,
                pubkey: bytes) -> BuilderBid:
        out = self._call(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}"
            f"/0x{pubkey.hex()}")
        data = out["data"]
        return BuilderBid(
            slot=slot,
            value_wei=int(data["value"]),
            payload_ssz=bytes.fromhex(data["payload_ssz_hex"]),
            fork=data["version"])

    def status(self) -> bool:
        try:
            self._call("GET", "/eth/v1/builder/status")
            return True
        except BuilderError:
            return False

    def submit_blinded_block(self, signed_blinded_ssz: bytes) -> bytes:
        """Reveal: POST the signed blinded block, get the payload SSZ
        (builder-specs submit_blinded_block; the builder publishes the
        full block itself in real life — the BN also imports locally)."""
        out = self._call("POST", "/eth/v1/builder/blinded_blocks",
                         {"ssz_hex": signed_blinded_ssz.hex()})
        return bytes.fromhex(out["data"]["payload_ssz_hex"])


class MockBuilder:
    """In-process builder (reference mock_builder.rs): bids a payload
    derived from the chain's own mock payload with a configurable value;
    can be told to misbehave for fault-injection tests."""

    def __init__(self, chain, port: int = 0, value_wei: int = 10**18):
        self.chain = chain
        self.port = port
        self.value_wei = value_wei
        self.fail_next = False          # fault injection (bid)
        self.fail_unblind = False       # fault injection (reveal)
        self.registrations: dict[str, dict] = {}
        self._bid_payloads: dict[str, tuple[str, bytes]] = {}  # hash->(fork, ssz)
        self._srv = None
        self._thread = None

    def start(self) -> "MockBuilder":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/eth/v1/builder/status":
                    return self._reply(200, {})
                parts = self.path.split("/")
                if len(parts) >= 7 and parts[3] == "builder" \
                        and parts[4] == "header":
                    if outer.fail_next:
                        outer.fail_next = False
                        return self._reply(500, {"message": "builder down"})
                    slot = int(parts[5])
                    from lighthouse_tpu.execution.mock_el import (
                        build_mock_payload,
                    )

                    payload = build_mock_payload(outer.chain, slot)
                    if payload is None:
                        return self._reply(404, {"message": "pre-merge"})
                    spec = outer.chain.spec
                    fork = spec.fork_at_epoch(
                        spec.compute_epoch_at_slot(slot))
                    # remember the payload behind the bid so the reveal
                    # endpoint can serve the unblinding request
                    outer._bid_payloads[
                        bytes(payload.block_hash).hex()] = (
                        fork, payload.serialize())
                    return self._reply(200, {"data": {
                        "value": str(outer.value_wei),
                        "payload_ssz_hex": payload.serialize().hex(),
                        "version": fork,
                    }})
                self._reply(404, {"message": "unknown route"})

            def do_POST(self):
                if self.path == "/eth/v1/builder/validators":
                    n = int(self.headers.get("Content-Length", 0))
                    regs = json.loads(self.rfile.read(n))
                    for r in regs:
                        outer.registrations[
                            r["message"]["pubkey"]] = r["message"]
                    return self._reply(200, {})
                if self.path == "/eth/v1/builder/blinded_blocks":
                    if outer.fail_unblind:
                        outer.fail_unblind = False
                        return self._reply(500, {"message": "reveal down"})
                    n = int(self.headers.get("Content-Length", 0))
                    raw = bytes.fromhex(
                        json.loads(self.rfile.read(n))["ssz_hex"])
                    from lighthouse_tpu.execution.blinded import (
                        decode_signed_blinded_block,
                    )

                    _, sb = decode_signed_blinded_block(outer.chain.t, raw)
                    if sb is None:
                        return self._reply(400, {"message": "undecodable"})
                    key = bytes(sb.message.body.execution_payload_header
                                .block_hash).hex()
                    hit = outer._bid_payloads.get(key)
                    if hit is None:
                        return self._reply(
                            404, {"message": "unknown payload header"})
                    fork, ssz_bytes = hit
                    return self._reply(200, {"data": {
                        "payload_ssz_hex": ssz_bytes.hex(),
                        "version": fork,
                    }})
                self._reply(404, {"message": "unknown route"})

        self._srv = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._srv.server_port
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()


def choose_payload(chain, slot: int, builder: BuilderApiClient | None,
                   pubkey: bytes | None = None,
                   local_payload=None):
    """The production-path race (reference get_payload local/builder
    race): prefer the builder's bid when it answers with a decodable
    payload; ANY builder fault falls back to the local payload."""
    if builder is None:
        return local_payload, "local"
    parent_hash = bytes(
        chain.head_state.latest_execution_payload_header.block_hash)
    try:
        bid = builder.get_bid(slot, parent_hash, pubkey or b"\x00" * 48)
        spec = chain.spec
        fork = spec.fork_at_epoch(spec.compute_epoch_at_slot(slot))
        cls = {
            "bellatrix": chain.t.ExecutionPayloadBellatrix,
            "capella": chain.t.ExecutionPayloadCapella,
            "deneb": chain.t.ExecutionPayloadDeneb,
            "electra": chain.t.ExecutionPayloadElectra,
        }[fork]
        if bid.value_wei <= 0:
            # a worthless bid loses the race to the local payload
            return local_payload, "local"
        payload = cls.deserialize(bid.payload_ssz)
        return payload, "builder"
    except (BuilderError, KeyError, ValueError):
        # builder faults fall back locally; programming errors propagate
        return local_payload, "local"


__all__ = [
    "BuilderApiClient",
    "BuilderBid",
    "BuilderError",
    "MockBuilder",
    "choose_payload",
]
