"""Mock execution engine: in-process JSON-RPC server + block generator.

Rebuild of /root/reference/beacon_node/execution_layer/src/test_utils/
(MockExecutionLayer, ExecutionBlockGenerator, handle_rpc.rs): an
in-memory execution chain that answers engine_newPayload /
engine_forkchoiceUpdated / engine_getPayload over real HTTP with JWT
checking, plus fault-injection hooks (static status overrides) the test
suite uses to exercise optimistic sync and invalid-payload handling.

Block hashes are sha256 over the canonical payload JSON (opaque to the
consensus layer; a mock needs determinism, not keccak).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from lighthouse_tpu.execution.engine_api import (
    json_to_payload_kwargs,
    payload_to_json,
)


def compute_block_hash(payload_json: dict) -> bytes:
    scrubbed = {k: v for k, v in payload_json.items() if k != "blockHash"}
    return hashlib.sha256(
        json.dumps(scrubbed, sort_keys=True).encode()).digest()


class ExecutionBlockGenerator:
    """In-memory execution block tree + payload production."""

    def __init__(self, terminal_block_hash: bytes = b"\x00" * 32):
        self.blocks: dict[bytes, dict] = {}
        self.head_hash = terminal_block_hash
        self.finalized_hash = b"\x00" * 32
        self.pending: dict[str, dict] = {}  # payload_id -> attributes
        self._next_payload_id = 1
        self._next_block_number = 1

    def new_payload(self, payload_json: dict) -> str:
        block_hash = bytes.fromhex(payload_json["blockHash"][2:])
        if compute_block_hash(payload_json) != block_hash:
            return "INVALID_BLOCK_HASH"
        parent = bytes.fromhex(payload_json["parentHash"][2:])
        if parent != b"\x00" * 32 and parent not in self.blocks \
                and self.blocks:
            return "SYNCING"
        self.blocks[block_hash] = payload_json
        return "VALID"

    def forkchoice_updated(self, head: bytes, finalized: bytes,
                           attributes: dict | None) -> tuple[str, str | None]:
        if head != b"\x00" * 32 and self.blocks and head not in self.blocks:
            return "SYNCING", None
        self.head_hash = head
        self.finalized_hash = finalized
        if attributes is None:
            return "VALID", None
        payload_id = f"0x{self._next_payload_id:016x}"
        self._next_payload_id += 1
        self.pending[payload_id] = dict(attributes, parent=head)
        return "VALID", payload_id

    def get_payload(self, payload_id: str) -> dict:
        attrs = self.pending.pop(payload_id, None)
        if attrs is None:
            raise KeyError("Unknown payload")
        parent = attrs["parent"]
        parent_block = self.blocks.get(parent)
        number = (int(parent_block["blockNumber"], 16) + 1
                  if parent_block else self._next_block_number)
        self._next_block_number = number + 1
        payload = {
            "parentHash": "0x" + bytes(parent).hex(),
            "feeRecipient": attrs["suggestedFeeRecipient"],
            "stateRoot": "0x" + hashlib.sha256(
                f"state{number}".encode()).hexdigest(),
            "receiptsRoot": "0x" + hashlib.sha256(b"receipts").hexdigest(),
            "logsBloom": "0x" + "00" * 256,
            "prevRandao": attrs["prevRandao"],
            "blockNumber": hex(number),
            "gasLimit": hex(30_000_000),
            "gasUsed": hex(21_000),
            "timestamp": attrs["timestamp"],
            "extraData": "0x",
            "baseFeePerGas": hex(7),
            "transactions": [],
        }
        if "withdrawals" in attrs:
            payload["withdrawals"] = attrs["withdrawals"]
        payload["blockHash"] = "0x" + compute_block_hash(payload).hex()
        return payload


class MockExecutionEngine:
    """JSON-RPC dispatch + fault injection over the generator."""

    def __init__(self, jwt_secret: bytes = b"\x42" * 32):
        self.jwt_secret = jwt_secret
        self.generator = ExecutionBlockGenerator()
        self.static_new_payload_status: str | None = None
        self.static_fcu_status: str | None = None
        self.lock = threading.Lock()

    def handle(self, method: str, params: list):
        with self.lock:
            if method == "engine_exchangeCapabilities":
                return ["engine_newPayloadV1", "engine_newPayloadV2",
                        "engine_newPayloadV3", "engine_forkchoiceUpdatedV1",
                        "engine_forkchoiceUpdatedV2",
                        "engine_forkchoiceUpdatedV3", "engine_getPayloadV1",
                        "engine_getPayloadV2", "engine_getPayloadV3"]
            if method.startswith("engine_newPayload"):
                status = (self.static_new_payload_status
                          or self.generator.new_payload(params[0]))
                return {"status": status, "latestValidHash": params[0].get(
                    "blockHash") if status == "VALID" else None,
                    "validationError": None}
            if method.startswith("engine_forkchoiceUpdated"):
                state, attrs = params[0], params[1] if len(params) > 1 else None
                status, payload_id = self.generator.forkchoice_updated(
                    bytes.fromhex(state["headBlockHash"][2:]),
                    bytes.fromhex(state["finalizedBlockHash"][2:]),
                    attrs)
                status = self.static_fcu_status or status
                return {"payloadStatus": {"status": status,
                                          "latestValidHash": None,
                                          "validationError": None},
                        "payloadId": payload_id}
            if method.startswith("engine_getPayload"):
                payload = self.generator.get_payload(params[0])
                if method.endswith("V1"):
                    return payload
                return {"executionPayload": payload,
                        "blockValue": "0x0"}
            raise ValueError(f"unknown method {method}")


class _Handler(BaseHTTPRequestHandler):
    engine: MockExecutionEngine = None

    def log_message(self, *args):
        pass

    def do_POST(self):
        auth = self.headers.get("Authorization", "")
        if not self._check_jwt(auth):
            self.send_response(401)
            self.end_headers()
            return
        length = int(self.headers.get("Content-Length") or 0)
        req = json.loads(self.rfile.read(length))
        try:
            result = self.engine.handle(req["method"], req.get("params", []))
            resp = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
        except Exception as e:
            resp = {"jsonrpc": "2.0", "id": req.get("id"),
                    "error": {"code": -32000, "message": str(e)}}
        payload = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _check_jwt(self, auth: str) -> bool:
        if not auth.startswith("Bearer "):
            return False
        token = auth[len("Bearer "):]
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            import base64

            pad = lambda s: s + "=" * (-len(s) % 4)  # noqa: E731
            sig = base64.urlsafe_b64decode(pad(sig_b64))
            expect = hmac.new(self.engine.jwt_secret,
                              f"{header_b64}.{payload_b64}".encode(),
                              "sha256").digest()
            return hmac.compare_digest(sig, expect)
        except Exception:
            return False


class MockExecutionLayer:
    """HTTP server wrapper: `url` + direct generator access for tests."""

    def __init__(self, jwt_secret: bytes = b"\x42" * 32,
                 host: str = "127.0.0.1", port: int = 0):
        self.engine = MockExecutionEngine(jwt_secret)
        handler = type("Handler", (_Handler,), {"engine": self.engine})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self.port = self._srv.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self.jwt_secret = jwt_secret
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)

    def start(self) -> "MockExecutionLayer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()


__all__ = [
    "ExecutionBlockGenerator",
    "MockExecutionEngine",
    "MockExecutionLayer",
    "compute_block_hash",
    "json_to_payload_kwargs",
    "payload_to_json",
]


def build_mock_payload(chain, slot: int):
    """Deterministic execution payload for a chain head (dev/sim nodes
    without a real EL — the reference's mock-EL payload production,
    execution_layer/src/test_utils/execution_block_generator.rs)."""
    import hashlib

    from lighthouse_tpu.state_transition import misc, state_advance

    spec = chain.spec
    fork = spec.fork_at_epoch(spec.compute_epoch_at_slot(slot))
    if fork in ("phase0", "altair"):
        return None
    pre = chain.state_for_block(chain.head_root).copy()
    if int(pre.slot) < slot:
        state_advance(pre, spec, slot)
    parent_hash = bytes(pre.latest_execution_payload_header.block_hash)
    block_hash = hashlib.sha256(
        parent_hash + slot.to_bytes(8, "little")).digest()
    cls = {
        "bellatrix": chain.t.ExecutionPayloadBellatrix,
        "capella": chain.t.ExecutionPayloadCapella,
        "deneb": chain.t.ExecutionPayloadDeneb,
        "electra": chain.t.ExecutionPayloadElectra,
    }[fork]
    kw = dict(
        parent_hash=parent_hash,
        prev_randao=misc.get_randao_mix(
            pre, spec, spec.compute_epoch_at_slot(slot)),
        block_number=slot,
        timestamp=int(pre.genesis_time) + slot * spec.seconds_per_slot,
        block_hash=block_hash,
    )
    if fork in ("capella", "deneb", "electra"):
        from lighthouse_tpu.state_transition.block_processing import (
            get_expected_withdrawals,
        )

        kw["withdrawals"] = get_expected_withdrawals(pre, spec)
    return cls(**kw)
