"""ExecutionLayer: engine orchestration with failover.

Rebuild of /root/reference/beacon_node/execution_layer/src/lib.rs +
engines.rs: a primary engine plus fallbacks behind one API; transport
errors rotate to the next healthy engine (the reference's Engines state
machine); payload verification runs as a FUTURE so the beacon state
transition overlaps with the EL's work
(block_verification.rs:1342-1415 — §2.9-5 pipeline parallelism).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

from lighthouse_tpu.execution.engine_api import (
    EngineApiClient,
    EngineApiError,
    EngineConnectionError,
    json_to_payload_kwargs,
    payload_attributes,
)


@dataclass
class PayloadStatus:
    status: str                 # VALID | INVALID | SYNCING | ...
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None

    @property
    def is_valid(self) -> bool:
        return self.status == "VALID"

    @property
    def is_invalid(self) -> bool:
        return self.status in ("INVALID", "INVALID_BLOCK_HASH")

    @property
    def is_optimistic(self) -> bool:
        return self.status in ("SYNCING", "ACCEPTED")


class NoEngineAvailable(EngineApiError):
    pass


class Engine:
    def __init__(self, client: EngineApiClient):
        self.client = client
        self.healthy = True


class ExecutionLayer:
    def __init__(self, engines: list[EngineApiClient],
                 default_fee_recipient: bytes = b"\x00" * 20):
        if not engines:
            raise ValueError("at least one engine endpoint required")
        self.engines = [Engine(c) for c in engines]
        self.default_fee_recipient = default_fee_recipient
        self._pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="engine-api")
        self._lock = threading.Lock()

    # -- failover ----------------------------------------------------------

    def _first_healthy(self) -> list[Engine]:
        ordered = sorted(self.engines, key=lambda e: not e.healthy)
        return ordered

    def _with_failover(self, fn):
        last_err: Exception | None = None
        for engine in self._first_healthy():
            try:
                out = fn(engine.client)
                engine.healthy = True
                return out
            except EngineConnectionError as e:
                engine.healthy = False
                last_err = e
        raise NoEngineAvailable(f"all engines offline: {last_err}")

    # -- API ----------------------------------------------------------------

    def notify_new_payload(self, payload, version: int = 2,
                           versioned_hashes: list[bytes] | None = None,
                           parent_beacon_block_root: bytes | None = None
                           ) -> PayloadStatus:
        def call(client):
            r = client.new_payload(
                payload, version=version,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=parent_beacon_block_root)
            lvh = r.get("latestValidHash")
            return PayloadStatus(
                r["status"],
                bytes.fromhex(lvh[2:]) if lvh else None,
                r.get("validationError"))

        return self._with_failover(call)

    def notify_new_payload_async(self, payload, version: int = 2,
                                 versioned_hashes: list[bytes] | None = None,
                                 parent_beacon_block_root: bytes | None = None
                                 ) -> Future:
        """The payload-verification future joined at import time."""
        return self._pool.submit(
            self.notify_new_payload, payload, version,
            versioned_hashes, parent_beacon_block_root)

    def notify_forkchoice_updated(
        self, head: bytes, safe: bytes, finalized: bytes,
        attributes: dict | None = None, version: int = 2
    ) -> tuple[PayloadStatus, str | None]:
        def call(client):
            r = client.forkchoice_updated(
                head, safe, finalized, attributes, version=version)
            ps = r["payloadStatus"]
            return (PayloadStatus(ps["status"], None,
                                  ps.get("validationError")),
                    r.get("payloadId"))

        return self._with_failover(call)

    def prepare_payload(self, head_block_hash: bytes, timestamp: int,
                        prev_randao: bytes, withdrawals: list | None = None,
                        fee_recipient: bytes | None = None,
                        version: int = 2,
                        parent_beacon_block_root: bytes | None = None
                        ) -> str | None:
        attrs = payload_attributes(
            timestamp, prev_randao,
            fee_recipient or self.default_fee_recipient, withdrawals,
            parent_beacon_block_root if version >= 3 else None)
        _, payload_id = self.notify_forkchoice_updated(
            head_block_hash, head_block_hash, b"\x00" * 32, attrs,
            version=version)
        return payload_id

    def get_payload(self, payload_id: str, payload_cls, version: int = 2):
        def call(client):
            r = client.get_payload(payload_id, version=version)
            obj = r["executionPayload"] if "executionPayload" in r else r
            return payload_cls(**json_to_payload_kwargs(obj))

        return self._with_failover(call)
