"""Execution layer (engine API client, orchestration, mock EL).

Reference: /root/reference/beacon_node/execution_layer.
"""

from lighthouse_tpu.execution.engine_api import (
    EngineApiClient,
    EngineApiError,
    EngineConnectionError,
    jwt_token,
    payload_attributes,
    payload_to_json,
)
from lighthouse_tpu.execution.execution_layer import (
    ExecutionLayer,
    NoEngineAvailable,
    PayloadStatus,
)
from lighthouse_tpu.execution.mock_el import MockExecutionLayer

__all__ = [
    "EngineApiClient",
    "EngineApiError",
    "EngineConnectionError",
    "ExecutionLayer",
    "MockExecutionLayer",
    "NoEngineAvailable",
    "PayloadStatus",
    "jwt_token",
    "payload_attributes",
    "payload_to_json",
]
