"""Engine API JSON-RPC client (consensus ⇄ execution boundary).

Rebuild of /root/reference/beacon_node/execution_layer/src/engine_api/
http.rs:34-47: engine_newPayloadV1-3, engine_forkchoiceUpdatedV1-3,
engine_getPayloadV1-3, engine_exchangeCapabilities over HTTP JSON-RPC
with JWT (HS256) bearer auth.  stdlib only — hmac for the JWT, urllib
for transport.
"""

from __future__ import annotations

import base64
import hmac
import json
import time
import urllib.error
import urllib.request


class EngineApiError(Exception):
    pass


class EngineConnectionError(EngineApiError):
    """Transport-level failure — triggers engine failover."""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def jwt_token(secret: bytes, iat: int | None = None) -> str:
    """HS256 JWT with an iat claim, as the engine API's auth demands."""
    header = _b64url(json.dumps(
        {"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())
    payload = _b64url(json.dumps(
        {"iat": int(iat if iat is not None else time.time())},
        separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret, signing_input, "sha256").digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def _hex(data: bytes) -> str:
    return "0x" + bytes(data).hex()


def _hex_int(value: int) -> str:
    return hex(int(value))


def payload_to_json(payload) -> dict:
    """ExecutionPayload container -> engine API JSON form."""
    out = {
        "parentHash": _hex(payload.parent_hash),
        "feeRecipient": _hex(payload.fee_recipient),
        "stateRoot": _hex(payload.state_root),
        "receiptsRoot": _hex(payload.receipts_root),
        "logsBloom": _hex(payload.logs_bloom),
        "prevRandao": _hex(payload.prev_randao),
        "blockNumber": _hex_int(payload.block_number),
        "gasLimit": _hex_int(payload.gas_limit),
        "gasUsed": _hex_int(payload.gas_used),
        "timestamp": _hex_int(payload.timestamp),
        "extraData": _hex(payload.extra_data),
        "baseFeePerGas": _hex_int(payload.base_fee_per_gas),
        "blockHash": _hex(payload.block_hash),
        "transactions": [_hex(tx) for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [{
            "index": _hex_int(w.index),
            "validatorIndex": _hex_int(w.validator_index),
            "address": _hex(w.address),
            "amount": _hex_int(w.amount),
        } for w in payload.withdrawals]
    if hasattr(payload, "blob_gas_used"):
        out["blobGasUsed"] = _hex_int(payload.blob_gas_used)
        out["excessBlobGas"] = _hex_int(payload.excess_blob_gas)
    return out


def json_to_payload_kwargs(obj: dict) -> dict:
    """Engine API JSON payload -> kwargs for our payload containers."""
    def b(h):
        return bytes.fromhex(h[2:])

    def i(h):
        return int(h, 16)

    kw = dict(
        parent_hash=b(obj["parentHash"]),
        fee_recipient=b(obj["feeRecipient"]),
        state_root=b(obj["stateRoot"]),
        receipts_root=b(obj["receiptsRoot"]),
        logs_bloom=b(obj["logsBloom"]),
        prev_randao=b(obj["prevRandao"]),
        block_number=i(obj["blockNumber"]),
        gas_limit=i(obj["gasLimit"]),
        gas_used=i(obj["gasUsed"]),
        timestamp=i(obj["timestamp"]),
        extra_data=b(obj["extraData"]),
        base_fee_per_gas=i(obj["baseFeePerGas"]),
        block_hash=b(obj["blockHash"]),
        transactions=[b(tx) for tx in obj.get("transactions", [])],
    )
    if "withdrawals" in obj:
        from lighthouse_tpu.types.containers import Withdrawal

        kw["withdrawals"] = [Withdrawal(
            index=i(w["index"]), validator_index=i(w["validatorIndex"]),
            address=b(w["address"]), amount=i(w["amount"]),
        ) for w in obj["withdrawals"]]
    if "blobGasUsed" in obj:
        kw["blob_gas_used"] = i(obj["blobGasUsed"])
        kw["excess_blob_gas"] = i(obj["excessBlobGas"])
    return kw


class EngineApiClient:
    """One execution engine endpoint."""

    def __init__(self, url: str, jwt_secret: bytes, timeout_s: float = 8.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout_s = timeout_s
        self._id = 0

    def __repr__(self) -> str:
        # engine URLs may embed credentials: redact in logs/errors
        from lighthouse_tpu.common.utils import SensitiveUrl

        return f"EngineApiClient({SensitiveUrl(self.url)})"

    def _call(self, method: str, params: list):
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id,
            "method": method, "params": params,
        }).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {jwt_token(self.jwt_secret)}",
            })
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                resp = json.loads(r.read())
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise EngineConnectionError(f"{method}: {e}") from e
        if "error" in resp and resp["error"]:
            raise EngineApiError(
                f"{method}: {resp['error'].get('message')}")
        return resp.get("result")

    # -- engine methods (versioned by fork) -------------------------------

    def exchange_capabilities(self, ours: list[str]) -> list[str]:
        return self._call("engine_exchangeCapabilities", [ours])

    def new_payload(self, payload, version: int = 2,
                    versioned_hashes: list[bytes] | None = None,
                    parent_beacon_block_root: bytes | None = None) -> dict:
        """V3+ requires the blob versioned hashes and the parent beacon
        block root — the EL cross-checks both, so callers must thread the
        real values through (a Deneb block with defaults would be
        rejected by a spec-conforming engine)."""
        params = [payload_to_json(payload)]
        if version >= 3:
            params += [
                [_hex(h) for h in (versioned_hashes or [])],
                _hex(parent_beacon_block_root or b"\x00" * 32),
            ]
        return self._call(f"engine_newPayloadV{version}", params)

    def forkchoice_updated(self, head: bytes, safe: bytes, finalized: bytes,
                           attributes: dict | None = None,
                           version: int = 2) -> dict:
        state = {
            "headBlockHash": _hex(head),
            "safeBlockHash": _hex(safe),
            "finalizedBlockHash": _hex(finalized),
        }
        return self._call(
            f"engine_forkchoiceUpdatedV{version}", [state, attributes])

    def get_payload(self, payload_id: str, version: int = 2) -> dict:
        return self._call(f"engine_getPayloadV{version}", [payload_id])


def payload_attributes(timestamp: int, prev_randao: bytes,
                       fee_recipient: bytes,
                       withdrawals: list | None = None,
                       parent_beacon_block_root: bytes | None = None) -> dict:
    attrs = {
        "timestamp": _hex_int(timestamp),
        "prevRandao": _hex(prev_randao),
        "suggestedFeeRecipient": _hex(fee_recipient),
    }
    if withdrawals is not None:
        attrs["withdrawals"] = [{
            "index": _hex_int(w.index),
            "validatorIndex": _hex_int(w.validator_index),
            "address": _hex(w.address),
            "amount": _hex_int(w.amount),
        } for w in withdrawals]
    if parent_beacon_block_root is not None:
        # PayloadAttributesV3 (Deneb+): a conforming engine rejects
        # attributes without this field
        attrs["parentBeaconBlockRoot"] = _hex(parent_beacon_block_root)
    return attrs
