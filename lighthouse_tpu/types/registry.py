"""Columnar (struct-of-arrays) state collections.

The reference reaches ~1M validators by wrapping every list in persistent
tree structures with interior hash caches (milhouse "tree-states",
/root/reference/consensus/types/src/beacon_state.rs:216-224).  A TPU-native
design inverts that: the validator registry, balances, participation flags
and inactivity scores live as flat numpy columns, so

- epoch processing is vectorized arithmetic over whole columns (one fused
  XLA program instead of a per-validator walk, reference single_pass.rs);
- merkleization builds all leaf chunks with numpy reshapes and runs the
  whole forest through the batched SHA-256 device kernel.

Object views (`Validator` containers) are materialized only at the API
boundary.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from lighthouse_tpu.ops import sha256 as sha_ops
from lighthouse_tpu.ssz import core as ssz_core
from lighthouse_tpu.ssz.core import SSZType, _batch_merkleize_subtrees


def _u64_chunks(arr: np.ndarray) -> np.ndarray:
    """uint64[N] -> uint32[N, 8] SSZ chunk words (LE value, BE word order)."""
    n = arr.shape[0]
    chunk = np.zeros((n, 32), dtype=np.uint8)
    chunk[:, :8] = arr.astype("<u8").view(np.uint8).reshape(n, 8)
    return np.frombuffer(chunk.tobytes(), dtype=">u4").astype(np.uint32).reshape(n, 8)


def _bytes_col_chunks(col: np.ndarray, width: int) -> np.ndarray:
    """uint8[N, width<=32] -> uint32[N, 8] chunk words."""
    n = col.shape[0]
    chunk = np.zeros((n, 32), dtype=np.uint8)
    chunk[:, :width] = col
    return np.frombuffer(chunk.tobytes(), dtype=">u4").astype(np.uint32).reshape(n, 8)


def _pack_bytes_to_chunk_words(data: bytes, n_chunks: int) -> np.ndarray:
    buf = np.zeros(n_chunks * 32, dtype=np.uint8)
    raw = np.frombuffer(data, dtype=np.uint8)
    buf[: raw.shape[0]] = raw
    return np.frombuffer(buf.tobytes(), dtype=">u4").astype(np.uint32).reshape(n_chunks, 8)


class U64List(SSZType):
    """SSZ List[uint64, limit] stored as a numpy uint64 column."""

    def __init__(self, limit: int):
        self.limit = limit
        self.fixed_size = None

    def _as_array(self, value) -> np.ndarray:
        arr = np.asarray(value, dtype=np.uint64)
        if arr.ndim != 1:
            raise ValueError("U64List expects a 1-D sequence")
        if arr.shape[0] > self.limit:
            raise ValueError(f"U64List over limit {self.limit}")
        return arr

    def serialize(self, value) -> bytes:
        return self._as_array(value).astype("<u8").tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        if len(data) % 8:
            raise ValueError("u64 list misalignment")
        arr = np.frombuffer(data, dtype="<u8").astype(np.uint64)
        if arr.shape[0] > self.limit:
            raise ValueError("U64List over limit")
        return arr

    def chunk_count(self) -> int:
        return (self.limit * 8 + 31) // 32

    def hash_tree_root(self, value) -> bytes:
        arr = self._as_array(value)
        n = arr.shape[0]
        n_chunks = (n + 3) // 4
        padded = np.zeros(n_chunks * 4, dtype=np.uint64)
        padded[:n] = arr
        raw = padded.astype("<u8").tobytes()
        words = np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(n_chunks, 8)
        root = sha_ops.merkleize_words(words, self.chunk_count())
        return sha_ops.mix_in_length(sha_ops.words_to_bytes(root), n)

    def default(self) -> np.ndarray:
        return np.zeros(0, dtype=np.uint64)

    def __repr__(self):
        return f"U64List[{self.limit}]"


class U64Vector(SSZType):
    """SSZ Vector[uint64, length] as a numpy column (e.g. slashings)."""

    def __init__(self, length: int):
        self.length = length
        self.fixed_size = 8 * length

    def serialize(self, value) -> bytes:
        arr = np.asarray(value, dtype=np.uint64)
        if arr.shape != (self.length,):
            raise ValueError(f"U64Vector length {self.length} mismatch")
        return arr.astype("<u8").tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        if len(data) != self.fixed_size:
            raise ValueError("U64Vector size mismatch")
        return np.frombuffer(data, dtype="<u8").astype(np.uint64)

    def chunk_count(self) -> int:
        return (self.length * 8 + 31) // 32

    def hash_tree_root(self, value) -> bytes:
        arr = np.asarray(value, dtype=np.uint64)
        n_chunks = self.chunk_count()
        padded = np.zeros(n_chunks * 4, dtype=np.uint64)
        padded[: arr.shape[0]] = arr
        raw = padded.astype("<u8").tobytes()
        words = np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(n_chunks, 8)
        return sha_ops.words_to_bytes(sha_ops.merkleize_words(words, n_chunks))

    def default(self) -> np.ndarray:
        return np.zeros(self.length, dtype=np.uint64)

    def __repr__(self):
        return f"U64Vector[{self.length}]"


class U8List(SSZType):
    """SSZ List[uint8, limit] as a numpy column (participation flags)."""

    def __init__(self, limit: int):
        self.limit = limit
        self.fixed_size = None

    def serialize(self, value) -> bytes:
        arr = np.asarray(value, dtype=np.uint8)
        if arr.shape[0] > self.limit:
            raise ValueError("U8List over limit")
        return arr.tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        if len(data) > self.limit:
            raise ValueError("U8List over limit")
        return np.frombuffer(data, dtype=np.uint8).copy()

    def chunk_count(self) -> int:
        return (self.limit + 31) // 32

    def hash_tree_root(self, value) -> bytes:
        arr = np.asarray(value, dtype=np.uint8)
        n = arr.shape[0]
        n_chunks = max((n + 31) // 32, 1) if n else 0
        words = _pack_bytes_to_chunk_words(arr.tobytes(), n_chunks) if n else np.zeros((0, 8), np.uint32)
        root = sha_ops.merkleize_words(words, self.chunk_count())
        return sha_ops.mix_in_length(sha_ops.words_to_bytes(root), n)

    def default(self) -> np.ndarray:
        return np.zeros(0, dtype=np.uint8)

    def __repr__(self):
        return f"U8List[{self.limit}]"


class RootsVector(SSZType):
    """SSZ Vector[Bytes32, length] as uint8[length, 32] (block/state roots,
    randao mixes)."""

    def __init__(self, length: int):
        self.length = length
        self.fixed_size = 32 * length

    def serialize(self, value) -> bytes:
        arr = self._as_array(value)
        return arr.tobytes()

    def _as_array(self, value) -> np.ndarray:
        if isinstance(value, np.ndarray):
            arr = value
        else:
            arr = np.frombuffer(b"".join(value), dtype=np.uint8).reshape(-1, 32)
        if arr.shape != (self.length, 32):
            raise ValueError(f"RootsVector shape {arr.shape} != ({self.length}, 32)")
        return np.ascontiguousarray(arr, dtype=np.uint8)

    def deserialize(self, data: bytes) -> np.ndarray:
        if len(data) != self.fixed_size:
            raise ValueError("RootsVector size mismatch")
        return np.frombuffer(data, dtype=np.uint8).reshape(self.length, 32).copy()

    def chunk_count(self) -> int:
        return self.length

    def hash_tree_root(self, value) -> bytes:
        arr = self._as_array(value)
        words = np.frombuffer(arr.tobytes(), dtype=">u4").astype(np.uint32).reshape(self.length, 8)
        return sha_ops.words_to_bytes(sha_ops.merkleize_words(words, self.length))

    def default(self) -> np.ndarray:
        return np.zeros((self.length, 32), dtype=np.uint8)

    def __repr__(self):
        return f"RootsVector[{self.length}]"


class RootsList(SSZType):
    """SSZ List[Bytes32, limit] as uint8[n, 32] (historical roots, etc.)."""

    def __init__(self, limit: int):
        self.limit = limit
        self.fixed_size = None

    def _as_array(self, value) -> np.ndarray:
        if isinstance(value, np.ndarray):
            arr = value.reshape(-1, 32)
        elif len(value) == 0:
            arr = np.zeros((0, 32), dtype=np.uint8)
        else:
            arr = np.frombuffer(b"".join(value), dtype=np.uint8).reshape(-1, 32)
        if arr.shape[0] > self.limit:
            raise ValueError("RootsList over limit")
        return np.ascontiguousarray(arr, dtype=np.uint8)

    def serialize(self, value) -> bytes:
        return self._as_array(value).tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        if len(data) % 32:
            raise ValueError("RootsList misalignment")
        return np.frombuffer(data, dtype=np.uint8).reshape(-1, 32).copy()

    def chunk_count(self) -> int:
        return self.limit

    def hash_tree_root(self, value) -> bytes:
        arr = self._as_array(value)
        n = arr.shape[0]
        words = (
            np.frombuffer(arr.tobytes(), dtype=">u4").astype(np.uint32).reshape(n, 8)
            if n
            else np.zeros((0, 8), np.uint32)
        )
        root = sha_ops.merkleize_words(words, self.limit)
        return sha_ops.mix_in_length(sha_ops.words_to_bytes(root), n)

    def default(self) -> np.ndarray:
        return np.zeros((0, 32), dtype=np.uint8)

    def __repr__(self):
        return f"RootsList[{self.limit}]"


# ---------------------------------------------------------------------------
# Validator registry
# ---------------------------------------------------------------------------

_VALIDATOR_RECORD_SIZE = 48 + 32 + 8 + 1 + 8 * 4  # = 121 bytes, SSZ field order


class Validators:
    """Columnar validator registry (mutable, numpy-backed).

    Columns are views into capacity-doubled backing arrays so `append`
    (one per deposit) is amortized O(1) — a deposit flood grows the
    registry linearly, not quadratically.  Element and mask writes go
    through the views; whole-column replacement uses the setters.
    """

    _COLUMNS = (
        "pubkeys",
        "withdrawal_credentials",
        "effective_balance",
        "slashed",
        "activation_eligibility_epoch",
        "activation_epoch",
        "exit_epoch",
        "withdrawable_epoch",
    )

    __slots__ = tuple("_" + c for c in _COLUMNS) + ("_n",)

    def __init__(self, n: int = 0):
        self._n = n
        self._pubkeys = np.zeros((n, 48), dtype=np.uint8)
        self._withdrawal_credentials = np.zeros((n, 32), dtype=np.uint8)
        self._effective_balance = np.zeros(n, dtype=np.uint64)
        self._slashed = np.zeros(n, dtype=bool)
        self._activation_eligibility_epoch = np.zeros(n, dtype=np.uint64)
        self._activation_epoch = np.zeros(n, dtype=np.uint64)
        self._exit_epoch = np.zeros(n, dtype=np.uint64)
        self._withdrawable_epoch = np.zeros(n, dtype=np.uint64)

    def __len__(self) -> int:
        return self._n

    def _grow_to(self, cap: int) -> None:
        for c in self._COLUMNS:
            backing = getattr(self, "_" + c)
            shape = (cap,) + backing.shape[1:]
            new = np.zeros(shape, dtype=backing.dtype)
            new[: self._n] = backing[: self._n]
            setattr(self, "_" + c, new)

    def append(
        self,
        *,
        pubkey: bytes,
        withdrawal_credentials: bytes,
        effective_balance: int,
        slashed: bool = False,
        activation_eligibility_epoch: int,
        activation_epoch: int,
        exit_epoch: int,
        withdrawable_epoch: int,
    ) -> None:
        if self._n == self._effective_balance.shape[0]:
            self._grow_to(max(64, 2 * self._n))
        i = self._n
        self._pubkeys[i] = np.frombuffer(pubkey, dtype=np.uint8)
        self._withdrawal_credentials[i] = np.frombuffer(
            withdrawal_credentials, dtype=np.uint8)
        self._effective_balance[i] = effective_balance
        self._slashed[i] = bool(slashed)
        self._activation_eligibility_epoch[i] = activation_eligibility_epoch
        self._activation_epoch[i] = activation_epoch
        self._exit_epoch[i] = exit_epoch
        self._withdrawable_epoch[i] = withdrawable_epoch
        self._n = i + 1

    def copy(self) -> "Validators":
        out = Validators(0)
        out._n = self._n
        for c in self._COLUMNS:
            setattr(out, "_" + c, getattr(self, c).copy())
        return out

    def __eq__(self, other) -> bool:
        return isinstance(other, Validators) and all(
            np.array_equal(getattr(self, f), getattr(other, f))
            for f in self._COLUMNS
        )

    def is_active(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return (self.activation_epoch <= e) & (e < self.exit_epoch)

    # Column views (length-n windows over the capacity arrays) are added
    # below the class body via _install_column_views().

    def is_eligible_for_activation_queue(self, max_effective_balance: int) -> np.ndarray:
        from lighthouse_tpu.types.spec import FAR_FUTURE_EPOCH

        return (self.activation_eligibility_epoch == np.uint64(FAR_FUTURE_EPOCH)) & (
            self.effective_balance == np.uint64(max_effective_balance)
        )

    def is_slashable(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return (
            ~self.slashed
            & (self.activation_epoch <= e)
            & (e < self.withdrawable_epoch)
        )


def _install_column_views() -> None:
    def make(col: str) -> property:
        backing = "_" + col

        def get(self):
            return getattr(self, backing)[: self._n]

        def set_(self, value):
            view = getattr(self, backing)[: self._n]
            arr = np.asarray(value, dtype=view.dtype)
            if arr.shape != view.shape:
                raise ValueError(
                    f"{col}: column assignment must keep shape {view.shape}, "
                    f"got {arr.shape}")
            view[...] = arr

        return property(get, set_)

    for c in Validators._COLUMNS:
        setattr(Validators, c, make(c))


_install_column_views()


class ValidatorRegistryType(SSZType):
    """SSZ List[Validator, limit] over the columnar `Validators` store."""

    def __init__(self, limit: int, validator_container=None):
        self.limit = limit
        self.fixed_size = None
        self.validator_container = validator_container  # object-view class

    def serialize(self, value: Validators) -> bytes:
        n = len(value)
        rec = np.zeros((n, _VALIDATOR_RECORD_SIZE), dtype=np.uint8)
        rec[:, 0:48] = value.pubkeys
        rec[:, 48:80] = value.withdrawal_credentials
        rec[:, 80:88] = value.effective_balance.astype("<u8").view(np.uint8).reshape(n, 8)
        rec[:, 88] = value.slashed.astype(np.uint8)
        off = 89
        for col in (
            value.activation_eligibility_epoch,
            value.activation_epoch,
            value.exit_epoch,
            value.withdrawable_epoch,
        ):
            rec[:, off: off + 8] = col.astype("<u8").view(np.uint8).reshape(n, 8)
            off += 8
        return rec.tobytes()

    def deserialize(self, data: bytes) -> Validators:
        if len(data) % _VALIDATOR_RECORD_SIZE:
            raise ValueError("validator record misalignment")
        n = len(data) // _VALIDATOR_RECORD_SIZE
        if n > self.limit:
            raise ValueError("registry over limit")
        rec = np.frombuffer(data, dtype=np.uint8).reshape(n, _VALIDATOR_RECORD_SIZE)
        out = Validators(n)
        out.pubkeys = rec[:, 0:48].copy()
        out.withdrawal_credentials = rec[:, 48:80].copy()
        out.effective_balance = rec[:, 80:88].copy().view("<u8").reshape(n).astype(np.uint64)
        bad = rec[:, 88] > 1
        if bad.any():
            raise ValueError("invalid slashed boolean")
        out.slashed = rec[:, 88] == 1
        off = 89
        for name in (
            "activation_eligibility_epoch",
            "activation_epoch",
            "exit_epoch",
            "withdrawable_epoch",
        ):
            setattr(out, name, rec[:, off: off + 8].copy().view("<u8").reshape(n).astype(np.uint64))
            off += 8
        return out

    def chunk_count(self) -> int:
        return self.limit

    def batch_roots(self, value: Validators) -> np.ndarray:
        """All validator roots as one lockstep device merkleization."""
        n = len(value)
        if n == 0:
            return np.zeros((0, 8), dtype=np.uint32)
        # pubkey (48B) root needs one pre-hash of its 2 chunks
        pk = np.zeros((n, 64), dtype=np.uint8)
        pk[:, :48] = value.pubkeys
        pk_pairs = np.frombuffer(pk.tobytes(), dtype=">u4").astype(np.uint32).reshape(n, 16)
        pk_roots = sha_ops.batch_hash_pairs(pk_pairs)
        leaves = np.zeros((n, 8, 8), dtype=np.uint32)
        leaves[:, 0] = pk_roots
        leaves[:, 1] = _bytes_col_chunks(value.withdrawal_credentials, 32)
        leaves[:, 2] = _u64_chunks(value.effective_balance)
        leaves[:, 3] = _bytes_col_chunks(
            value.slashed.astype(np.uint8).reshape(n, 1), 1
        )
        leaves[:, 4] = _u64_chunks(value.activation_eligibility_epoch)
        leaves[:, 5] = _u64_chunks(value.activation_epoch)
        leaves[:, 6] = _u64_chunks(value.exit_epoch)
        leaves[:, 7] = _u64_chunks(value.withdrawable_epoch)
        return _batch_merkleize_subtrees(leaves)

    def hash_tree_root(self, value: Validators) -> bytes:
        roots = self.batch_roots(value)
        root = sha_ops.merkleize_words(roots, self.limit)
        return sha_ops.mix_in_length(sha_ops.words_to_bytes(root), len(value))

    def default(self) -> Validators:
        return Validators(0)

    def __repr__(self):
        return f"ValidatorRegistry[{self.limit}]"
