"""Consensus containers, multi-fork, parameterized by preset.

Reference equivalent: /root/reference/consensus/types/src/*.rs, where the
`superstruct` macro generates Base/Altair/Bellatrix/Capella/Deneb variants
(beacon_state.rs:225, beacon_block_body, execution_payload).  Here fork
variants are explicit classes produced by `make_types(preset)`; big state
columns use the columnar numpy-backed SSZ types from
lighthouse_tpu.types.registry so epoch processing and merkleization stay
vectorized (TPU-first).

Field orders follow the consensus spec exactly — they are consensus-critical
(hash_tree_root depends on them).
"""

from functools import lru_cache
from types import SimpleNamespace

from lighthouse_tpu import ssz
from lighthouse_tpu.types.registry import (
    RootsList,
    RootsVector,
    U8List,
    U64List,
    U64Vector,
    ValidatorRegistryType,
    Validators,
)
from lighthouse_tpu.types.spec import Preset

DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4
KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = 17


# --- preset-independent containers -----------------------------------------

class Fork(ssz.Container):
    previous_version: ssz.Bytes4
    current_version: ssz.Bytes4
    epoch: ssz.uint64


class ForkData(ssz.Container):
    current_version: ssz.Bytes4
    genesis_validators_root: ssz.Bytes32


class Checkpoint(ssz.Container):
    epoch: ssz.uint64
    root: ssz.Bytes32


class Validator(ssz.Container):
    """Object view of one registry row (columnar store: registry.Validators)."""

    pubkey: ssz.Bytes48
    withdrawal_credentials: ssz.Bytes32
    effective_balance: ssz.uint64
    slashed: ssz.boolean
    activation_eligibility_epoch: ssz.uint64
    activation_epoch: ssz.uint64
    exit_epoch: ssz.uint64
    withdrawable_epoch: ssz.uint64


class AttestationData(ssz.Container):
    slot: ssz.uint64
    index: ssz.uint64
    beacon_block_root: ssz.Bytes32
    source: Checkpoint
    target: Checkpoint


class SigningData(ssz.Container):
    object_root: ssz.Bytes32
    domain: ssz.Bytes32


class BeaconBlockHeader(ssz.Container):
    slot: ssz.uint64
    proposer_index: ssz.uint64
    parent_root: ssz.Bytes32
    state_root: ssz.Bytes32
    body_root: ssz.Bytes32


class SignedBeaconBlockHeader(ssz.Container):
    message: BeaconBlockHeader
    signature: ssz.Bytes96


class Eth1Data(ssz.Container):
    deposit_root: ssz.Bytes32
    deposit_count: ssz.uint64
    block_hash: ssz.Bytes32


class DepositMessage(ssz.Container):
    pubkey: ssz.Bytes48
    withdrawal_credentials: ssz.Bytes32
    amount: ssz.uint64


class DepositData(ssz.Container):
    pubkey: ssz.Bytes48
    withdrawal_credentials: ssz.Bytes32
    amount: ssz.uint64
    signature: ssz.Bytes96


class Deposit(ssz.Container):
    proof: ssz.Vector(ssz.Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1)
    data: DepositData


class VoluntaryExit(ssz.Container):
    epoch: ssz.uint64
    validator_index: ssz.uint64


class SignedVoluntaryExit(ssz.Container):
    message: VoluntaryExit
    signature: ssz.Bytes96


class ProposerSlashing(ssz.Container):
    signed_header_1: SignedBeaconBlockHeader
    signed_header_2: SignedBeaconBlockHeader


class Withdrawal(ssz.Container):
    index: ssz.uint64
    validator_index: ssz.uint64
    address: ssz.Bytes20
    amount: ssz.uint64


class BLSToExecutionChange(ssz.Container):
    validator_index: ssz.uint64
    from_bls_pubkey: ssz.Bytes48
    to_execution_address: ssz.Bytes20


class SignedBLSToExecutionChange(ssz.Container):
    message: BLSToExecutionChange
    signature: ssz.Bytes96


class HistoricalSummary(ssz.Container):
    block_summary_root: ssz.Bytes32
    state_summary_root: ssz.Bytes32


class SyncCommitteeMessage(ssz.Container):
    slot: ssz.uint64
    beacon_block_root: ssz.Bytes32
    validator_index: ssz.uint64
    signature: ssz.Bytes96


class SyncAggregatorSelectionData(ssz.Container):
    slot: ssz.uint64
    subcommittee_index: ssz.uint64


class Eth1Block(ssz.Container):
    timestamp: ssz.uint64
    deposit_root: ssz.Bytes32
    deposit_count: ssz.uint64


# --- electra containers (reference consensus/types/src/{pending_balance_
# deposit,pending_partial_withdrawal,pending_consolidation,consolidation,
# deposit_request,execution_layer_withdrawal_request}.rs) -------------------

class PendingBalanceDeposit(ssz.Container):
    index: ssz.uint64
    amount: ssz.uint64


class PendingPartialWithdrawal(ssz.Container):
    index: ssz.uint64
    amount: ssz.uint64
    withdrawable_epoch: ssz.uint64


class PendingConsolidation(ssz.Container):
    source_index: ssz.uint64
    target_index: ssz.uint64


class Consolidation(ssz.Container):
    source_index: ssz.uint64
    target_index: ssz.uint64
    epoch: ssz.uint64


class SignedConsolidation(ssz.Container):
    message: Consolidation
    signature: ssz.Bytes96


class DepositRequest(ssz.Container):
    pubkey: ssz.Bytes48
    withdrawal_credentials: ssz.Bytes32
    amount: ssz.uint64
    signature: ssz.Bytes96
    index: ssz.uint64


class ExecutionLayerWithdrawalRequest(ssz.Container):
    source_address: ssz.Bytes20
    validator_pubkey: ssz.Bytes48
    amount: ssz.uint64


def _container(name: str, field_specs: list[tuple[str, object]], doc: str = ""):
    """Build an ssz.Container subclass with exact field order."""
    ns = {"__annotations__": {f: t for f, t in field_specs}}
    if doc:
        ns["__doc__"] = doc
    return type(name, (ssz.Container,), ns)


@lru_cache(maxsize=4)
def make_types(preset: Preset) -> SimpleNamespace:
    """All preset-dependent containers for every fork, as a namespace.

    Access pattern: ``t = make_types(spec.preset); t.AttestationPhase0`` …
    Fork-variant lookup helpers: ``t.beacon_state_class('capella')``.
    """
    P = preset
    validators_per_slot = P.max_validators_per_committee * P.max_committees_per_slot

    IndexedAttestation = _container("IndexedAttestation", [
        ("attesting_indices", U64List(P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", ssz.Bytes96),
    ])

    PendingAttestation = _container("PendingAttestation", [
        ("aggregation_bits", ssz.Bitlist(P.max_validators_per_committee)),
        ("data", AttestationData),
        ("inclusion_delay", ssz.uint64),
        ("proposer_index", ssz.uint64),
    ])

    Attestation = _container("Attestation", [
        ("aggregation_bits", ssz.Bitlist(P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", ssz.Bytes96),
    ])

    AttesterSlashing = _container("AttesterSlashing", [
        ("attestation_1", IndexedAttestation),
        ("attestation_2", IndexedAttestation),
    ])

    # electra (EIP-7549): attestations span every committee of the slot;
    # committee membership moves from data.index to committee_bits
    # (reference attestation.rs superstruct Electra variant — note this
    # snapshot's field order places committee_bits BEFORE signature)
    AttestationElectra = _container("AttestationElectra", [
        ("aggregation_bits", ssz.Bitlist(validators_per_slot)),
        ("data", AttestationData),
        ("committee_bits", ssz.Bitvector(P.max_committees_per_slot)),
        ("signature", ssz.Bytes96),
    ])

    IndexedAttestationElectra = _container("IndexedAttestationElectra", [
        ("attesting_indices", U64List(validators_per_slot)),
        ("data", AttestationData),
        ("signature", ssz.Bytes96),
    ])

    AttesterSlashingElectra = _container("AttesterSlashingElectra", [
        ("attestation_1", IndexedAttestationElectra),
        ("attestation_2", IndexedAttestationElectra),
    ])

    AggregateAndProof = _container("AggregateAndProof", [
        ("aggregator_index", ssz.uint64),
        ("aggregate", Attestation),
        ("selection_proof", ssz.Bytes96),
    ])

    SignedAggregateAndProof = _container("SignedAggregateAndProof", [
        ("message", AggregateAndProof),
        ("signature", ssz.Bytes96),
    ])

    AggregateAndProofElectra = _container("AggregateAndProofElectra", [
        ("aggregator_index", ssz.uint64),
        ("aggregate", AttestationElectra),
        ("selection_proof", ssz.Bytes96),
    ])

    SignedAggregateAndProofElectra = _container(
        "SignedAggregateAndProofElectra", [
            ("message", AggregateAndProofElectra),
            ("signature", ssz.Bytes96),
        ])

    SyncAggregate = _container("SyncAggregate", [
        ("sync_committee_bits", ssz.Bitvector(P.sync_committee_size)),
        ("sync_committee_signature", ssz.Bytes96),
    ])

    SyncCommittee = _container("SyncCommittee", [
        ("pubkeys", ssz.Vector(ssz.Bytes48, P.sync_committee_size)),
        ("aggregate_pubkey", ssz.Bytes48),
    ])

    SyncCommitteeContribution = _container("SyncCommitteeContribution", [
        ("slot", ssz.uint64),
        ("beacon_block_root", ssz.Bytes32),
        ("subcommittee_index", ssz.uint64),
        ("aggregation_bits", ssz.Bitvector(P.sync_committee_size // 4)),
        ("signature", ssz.Bytes96),
    ])

    ContributionAndProof = _container("ContributionAndProof", [
        ("aggregator_index", ssz.uint64),
        ("contribution", SyncCommitteeContribution),
        ("selection_proof", ssz.Bytes96),
    ])

    SignedContributionAndProof = _container("SignedContributionAndProof", [
        ("message", ContributionAndProof),
        ("signature", ssz.Bytes96),
    ])

    Transactions = ssz.List(
        ssz.ByteList(P.max_bytes_per_transaction), P.max_transactions_per_payload
    )

    _payload_base = [
        ("parent_hash", ssz.Bytes32),
        ("fee_recipient", ssz.Bytes20),
        ("state_root", ssz.Bytes32),
        ("receipts_root", ssz.Bytes32),
        ("logs_bloom", ssz.ByteVector(P.bytes_per_logs_bloom)),
        ("prev_randao", ssz.Bytes32),
        ("block_number", ssz.uint64),
        ("gas_limit", ssz.uint64),
        ("gas_used", ssz.uint64),
        ("timestamp", ssz.uint64),
        ("extra_data", ssz.ByteList(P.max_extra_data_bytes)),
        ("base_fee_per_gas", ssz.uint256),
        ("block_hash", ssz.Bytes32),
    ]
    _withdrawals = ("withdrawals", ssz.List(Withdrawal, P.max_withdrawals_per_payload))
    _blob_gas = [("blob_gas_used", ssz.uint64), ("excess_blob_gas", ssz.uint64)]

    ExecutionPayloadBellatrix = _container(
        "ExecutionPayloadBellatrix", _payload_base + [("transactions", Transactions)]
    )
    ExecutionPayloadCapella = _container(
        "ExecutionPayloadCapella",
        _payload_base + [("transactions", Transactions), _withdrawals],
    )
    ExecutionPayloadDeneb = _container(
        "ExecutionPayloadDeneb",
        _payload_base + [("transactions", Transactions), _withdrawals] + _blob_gas,
    )
    _el_requests = [
        ("deposit_requests", ssz.List(
            DepositRequest, P.max_deposit_requests_per_payload)),
        ("withdrawal_requests", ssz.List(
            ExecutionLayerWithdrawalRequest,
            P.max_withdrawal_requests_per_payload)),
    ]
    ExecutionPayloadElectra = _container(
        "ExecutionPayloadElectra",
        _payload_base + [("transactions", Transactions), _withdrawals]
        + _blob_gas + _el_requests,
    )

    _header_mid = [("transactions_root", ssz.Bytes32)]
    ExecutionPayloadHeaderBellatrix = _container(
        "ExecutionPayloadHeaderBellatrix", _payload_base + _header_mid
    )
    ExecutionPayloadHeaderCapella = _container(
        "ExecutionPayloadHeaderCapella",
        _payload_base + _header_mid + [("withdrawals_root", ssz.Bytes32)],
    )
    ExecutionPayloadHeaderDeneb = _container(
        "ExecutionPayloadHeaderDeneb",
        _payload_base + _header_mid + [("withdrawals_root", ssz.Bytes32)] + _blob_gas,
    )
    ExecutionPayloadHeaderElectra = _container(
        "ExecutionPayloadHeaderElectra",
        _payload_base + _header_mid + [("withdrawals_root", ssz.Bytes32)]
        + _blob_gas + [("deposit_requests_root", ssz.Bytes32),
                       ("withdrawal_requests_root", ssz.Bytes32)],
    )

    KzgCommitments = ssz.List(ssz.Bytes48, P.max_blob_commitments_per_block)

    # --- block bodies per fork ------------------------------------------

    _body_base = [
        ("randao_reveal", ssz.Bytes96),
        ("eth1_data", Eth1Data),
        ("graffiti", ssz.Bytes32),
        ("proposer_slashings", ssz.List(ProposerSlashing, P.max_proposer_slashings)),
        ("attester_slashings", ssz.List(AttesterSlashing, P.max_attester_slashings)),
        ("attestations", ssz.List(Attestation, P.max_attestations)),
        ("deposits", ssz.List(Deposit, P.max_deposits)),
        ("voluntary_exits", ssz.List(SignedVoluntaryExit, P.max_voluntary_exits)),
    ]
    _sync = ("sync_aggregate", SyncAggregate)
    _blschanges = (
        "bls_to_execution_changes",
        ssz.List(SignedBLSToExecutionChange, P.max_bls_to_execution_changes),
    )

    BeaconBlockBodyPhase0 = _container("BeaconBlockBodyPhase0", list(_body_base))
    BeaconBlockBodyAltair = _container("BeaconBlockBodyAltair", _body_base + [_sync])
    BeaconBlockBodyBellatrix = _container(
        "BeaconBlockBodyBellatrix",
        _body_base + [_sync, ("execution_payload", ExecutionPayloadBellatrix)],
    )
    BeaconBlockBodyCapella = _container(
        "BeaconBlockBodyCapella",
        _body_base
        + [_sync, ("execution_payload", ExecutionPayloadCapella), _blschanges],
    )
    BeaconBlockBodyDeneb = _container(
        "BeaconBlockBodyDeneb",
        _body_base
        + [
            _sync,
            ("execution_payload", ExecutionPayloadDeneb),
            _blschanges,
            ("blob_kzg_commitments", KzgCommitments),
        ],
    )
    # electra body: base ops swap to the electra attestation containers
    # with their own (smaller) per-block limits; consolidations appended
    # (reference beacon_block_body.rs Electra variant)
    _body_base_electra = [
        spec if spec[0] not in ("attester_slashings", "attestations") else (
            ("attester_slashings", ssz.List(
                AttesterSlashingElectra, P.max_attester_slashings_electra))
            if spec[0] == "attester_slashings"
            else ("attestations", ssz.List(
                AttestationElectra, P.max_attestations_electra)))
        for spec in _body_base
    ]
    BeaconBlockBodyElectra = _container(
        "BeaconBlockBodyElectra",
        _body_base_electra
        + [
            _sync,
            ("execution_payload", ExecutionPayloadElectra),
            _blschanges,
            ("blob_kzg_commitments", KzgCommitments),
            ("consolidations", ssz.List(
                SignedConsolidation, P.max_consolidations)),
        ],
    )

    def _block(name, body_cls):
        return _container(name, [
            ("slot", ssz.uint64),
            ("proposer_index", ssz.uint64),
            ("parent_root", ssz.Bytes32),
            ("state_root", ssz.Bytes32),
            ("body", body_cls),
        ])

    BeaconBlockPhase0 = _block("BeaconBlockPhase0", BeaconBlockBodyPhase0)
    BeaconBlockAltair = _block("BeaconBlockAltair", BeaconBlockBodyAltair)
    BeaconBlockBellatrix = _block("BeaconBlockBellatrix", BeaconBlockBodyBellatrix)
    BeaconBlockCapella = _block("BeaconBlockCapella", BeaconBlockBodyCapella)
    BeaconBlockDeneb = _block("BeaconBlockDeneb", BeaconBlockBodyDeneb)
    BeaconBlockElectra = _block("BeaconBlockElectra", BeaconBlockBodyElectra)

    def _signed(name, block_cls):
        return _container(name, [
            ("message", block_cls),
            ("signature", ssz.Bytes96),
        ])

    SignedBeaconBlockPhase0 = _signed("SignedBeaconBlockPhase0", BeaconBlockPhase0)
    SignedBeaconBlockAltair = _signed("SignedBeaconBlockAltair", BeaconBlockAltair)
    SignedBeaconBlockBellatrix = _signed("SignedBeaconBlockBellatrix", BeaconBlockBellatrix)
    SignedBeaconBlockCapella = _signed("SignedBeaconBlockCapella", BeaconBlockCapella)
    SignedBeaconBlockDeneb = _signed("SignedBeaconBlockDeneb", BeaconBlockDeneb)
    SignedBeaconBlockElectra = _signed("SignedBeaconBlockElectra", BeaconBlockElectra)

    # --- blinded blocks (builder/MEV path) --------------------------------
    # The body swaps execution_payload for its HEADER; since an
    # ExecutionPayloadHeader's hash_tree_root equals the payload's (the
    # header IS the payload's field-root vector), a blinded block's
    # hash_tree_root — hence its signing root — equals the full block's
    # (reference consensus/types/src/beacon_block_body.rs blinded variants)

    def _blinded_body(name, full_body_cls, header_cls):
        # derive from the BUILT full body so the field lists can never
        # drift (the root-equality invariant depends on identical order)
        return _container(name, [
            ("execution_payload_header", header_cls)
            if fname == "execution_payload" else (fname, ftype)
            for fname, ftype in full_body_cls.fields.items()])

    BlindedBeaconBlockBodyBellatrix = _blinded_body(
        "BlindedBeaconBlockBodyBellatrix", BeaconBlockBodyBellatrix,
        ExecutionPayloadHeaderBellatrix)
    BlindedBeaconBlockBodyCapella = _blinded_body(
        "BlindedBeaconBlockBodyCapella", BeaconBlockBodyCapella,
        ExecutionPayloadHeaderCapella)
    BlindedBeaconBlockBodyDeneb = _blinded_body(
        "BlindedBeaconBlockBodyDeneb", BeaconBlockBodyDeneb,
        ExecutionPayloadHeaderDeneb)
    BlindedBeaconBlockBodyElectra = _blinded_body(
        "BlindedBeaconBlockBodyElectra", BeaconBlockBodyElectra,
        ExecutionPayloadHeaderElectra)

    BlindedBeaconBlockBellatrix = _block(
        "BlindedBeaconBlockBellatrix", BlindedBeaconBlockBodyBellatrix)
    BlindedBeaconBlockCapella = _block(
        "BlindedBeaconBlockCapella", BlindedBeaconBlockBodyCapella)
    BlindedBeaconBlockDeneb = _block(
        "BlindedBeaconBlockDeneb", BlindedBeaconBlockBodyDeneb)
    BlindedBeaconBlockElectra = _block(
        "BlindedBeaconBlockElectra", BlindedBeaconBlockBodyElectra)

    SignedBlindedBeaconBlockBellatrix = _signed(
        "SignedBlindedBeaconBlockBellatrix", BlindedBeaconBlockBellatrix)
    SignedBlindedBeaconBlockCapella = _signed(
        "SignedBlindedBeaconBlockCapella", BlindedBeaconBlockCapella)
    SignedBlindedBeaconBlockDeneb = _signed(
        "SignedBlindedBeaconBlockDeneb", BlindedBeaconBlockDeneb)
    SignedBlindedBeaconBlockElectra = _signed(
        "SignedBlindedBeaconBlockElectra", BlindedBeaconBlockElectra)

    HistoricalBatch = _container("HistoricalBatch", [
        ("block_roots", RootsVector(P.slots_per_historical_root)),
        ("state_roots", RootsVector(P.slots_per_historical_root)),
    ])

    # --- states per fork -------------------------------------------------

    _state_pre = [
        ("genesis_time", ssz.uint64),
        ("genesis_validators_root", ssz.Bytes32),
        ("slot", ssz.uint64),
        ("fork", Fork),
        ("latest_block_header", BeaconBlockHeader),
        ("block_roots", RootsVector(P.slots_per_historical_root)),
        ("state_roots", RootsVector(P.slots_per_historical_root)),
        ("historical_roots", RootsList(P.historical_roots_limit)),
        ("eth1_data", Eth1Data),
        ("eth1_data_votes", ssz.List(
            Eth1Data, P.epochs_per_eth1_voting_period * P.slots_per_epoch)),
        ("eth1_deposit_index", ssz.uint64),
        ("validators", ValidatorRegistryType(P.validator_registry_limit, Validator)),
        ("balances", U64List(P.validator_registry_limit)),
        ("randao_mixes", RootsVector(P.epochs_per_historical_vector)),
        ("slashings", U64Vector(P.epochs_per_slashings_vector)),
    ]
    _state_post = [
        ("justification_bits", ssz.Bitvector(JUSTIFICATION_BITS_LENGTH)),
        ("previous_justified_checkpoint", Checkpoint),
        ("current_justified_checkpoint", Checkpoint),
        ("finalized_checkpoint", Checkpoint),
    ]
    _participation = [
        ("previous_epoch_participation", U8List(P.validator_registry_limit)),
        ("current_epoch_participation", U8List(P.validator_registry_limit)),
    ]
    _altair_tail = [
        ("inactivity_scores", U64List(P.validator_registry_limit)),
        ("current_sync_committee", SyncCommittee),
        ("next_sync_committee", SyncCommittee),
    ]
    _capella_tail = [
        ("next_withdrawal_index", ssz.uint64),
        ("next_withdrawal_validator_index", ssz.uint64),
        ("historical_summaries", ssz.List(HistoricalSummary, P.historical_roots_limit)),
    ]

    BeaconStatePhase0 = _container("BeaconStatePhase0", _state_pre + [
        ("previous_epoch_attestations", ssz.List(
            PendingAttestation, P.max_attestations * P.slots_per_epoch)),
        ("current_epoch_attestations", ssz.List(
            PendingAttestation, P.max_attestations * P.slots_per_epoch)),
    ] + _state_post)

    BeaconStateAltair = _container(
        "BeaconStateAltair",
        _state_pre + _participation + _state_post + _altair_tail,
    )
    BeaconStateBellatrix = _container(
        "BeaconStateBellatrix",
        _state_pre + _participation + _state_post + _altair_tail
        + [("latest_execution_payload_header", ExecutionPayloadHeaderBellatrix)],
    )
    BeaconStateCapella = _container(
        "BeaconStateCapella",
        _state_pre + _participation + _state_post + _altair_tail
        + [("latest_execution_payload_header", ExecutionPayloadHeaderCapella)]
        + _capella_tail,
    )
    BeaconStateDeneb = _container(
        "BeaconStateDeneb",
        _state_pre + _participation + _state_post + _altair_tail
        + [("latest_execution_payload_header", ExecutionPayloadHeaderDeneb)]
        + _capella_tail,
    )
    _electra_tail = [
        ("deposit_requests_start_index", ssz.uint64),
        ("deposit_balance_to_consume", ssz.uint64),
        ("exit_balance_to_consume", ssz.uint64),
        ("earliest_exit_epoch", ssz.uint64),
        ("consolidation_balance_to_consume", ssz.uint64),
        ("earliest_consolidation_epoch", ssz.uint64),
        ("pending_balance_deposits", ssz.List(
            PendingBalanceDeposit, P.pending_deposits_limit)),
        ("pending_partial_withdrawals", ssz.List(
            PendingPartialWithdrawal, P.pending_partial_withdrawals_limit)),
        ("pending_consolidations", ssz.List(
            PendingConsolidation, P.pending_consolidations_limit)),
    ]
    BeaconStateElectra = _container(
        "BeaconStateElectra",
        _state_pre + _participation + _state_post + _altair_tail
        + [("latest_execution_payload_header", ExecutionPayloadHeaderElectra)]
        + _capella_tail + _electra_tail,
    )

    BlobSidecar = _container("BlobSidecar", [
        ("index", ssz.uint64),
        ("blob", ssz.ByteVector(P.field_elements_per_blob * 32)),
        ("kzg_commitment", ssz.Bytes48),
        ("kzg_proof", ssz.Bytes48),
        ("signed_block_header", SignedBeaconBlockHeader),
        ("kzg_commitment_inclusion_proof", ssz.Vector(
            ssz.Bytes32, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)),
    ])

    ns = SimpleNamespace(**{
        k: v for k, v in locals().items()
        if isinstance(v, (type, ssz.SSZType)) and not k.startswith("_")
    })
    ns.preset = P

    _by_fork = {
        "phase0": (BeaconStatePhase0, BeaconBlockPhase0, SignedBeaconBlockPhase0,
                   BeaconBlockBodyPhase0),
        "altair": (BeaconStateAltair, BeaconBlockAltair, SignedBeaconBlockAltair,
                   BeaconBlockBodyAltair),
        "bellatrix": (BeaconStateBellatrix, BeaconBlockBellatrix,
                      SignedBeaconBlockBellatrix, BeaconBlockBodyBellatrix),
        "capella": (BeaconStateCapella, BeaconBlockCapella,
                    SignedBeaconBlockCapella, BeaconBlockBodyCapella),
        "deneb": (BeaconStateDeneb, BeaconBlockDeneb, SignedBeaconBlockDeneb,
                  BeaconBlockBodyDeneb),
        "electra": (BeaconStateElectra, BeaconBlockElectra,
                    SignedBeaconBlockElectra, BeaconBlockBodyElectra),
    }
    ns.beacon_state_class = lambda fork: _by_fork[fork][0]
    ns.beacon_block_class = lambda fork: _by_fork[fork][1]
    ns.signed_beacon_block_class = lambda fork: _by_fork[fork][2]
    ns.beacon_block_body_class = lambda fork: _by_fork[fork][3]
    ns.forks = tuple(_by_fork)

    _blinded_by_fork = {
        "bellatrix": (BlindedBeaconBlockBellatrix,
                      SignedBlindedBeaconBlockBellatrix,
                      ExecutionPayloadHeaderBellatrix),
        "capella": (BlindedBeaconBlockCapella,
                    SignedBlindedBeaconBlockCapella,
                    ExecutionPayloadHeaderCapella),
        "deneb": (BlindedBeaconBlockDeneb, SignedBlindedBeaconBlockDeneb,
                  ExecutionPayloadHeaderDeneb),
        "electra": (BlindedBeaconBlockElectra,
                    SignedBlindedBeaconBlockElectra,
                    ExecutionPayloadHeaderElectra),
    }
    ns.blinded_beacon_block_class = lambda fork: _blinded_by_fork[fork][0]
    ns.signed_blinded_beacon_block_class = \
        lambda fork: _blinded_by_fork[fork][1]
    ns.execution_payload_header_class = \
        lambda fork: _blinded_by_fork[fork][2]

    def decode_signed_block(raw: bytes):
        """Decode a SignedBeaconBlock of unknown fork (newest first —
        later forks are supersets, so they must be tried first).
        Returns None if no fork's layout fits."""
        for f in reversed(ns.forks):
            try:
                return ns.signed_beacon_block_class(f).deserialize(raw)
            except Exception:
                continue
        return None

    ns.decode_signed_block = decode_signed_block
    return ns
