"""Chain specification: runtime constants, presets, forks, domains.

Reference equivalents: `ChainSpec` (/root/reference/consensus/types/src/
chain_spec.rs) for runtime constants and the `EthSpec` preset trait
(/root/reference/consensus/types/src/eth_spec.rs) for compile-time sizes.
Here both are plain data: a `Preset` (sizes that shape SSZ types) and a
`ChainSpec` (tunables + fork schedule), with `mainnet` and `minimal`
constructors.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_SLOT = 0
GENESIS_EPOCH = 0

# Fork names in activation order.
FORKS = ("phase0", "altair", "bellatrix", "capella", "deneb", "electra")


@dataclass(frozen=True)
class Preset:
    """Compile-time sizes (shape SSZ types and committee math)."""

    name: str
    # time
    slots_per_epoch: int
    # committees
    max_committees_per_slot: int
    target_committee_size: int
    max_validators_per_committee: int
    shuffle_round_count: int
    # state list sizes
    slots_per_historical_root: int
    epochs_per_historical_vector: int
    epochs_per_slashings_vector: int
    historical_roots_limit: int
    validator_registry_limit: int
    epochs_per_eth1_voting_period: int
    # block operation caps
    max_proposer_slashings: int
    max_attester_slashings: int
    max_attestations: int
    max_deposits: int
    max_voluntary_exits: int
    max_bls_to_execution_changes: int
    # sync committee (altair)
    sync_committee_size: int
    epochs_per_sync_committee_period: int
    # execution (bellatrix)
    max_bytes_per_transaction: int
    max_transactions_per_payload: int
    bytes_per_logs_bloom: int
    max_extra_data_bytes: int
    # withdrawals (capella)
    max_withdrawals_per_payload: int
    max_validators_per_withdrawals_sweep: int
    # blobs (deneb)
    max_blob_commitments_per_block: int
    field_elements_per_blob: int
    max_blobs_per_block: int = 6
    # electra
    max_attester_slashings_electra: int = 1
    max_attestations_electra: int = 8
    pending_deposits_limit: int = 2**27
    pending_partial_withdrawals_limit: int = 2**27
    pending_consolidations_limit: int = 2**18
    max_deposit_requests_per_payload: int = 8192
    max_withdrawal_requests_per_payload: int = 16
    max_consolidation_requests_per_payload: int = 2
    max_consolidations: int = 1
    max_pending_partials_per_withdrawals_sweep: int = 8
    max_pending_deposits_per_epoch: int = 16


MAINNET_PRESET = Preset(
    name="mainnet",
    slots_per_epoch=32,
    max_committees_per_slot=64,
    target_committee_size=128,
    max_validators_per_committee=2048,
    shuffle_round_count=90,
    slots_per_historical_root=8192,
    epochs_per_historical_vector=65536,
    epochs_per_slashings_vector=8192,
    historical_roots_limit=2**24,
    validator_registry_limit=2**40,
    epochs_per_eth1_voting_period=64,
    max_proposer_slashings=16,
    max_attester_slashings=2,
    max_attestations=128,
    max_deposits=16,
    max_voluntary_exits=16,
    max_bls_to_execution_changes=16,
    sync_committee_size=512,
    epochs_per_sync_committee_period=256,
    max_bytes_per_transaction=2**30,
    max_transactions_per_payload=2**20,
    bytes_per_logs_bloom=256,
    max_extra_data_bytes=32,
    max_withdrawals_per_payload=16,
    max_validators_per_withdrawals_sweep=16384,
    max_blob_commitments_per_block=4096,
    field_elements_per_blob=4096,
)

MINIMAL_PRESET = Preset(
    name="minimal",
    slots_per_epoch=8,
    max_committees_per_slot=4,
    target_committee_size=4,
    max_validators_per_committee=2048,
    shuffle_round_count=10,
    slots_per_historical_root=64,
    epochs_per_historical_vector=64,
    epochs_per_slashings_vector=64,
    historical_roots_limit=2**24,
    validator_registry_limit=2**40,
    epochs_per_eth1_voting_period=4,
    max_proposer_slashings=16,
    max_attester_slashings=2,
    max_attestations=128,
    max_deposits=16,
    max_voluntary_exits=16,
    max_bls_to_execution_changes=16,
    sync_committee_size=32,
    epochs_per_sync_committee_period=8,
    max_bytes_per_transaction=2**30,
    max_transactions_per_payload=2**20,
    bytes_per_logs_bloom=256,
    max_extra_data_bytes=32,
    max_withdrawals_per_payload=4,
    max_validators_per_withdrawals_sweep=16,
    max_blob_commitments_per_block=4096,
    field_elements_per_blob=4096,
    # electra (minimal preset overrides)
    pending_partial_withdrawals_limit=64,
    pending_consolidations_limit=64,
    max_deposit_requests_per_payload=4,
    max_withdrawal_requests_per_payload=2,
    max_pending_partials_per_withdrawals_sweep=1,
)


@dataclass(frozen=True)
class ChainSpec:
    """Runtime tunables + fork schedule (reference chain_spec.rs)."""

    preset: Preset = MAINNET_PRESET
    config_name: str = "mainnet"

    seconds_per_slot: int = 12
    genesis_delay: int = 604800
    min_genesis_time: int = 1606824000
    min_genesis_active_validator_count: int = 16384

    # deposits / balances (Gwei)
    min_deposit_amount: int = 10**9
    max_effective_balance: int = 32 * 10**9
    effective_balance_increment: int = 10**9
    ejection_balance: int = 16 * 10**9
    hysteresis_quotient: int = 4
    hysteresis_downward_multiplier: int = 1
    hysteresis_upward_multiplier: int = 5

    # time parameters
    min_attestation_inclusion_delay: int = 1
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    min_epochs_to_inactivity_penalty: int = 4
    eth1_follow_distance: int = 2048

    # rewards & penalties
    base_reward_factor: int = 64
    whistleblower_reward_quotient: int = 512
    proposer_reward_quotient: int = 8
    inactivity_penalty_quotient: int = 2**26
    min_slashing_penalty_quotient: int = 128
    proportional_slashing_multiplier: int = 1
    # altair overrides
    inactivity_penalty_quotient_altair: int = 3 * 2**24
    min_slashing_penalty_quotient_altair: int = 64
    proportional_slashing_multiplier_altair: int = 2
    # bellatrix overrides
    inactivity_penalty_quotient_bellatrix: int = 2**24
    min_slashing_penalty_quotient_bellatrix: int = 32
    proportional_slashing_multiplier_bellatrix: int = 3
    # altair participation
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16

    # validator cycle
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 2**16
    max_per_epoch_activation_churn_limit: int = 8
    # electra
    min_activation_balance: int = 32 * 10**9
    max_effective_balance_electra: int = 2048 * 10**9
    min_per_epoch_churn_limit_electra: int = 128 * 10**9
    max_per_epoch_activation_exit_churn_limit: int = 256 * 10**9
    whistleblower_reward_quotient_electra: int = 4096
    min_slashing_penalty_quotient_electra: int = 4096

    # fork choice
    proposer_score_boost: int = 40
    reorg_head_weight_threshold: int = 20
    reorg_parent_weight_threshold: int = 160
    reorg_max_epochs_since_finalization: int = 2

    # fork schedule: version (4 bytes) and activation epoch per fork
    genesis_fork_version: bytes = b"\x00\x00\x00\x00"
    altair_fork_version: bytes = b"\x01\x00\x00\x00"
    bellatrix_fork_version: bytes = b"\x02\x00\x00\x00"
    capella_fork_version: bytes = b"\x03\x00\x00\x00"
    deneb_fork_version: bytes = b"\x04\x00\x00\x00"
    electra_fork_version: bytes = b"\x05\x00\x00\x00"
    altair_fork_epoch: int = 74240
    bellatrix_fork_epoch: int = 144896
    capella_fork_epoch: int = 194048
    deneb_fork_epoch: int = 269568
    electra_fork_epoch: int = FAR_FUTURE_EPOCH

    # domains (4-byte little-endian tags)
    domain_beacon_proposer: int = 0
    domain_beacon_attester: int = 1
    domain_randao: int = 2
    domain_deposit: int = 3
    domain_voluntary_exit: int = 4
    domain_selection_proof: int = 5
    domain_aggregate_and_proof: int = 6
    domain_sync_committee: int = 7
    domain_sync_committee_selection_proof: int = 8
    domain_contribution_and_proof: int = 9
    domain_bls_to_execution_change: int = 10
    domain_consolidation: int = 11
    domain_application_mask: int = 0x00000001

    # networking-ish constants used by subnet scheduling
    attestation_subnet_count: int = 64
    sync_committee_subnet_count: int = 4
    target_aggregators_per_committee: int = 16

    # deposit contract
    deposit_contract_address: bytes = bytes.fromhex(
        "00000000219ab540356cbb839cbe05303d7705fa"
    )
    deposit_chain_id: int = 1
    deposit_network_id: int = 1

    # -- derived helpers -------------------------------------------------

    @property
    def slots_per_epoch(self) -> int:
        return self.preset.slots_per_epoch

    def fork_version(self, fork: str) -> bytes:
        return {
            "phase0": self.genesis_fork_version,
            "altair": self.altair_fork_version,
            "bellatrix": self.bellatrix_fork_version,
            "capella": self.capella_fork_version,
            "deneb": self.deneb_fork_version,
            "electra": self.electra_fork_version,
        }[fork]

    def fork_epoch(self, fork: str) -> int:
        return {
            "phase0": GENESIS_EPOCH,
            "altair": self.altair_fork_epoch,
            "bellatrix": self.bellatrix_fork_epoch,
            "capella": self.capella_fork_epoch,
            "deneb": self.deneb_fork_epoch,
            "electra": self.electra_fork_epoch,
        }[fork]

    def fork_at_epoch(self, epoch: int) -> str:
        current = "phase0"
        for f in FORKS[1:]:
            if self.fork_epoch(f) <= epoch:
                current = f
        return current

    @staticmethod
    def fork_at_least(fork: str, base: str) -> bool:
        """fork >= base in activation order.  Use this instead of
        hardcoded suffix tuples like `fork in ("deneb", "electra")` —
        those silently exclude every later fork added to FORKS."""
        return FORKS.index(fork) >= FORKS.index(base)

    def compute_epoch_at_slot(self, slot: int) -> int:
        return slot // self.slots_per_epoch

    def compute_start_slot_at_epoch(self, epoch: int) -> int:
        return epoch * self.slots_per_epoch

    def compute_activation_exit_epoch(self, epoch: int) -> int:
        return epoch + 1 + self.max_seed_lookahead

    def sync_committee_period_at_slot(self, slot: int) -> int:
        """compute_sync_committee_period_at_slot (altair validator.md)."""
        return (self.compute_epoch_at_slot(int(slot))
                // self.preset.epochs_per_sync_committee_period)

    def balance_churn_limit(self, active_validator_count: int) -> int:
        return max(
            self.min_per_epoch_churn_limit,
            active_validator_count // self.churn_limit_quotient,
        )

    @staticmethod
    def mainnet() -> "ChainSpec":
        return ChainSpec()

    @staticmethod
    def minimal() -> "ChainSpec":
        return ChainSpec(
            preset=MINIMAL_PRESET,
            config_name="minimal",
            seconds_per_slot=6,
            min_genesis_active_validator_count=64,
            shard_committee_period=64,
            eth1_follow_distance=16,
            # minimal config activates all forks at genesis-adjacent epochs
            # only when a test overrides them; defaults stay far-future so
            # fork logic is exercised explicitly.
            altair_fork_epoch=FAR_FUTURE_EPOCH,
            bellatrix_fork_epoch=FAR_FUTURE_EPOCH,
            capella_fork_epoch=FAR_FUTURE_EPOCH,
            deneb_fork_epoch=FAR_FUTURE_EPOCH,
        )

    def with_forks_at(self, epoch: int, through: str = "capella") -> "ChainSpec":
        """Testing helper: activate forks up to `through` at `epoch`."""
        kw = {}
        for f in FORKS[1:]:
            idx_f, idx_t = FORKS.index(f), FORKS.index(through)
            kw[f"{f}_fork_epoch"] = epoch if idx_f <= idx_t else FAR_FUTURE_EPOCH
        return replace(self, **kw)
