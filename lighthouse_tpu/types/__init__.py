"""Consensus types: spec/presets, columnar registry, multi-fork containers."""

from lighthouse_tpu.types.spec import (
    FAR_FUTURE_EPOCH,
    FORKS,
    GENESIS_EPOCH,
    GENESIS_SLOT,
    MAINNET_PRESET,
    MINIMAL_PRESET,
    ChainSpec,
    Preset,
)
from lighthouse_tpu.types.registry import (
    RootsList,
    RootsVector,
    U8List,
    U64List,
    U64Vector,
    ValidatorRegistryType,
    Validators,
)
from lighthouse_tpu.types.containers import (
    AttestationData,
    BeaconBlockHeader,
    BLSToExecutionChange,
    Checkpoint,
    Deposit,
    DepositData,
    DepositMessage,
    Eth1Data,
    Fork,
    ForkData,
    HistoricalSummary,
    ProposerSlashing,
    SignedBeaconBlockHeader,
    SignedBLSToExecutionChange,
    SignedVoluntaryExit,
    SigningData,
    SyncCommitteeMessage,
    Validator,
    VoluntaryExit,
    Withdrawal,
    make_types,
)

__all__ = [
    "FAR_FUTURE_EPOCH", "FORKS", "GENESIS_EPOCH", "GENESIS_SLOT",
    "MAINNET_PRESET", "MINIMAL_PRESET", "ChainSpec", "Preset",
    "RootsList", "RootsVector", "U8List", "U64List", "U64Vector",
    "ValidatorRegistryType", "Validators",
    "AttestationData", "BeaconBlockHeader", "BLSToExecutionChange",
    "Checkpoint", "Deposit", "DepositData", "DepositMessage", "Eth1Data",
    "Fork", "ForkData", "HistoricalSummary", "ProposerSlashing",
    "SignedBeaconBlockHeader", "SignedBLSToExecutionChange",
    "SignedVoluntaryExit", "SigningData", "SyncCommitteeMessage",
    "Validator", "VoluntaryExit", "Withdrawal", "make_types",
]
