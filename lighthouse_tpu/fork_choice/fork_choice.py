"""Spec fork choice: on_block / on_attestation / get_head over ProtoArray.

Rebuild of /root/reference/consensus/fork_choice/src/fork_choice.rs
(`on_block` :642, `on_attestation` :1037, `on_attester_slashing` :1089,
`get_head` :468) plus the vote-delta machinery from
proto_array/src/proto_array_fork_choice.rs (`compute_deltas`).

TPU-first data layout: votes are three numpy columns over validator index
(current vote node, next vote node, next vote epoch), so `compute_deltas`
is two vectorized scatter-adds (np.add.at) instead of a per-validator loop
— the same shape the device-side batch reductions use.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from lighthouse_tpu import types as T
from lighthouse_tpu.fork_choice.proto_array import (
    EXEC_IRRELEVANT,
    NONE,
    CheckpointKey,
    ProtoArray,
    ProtoArrayError,
)
from lighthouse_tpu.state_transition import misc
from lighthouse_tpu.state_transition.epoch_processing import (
    process_justification_and_finalization,
)


class ForkChoiceError(ValueError):
    pass


def _ckpt(cp) -> CheckpointKey:
    return CheckpointKey(int(cp.epoch), bytes(cp.root))


class QueuedAttestation:
    __slots__ = ("slot", "indices", "root", "target_epoch")

    def __init__(self, slot, indices, root, target_epoch):
        self.slot, self.indices = slot, indices
        self.root, self.target_epoch = root, target_epoch


class ForkChoice:
    """The protocol store + proto-array + columnar vote tracker."""

    def __init__(
        self,
        spec: T.ChainSpec,
        anchor_root: bytes,
        anchor_state,
        balances_fn: Callable[[bytes], np.ndarray] | None = None,
    ):
        self.spec = spec
        self.proto = ProtoArray()
        self.time_slot = int(anchor_state.slot)
        self.genesis_time = int(anchor_state.genesis_time)

        anchor_epoch = spec.compute_epoch_at_slot(int(anchor_state.slot))
        anchor_cp = CheckpointKey(anchor_epoch, anchor_root)
        jc, fc = anchor_state.current_justified_checkpoint, anchor_state.finalized_checkpoint
        self.justified = _ckpt(jc) if int(jc.epoch) else anchor_cp
        self.finalized = _ckpt(fc) if int(fc.epoch) else anchor_cp
        # the anchor must be findable by the justified root
        if self.justified.root not in (anchor_root,):
            self.justified = anchor_cp
        if self.finalized.root not in (anchor_root,):
            self.finalized = anchor_cp

        self._balances_fn = balances_fn
        self._balance_snapshots: dict[bytes, np.ndarray] = {}
        eb = np.asarray(anchor_state.validators.effective_balance, np.int64).copy()
        active = anchor_state.validators.is_active(anchor_epoch)
        eb[~active] = 0
        self._balance_snapshots[anchor_root] = eb
        self.justified_balances = self._balances_for(self.justified.root)

        nv = eb.shape[0]
        self._vote_current = np.full(nv, NONE, np.int32)
        self._vote_next = np.full(nv, NONE, np.int32)
        self._vote_next_epoch = np.full(nv, -1, np.int64)  # -1 = no vote yet
        self._old_balances = np.zeros(nv, np.int64)
        self.equivocating = np.zeros(nv, bool)

        self.proposer_boost_root: bytes | None = None
        self._applied_boost_root: bytes | None = None
        self._applied_boost_amount = 0
        self._queued: list[QueuedAttestation] = []
        # best unrealized checkpoints seen this epoch; promoted into the
        # store at the next epoch tick (spec pull_up_store_checkpoints)
        self._best_unrealized_j = self.justified
        self._best_unrealized_f = self.finalized

        self.proto.add_block(
            anchor_root, None, int(anchor_state.slot),
            self.justified, self.finalized,
            execution_status=EXEC_IRRELEVANT,
        )

    # -- balances ---------------------------------------------------------

    def _balances_for(self, root: bytes) -> np.ndarray:
        if root in self._balance_snapshots:
            return self._balance_snapshots[root]
        if self._balances_fn is not None:
            b = np.asarray(self._balances_fn(root), np.int64)
            self._balance_snapshots[root] = b
            return b
        # fall back to the most recent snapshot
        return next(reversed(self._balance_snapshots.values()))

    def _grow_votes(self, n: int):
        cur = self._vote_current.shape[0]
        if n <= cur:
            return
        pad = n - cur
        self._vote_current = np.concatenate([self._vote_current, np.full(pad, NONE, np.int32)])
        self._vote_next = np.concatenate([self._vote_next, np.full(pad, NONE, np.int32)])
        self._vote_next_epoch = np.concatenate([self._vote_next_epoch, np.full(pad, -1, np.int64)])
        self._old_balances = np.concatenate([self._old_balances, np.zeros(pad, np.int64)])
        self.equivocating = np.concatenate([self.equivocating, np.zeros(pad, bool)])

    # -- time -------------------------------------------------------------

    def update_time(self, current_slot: int) -> None:
        if current_slot > self.time_slot:
            prev_epoch = self.spec.compute_epoch_at_slot(self.time_slot)
            self.time_slot = current_slot
            # boost expires every slot (spec: on_tick resets proposer boost)
            self.proposer_boost_root = None
            if self.spec.compute_epoch_at_slot(current_slot) > prev_epoch:
                # epoch tick: pull unrealized checkpoints into the store
                # (spec on_tick → pull_up_store_checkpoints)
                self._update_checkpoints(
                    self._best_unrealized_j, self._best_unrealized_f)
            self._dequeue(current_slot)

    def _dequeue(self, current_slot: int):
        still = []
        for q in self._queued:
            if q.slot < current_slot:
                self._apply_attestation(q.indices, q.root, q.target_epoch)
            else:
                still.append(q)
        self._queued = still

    # -- on_block ---------------------------------------------------------

    def on_block(
        self,
        current_slot: int,
        block,
        block_root: bytes,
        state,
        execution_status: int = EXEC_IRRELEVANT,
        is_timely: bool = False,
    ) -> None:
        """Register an imported block (reference fork_choice.rs:642).

        `state` is the post-state of the block; unrealized justification is
        computed from it directly (run justification weighing on the live
        participation counters, then restore — the reference computes the
        same via its ParticipationCache without cloning the state).
        """
        spec = self.spec
        self.update_time(max(current_slot, self.time_slot))
        slot = int(block.slot)
        if block_root in self.proto:
            return
        parent_root = bytes(block.parent_root)
        if parent_root not in self.proto:
            raise ForkChoiceError(f"unknown parent {parent_root.hex()[:16]}")
        if slot > current_slot:
            raise ForkChoiceError("block from the future")
        fin_slot = spec.compute_start_slot_at_epoch(self.finalized.epoch)
        if slot <= fin_slot:
            raise ForkChoiceError("block slot not beyond finalized slot")
        if self.proto.get_ancestor(parent_root, fin_slot) != self.finalized.root:
            raise ForkChoiceError("block does not descend from finalized root")

        justified = _ckpt(state.current_justified_checkpoint)
        finalized = _ckpt(state.finalized_checkpoint)
        unrealized_j, unrealized_f = self._compute_unrealized(state, justified, finalized)

        block_epoch = spec.compute_epoch_at_slot(slot)
        current_epoch = spec.compute_epoch_at_slot(current_slot)
        if block_epoch < current_epoch:
            # pull-up tip: blocks from prior epochs adopt their unrealized
            # checkpoints immediately (spec compute_pulled_up_tip)
            node_j, node_f = unrealized_j, unrealized_f
        else:
            node_j, node_f = justified, finalized

        self._update_checkpoints(node_j, node_f)
        # unrealized checkpoints are remembered but only promoted into the
        # store at the next epoch tick (spec update_unrealized_checkpoints)
        if unrealized_j.epoch > self._best_unrealized_j.epoch:
            self._best_unrealized_j = unrealized_j
        if unrealized_f.epoch > self._best_unrealized_f.epoch:
            self._best_unrealized_f = unrealized_f

        # snapshot effective balances only for justified-checkpoint
        # candidates: blocks that begin a new epoch along their branch
        # (a checkpoint root is always the first block at/after the epoch
        # start).  Everything else resolves via _balances_fn on demand.
        parent_idx = self.proto.indices[parent_root]
        parent_epoch = spec.compute_epoch_at_slot(int(self.proto.slots[parent_idx]))
        if block_epoch > parent_epoch or self._balances_fn is None:
            eb = np.asarray(state.validators.effective_balance, np.int64).copy()
            eb[~state.validators.is_active(block_epoch)] = 0
            self._balance_snapshots[block_root] = eb
        self._grow_votes(state.validators.effective_balance.shape[0])

        if (is_timely and slot == current_slot
                and self.proposer_boost_root is None):
            # spec on_block: only the FIRST timely block in a slot gets the
            # boost (equivocation/ex-ante-reorg defence)
            self.proposer_boost_root = block_root

        self.proto.add_block(
            block_root, parent_root, slot,
            node_j, node_f, unrealized_j, unrealized_f, execution_status,
        )

    def _compute_unrealized(self, state, justified, finalized):
        spec = self.spec
        epoch = misc.current_epoch(state, spec)
        if epoch <= T.GENESIS_EPOCH + 1:
            return justified, finalized
        snap = (
            state.previous_justified_checkpoint,
            state.current_justified_checkpoint,
            state.finalized_checkpoint,
            list(state.justification_bits),
        )
        try:
            process_justification_and_finalization(state, spec)
            uj = _ckpt(state.current_justified_checkpoint)
            uf = _ckpt(state.finalized_checkpoint)
        finally:
            (state.previous_justified_checkpoint,
             state.current_justified_checkpoint,
             state.finalized_checkpoint) = snap[:3]
            state.justification_bits = snap[3]
        return uj, uf

    def _update_checkpoints(self, justified: CheckpointKey, finalized: CheckpointKey):
        if justified.epoch > self.justified.epoch:
            self.justified = justified
            self.justified_balances = self._balances_for(justified.root)
        if finalized.epoch > self.finalized.epoch:
            self.finalized = finalized

    # -- attestations ------------------------------------------------------

    def on_attestation(
        self,
        current_slot: int,
        attesting_indices: np.ndarray,
        beacon_block_root: bytes,
        target_epoch: int,
        att_slot: int,
        is_from_block: bool = False,
    ) -> None:
        """Register LMD votes (reference fork_choice.rs:1037).

        Chain-level validity (committee membership, signature) is the
        caller's job; here: known head block, sane target, and the spec's
        one-slot delay for gossip attestations (queued until next slot).
        """
        spec = self.spec
        self.update_time(max(current_slot, self.time_slot))
        current_epoch = spec.compute_epoch_at_slot(current_slot)
        if not is_from_block:
            if target_epoch not in (current_epoch, max(current_epoch - 1, 0)):
                raise ForkChoiceError("attestation target epoch not current/previous")
        if beacon_block_root not in self.proto:
            raise ForkChoiceError("attestation for unknown block")
        i = self.proto.indices[beacon_block_root]
        if int(self.proto.slots[i]) > att_slot:
            raise ForkChoiceError("attestation for block newer than attestation slot")
        idx = np.asarray(attesting_indices, np.int64)
        if not is_from_block and att_slot >= current_slot:
            self._queued.append(
                QueuedAttestation(att_slot, idx, beacon_block_root, target_epoch))
            return
        self._apply_attestation(idx, beacon_block_root, target_epoch)

    def _apply_attestation(self, idx: np.ndarray, root: bytes, target_epoch: int):
        node = self.proto.indices.get(root)
        if node is None:
            return
        self._grow_votes(int(idx.max()) + 1 if idx.size else 0)
        newer = target_epoch > self._vote_next_epoch[idx]
        sel = idx[newer & ~self.equivocating[idx]]
        self._vote_next[sel] = node
        self._vote_next_epoch[sel] = target_epoch

    def on_attester_slashing(self, attesting_indices: np.ndarray) -> None:
        """Zero equivocating validators out of fork choice forever
        (reference fork_choice.rs:1089)."""
        idx = np.asarray(attesting_indices, np.int64)
        if idx.size == 0:
            return
        self._grow_votes(int(idx.max()) + 1)
        self.equivocating[idx] = True

    # -- get_head ----------------------------------------------------------

    def _compute_deltas(self) -> np.ndarray:
        """Vectorized compute_deltas (proto_array_fork_choice.rs).

        For every validator: subtract old balance at the current vote,
        add new balance at the next vote, then commit next → current.
        Equivocating validators contribute zero new weight.
        """
        n_nodes = len(self.proto)
        deltas = np.zeros(n_nodes, np.int64)
        nv = self._vote_current.shape[0]
        new_bal = np.zeros(nv, np.int64)
        jb = self.justified_balances
        new_bal[: min(nv, jb.shape[0])] = jb[: min(nv, jb.shape[0])]
        new_bal[self.equivocating] = 0
        # equivocators never vote again; their next vote is cleared so the
        # subtraction below removes their old weight exactly once
        self._vote_next[self.equivocating] = NONE

        cur, nxt = self._vote_current, self._vote_next
        has_cur = (cur != NONE) & (cur < n_nodes)
        has_nxt = nxt != NONE
        np.add.at(deltas, cur[has_cur], -self._old_balances[has_cur])
        np.add.at(deltas, nxt[has_nxt], new_bal[has_nxt])
        # commit
        self._vote_current = np.where(has_nxt, nxt, NONE).astype(np.int32)
        self._old_balances = np.where(has_nxt, new_bal, 0)
        return deltas

    def _proposer_boost_amount(self) -> int:
        spec = self.spec
        total = int(self.justified_balances.sum())
        committee_weight = total // spec.slots_per_epoch
        return committee_weight * spec.proposer_score_boost // 100

    def get_head(self, current_slot: int | None = None) -> bytes:
        if current_slot is not None:
            self.update_time(current_slot)
        slot = self.time_slot
        current_epoch = self.spec.compute_epoch_at_slot(slot)
        deltas = self._compute_deltas()
        # proposer boost: remove the previously applied boost, apply current
        if self._applied_boost_root is not None:
            i = self.proto.indices.get(self._applied_boost_root)
            if i is not None:
                deltas[i] -= self._applied_boost_amount
            self._applied_boost_root = None
            self._applied_boost_amount = 0
        if self.proposer_boost_root is not None:
            i = self.proto.indices.get(self.proposer_boost_root)
            if i is not None:
                amt = self._proposer_boost_amount()
                deltas[i] += amt
                self._applied_boost_root = self.proposer_boost_root
                self._applied_boost_amount = amt
        self.proto.apply_score_changes(
            deltas, self.justified, self.finalized, current_epoch)
        return self.proto.find_head(
            self.justified.root, self.justified, self.finalized, current_epoch)

    # -- proposer re-org ---------------------------------------------------

    def get_proposer_head(
        self, head_root: bytes, proposal_slot: int
    ) -> bytes:
        """Reference `get_proposer_head` (fork_choice.rs:516): propose on the
        parent when the head block is late/weak and the parent is strong."""
        spec = self.spec
        i = self.proto.indices.get(head_root)
        if i is None:
            return head_root
        head_slot = int(self.proto.slots[i])
        p = self.proto.parents[i]
        if p == NONE or head_slot + 1 != proposal_slot:
            return head_root
        if (self.spec.compute_epoch_at_slot(proposal_slot) - self.finalized.epoch
                > spec.reorg_max_epochs_since_finalization):
            return head_root
        total = int(self.justified_balances.sum())
        committee_weight = total // spec.slots_per_epoch
        head_weak = int(self.proto.weights[i]) * 100 < (
            committee_weight * spec.reorg_head_weight_threshold)
        parent_strong = int(self.proto.weights[p]) * 100 > (
            committee_weight * spec.reorg_parent_weight_threshold)
        if head_weak and parent_strong:
            return self.proto.roots[p]
        return head_root

    # -- optimistic sync / pruning ----------------------------------------

    def on_valid_execution_payload(self, root: bytes) -> None:
        self.proto.set_execution_valid(root)

    def on_invalid_execution_payload(self, root: bytes) -> None:
        self.proto.set_execution_invalid(root)

    def prune(self) -> None:
        mapping = self.proto.prune(self.finalized.root)
        # re-map vote node indices through the pruned index space
        lut = np.full(max(mapping.keys(), default=0) + 1, NONE, np.int32)
        for old, new in mapping.items():
            lut[old] = new
        for name in ("_vote_current", "_vote_next"):
            col = getattr(self, name)
            ok = (col != NONE) & (col < lut.shape[0])
            out = np.full_like(col, NONE)
            out[ok] = lut[col[ok]]
            setattr(self, name, out)
        # drop balance snapshots for pruned roots
        live = set(self.proto.indices)
        live.add(self.justified.root)
        self._balance_snapshots = {
            r: b for r, b in self._balance_snapshots.items() if r in live}

    def contains_block(self, root: bytes) -> bool:
        return root in self.proto

    def block_slot(self, root: bytes) -> int | None:
        i = self.proto.indices.get(root)
        return int(self.proto.slots[i]) if i is not None else None

    # -- persistence (reference PersistedForkChoice / proto_array
    # ssz_container.rs — here the columnar arrays snapshot as npz + json
    # since the store IS struct-of-arrays) --------------------------------

    def to_bytes(self) -> bytes:
        import io
        import json as _json

        import numpy as _np

        # an applied proposer boost lives inside proto.weights; a restart
        # must not inherit it (boosts are one-slot) — unapply via the
        # same delta path get_head uses before snapshotting
        if self._applied_boost_root is not None:
            i = self.proto.indices.get(self._applied_boost_root)
            if i is not None:
                deltas = _np.zeros(self.proto.n_nodes, _np.int64)
                deltas[i] = -self._applied_boost_amount
                self.proto.apply_score_changes(
                    deltas, self.justified, self.finalized,
                    self.spec.compute_epoch_at_slot(self.time_slot))
            self._applied_boost_root = None
            self._applied_boost_amount = 0
            self.proposer_boost_root = None

        n = self.proto.n_nodes
        buf = io.BytesIO()
        _np.savez(
            buf,
            slots=self.proto.slots[:n],
            parents=self.proto.parents[:n],
            weights=self.proto.weights[:n],
            best_child=self.proto.best_child[:n],
            best_descendant=self.proto.best_descendant[:n],
            justified_epoch=self.proto.justified_epoch[:n],
            finalized_epoch=self.proto.finalized_epoch[:n],
            unrealized_justified_epoch=(
                self.proto.unrealized_justified_epoch[:n]),
            unrealized_finalized_epoch=(
                self.proto.unrealized_finalized_epoch[:n]),
            execution_status=self.proto.execution_status[:n],
            vote_current=self._vote_current,
            vote_next=self._vote_next,
            vote_next_epoch=self._vote_next_epoch,
            old_balances=self._old_balances,
            equivocating=self.equivocating,
            justified_balances=self.justified_balances,
        )
        meta = _json.dumps({
            "roots": [r.hex() for r in self.proto.roots[:n]],
            "justified_roots": [
                r.hex() for r in self.proto.justified_roots[:n]],
            "unrealized_justified_roots": [
                r.hex() for r in self.proto.unrealized_justified_roots[:n]],
            "justified": [self.justified.epoch, self.justified.root.hex()],
            "finalized": [self.finalized.epoch, self.finalized.root.hex()],
            "best_unrealized_j": [self._best_unrealized_j.epoch,
                                  self._best_unrealized_j.root.hex()],
            "best_unrealized_f": [self._best_unrealized_f.epoch,
                                  self._best_unrealized_f.root.hex()],
            "time_slot": self.time_slot,
            "genesis_time": self.genesis_time,
        }).encode()
        arrays = buf.getvalue()
        return len(meta).to_bytes(8, "little") + meta + arrays

    @classmethod
    def from_bytes(cls, spec, data: bytes,
                   balances_fn=None) -> "ForkChoice":
        import io
        import json as _json

        import numpy as _np

        meta_len = int.from_bytes(data[:8], "little")
        meta = _json.loads(data[8:8 + meta_len])
        arrays = _np.load(io.BytesIO(data[8 + meta_len:]))

        fc = cls.__new__(cls)
        fc.spec = spec
        fc.proto = ProtoArray()
        n = len(meta["roots"])
        grow = max(((n + ProtoArray._GROW - 1)
                    // ProtoArray._GROW) * ProtoArray._GROW,
                   ProtoArray._GROW)

        def col(name, dtype, fill=0):
            out = _np.full(grow, fill, dtype)
            out[:n] = arrays[name]
            return out

        p = fc.proto
        p.n_nodes = n
        p.slots = col("slots", _np.int64)
        p.parents = col("parents", _np.int32, NONE)
        p.weights = col("weights", _np.int64)
        p.best_child = col("best_child", _np.int32, NONE)
        p.best_descendant = col("best_descendant", _np.int32, NONE)
        p.justified_epoch = col("justified_epoch", _np.int64)
        p.finalized_epoch = col("finalized_epoch", _np.int64)
        p.unrealized_justified_epoch = col(
            "unrealized_justified_epoch", _np.int64)
        p.unrealized_finalized_epoch = col(
            "unrealized_finalized_epoch", _np.int64)
        p.execution_status = col("execution_status", _np.int8)
        p.roots = [bytes.fromhex(r) for r in meta["roots"]]
        p.indices = {r: i for i, r in enumerate(p.roots)}
        p.justified_roots = [
            bytes.fromhex(r) for r in meta["justified_roots"]]
        p.unrealized_justified_roots = [
            bytes.fromhex(r) for r in meta["unrealized_justified_roots"]]

        fc.time_slot = int(meta["time_slot"])
        fc.genesis_time = int(meta["genesis_time"])
        fc.justified = CheckpointKey(
            int(meta["justified"][0]), bytes.fromhex(meta["justified"][1]))
        fc.finalized = CheckpointKey(
            int(meta["finalized"][0]), bytes.fromhex(meta["finalized"][1]))
        fc._best_unrealized_j = CheckpointKey(
            int(meta["best_unrealized_j"][0]),
            bytes.fromhex(meta["best_unrealized_j"][1]))
        fc._best_unrealized_f = CheckpointKey(
            int(meta["best_unrealized_f"][0]),
            bytes.fromhex(meta["best_unrealized_f"][1]))
        fc._balances_fn = balances_fn
        fc.justified_balances = _np.asarray(
            arrays["justified_balances"], _np.int64)
        # checkpoint-balances cache: reseeded from the snapshot's
        # justified balances, refilled lazily via balances_fn
        fc._balance_snapshots = {fc.justified.root: fc.justified_balances}
        fc._vote_current = _np.asarray(arrays["vote_current"], _np.int32)
        fc._vote_next = _np.asarray(arrays["vote_next"], _np.int32)
        fc._vote_next_epoch = _np.asarray(
            arrays["vote_next_epoch"], _np.int64)
        fc._old_balances = _np.asarray(arrays["old_balances"], _np.int64)
        fc.equivocating = _np.asarray(arrays["equivocating"], bool)
        fc.proposer_boost_root = None       # boosts never survive restart
        fc._applied_boost_root = None
        fc._applied_boost_amount = 0
        fc._queued = []
        return fc
