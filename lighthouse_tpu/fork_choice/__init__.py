"""Fork choice: columnar LMD-GHOST proto-array + spec store.

Reference: /root/reference/consensus/{proto_array,fork_choice}.
"""

from lighthouse_tpu.fork_choice.fork_choice import (
    ForkChoice,
    ForkChoiceError,
    QueuedAttestation,
)
from lighthouse_tpu.fork_choice.proto_array import (
    EXEC_INVALID,
    EXEC_IRRELEVANT,
    EXEC_OPTIMISTIC,
    EXEC_VALID,
    CheckpointKey,
    ProtoArray,
    ProtoArrayError,
)

__all__ = [
    "ForkChoice",
    "ForkChoiceError",
    "QueuedAttestation",
    "ProtoArray",
    "ProtoArrayError",
    "CheckpointKey",
    "EXEC_IRRELEVANT",
    "EXEC_OPTIMISTIC",
    "EXEC_VALID",
    "EXEC_INVALID",
]
