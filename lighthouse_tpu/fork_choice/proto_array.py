"""Columnar LMD-GHOST proto-array.

Rebuild of the reference's flat-array fork choice store
(/root/reference/consensus/proto_array/src/proto_array.rs).  The reference
keeps a Vec of node structs; here the node store is a struct-of-arrays —
every per-node field is one numpy column (parents, weights, best-child
pointers, checkpoint epochs) so weight application and viability filtering
are vectorized sweeps over the whole block DAG instead of per-node struct
walks.  The only inherently sequential step — propagating child deltas into
parents — is a single reverse pass over an int32 column (nodes are
insertion-ordered, so every parent precedes its children).

Execution status mirrors the reference's optimistic-sync statuses
(proto_array.rs `ExecutionStatus`): blocks verified by an execution engine
are Valid, known-bad payloads are Invalid (never viable for head), and
not-yet-checked payloads are Optimistic.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NONE = -1

# execution status column values
EXEC_IRRELEVANT = 0  # pre-merge / no payload
EXEC_OPTIMISTIC = 1  # payload not yet verified by an EL
EXEC_VALID = 2
EXEC_INVALID = 3


@dataclass(frozen=True)
class CheckpointKey:
    epoch: int
    root: bytes


class ProtoArrayError(ValueError):
    pass


class ProtoArray:
    """Struct-of-arrays node store for LMD-GHOST."""

    _GROW = 1024

    def __init__(self):
        n = self._GROW
        self.n_nodes = 0
        self.slots = np.zeros(n, np.int64)
        self.parents = np.full(n, NONE, np.int32)
        self.weights = np.zeros(n, np.int64)
        self.best_child = np.full(n, NONE, np.int32)
        self.best_descendant = np.full(n, NONE, np.int32)
        self.justified_epoch = np.zeros(n, np.int64)
        self.finalized_epoch = np.zeros(n, np.int64)
        self.unrealized_justified_epoch = np.zeros(n, np.int64)
        self.unrealized_finalized_epoch = np.zeros(n, np.int64)
        self.execution_status = np.zeros(n, np.int8)
        self.roots: list[bytes] = []
        self.indices: dict[bytes, int] = {}
        # per-node checkpoint roots (small python lists; epochs above are the
        # columns used in the vectorized viability filter)
        self.justified_roots: list[bytes] = []
        self.unrealized_justified_roots: list[bytes] = []

    # -- plumbing ---------------------------------------------------------

    def __len__(self) -> int:
        return self.n_nodes

    def __contains__(self, root: bytes) -> bool:
        return root in self.indices

    def _ensure_capacity(self):
        if self.n_nodes < self.slots.shape[0]:
            return
        for name in ("slots", "parents", "weights", "best_child",
                     "best_descendant", "justified_epoch", "finalized_epoch",
                     "unrealized_justified_epoch", "unrealized_finalized_epoch",
                     "execution_status"):
            col = getattr(self, name)
            fill = NONE if name in ("parents", "best_child", "best_descendant") else 0
            grown = np.full(col.shape[0] * 2, fill, col.dtype)
            grown[: col.shape[0]] = col
            setattr(self, name, grown)

    # -- mutation ---------------------------------------------------------

    def add_block(
        self,
        root: bytes,
        parent_root: bytes | None,
        slot: int,
        justified: CheckpointKey,
        finalized: CheckpointKey,
        unrealized_justified: CheckpointKey | None = None,
        unrealized_finalized: CheckpointKey | None = None,
        execution_status: int = EXEC_IRRELEVANT,
    ) -> int:
        if root in self.indices:
            return self.indices[root]
        parent = self.indices.get(parent_root, NONE) if parent_root else NONE
        if parent_root is not None and parent == NONE and self.n_nodes > 0:
            raise ProtoArrayError(f"unknown parent {parent_root.hex()[:16]}")
        self._ensure_capacity()
        i = self.n_nodes
        self.n_nodes += 1
        uj = unrealized_justified or justified
        uf = unrealized_finalized or finalized
        self.slots[i] = slot
        self.parents[i] = parent
        self.weights[i] = 0
        self.best_child[i] = NONE
        self.best_descendant[i] = NONE
        self.justified_epoch[i] = justified.epoch
        self.finalized_epoch[i] = finalized.epoch
        self.unrealized_justified_epoch[i] = uj.epoch
        self.unrealized_finalized_epoch[i] = uf.epoch
        self.execution_status[i] = execution_status
        self.roots.append(root)
        self.indices[root] = i
        self.justified_roots.append(justified.root)
        self.unrealized_justified_roots.append(uj.root)
        return i

    # -- viability --------------------------------------------------------

    def _viable_mask(
        self, justified: CheckpointKey, finalized: CheckpointKey, current_epoch: int
    ) -> np.ndarray:
        """Vectorized `node_is_viable_for_head` over all nodes.

        Mirrors the spec's filter_block_tree / the reference's
        `node_is_viable_for_head`: the node's voting source must match the
        store's justified epoch, or have been pulled up to it, or be recent
        enough (within 2 epochs, the "lenient" rule); the node must descend
        from the finalized block (one vectorizable forward sweep — parents
        precede children, so descendant status propagates in index order);
        invalid execution disqualifies outright.
        """
        n = self.n_nodes
        je = self.justified_epoch[:n]
        uje = self.unrealized_justified_epoch[:n]
        ok_j = (
            (justified.epoch == 0)
            | (je == justified.epoch)
            | (uje >= justified.epoch)
            | (je + 2 >= current_epoch)
        )
        if finalized.epoch == 0 or finalized.root not in self.indices:
            ok_f = np.ones(n, bool)
        else:
            fin = self.indices[finalized.root]
            ok_f = np.zeros(n, bool)
            ok_f[fin] = True
            parents = self.parents[:n]
            for i in range(fin + 1, n):
                p = parents[i]
                if p != NONE and ok_f[p]:
                    ok_f[i] = True
        ok_exec = self.execution_status[:n] != EXEC_INVALID
        return ok_j & ok_f & ok_exec

    # -- the core update --------------------------------------------------

    def apply_score_changes(
        self,
        deltas: np.ndarray,
        justified: CheckpointKey,
        finalized: CheckpointKey,
        current_epoch: int,
    ) -> None:
        """Add `deltas` (int64[n_nodes]) to node weights, propagate child →
        parent, and rebuild best_child/best_descendant pointers.

        Reference: proto_array.rs `apply_score_changes` +
        `maybe_update_best_child_and_descendant`.  Deltas are propagated in
        one reverse sweep; the best-pointer rebuild is a second reverse
        sweep using the vectorized viability mask.
        """
        n = self.n_nodes
        if n == 0:
            return
        if deltas.shape[0] != n:
            raise ProtoArrayError("delta length mismatch")
        d = deltas.astype(np.int64, copy=True)
        parents = self.parents[:n]
        # child → parent accumulation (reverse insertion order = reverse topo)
        for i in range(n - 1, 0, -1):
            p = parents[i]
            if p != NONE:
                d[p] += d[i]
        self.weights[:n] += d

        viable = self._viable_mask(justified, finalized, current_epoch)
        weights = self.weights[:n]
        best_child = np.full(n, NONE, np.int32)
        best_descendant = np.full(n, NONE, np.int32)
        # reverse sweep: children of a node appear after it, so by the time
        # we visit child i its own best_descendant is final.
        for i in range(n - 1, -1, -1):
            p = parents[i]
            if p == NONE:
                continue
            # is node i a viable head candidate (itself or via descendants)?
            if not viable[i] and best_descendant[i] == NONE:
                continue
            cur = best_child[p]
            if cur == NONE:
                take = True
            else:
                w_i, w_c = weights[i], weights[cur]
                if w_i != w_c:
                    take = w_i > w_c
                else:
                    # tie-break on root bytes (reference: op_root comparison)
                    take = self.roots[i] > self.roots[cur]
            if take:
                best_child[p] = i
                bd = best_descendant[i]
                best_descendant[p] = bd if bd != NONE else (
                    i if viable[i] else NONE)
        # a viable node is its own best descendant when it has no best child
        own = (best_descendant[:n] == NONE) & viable
        best_descendant[own] = np.nonzero(own)[0]
        self.best_child[:n] = best_child
        self.best_descendant[:n] = best_descendant
        self._viable = viable

    def find_head(
        self,
        justified_root: bytes,
        justified: CheckpointKey,
        finalized: CheckpointKey,
        current_epoch: int,
    ) -> bytes:
        if justified_root not in self.indices:
            raise ProtoArrayError(f"unknown justified root {justified_root.hex()[:16]}")
        start = self.indices[justified_root]
        bd = self.best_descendant[start]
        head = bd if bd != NONE else start
        viable = getattr(self, "_viable", None)
        if viable is not None and head < viable.shape[0] and not viable[head]:
            # fall back to the justified node itself (always permitted head)
            head = start
        return self.roots[head]

    # -- ancestry ---------------------------------------------------------

    def get_ancestor(self, root: bytes, slot: int) -> bytes | None:
        i = self.indices.get(root)
        if i is None:
            return None
        while i != NONE and self.slots[i] > slot:
            i = self.parents[i]
        return self.roots[i] if i != NONE else None

    def common_ancestor(self, a: bytes, b: bytes) -> bytes | None:
        """Deepest common ancestor of two known roots (the reorg
        detector's classification walk).  Nodes are insertion-ordered —
        every parent precedes its children — so repeatedly stepping the
        HIGHER-indexed side to its parent converges on the fork point
        without comparing slots, in O(depth of the deeper branch)."""
        ia = self.indices.get(a)
        ib = self.indices.get(b)
        if ia is None or ib is None:
            return None
        while ia != ib:
            if ia == NONE or ib == NONE:
                return None  # disjoint trees (pruned-away branch)
            if ia > ib:
                ia = int(self.parents[ia])
            else:
                ib = int(self.parents[ib])
        return self.roots[ia]

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        if a is None:
            return False
        got = self.get_ancestor(descendant_root, int(self.slots[a]))
        return got == ancestor_root

    # -- optimistic sync --------------------------------------------------

    def set_execution_valid(self, root: bytes) -> None:
        """Mark `root` and all ancestors with payloads as valid."""
        i = self.indices.get(root)
        while i is not None and i != NONE:
            if self.execution_status[i] == EXEC_INVALID:
                raise ProtoArrayError("valid block descends from invalid block")
            if self.execution_status[i] in (EXEC_VALID, EXEC_IRRELEVANT):
                break
            self.execution_status[i] = EXEC_VALID
            i = self.parents[i]

    def set_execution_invalid(self, root: bytes) -> None:
        """Mark `root` and all descendants invalid (reference
        `propagate_execution_status` on invalid payloads)."""
        start = self.indices.get(root)
        if start is None:
            return
        n = self.n_nodes
        bad = np.zeros(n, bool)
        bad[start] = True
        parents = self.parents[:n]
        for i in range(start + 1, n):
            p = parents[i]
            if p != NONE and bad[p]:
                bad[i] = True
        self.execution_status[:n][bad] = EXEC_INVALID

    # -- pruning ----------------------------------------------------------

    def prune(self, finalized_root: bytes) -> dict[int, int]:
        """Drop every node that is not the finalized block or a descendant
        of it.  Returns the old→new index mapping for callers holding node
        indices (the vote tracker re-maps through it)."""
        if finalized_root not in self.indices:
            raise ProtoArrayError("cannot prune to unknown root")
        fin = self.indices[finalized_root]
        n = self.n_nodes
        keep = np.zeros(n, bool)
        keep[fin] = True
        parents = self.parents[:n]
        for i in range(fin + 1, n):
            p = parents[i]
            if p != NONE and keep[p]:
                keep[i] = True
        if keep.all():
            return {i: i for i in range(n)}
        new_of_old = np.cumsum(keep) - 1
        mapping = {i: int(new_of_old[i]) for i in range(n) if keep[i]}
        kept_idx = np.nonzero(keep)[0]
        m = kept_idx.shape[0]
        for name in ("slots", "weights", "justified_epoch", "finalized_epoch",
                     "unrealized_justified_epoch", "unrealized_finalized_epoch",
                     "execution_status"):
            col = getattr(self, name)
            col[:m] = col[kept_idx]
        # pointer columns need re-mapping
        for name in ("parents", "best_child", "best_descendant"):
            col = getattr(self, name)
            vals = col[kept_idx]
            remapped = np.full(m, NONE, np.int32)
            ok = vals != NONE
            remapped[ok] = new_of_old[vals[ok]]
            # parents outside the kept set (the finalized node's parent) drop
            if name == "parents":
                outside = ok & ~keep[np.clip(vals, 0, n - 1)]
                remapped[outside] = NONE
            col[:m] = remapped
        self.roots = [self.roots[i] for i in kept_idx]
        self.justified_roots = [self.justified_roots[i] for i in kept_idx]
        self.unrealized_justified_roots = [
            self.unrealized_justified_roots[i] for i in kept_idx]
        self.indices = {r: i for i, r in enumerate(self.roots)}
        if hasattr(self, "_viable"):
            self._viable = self._viable[kept_idx]
        self.n_nodes = m
        return mapping
