"""Pre-BLS coalescing: dedup + blinded same-message merge.

"Performance of EdDSA and BLS Signatures in Committee-Based Consensus"
(PAPERS.md) makes the cost model explicit: the pairing is the unit of
account, and every overlapping committee contribution merged *before*
verification is a pairing never paid for.  At mainnet width one slot's
unaggregated attestations are thousands of signature sets that share a
handful of distinct messages (one per (slot, committee index,
beacon_block_root) — the AttestationData signing root), so the flood
batch the dispatch thread sweeps up is massively mergeable.

Two stages, both applied to the flat ``SignatureSet`` list immediately
before ``verify_signature_sets``:

1. **Exact-duplicate dedup** — a hostile duplicate flood (or honest
   gossip re-delivery) puts byte-identical sets in one sweep; the dup
   caches only reject them AFTER signature verification (by design:
   unauthenticated garbage must not suppress honest messages), so
   without this stage every copy costs BLS work.  Byte-equal sets
   verify once.

2. **Blinded same-message merge** — sets sharing a message fold into
   ONE set: ``merged_sig = Σ rᵢ·sigᵢ`` with per-constituent random
   64-bit blinders ``rᵢ`` and pubkeys ``[rᵢ·aggpkᵢ]``.  The blinders
   make the fold sound: without them two adversarially-crafted invalid
   signatures could cancel (``sig₁ = good+δ, sig₂ = good₂−δ``) and ride
   a merged set through verification — exactly the attack the batch
   backends' own random coefficients exist to stop, applied here one
   level earlier.  With blinding, the merged set verifies iff (with
   probability 1 − 2⁻⁶⁴ per constituent) every constituent verifies —
   the property tests/test_pool.py pins.

Failure semantics are strictly conservative: a group whose members
don't decompress (fake-crypto tests), carry an infinity signature, or
fail any step of the fold passes through UNMERGED — the backend then
sees the original sets and the existing bisection fallback attributes
failures item-by-item.  Coalescing can only remove redundant pairings,
never change a verdict.

``LHTPU_PRE_BLS=0`` disables the stage (chaos/debug escape hatch).
"""

from __future__ import annotations

from dataclasses import dataclass

from lighthouse_tpu.common import env as envreg
from lighthouse_tpu.common.metrics import record_swallowed


@dataclass
class CoalesceStats:
    sets_in: int = 0
    sets_out: int = 0
    deduped: int = 0          # byte-identical sets dropped
    merged: int = 0           # constituents folded into merged sets
    merge_groups: int = 0     # merged sets produced
    unmergeable: int = 0      # group members passed through on fold failure

    @property
    def pairings_saved(self) -> int:
        """Pairing lanes removed from the batch: each deduped set and
        each folded constituent beyond its group's first."""
        return self.sets_in - self.sets_out


def enabled() -> bool:
    return envreg.get_bool("LHTPU_PRE_BLS", True)


def _set_key(s) -> tuple:
    return (s.signature.to_bytes(), s.message,
            tuple(pk.to_bytes() for pk in s.pubkeys))


def dedup_sets(sets: list) -> tuple[list, "CoalesceStats"]:
    """Drop byte-identical sets (one verification covers every copy)."""
    stats = CoalesceStats(sets_in=len(sets))
    seen: set[tuple] = set()
    out = []
    for s in sets:
        key = _set_key(s)
        if key in seen:
            stats.deduped += 1
            continue
        seen.add(key)
        out.append(s)
    stats.sets_out = len(out)
    return out, stats


def merge_same_message(sets: list) -> tuple[list, "CoalesceStats"]:
    """Fold same-message sets into one blinded set each (see module
    docstring for the soundness argument).  Unfoldable groups pass
    through unchanged."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.crypto.bls import curve as cv

    stats = CoalesceStats(sets_in=len(sets))
    groups: dict[bytes, list] = {}
    order: list[bytes] = []
    for s in sets:
        if s.message not in groups:
            order.append(s.message)
        groups.setdefault(s.message, []).append(s)
    out = []
    for message in order:
        group = groups[message]
        if len(group) == 1:
            out.append(group[0])
            continue
        merged = _fold_group(group, message, bls, cv)
        if merged is None:
            # conservative pass-through: the batch backend + bisection
            # fallback handle whatever made the group unfoldable
            stats.unmergeable += len(group)
            out.extend(group)
            continue
        stats.merged += len(group)
        stats.merge_groups += 1
        out.append(merged)
    stats.sets_out = len(out)
    return out, stats


def _fold_group(group: list, message: bytes, bls, cv):
    """One blinded merged set for a same-message group, or None when any
    constituent resists the fold (bad decompress, infinity, missing
    pubkeys)."""
    import secrets

    sig_acc = cv.INF
    pubkeys = []
    try:
        for s in group:
            sig_pt = s.signature.point  # decompress + subgroup check
            if sig_pt is cv.INF or not s.pubkeys:
                return None
            agg_pk = s.aggregate_pubkey()
            r = 0
            while r == 0:
                r = secrets.randbits(64)
            sig_acc = cv.g2_add(sig_acc, cv.g2_mul(sig_pt, r))
            pk_pt = cv.g1_mul(agg_pk, r)
            pubkeys.append(bls.PublicKey(cv.g1_to_bytes(pk_pt), pk_pt))
        merged_sig = bls.Signature(cv.g2_to_bytes(sig_acc), sig_acc)
    except (bls.BlsError, ValueError, TypeError) as e:
        record_swallowed("pre_aggregation.fold", e)
        return None
    return bls.SignatureSet(merged_sig, pubkeys, message)


def coalesce_sets(sets: list) -> tuple[list, "CoalesceStats"]:
    """The full pre-BLS stage: dedup, then blinded same-message merge.
    Returns the coalesced list and combined stats; with LHTPU_PRE_BLS=0
    (or fewer than 2 sets) the input passes through untouched."""
    stats = CoalesceStats(sets_in=len(sets), sets_out=len(sets))
    if len(sets) < 2 or not enabled():
        return list(sets), stats
    unique, dstats = dedup_sets(sets)
    merged, mstats = merge_same_message(unique)
    stats.sets_out = len(merged)
    stats.deduped = dstats.deduped
    stats.merged = mstats.merged
    stats.merge_groups = mstats.merge_groups
    stats.unmergeable = mstats.unmergeable
    _record(stats)
    return merged, stats


def _record(stats: CoalesceStats) -> None:
    if stats.sets_in == stats.sets_out:
        return
    try:
        from lighthouse_tpu.common.metrics import REGISTRY

        REGISTRY.counter(
            "pre_bls_deduped_total",
            "byte-identical signature sets dropped before BLS",
        ).inc(stats.deduped)
        REGISTRY.counter(
            "pre_bls_merged_total",
            "signature sets folded into blinded same-message merges",
        ).inc(stats.merged)
        REGISTRY.counter(
            "pre_bls_pairings_saved_total",
            "pairing lanes removed from batches by pre-BLS coalescing",
        ).inc(stats.pairings_saved)
    except (AttributeError, KeyError, TypeError, ValueError) as e:
        record_swallowed("pre_aggregation.record", e)


__all__ = [
    "CoalesceStats",
    "coalesce_sets",
    "dedup_sets",
    "enabled",
    "merge_same_message",
]
