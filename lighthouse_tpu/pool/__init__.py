"""Operation + aggregation pools (reference beacon_node/operation_pool,
beacon_chain/naive_aggregation_pool)."""

from lighthouse_tpu.pool.max_cover import CoverItem, maximum_cover
from lighthouse_tpu.pool.naive_aggregation import NaiveAggregationPool
from lighthouse_tpu.pool.operation_pool import OperationPool
from lighthouse_tpu.pool.pre_aggregation import CoalesceStats, coalesce_sets

__all__ = [
    "CoverItem",
    "maximum_cover",
    "NaiveAggregationPool",
    "OperationPool",
    "CoalesceStats",
    "coalesce_sets",
]
