"""Operation + aggregation pools (reference beacon_node/operation_pool,
beacon_chain/naive_aggregation_pool)."""

from lighthouse_tpu.pool.max_cover import CoverItem, maximum_cover
from lighthouse_tpu.pool.naive_aggregation import NaiveAggregationPool
from lighthouse_tpu.pool.operation_pool import OperationPool

__all__ = [
    "CoverItem",
    "maximum_cover",
    "NaiveAggregationPool",
    "OperationPool",
]
