"""Sync-committee contribution pool.

Rebuild of the sync side of /root/reference/beacon_node/beacon_chain/src/
naive_aggregation_pool.rs plus the SyncAggregate assembly used by block
production: verified gossip sync messages OR into per-(slot, block_root,
subcommittee) contributions; `produce_sync_aggregate` stitches the four
subcommittee contributions into the block's SyncAggregate.
"""

from __future__ import annotations

import numpy as np

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.pool.naive_aggregation import _aggregate


class SyncContributionPool:
    def __init__(self, retained_slots: int = 8):
        self.retained_slots = retained_slots
        # (slot, root, subnet) -> (bits np.bool_[sub_size], [signatures])
        self._entries: dict[tuple, tuple] = {}

    def insert_message(self, message, positions: list[tuple[int, int]],
                       spec) -> bool:
        """Fold one verified SyncCommitteeMessage at its (subnet, position)
        seats.  Returns True if any new bit was contributed."""
        sub_size = (spec.preset.sync_committee_size
                    // spec.sync_committee_subnet_count)
        slot = int(message.slot)
        root = bytes(message.beacon_block_root)
        sig = bls.Signature(bytes(message.signature))
        fresh = False
        for subnet, pos in positions:
            key = (slot, root, int(subnet))
            entry = self._entries.get(key)
            if entry is None:
                bits = np.zeros(sub_size, dtype=bool)
                bits[pos] = True
                self._entries[key] = (bits, [sig])
                fresh = True
                continue
            bits, sigs = entry
            if bits[pos]:
                continue
            bits[pos] = True
            sigs.append(sig)
            fresh = True
        if fresh:
            self._prune()
        return fresh

    def insert_contribution(self, contribution) -> bool:
        """Fold a whole verified SyncCommitteeContribution (non-overlapping
        only, as the naive pool semantics demand)."""
        slot = int(contribution.slot)
        root = bytes(contribution.beacon_block_root)
        subnet = int(contribution.subcommittee_index)
        cbits = np.asarray(contribution.aggregation_bits, dtype=bool)
        sig = bls.Signature(bytes(contribution.signature))
        key = (slot, root, subnet)
        entry = self._entries.get(key)
        if entry is None:
            self._entries[key] = (cbits.copy(), [sig])
            self._prune()
            return True
        bits, sigs = entry
        if (cbits & bits).any() or not (cbits & ~bits).any():
            return False
        bits |= cbits
        sigs.append(sig)
        return True

    def best_contribution(self, slot: int, root: bytes, subnet: int):
        entry = self._entries.get((int(slot), bytes(root), int(subnet)))
        if entry is None:
            return None
        bits, sigs = entry
        return bits.copy(), _aggregate(sigs)

    def produce_sync_aggregate(self, slot: int, root: bytes, spec, t):
        """SyncAggregate for a block whose parent is `root` at `slot`
        (reference: get_sync_aggregate in block production)."""
        size = spec.preset.sync_committee_size
        sub_size = size // spec.sync_committee_subnet_count
        bits = np.zeros(size, dtype=bool)
        sigs = []
        for subnet in range(spec.sync_committee_subnet_count):
            best = self.best_contribution(slot, root, subnet)
            if best is None:
                continue
            sub_bits, sig = best
            bits[subnet * sub_size:(subnet + 1) * sub_size] = sub_bits
            sigs.append(sig)
        if not sigs:
            return t.SyncAggregate(
                sync_committee_bits=[False] * size,
                sync_committee_signature=b"\xc0" + b"\x00" * 95)
        agg = _aggregate(sigs)
        return t.SyncAggregate(
            sync_committee_bits=[bool(b) for b in bits],
            sync_committee_signature=agg.to_bytes()
            if hasattr(agg, "to_bytes") else bytes(agg))

    def _prune(self):
        from lighthouse_tpu.pool.accounting import record_pool_dropped

        slots = {k[0] for k in self._entries}
        if len(slots) <= self.retained_slots:
            return
        cutoff = sorted(slots)[-self.retained_slots]
        for k in [k for k in self._entries if k[0] < cutoff]:
            record_pool_dropped("sync_contribution", "retention")
            del self._entries[k]

    def prune_below(self, slot: int):
        from lighthouse_tpu.pool.accounting import record_pool_dropped

        for k in [k for k in self._entries if k[0] < slot]:
            record_pool_dropped("sync_contribution", "finalized")
            del self._entries[k]

    def __len__(self):
        return len(self._entries)
