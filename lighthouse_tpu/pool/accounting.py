"""Shed/drop accounting funnel for the pools.

The zero-unaccounted-drops discipline (lhlint LH603) extends past the
processor queues: an aggregate evicted from a pool is queued work
discarded, and an operator debugging a missing attestation needs to see
WHERE it went.  Every pool discard routes through
:func:`record_pool_dropped`, the single owner of the
``pool_dropped_total{pool,reason}`` family.

Retention pruning is accounted too — not because pruning is wrong (it
is the design), but because "dropped for retention" vs "dropped under
overload" is exactly the distinction the labels exist to make.
"""

from __future__ import annotations

from lighthouse_tpu.common.metrics import REGISTRY, record_swallowed


def record_pool_dropped(pool: str, reason: str, n: int = 1) -> None:
    """Count ``n`` items discarded from ``pool`` (naive_aggregation /
    op_pool / sync_contribution / reprocess) for ``reason``."""
    if n <= 0:
        return
    try:
        REGISTRY.counter(
            "pool_dropped_total",
            "items discarded from the aggregation/operation pools, by "
            "pool and reason",
        ).labels(pool=pool, reason=reason).inc(n)
    except (AttributeError, KeyError, TypeError, ValueError) as e:
        record_swallowed("pool.accounting", e)


__all__ = ["record_pool_dropped"]
