"""Operation pool: attestations (max-cover packed), slashings, exits,
BLS-to-execution changes.

Rebuild of /root/reference/beacon_node/operation_pool (attestation_storage
+ max_cover + persistence): gossip-verified operations accumulate here and
block production packs them — attestations by greedy weighted max-cover
against the target state's participation flags, other ops by re-checking
validity against the target state.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.types.spec import FAR_FUTURE_EPOCH
from lighthouse_tpu.pool.max_cover import CoverItem, maximum_cover
from lighthouse_tpu.state_transition.misc import get_beacon_committee

_TIMELY_TARGET_BIT = 1 << 1  # TIMELY_TARGET_FLAG_INDEX


@dataclass
class _StoredAttestation:
    data: object
    bits: np.ndarray
    signature: object  # bls.Signature
    committee: int = 0  # electra data carries index=0; track it here


@dataclass
class OperationPool:
    """All pools keyed for dedup; pruning is against a finalized state."""

    attestations: dict = field(default_factory=dict)   # data_root -> [stored]
    exits: dict = field(default_factory=dict)          # vindex -> signed exit
    proposer_slashings: dict = field(default_factory=dict)  # vindex -> op
    attester_slashings: list = field(default_factory=list)
    bls_changes: dict = field(default_factory=dict)    # vindex -> signed change
    max_variants_per_data: int = 8

    # -- attestations -------------------------------------------------------

    def insert_attestation(self, data, bits: np.ndarray, signature,
                           committee_index: int | None = None) -> bool:
        """Insert an aggregate (from the naive pool or gossip aggregates).
        Keeps up to `max_variants_per_data` non-subsumed bitsets per data.
        `committee_index` must be passed for electra-format entries whose
        data.index is 0 (EIP-7549); defaults to data.index."""
        ci = int(data.index) if committee_index is None else committee_index
        root = (data.hash_tree_root(), ci)
        bits = np.asarray(bits, dtype=bool)
        variants = self.attestations.setdefault(root, [])
        for v in variants:
            if (bits & ~v.bits).sum() == 0:
                return False  # subsumed by an existing aggregate
        variants[:] = [v for v in variants if (v.bits & ~bits).any()]
        variants.append(_StoredAttestation(
            data, bits, signature if isinstance(signature, bls.Signature)
            else bls.Signature(bytes(signature)), ci))
        if len(variants) > self.max_variants_per_data:
            from lighthouse_tpu.pool.accounting import record_pool_dropped

            variants.sort(key=lambda v: int(v.bits.sum()), reverse=True)
            record_pool_dropped("op_pool", "variant_eviction",
                                len(variants) - self.max_variants_per_data)
            del variants[self.max_variants_per_data:]
        return True

    def get_attestations(self, state, spec, shuffle_for_epoch, limit=None,
                         t=None):
        """Max-cover pack attestations for a block on `state`
        (/root/reference/beacon_node/operation_pool/src/attestation.rs).

        shuffle_for_epoch: epoch -> full committee shuffle (the chain's
        shuffling cache hook).  Weight = effective balance of attesters
        whose TIMELY_TARGET flag is still unset for the matching epoch.
        """
        slot = int(state.slot)
        cur_epoch = spec.compute_epoch_at_slot(slot)
        fork_now = spec.fork_at_epoch(cur_epoch)
        electra = spec.fork_at_least(fork_now, "electra")
        if limit is None:
            # electra blocks carry fewer, wider attestations (EIP-7549)
            limit = (spec.preset.max_attestations_electra if electra
                     else spec.preset.max_attestations)
        prev_epoch = max(cur_epoch - 1, 0)
        items = []
        eb = np.asarray(state.validators.effective_balance, np.int64)
        cur_part = np.asarray(state.current_epoch_participation, np.uint8)
        prev_part = np.asarray(state.previous_epoch_participation, np.uint8)
        # pre-deneb inclusion window: delay <= SLOTS_PER_EPOCH (deneb
        # removed the upper bound, EIP-7045); constant per call, hoisted
        post_7045 = spec.fork_at_least(fork_now, "deneb")
        # the state's justified checkpoints are constant per call too;
        # the per-attestation source gate compares against these tuples
        cur_src = (int(state.current_justified_checkpoint.epoch),
                   bytes(state.current_justified_checkpoint.root))
        prev_src = (int(state.previous_justified_checkpoint.epoch),
                    bytes(state.previous_justified_checkpoint.root))
        for variants in self.attestations.values():
            for stored in variants:
                att_slot = int(stored.data.slot)
                target_epoch = int(stored.data.target.epoch)
                if target_epoch not in (cur_epoch, prev_epoch):
                    continue
                if att_slot + spec.min_attestation_inclusion_delay > slot:
                    continue
                if (not post_7045
                        and slot - att_slot > spec.preset.slots_per_epoch):
                    continue
                # format boundary (EIP-7549): the signature commits to
                # data.index, so electra blocks can only carry entries
                # signed over index=0, and legacy blocks only entries
                # whose index matches their committee
                if electra and int(stored.data.index) != 0:
                    continue
                if not electra and stored.committee != int(stored.data.index):
                    continue
                # the transition hard-fails attestations whose source is
                # not THIS state's justified checkpoint (spec
                # is_matching_source); on a forked network the pool
                # holds votes from both branches, so packing one from
                # the other side would abort the whole block build
                src = (cur_src if target_epoch == cur_epoch else prev_src)
                if (int(stored.data.source.epoch),
                        bytes(stored.data.source.root)) != src:
                    continue
                part = cur_part if target_epoch == cur_epoch else prev_part
                try:
                    shuffle = shuffle_for_epoch(target_epoch)
                    committee = get_beacon_committee(
                        state, spec, att_slot, stored.committee, shuffle)
                except Exception:
                    continue
                if committee.shape[0] != stored.bits.shape[0]:
                    continue
                attesters = committee[stored.bits]
                in_range = attesters[attesters < part.shape[0]]
                fresh = in_range[(part[in_range] & _TIMELY_TARGET_BIT) == 0]
                if fresh.size == 0:
                    continue
                items.append(CoverItem(
                    stored, {int(v): int(eb[v]) for v in fresh}))
        if t is None:
            raise TypeError("pass t= (the preset type namespace)")
        chosen = maximum_cover(items, limit)
        out = []
        for c in chosen:
            s = c.item
            if electra:
                # on-chain electra format (EIP-7549): data.index is
                # already 0 (filtered above — the SIGNATURE commits to
                # it); the committee rides in committee_bits
                committee_bits = [
                    i == s.committee
                    for i in range(spec.preset.max_committees_per_slot)]
                att = t.AttestationElectra(
                    aggregation_bits=[bool(b) for b in s.bits],
                    data=s.data, committee_bits=committee_bits,
                    signature=s.signature.to_bytes())
            else:
                att = t.Attestation(
                    aggregation_bits=[bool(b) for b in s.bits],
                    data=s.data,
                    signature=s.signature.to_bytes())
            out.append(att)
        return out

    # -- other operations ---------------------------------------------------

    def insert_voluntary_exit(self, signed_exit) -> bool:
        idx = int(signed_exit.message.validator_index)
        if idx in self.exits:
            return False
        self.exits[idx] = signed_exit
        return True

    def insert_proposer_slashing(self, slashing) -> bool:
        idx = int(slashing.signed_header_1.message.proposer_index)
        if idx in self.proposer_slashings:
            return False
        self.proposer_slashings[idx] = slashing
        return True

    def insert_attester_slashing(self, slashing) -> bool:
        a1 = set(int(i) for i in slashing.attestation_1.attesting_indices)
        a2 = set(int(i) for i in slashing.attestation_2.attesting_indices)
        new = a1 & a2
        for existing in self.attester_slashings:
            e1 = set(int(i) for i in existing.attestation_1.attesting_indices)
            e2 = set(int(i) for i in existing.attestation_2.attesting_indices)
            if new <= (e1 & e2):
                return False
        self.attester_slashings.append(slashing)
        return True

    def insert_bls_to_execution_change(self, signed_change) -> bool:
        idx = int(signed_change.message.validator_index)
        if idx in self.bls_changes:
            return False
        self.bls_changes[idx] = signed_change
        return True

    def get_voluntary_exits(self, state, spec, limit=None):
        limit = limit if limit is not None else spec.preset.max_voluntary_exits
        epoch = spec.compute_epoch_at_slot(int(state.slot))
        exit_epochs = np.asarray(state.validators.exit_epoch, np.uint64)
        far = FAR_FUTURE_EPOCH
        out = []
        for idx, ex in self.exits.items():
            if len(out) >= limit:
                break
            if idx < exit_epochs.shape[0] and int(exit_epochs[idx]) == far \
                    and int(ex.message.epoch) <= epoch:
                out.append(ex)
        return out

    def get_slashings(self, state, spec):
        slashed = np.asarray(state.validators.slashed, bool)
        prop = []
        for idx, op in self.proposer_slashings.items():
            if len(prop) >= spec.preset.max_proposer_slashings:
                break
            if idx < slashed.shape[0] and not slashed[idx]:
                prop.append(op)
        att = []
        for op in self.attester_slashings:
            if len(att) >= spec.preset.max_attester_slashings:
                break
            a1 = set(int(i) for i in op.attestation_1.attesting_indices)
            a2 = set(int(i) for i in op.attestation_2.attesting_indices)
            live = [i for i in (a1 & a2)
                    if i < slashed.shape[0] and not slashed[i]]
            if live:
                att.append(op)
        return prop, att

    def get_bls_to_execution_changes(self, state, spec, limit=None):
        limit = (limit if limit is not None
                 else spec.preset.max_bls_to_execution_changes)
        wc = state.validators.withdrawal_credentials
        out = []
        for idx, change in self.bls_changes.items():
            if len(out) >= limit:
                break
            if idx < len(state.validators) and wc[idx][0] == 0x00:
                out.append(change)
        return out

    # -- maintenance --------------------------------------------------------

    def prune(self, head_state, spec):
        """Drop operations that can never be included again."""
        cur_epoch = spec.compute_epoch_at_slot(int(head_state.slot))
        keep: dict = {}
        for root, variants in self.attestations.items():
            if variants and int(variants[0].data.target.epoch) + 1 >= cur_epoch:
                keep[root] = variants
        self.attestations = keep
        exit_epochs = np.asarray(head_state.validators.exit_epoch, np.uint64)
        far = FAR_FUTURE_EPOCH
        self.exits = {i: e for i, e in self.exits.items()
                      if i < exit_epochs.shape[0]
                      and int(exit_epochs[i]) == far}
        slashed = np.asarray(head_state.validators.slashed, bool)
        self.proposer_slashings = {
            i: s for i, s in self.proposer_slashings.items()
            if i < slashed.shape[0] and not slashed[i]}
        self.attester_slashings = [
            s for s in self.attester_slashings
            if any(i < slashed.shape[0] and not slashed[i]
                   for i in (set(int(x) for x in s.attestation_1.attesting_indices)
                             & set(int(x) for x in s.attestation_2.attesting_indices)))]

    def num_attestations(self) -> int:
        return sum(len(v) for v in self.attestations.values())
