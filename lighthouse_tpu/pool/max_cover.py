"""Greedy weighted maximum-coverage packing.

Rebuild of /root/reference/beacon_node/operation_pool/src/max_cover.rs:
pick up to `limit` items maximizing total covered weight, rescoring the
remaining candidates after every pick (the classic (1 - 1/e)
approximation).  Items expose their coverage as a dict of
element -> weight; chosen items report only their FRESH coverage.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Generic, Iterable, TypeVar

T = TypeVar("T")


@dataclass
class CoverItem(Generic[T]):
    item: T
    covering: dict          # element -> weight (mutated during packing)


def maximum_cover(items: Iterable[CoverItem], limit: int) -> list[CoverItem]:
    """Greedy max-cover; each returned CoverItem.covering holds exactly
    the elements it was credited with (its marginal contribution)."""
    candidates = [CoverItem(c.item, dict(c.covering)) for c in items]
    chosen: list[CoverItem] = []
    while candidates and len(chosen) < limit:
        best_i = max(range(len(candidates)),
                     key=lambda i: sum(candidates[i].covering.values()))
        best = candidates.pop(best_i)
        if not best.covering or sum(best.covering.values()) == 0:
            break
        for c in candidates:
            for k in best.covering:
                c.covering.pop(k, None)
        chosen.append(best)
        candidates = [c for c in candidates if c.covering]
    return chosen
