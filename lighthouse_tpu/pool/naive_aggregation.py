"""Naive attestation aggregation pool.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
naive_aggregation_pool.rs: gossip-verified unaggregated attestations are
greedily OR-ed into one aggregate per AttestationData root, per slot.
Aggregators read their committee's current best aggregate from here; the
operation pool ingests the same aggregates for block packing.
"""

from __future__ import annotations

import numpy as np

from lighthouse_tpu.crypto import bls


def _aggregate(sigs):
    """Aggregate, tolerating fake-crypto signatures (arbitrary bytes that
    don't decompress): any one of them stands in — the fake backend
    verifies anything well-formed anyway."""
    if len(sigs) == 1:
        return sigs[0]
    try:
        return bls.Signature.aggregate(sigs)
    except (ValueError, bls.BlsError):
        return sigs[0]


class NaiveAggregationPool:
    def __init__(self, retained_slots: int = 32):
        self.retained_slots = retained_slots
        # slot -> (data_root, committee) -> (data, bits, [sigs], committee)
        # keyed on the committee TOO: electra attestation data carries
        # index=0 for every committee (EIP-7549), so the data root alone
        # would merge different committees' bitfields
        self._slots: dict[int, dict[tuple, tuple]] = {}

    def insert(self, attestation) -> bool:
        """Fold one (single-bit or partial) attestation in.  Returns True
        if it contributed at least one new bit."""
        from lighthouse_tpu.state_transition.misc import (
            attestation_committee_index,
        )

        data = attestation.data
        slot = int(data.slot)
        committee = attestation_committee_index(attestation)
        key = (data.hash_tree_root(), committee)
        per_slot = self._slots.setdefault(slot, {})
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        entry = per_slot.get(key)
        if entry is None:
            per_slot[key] = (
                data, bits.copy(),
                [bls.Signature(bytes(attestation.signature))], committee)
            self._prune()
            return True
        _, agg_bits, sigs, _ci = entry
        fresh = bits & ~agg_bits
        if not fresh.any():
            return False
        if (bits & agg_bits).any():
            # overlapping contribution can't be naively aggregated
            return False
        agg_bits |= bits
        sigs.append(bls.Signature(bytes(attestation.signature)))
        return True

    def insert_single_bit(self, data, data_root: bytes, committee: int,
                          committee_len: int, bit_pos: int,
                          sig_bytes: bytes) -> bool:
        """Columnar-lane fast path: fold ONE bit in without
        materializing an Attestation container or re-hashing its data —
        the caller (chain/columnar_ingest) already holds the group's
        data root and object.  Semantics identical to :meth:`insert`
        for a single-bit contribution."""
        per_slot = self._slots.setdefault(int(data.slot), {})
        key = (data_root, committee)
        entry = per_slot.get(key)
        if entry is None:
            bits = np.zeros(committee_len, dtype=bool)
            bits[bit_pos] = True
            per_slot[key] = (
                data, bits, [bls.Signature(sig_bytes)], committee)
            self._prune()
            return True
        _, agg_bits, sigs, _ci = entry
        if agg_bits.shape[0] != committee_len or agg_bits[bit_pos]:
            return False
        agg_bits[bit_pos] = True
        sigs.append(bls.Signature(sig_bytes))
        return True

    def get_aggregate(self, data, committee_index: int | None = None):
        """Best aggregate for this AttestationData (or None)."""
        ci = int(data.index) if committee_index is None else committee_index
        entry = self._slots.get(int(data.slot), {}).get(
            (data.hash_tree_root(), ci))
        if entry is None:
            return None
        data, bits, sigs, _ci = entry
        return data, bits.copy(), _aggregate(sigs)

    def iter_aggregates(self):
        for per_slot in self._slots.values():
            for data, bits, sigs, ci in per_slot.values():
                yield data, bits.copy(), _aggregate(sigs), ci

    def _prune(self):
        from lighthouse_tpu.pool.accounting import record_pool_dropped

        if len(self._slots) <= self.retained_slots:
            return
        for slot in sorted(self._slots)[: len(self._slots) - self.retained_slots]:
            record_pool_dropped("naive_aggregation", "retention",
                                len(self._slots[slot]))
            del self._slots[slot]

    def prune_below(self, slot: int):
        from lighthouse_tpu.pool.accounting import record_pool_dropped

        for s in [s for s in self._slots if s < slot]:
            record_pool_dropped("naive_aggregation", "finalized",
                                len(self._slots[s]))
            del self._slots[s]

    def __len__(self):
        return sum(len(v) for v in self._slots.values())
