"""Naive attestation aggregation pool.

Rebuild of /root/reference/beacon_node/beacon_chain/src/
naive_aggregation_pool.rs: gossip-verified unaggregated attestations are
greedily OR-ed into one aggregate per AttestationData root, per slot.
Aggregators read their committee's current best aggregate from here; the
operation pool ingests the same aggregates for block packing.
"""

from __future__ import annotations

import numpy as np

from lighthouse_tpu.crypto import bls


def _aggregate(sigs):
    """Aggregate, tolerating fake-crypto signatures (arbitrary bytes that
    don't decompress): any one of them stands in — the fake backend
    verifies anything well-formed anyway."""
    if len(sigs) == 1:
        return sigs[0]
    try:
        return bls.Signature.aggregate(sigs)
    except (ValueError, bls.BlsError):
        return sigs[0]


class NaiveAggregationPool:
    def __init__(self, retained_slots: int = 32):
        self.retained_slots = retained_slots
        # slot -> data_root -> (data, bits np.bool_, [signatures])
        self._slots: dict[int, dict[bytes, tuple]] = {}

    def insert(self, attestation) -> bool:
        """Fold one (single-bit or partial) attestation in.  Returns True
        if it contributed at least one new bit."""
        data = attestation.data
        slot = int(data.slot)
        data_root = data.hash_tree_root()
        per_slot = self._slots.setdefault(slot, {})
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        entry = per_slot.get(data_root)
        if entry is None:
            per_slot[data_root] = (
                data, bits.copy(),
                [bls.Signature(bytes(attestation.signature))])
            self._prune()
            return True
        _, agg_bits, sigs = entry
        fresh = bits & ~agg_bits
        if not fresh.any():
            return False
        if (bits & agg_bits).any():
            # overlapping contribution can't be naively aggregated
            return False
        agg_bits |= bits
        sigs.append(bls.Signature(bytes(attestation.signature)))
        return True

    def get_aggregate(self, data) -> "object | None":
        """Best aggregate for this AttestationData (or None)."""
        entry = self._slots.get(int(data.slot), {}).get(data.hash_tree_root())
        if entry is None:
            return None
        data, bits, sigs = entry
        return data, bits.copy(), _aggregate(sigs)

    def iter_aggregates(self):
        for per_slot in self._slots.values():
            for data, bits, sigs in per_slot.values():
                yield data, bits.copy(), _aggregate(sigs)

    def _prune(self):
        if len(self._slots) <= self.retained_slots:
            return
        for slot in sorted(self._slots)[: len(self._slots) - self.retained_slots]:
            del self._slots[slot]

    def prune_below(self, slot: int):
        for s in [s for s in self._slots if s < slot]:
            del self._slots[s]

    def __len__(self):
        return sum(len(v) for v in self._slots.values())
