"""Eth1 deposit follower + genesis (reference beacon_node/eth1,
beacon_node/genesis)."""

from lighthouse_tpu.eth1.deposit_tree import DepositTree
from lighthouse_tpu.eth1.service import (
    DepositLog,
    Eth1Block,
    Eth1GenesisService,
    Eth1Service,
    Eth1ServiceConfig,
    MockEth1Endpoint,
)

__all__ = [
    "DepositLog",
    "DepositTree",
    "Eth1Block",
    "Eth1GenesisService",
    "Eth1Service",
    "Eth1ServiceConfig",
    "MockEth1Endpoint",
]
