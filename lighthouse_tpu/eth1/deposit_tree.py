"""Incremental deposit-contract merkle tree (depth 32) with proofs.

Rebuild of the deposit-tree logic the reference gets from its
`deposit_contract`/merkle code (/root/reference/common/deposit_contract,
consensus/merkle_proof): the classic incremental algorithm the contract
itself runs (branch array of left siblings), extended with full-leaf
retention so inclusion proofs for any (index, count) pair can be built —
what `process_deposit`'s `is_valid_merkle_branch` verifies against
`eth1_data.deposit_root` (block_processing.py:436).
"""

from __future__ import annotations

import hashlib

DEPOSIT_CONTRACT_TREE_DEPTH = 32


def _h(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _zero_hashes() -> list[bytes]:
    out = [b"\x00" * 32]
    for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH):
        out.append(_h(out[-1], out[-1]))
    return out


_ZEROS = _zero_hashes()


class DepositTree:
    def __init__(self):
        self.leaves: list[bytes] = []
        self._zeros = _ZEROS

    def push(self, deposit_data_root: bytes) -> None:
        self.leaves.append(bytes(deposit_data_root))

    def __len__(self) -> int:
        return len(self.leaves)

    def _root_at(self, count: int) -> bytes:
        """Tree root over the first `count` leaves (no length mix-in)."""
        level = self.leaves[:count]
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if len(level) % 2:
                level = level + [self._zeros[d]]
            level = [_h(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
            if not level:
                level = [self._zeros[d + 1]]
        return level[0]

    def root(self, count: int | None = None) -> bytes:
        """deposit_root as the contract reports it: tree root mixed with
        the deposit count (SSZ List semantics)."""
        n = len(self.leaves) if count is None else count
        return _h(self._root_at(n), n.to_bytes(32, "little"))

    def _subtree_root(self, offset: int, size: int) -> bytes:
        """Root of the FULL subtree over leaves[offset:offset+size]
        (size a power of two)."""
        level = [bytes(x) for x in self.leaves[offset:offset + size]]
        while len(level) > 1:
            level = [_h(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
        return level[0]

    def snapshot(self, count: int | None = None) -> dict:
        """EIP-4881 deposit tree snapshot: the minimal set of finalized
        node hashes (full-subtree roots, left to right — one per set bit
        of count) from which the tree over the first `count` deposits is
        reconstructible, plus the summary fields the standard
        /eth/v1/beacon/deposit_snapshot endpoint serves (reference
        deposit_snapshot.rs / the eip_4881 crate)."""
        n = len(self.leaves) if count is None else count
        finalized = []
        offset = 0
        for bit in reversed(range(max(n.bit_length(), 1))):
            size = 1 << bit
            if n & size:
                finalized.append(self._subtree_root(offset, size))
                offset += size
        return {
            "finalized": finalized,
            "deposit_root": self.root(n),
            "deposit_count": n,
        }

    @staticmethod
    def from_snapshot(snapshot: dict) -> "DepositTreeSummary":
        """Reconstruct a verifier for the snapshot (root recomputation —
        the EIP-4881 resume path)."""
        return DepositTreeSummary(
            [bytes(h) for h in snapshot["finalized"]],
            int(snapshot["deposit_count"]))

    def proof(self, index: int, count: int | None = None) -> list[bytes]:
        """33-element branch (32 siblings + length mix-in) proving leaf
        `index` against root(count)."""
        n = len(self.leaves) if count is None else count
        if not 0 <= index < n:
            raise IndexError("deposit index outside tree")
        level = [bytes(x) for x in self.leaves[:n]]
        path = []
        idx = index
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if len(level) % 2:
                level = level + [self._zeros[d]]
            sibling = idx ^ 1
            path.append(level[sibling] if sibling < len(level)
                        else self._zeros[d])
            level = [_h(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
            if not level:
                level = [self._zeros[d + 1]]
            idx //= 2
        path.append(n.to_bytes(32, "little"))
        return path


class DepositTreeSummary:
    """Deposit tree reconstructed from an EIP-4881 snapshot: enough to
    recompute deposit_root and keep appending new deposits WITHOUT the
    pre-snapshot leaves (the whole point of the format — a checkpoint-
    synced node never replays historical deposit logs)."""

    def __init__(self, finalized: list[bytes], deposit_count: int):
        self.finalized = list(finalized)
        self.deposit_count = int(deposit_count)
        self._zeros = _ZEROS

    def root(self) -> bytes:
        """deposit_root from the finalized subtree roots alone (must
        equal DepositTree.root(count)).

        Depth walk: `node` is the root of the rightmost partial region
        at depth d.  A set bit of count at depth d means a full finalized
        subtree sits to the LEFT (consume the next ascending-size root);
        a clear bit means the region's right sibling is all zeros."""
        n = self.deposit_count
        fin = list(reversed(self.finalized))      # ascending sizes
        node = self._zeros[0]
        i = 0
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if (n >> d) & 1:
                node = _h(fin[i], node)
                i += 1
            else:
                node = _h(node, self._zeros[d])
        return _h(node, n.to_bytes(32, "little"))
