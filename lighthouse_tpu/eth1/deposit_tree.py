"""Incremental deposit-contract merkle tree (depth 32) with proofs.

Rebuild of the deposit-tree logic the reference gets from its
`deposit_contract`/merkle code (/root/reference/common/deposit_contract,
consensus/merkle_proof): the classic incremental algorithm the contract
itself runs (branch array of left siblings), extended with full-leaf
retention so inclusion proofs for any (index, count) pair can be built —
what `process_deposit`'s `is_valid_merkle_branch` verifies against
`eth1_data.deposit_root` (block_processing.py:436).
"""

from __future__ import annotations

import hashlib

DEPOSIT_CONTRACT_TREE_DEPTH = 32


def _h(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


class DepositTree:
    def __init__(self):
        self.leaves: list[bytes] = []
        self._zeros = [b"\x00" * 32]
        for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            self._zeros.append(_h(self._zeros[-1], self._zeros[-1]))

    def push(self, deposit_data_root: bytes) -> None:
        self.leaves.append(bytes(deposit_data_root))

    def __len__(self) -> int:
        return len(self.leaves)

    def _root_at(self, count: int) -> bytes:
        """Tree root over the first `count` leaves (no length mix-in)."""
        level = self.leaves[:count]
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if len(level) % 2:
                level = level + [self._zeros[d]]
            level = [_h(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
            if not level:
                level = [self._zeros[d + 1]]
        return level[0]

    def root(self, count: int | None = None) -> bytes:
        """deposit_root as the contract reports it: tree root mixed with
        the deposit count (SSZ List semantics)."""
        n = len(self.leaves) if count is None else count
        return _h(self._root_at(n), n.to_bytes(32, "little"))

    def proof(self, index: int, count: int | None = None) -> list[bytes]:
        """33-element branch (32 siblings + length mix-in) proving leaf
        `index` against root(count)."""
        n = len(self.leaves) if count is None else count
        if not 0 <= index < n:
            raise IndexError("deposit index outside tree")
        level = [bytes(x) for x in self.leaves[:n]]
        path = []
        idx = index
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if len(level) % 2:
                level = level + [self._zeros[d]]
            sibling = idx ^ 1
            path.append(level[sibling] if sibling < len(level)
                        else self._zeros[d])
            level = [_h(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
            if not level:
                level = [self._zeros[d + 1]]
            idx //= 2
        path.append(n.to_bytes(32, "little"))
        return path
