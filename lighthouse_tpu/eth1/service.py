"""Eth1 deposit-contract follower + eth1data voting + eth1 genesis.

Rebuild of /root/reference/beacon_node/eth1/src/service.rs:393-463 and
beacon_node/genesis/src/eth1_genesis_service.rs: poll an execution
endpoint for deposit logs and eth1 blocks into caches, serve
`get_eth1_vote` for block production (majority vote within the voting
period, else the follow-distance candidate), and drive genesis from
deposit events once the min-validator/genesis-time conditions hold.

The endpoint interface is the tiny slice of eth JSON-RPC the reference
uses (blockNumber / getBlockByNumber / deposit logs); `MockEth1Endpoint`
implements it in-process and is also served over HTTP by the mock EL.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lighthouse_tpu import types as T
from lighthouse_tpu.eth1.deposit_tree import DepositTree


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int
    deposit_count: int
    deposit_root: bytes


@dataclass
class DepositLog:
    index: int
    block_number: int
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: int
    signature: bytes

    def to_deposit_data(self):
        return T.DepositData(
            pubkey=self.pubkey,
            withdrawal_credentials=self.withdrawal_credentials,
            amount=self.amount, signature=self.signature)


class MockEth1Endpoint:
    """In-process deposit-contract chain for tests/genesis drills."""

    def __init__(self, seconds_per_block: int = 14, genesis_time: int = 0):
        self.seconds_per_block = seconds_per_block
        self.blocks: list[Eth1Block] = [Eth1Block(
            0, b"\x11" * 32, genesis_time, 0, DepositTree().root(0))]
        self.logs: list[DepositLog] = []
        self.tree = DepositTree()

    def add_deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                    amount: int, signature: bytes) -> DepositLog:
        log = DepositLog(
            index=len(self.logs), block_number=len(self.blocks),
            pubkey=pubkey, withdrawal_credentials=withdrawal_credentials,
            amount=amount, signature=signature)
        self.logs.append(log)
        self.tree.push(log.to_deposit_data().hash_tree_root())
        self.mine_block()
        return log

    def mine_block(self) -> Eth1Block:
        prev = self.blocks[-1]
        import hashlib

        num = prev.number + 1
        blk = Eth1Block(
            number=num,
            hash=hashlib.sha256(b"eth1" + num.to_bytes(8, "little")).digest(),
            timestamp=prev.timestamp + self.seconds_per_block,
            deposit_count=len(self.logs),
            deposit_root=self.tree.root(len(self.logs)))
        self.blocks.append(blk)
        return blk

    # -- the JSON-RPC-shaped read interface -------------------------------

    def block_number(self) -> int:
        return self.blocks[-1].number

    def block_by_number(self, number: int) -> Eth1Block | None:
        return self.blocks[number] if 0 <= number < len(self.blocks) else None

    def deposit_logs_in_range(self, lo: int, hi: int) -> list[DepositLog]:
        return [l for l in self.logs if lo <= l.block_number < hi]


@dataclass
class Eth1ServiceConfig:
    follow_distance: int = 16
    max_blocks_per_poll: int = 1024


class Eth1Service:
    """Deposit/block cache updater (reference service.rs update loop)."""

    def __init__(self, endpoint, spec: T.ChainSpec,
                 config: Eth1ServiceConfig | None = None):
        self.endpoint = endpoint
        self.spec = spec
        self.config = config or Eth1ServiceConfig()
        self.blocks: list[Eth1Block] = []
        self.deposits: list[DepositLog] = []
        self.tree = DepositTree()
        self._next_block = 0

    def update(self) -> int:
        """One poll: ingest new blocks (up to the follow head) + logs.
        Returns how many blocks were ingested."""
        head = self.endpoint.block_number()
        target = max(head - self.config.follow_distance, 0)
        n = 0
        while (self._next_block <= target
               and n < self.config.max_blocks_per_poll):
            blk = self.endpoint.block_by_number(self._next_block)
            if blk is None:
                break
            for log in self.endpoint.deposit_logs_in_range(
                    self._next_block, self._next_block + 1):
                self.deposits.append(log)
                self.tree.push(log.to_deposit_data().hash_tree_root())
            self.blocks.append(blk)
            self._next_block += 1
            n += 1
        return n

    # -- eth1data voting (reference: eth1_chain.rs vote calculation) ------

    def eth1_data_for_block(self, block: Eth1Block) -> T.Eth1Data:
        return T.Eth1Data(
            deposit_root=block.deposit_root,
            deposit_count=block.deposit_count,
            block_hash=block.hash)

    def get_eth1_vote(self, state) -> T.Eth1Data:
        spec = self.spec
        period_slots = (spec.preset.epochs_per_eth1_voting_period
                        * spec.slots_per_epoch)
        period_start_slot = (int(state.slot) // period_slots) * period_slots
        period_start_time = (int(state.genesis_time)
                             + period_start_slot * spec.seconds_per_slot)
        lookahead = (self.config.follow_distance
                     * 14)  # seconds per eth1 block, spec-nominal
        candidates = [b for b in self.blocks
                      if b.timestamp + lookahead <= period_start_time
                      and b.deposit_count
                      >= int(state.eth1_data.deposit_count)]
        votes = {}
        for vote in state.eth1_data_votes:
            key = (bytes(vote.deposit_root), int(vote.deposit_count),
                   bytes(vote.block_hash))
            votes[key] = votes.get(key, 0) + 1
        valid_keys = {(bytes(b.deposit_root), b.deposit_count, b.hash)
                      for b in candidates}
        cast = [(count, key) for key, count in votes.items()
                if key in valid_keys]
        if cast:
            _, key = max(cast)
            return T.Eth1Data(deposit_root=key[0], deposit_count=key[1],
                              block_hash=key[2])
        if candidates:
            b = candidates[-1]
            return self.eth1_data_for_block(b)
        return state.eth1_data

    def deposits_for_inclusion(self, state, max_deposits: int,
                               eth1_data=None) -> list:
        """Deposits [state.eth1_deposit_index, …) with proofs against the
        given eth1_data root — the POST-vote data when the block's vote
        reaches majority (reference deposit_cache get_deposits)."""
        data = eth1_data if eth1_data is not None else state.eth1_data
        start = int(state.eth1_deposit_index)
        count = int(data.deposit_count)
        end = min(start + max_deposits, count, len(self.deposits))
        out = []
        for i in range(start, end):
            log = self.deposits[i]
            out.append(T.Deposit(
                proof=self.tree.proof(i, count),
                data=log.to_deposit_data()))
        return out


class Eth1GenesisService:
    """Drive genesis from deposit-contract events
    (reference eth1_genesis_service.rs): wait until enough valid deposits
    and a genesis time, then build the genesis state by applying the
    deposits in order."""

    def __init__(self, eth1: Eth1Service, spec: T.ChainSpec,
                 fork: str = "phase0"):
        self.eth1 = eth1
        self.spec = spec
        self.fork = fork

    def try_genesis(self, min_validators: int | None = None):
        """One attempt: returns the genesis BeaconState or None."""
        from lighthouse_tpu.state_transition import genesis as gen
        from lighthouse_tpu.state_transition.block_processing import (
            apply_deposit,
        )

        spec = self.spec
        need = (min_validators if min_validators is not None
                else spec.min_genesis_active_validator_count)
        if len(self.eth1.deposits) < need or not self.eth1.blocks:
            return None
        anchor = self.eth1.blocks[-1]
        state = gen.genesis_state(0, spec, self.fork,
                                  genesis_time=anchor.timestamp
                                  + spec.genesis_delay)
        count = len(self.eth1.deposits)
        state.eth1_data = T.Eth1Data(
            deposit_root=self.eth1.tree.root(count),
            deposit_count=count, block_hash=anchor.hash)
        for log in self.eth1.deposits:
            apply_deposit(state, spec, log.to_deposit_data())
            state.eth1_deposit_index += 1
        if len(state.validators) < need:
            return None  # some deposits had invalid signatures
        state.genesis_validators_root = T.ValidatorRegistryType(
            spec.preset.validator_registry_limit
        ).hash_tree_root(state.validators)
        return state
