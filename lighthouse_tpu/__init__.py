"""lighthouse_tpu — a TPU-native Ethereum consensus framework.

A from-scratch rebuild of the capabilities of Lighthouse (the Rust consensus
client, see /root/reference) designed TPU-first:

- The data plane — BLS12-381 batch signature verification, SSZ/SHA-256
  merkleization, KZG blob-proof batches, and vectorized epoch processing —
  runs as JAX/XLA programs (jnp + pallas) over batched lanes.
- The control plane — fork choice, chain orchestration, work scheduling,
  stores, APIs — is host-side Python/C++ built around a beacon-processor
  style batching queue that accumulates device-sized batches.

The architectural seams mirror the reference's (crypto backend trait,
pluggable tree-hash hasher, batching work queue) without porting its code.

Layout:
    ops/               JAX/Pallas device kernels (sha256, bls field/curve, kzg)
    crypto/            BLS & KZG backend registry (reference / fake / tpu)
    ssz/               SSZ types, serialization, hash_tree_root
    types/             Consensus containers (multi-fork), ChainSpec
    state_transition/  per-slot / per-block / per-epoch pure transition
    fork_choice/       proto-array LMD-GHOST
    processor/         priority batching work queue
    parallel/          mesh/sharding helpers for multi-chip scaling
    models/            end-to-end assembled pipelines ("the beacon node core")
    utils/             misc (hex, clock, metrics)
"""

__version__ = "0.1.0"
