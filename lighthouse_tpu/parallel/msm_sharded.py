"""The MSM plane's sharded mesh rung: folds partitioned over devices.

Same model as parallel/epoch_sharded: a windowed MSM fold is pure lane
parallelism (each lane multiplies its own point by its own scalar; the
segment tree only combines lanes of one group), so the lanes partition
over a pow2 1-D mesh with any resident table replicated, and GSPMD
splits the one fused program — no second kernel, no per-device
re-padding (ops/msm's pow2 lane/group buckets always cover a pow2
mesh).  This replaces the per-consumer sharding that lived in
parallel/pubkey_sharded: every gather-track consumer (today the pubkey
plane; LHTPU_MSM_SHARDED gates its auto-pick) shares this one rung.
"""

from __future__ import annotations

import numpy as np

import jax

from lighthouse_tpu.ops import pubkey_kernels


def msm_mesh(n_devices: int | None = None):
    """A pow2-sized 1-D mesh over the available devices."""
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    n = 1 << max(n.bit_length() - 1, 0)  # round DOWN to a power of two
    return Mesh(np.array(devs[:n]), axis_names=("data",))


def gather_fold_sharded(table, row_of_lane: np.ndarray,
                        scalars: np.ndarray, group_of_lane: np.ndarray,
                        n_groups: int, mesh=None):
    """Mesh-sharded :func:`ops.pubkey_kernels.gather_fold` — identical
    contract and verdicts (digest-identity pinned by the property
    suite on virtual devices)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = msm_mesh()
    lane_sh = NamedSharding(mesh, P("data"))
    tbl_sh = NamedSharding(mesh, P())
    return pubkey_kernels.gather_fold(
        table, row_of_lane, scalars, group_of_lane, n_groups,
        shardings=(lane_sh, tbl_sh))


__all__ = ["gather_fold_sharded", "msm_mesh"]
