"""Registry columns sharded over the device mesh for the epoch pass.

The fused epoch program (ops/epoch_kernels) is pure lane parallelism —
every validator's update depends only on its own columns plus small
replicated gather tables — so sharding is exactly the bls_sharded
model with the roles swapped: signature *lanes* there, registry *rows*
here.  Columns are placed with ``NamedSharding(P("data"))``, the
reward/penalty/slashing tables and the packed scalar vector are
replicated, and GSPMD partitions the one fused program across the mesh
with zero cross-chip traffic (table gathers read replicated operands).

The pow2 shape buckets (≥ 256) are always divisible by a pow2 mesh, so
no per-device re-padding is needed — the same jit program and the same
masked-tail semantics as the single-device path apply unchanged.
"""

from __future__ import annotations

import numpy as np

import jax

from lighthouse_tpu.ops import epoch_kernels


def epoch_mesh(n_devices: int | None = None):
    """A pow2-sized 1-D mesh over the available devices."""
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    n = 1 << max(n.bit_length() - 1, 0)  # round DOWN to a power of two
    return Mesh(np.array(devs[:n]), axis_names=("data",))


def epoch_pass_sharded(columns: dict, tables: dict, params: np.ndarray, *,
                       apply_eb: bool, mesh=None):
    """Mesh-sharded fused epoch pass; same contract as
    ops/epoch_kernels.epoch_pass_device (host numpy in/out, one
    dispatch, all fetches before return)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = epoch_mesh()
    n_dev = int(mesh.devices.size)
    bucket = columns["balances"].shape[0]
    assert bucket % n_dev == 0, "pow2 bucket must cover the pow2 mesh"
    col_sh = NamedSharding(mesh, P("data"))
    tbl_sh = NamedSharding(mesh, P())
    return epoch_kernels.epoch_pass_device(
        columns, tables, params, apply_eb=apply_eb,
        shardings=(col_sh, tbl_sh))
