"""Multi-chip dry-run worker: runs in a fresh ``JAX_PLATFORMS=cpu`` process.

Executed as ``python -m lighthouse_tpu.parallel.dryrun_worker N`` by
``__graft_entry__.dryrun_multichip`` with a scrubbed environment, so jax
initializes ONLY the host-CPU platform with N virtual devices — the remote
TPU plugin can never be touched (round-1 failure mode: the in-process
dryrun initialized the TPU backend before re-provisioning CPU devices and
hung; see VERDICT.md weak #2).

The step jitted here is the sharded flagship data plane:

- SSZ/SHA-256 merkleization fold sharded over leaf lanes (the reference's
  tree_hash hot path, /root/reference/consensus/types/src/beacon_state.rs:2031):
  local subtree fold per device, all_gather of the 8 subroots, replicated
  top fold — one jit, bounded compile.
- BLS batch-verify lanes sharded over the mesh: per-device Miller loops,
  psum-style tiny combine of the per-device Fq12 partial products (the
  SURVEY §2.9 data-parallel-over-sets design).  On by default; set
  LHTPU_DRYRUN_BLS=0 to skip (the first cold-cache CPU compile of the
  sharded Miller program costs minutes; it lands in .jax_cache after).

Cross-checks run on the host numpy/hashlib path — no extra device
programs, so the compile count is fixed and small.
"""

from __future__ import annotations

import os
import sys
import time

from lighthouse_tpu.ops import program_store as _pstore

# AOT program-store coverage (lhlint LH606): the dryrun's sharded
# merkle fold is prewarmed by the "dryrun" driver in ops/prewarm
_pstore.register_entry("parallel/dryrun_worker.py::_merkle_dryrun@sharded",
                       driver="dryrun")


def _merkle_dryrun(n_devices: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from lighthouse_tpu.ops import sha256 as sha_ops

    devices = np.array(jax.devices()[:n_devices])
    mesh = Mesh(devices, axis_names=("data",))

    log_local = 6  # 64 leaves per device — tiny shapes, one compile
    n_leaves = n_devices * (1 << log_local)
    leaves = np.arange(n_leaves * 8, dtype=np.uint32).reshape(n_leaves, 8)

    # pad gathered per-device subroots to a power of two so the top fold
    # works for any n_devices (padding lanes are zero words)
    top_n = 1 << max(n_devices - 1, 0).bit_length()

    def local(leaves_block):
        sub = sha_ops.fold_to_root_device(leaves_block)  # [1, 8] subroot
        roots = jax.lax.all_gather(sub[0], "data")  # [n_devices, 8]
        if top_n != n_devices:
            pad = jnp.zeros((top_n - n_devices, 8), jnp.uint32)
            roots = jnp.concatenate([roots, pad], axis=0)
        return sha_ops.fold_to_root_device(roots)  # replicated top fold

    sharded = shard_map(
        local, mesh=mesh, in_specs=(P("data", None),),
        out_specs=P(None, None), check_rep=False)

    arr = jax.device_put(leaves, NamedSharding(mesh, P("data", None)))
    # one-shot warmup compile by design — the whole point of the dryrun
    from lighthouse_tpu.common import device_telemetry as _dtel

    root = _dtel.instrument(
        "parallel/dryrun_worker.py::_merkle_dryrun@sharded",
        jax.jit(sharded))(arr)  # lhlint: allow(jit-in-function)
    root.block_until_ready()

    # host cross-check (hashlib path, zero extra compiles)
    lvl = leaves
    while lvl.shape[0] > top_n:
        lvl = sha_ops.hash_pairs_np(lvl.reshape(lvl.shape[0] // 2, 16))
    tops = np.zeros((top_n, 8), np.uint32)
    tops[: lvl.shape[0]] = lvl
    while tops.shape[0] > 1:
        tops = sha_ops.hash_pairs_np(tops.reshape(tops.shape[0] // 2, 16))
    if not np.array_equal(tops, np.asarray(root)):
        raise AssertionError("multichip merkle root != host root")
    print(f"dryrun merkle ok: {n_devices} devices, root "
          f"{bytes(np.asarray(root)[0].view(np.uint8))[:8].hex()}…")


def _bls_dryrun(n_devices: int) -> None:
    import jax
    import numpy as np

    from lighthouse_tpu.parallel.bls_sharded import verify_signature_sets_sharded
    from lighthouse_tpu.crypto import bls

    sks = [bls.SecretKey.from_bytes(bytes([0] * 31 + [i + 1]))
           for i in range(n_devices)]
    msg = b"m" * 32
    sets = [bls.SignatureSet(sk.sign(msg), [sk.public_key()], msg)
            for sk in sks]
    ok = verify_signature_sets_sharded(sets, n_devices=n_devices)
    if not ok:
        raise AssertionError("sharded BLS batch verify rejected valid sets")
    bad = list(sets)
    bad[0] = bls.SignatureSet(sks[1].sign(msg), [sks[0].public_key()], msg)
    if verify_signature_sets_sharded(bad, n_devices=n_devices):
        raise AssertionError("sharded BLS batch verify accepted invalid set")
    print(f"dryrun bls ok: {n_devices} devices")


def main() -> int:
    n_devices = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    t0 = time.perf_counter()
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    # belt-and-braces: even if a sitecustomize hook forced another
    # platform into the config at interpreter start, pin CPU before any
    # backend initializes (same pattern as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        if isinstance(getattr(_xb, "_backend_factories", None), dict):
            for plat in list(_xb._backend_factories):
                if plat not in ("cpu", "interpreter"):
                    _xb._backend_factories.pop(plat, None)
    except (ImportError, AttributeError):
        # jax moved its private registry — the worker still runs, it just
        # pays the full backend probe
        pass

    n_have = len(jax.devices())
    if n_have < n_devices:
        raise RuntimeError(
            f"worker has {n_have} devices, need {n_devices}; env "
            f"JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r} "
            f"XLA_FLAGS={os.environ.get('XLA_FLAGS')!r}")
    plats = {d.platform for d in jax.devices()[:n_devices]}
    print(f"worker devices: {n_have} ({sorted(plats)}), "
          f"init {time.perf_counter() - t0:.1f}s", flush=True)

    _merkle_dryrun(n_devices)
    # sharded BLS is part of the standard dryrun (the first-ever compile
    # costs minutes on CPU but lands in the persistent .jax_cache; set
    # LHTPU_DRYRUN_BLS=0 to skip explicitly)
    if os.environ.get("LHTPU_DRYRUN_BLS", "1") != "0":
        _bls_dryrun(n_devices)
    print(f"dryrun total {time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
