"""Multi-chip BLS batch verification: signature-set lanes over a device mesh.

The SURVEY §2.9 scaling design: batch signature verification is pure data
parallelism over sets — each device runs Miller loops for its slice of the
(pair) lanes and tree-reduces them to ONE local Fq12 partial product; the
only cross-chip traffic is the tiny all_gather of per-device partials
(12 Fp elements each), multiplied together replicated.  The single final
exponentiation runs on the host once per batch.

Mirrors the single-device path in ops/bls12_381.multi_pairing_device and
the blst batch semantics (/root/reference/crypto/bls/src/impls/blst.rs:37-119).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.ops import bls12_381 as dev
from lighthouse_tpu.ops import bigint as bi


_SHARDED_JIT_CACHE: dict = {}


def _sharded_miller_reduce(mesh, per_dev: int):
    """Jitted shard_map program: lanes [n_dev*per_dev] -> one Fq12 pytree.

    Memoized per (mesh devices, per_dev) — the Miller program costs
    minutes of XLA compile; rebuilding the jit per call would recompile."""
    key = (tuple(d.id for d in mesh.devices.flat), per_dev)
    cached = _SHARDED_JIT_CACHE.get(key)
    if cached is not None:
        return cached
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.devices.size
    assert n_dev & (n_dev - 1) == 0, "mesh size must be a power of two"

    def local(xp, yp, xqa, xqb, yqa, yqb, mask):
        f = dev.batch_miller_loop(xp, yp, xqa, xqb, yqa, yqb)
        part = dev.reduce_product(f, mask)  # [1]-lane local partial
        parts = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, "data", axis=0, tiled=True), part)
        # multiply the n_dev partials down to one lane, replicated
        return dev.reduce_product(
            parts, jnp.ones((n_dev,), bool)) if n_dev > 1 else parts

    spec = P("data", None)
    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(spec,) * 6 + (P("data"),),
        out_specs=P(None, None),
        check_rep=False))
    _SHARDED_JIT_CACHE[key] = fn
    return fn


def multi_pairing_sharded(pairs, mesh) -> "object":
    """Device multi-pairing over a mesh: prod Miller(P_i, Q_i), host final exp.

    Stage wall times land in ``bls_verify_stage_seconds{backend="sharded"}``
    (prep_host / h2d / kernel / d2h / final_exp).  The kernel stage syncs
    the sharded result before timing — one batch-level sync the d2h fetch
    right after would pay anyway, so the pipeline is not serialized."""
    import time

    from lighthouse_tpu.common import tracing
    from lighthouse_tpu.crypto.bls.api import record_stage
    from lighthouse_tpu.crypto.bls.fields import final_exponentiation_fast
    from jax.sharding import NamedSharding, PartitionSpec as P

    with tracing.span("bls.multi_pairing_sharded", lanes=len(pairs),
                      devices=int(mesh.devices.size)):
        n_dev = mesh.devices.size
        t0 = time.perf_counter()
        cols, mask = dev.points_to_device(pairs)
        n = len(pairs)
        # pad so every device holds a power-of-two lane count
        per_dev = 1 << max((n + n_dev - 1) // n_dev - 1, 0).bit_length()
        padded = per_dev * n_dev
        if padded != n:
            cols = [np.concatenate([c, np.tile(c[-1:], (padded - n, 1))])
                    for c in cols]
            mask = np.concatenate([mask, np.zeros(padded - n, bool)])
        fn = _sharded_miller_reduce(mesh, per_dev)
        now = time.perf_counter()
        record_stage("sharded", "prep_host", now - t0)
        t0 = now
        sh = NamedSharding(mesh, P("data", None))
        shm = NamedSharding(mesh, P("data"))
        args = [jax.device_put(jnp.asarray(c), sh) for c in cols]
        mask_dev = jax.device_put(jnp.asarray(mask), shm)
        now = time.perf_counter()
        record_stage("sharded", "h2d", now - t0)
        t0 = now
        f = fn(*args, mask_dev)
        jax.block_until_ready(f)
        now = time.perf_counter()
        record_stage("sharded", "kernel", now - t0)
        t0 = now
        f_host = dev.fq12_from_device(jax.device_get(f))
        now = time.perf_counter()
        record_stage("sharded", "d2h", now - t0)
        t0 = now
        out = final_exponentiation_fast(f_host)
        record_stage("sharded", "final_exp", time.perf_counter() - t0)
        return out


def verify_signature_sets_sharded(
    sets: Sequence, *, n_devices: int | None = None, mesh=None
) -> bool:
    """Batch-verify signature sets with Miller-loop lanes sharded over a mesh.

    Agrees with the single-device "tpu" backend by construction: same host
    prep (ops/bls_backend.prepare_pairs), same Miller formulas, only the
    lane placement differs.
    """
    from jax.sharding import Mesh
    from lighthouse_tpu.crypto.bls.api import record_batch
    from lighthouse_tpu.ops.bls_backend import prepare_pairs

    if not sets:
        return False
    record_batch("sharded", len(sets))
    pairs = prepare_pairs(sets)
    if pairs is None:
        return False
    if mesh is None:
        devs = jax.devices()
        n = n_devices or len(devs)
        mesh = Mesh(np.array(devs[:n]), axis_names=("data",))
    return multi_pairing_sharded(pairs, mesh).is_one()
