"""Multi-chip BLS batch verification: signature-set lanes over a device mesh.

The SURVEY §2.9 scaling design: batch signature verification is pure data
parallelism over sets — each device runs Miller loops for its slice of the
(pair) lanes and tree-reduces them to ONE local Fq12 partial product; the
only cross-chip traffic is the tiny all_gather of per-device partials
(12 Fp elements each), multiplied together replicated.  The single final
exponentiation runs on the host once per batch.

Mirrors the single-device path in ops/bls12_381.multi_pairing_device and
the blst batch semantics (/root/reference/crypto/bls/src/impls/blst.rs:37-119).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.common import device_telemetry as _dtel
from lighthouse_tpu.ops import bls12_381 as dev
from lighthouse_tpu.ops import program_store as _pstore

# AOT program-store coverage (lhlint LH606): the mesh Miller program is
# prewarmed by the "sharded" driver in ops/prewarm
_pstore.register_entry(
    "parallel/bls_sharded.py::_sharded_miller_reduce@shard_map",
    driver="sharded")
from lighthouse_tpu.ops import bigint as bi
from lighthouse_tpu.ops import faults


_SHARDED_JIT_CACHE: dict = {}


def _sharded_miller_reduce(mesh, per_dev: int):
    """Jitted shard_map program: lanes [n_dev*per_dev] -> one Fq12 pytree.

    Memoized per (mesh devices, per_dev) — the Miller program costs
    minutes of XLA compile; rebuilding the jit per call would recompile."""
    key = (tuple(d.id for d in mesh.devices.flat), per_dev)
    cached = _SHARDED_JIT_CACHE.get(key)
    if cached is not None:
        return cached
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.devices.size
    assert n_dev & (n_dev - 1) == 0, "mesh size must be a power of two"

    def local(xp, yp, xqa, xqb, yqa, yqb, mask):
        f = dev.batch_miller_loop(xp, yp, xqa, xqb, yqa, yqb)
        part = dev.reduce_product(f, mask)  # [1]-lane local partial
        parts = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, "data", axis=0, tiled=True), part)
        # multiply the n_dev partials down to one lane, replicated
        return dev.reduce_product(
            parts, jnp.ones((n_dev,), bool)) if n_dev > 1 else parts

    spec = P("data", None)
    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(spec,) * 6 + (P("data"),),
        out_specs=P(None, None),
        check_rep=False))
    fn = _dtel.instrument(
        "parallel/bls_sharded.py::_sharded_miller_reduce@shard_map", fn)
    _SHARDED_JIT_CACHE[key] = fn
    return fn


def _dispatch_chunk(pairs, mesh, stage):
    """Prep + h2d + dispatch for one lane chunk; returns the (not yet
    synced) replicated Fq12 partial.  ``stage`` accumulates prep_host/h2d
    wall seconds so chunked runs report per-stage totals."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = mesh.devices.size
    t0 = time.perf_counter()
    cols, mask = dev.points_to_device(pairs)
    n = len(pairs)
    # pad so every device holds a power-of-two lane count
    per_dev = 1 << max((n + n_dev - 1) // n_dev - 1, 0).bit_length()
    padded = per_dev * n_dev
    if padded != n:
        cols = [np.concatenate([c, np.tile(c[-1:], (padded - n, 1))])
                for c in cols]
        mask = np.concatenate([mask, np.zeros(padded - n, bool)])
    fn = _sharded_miller_reduce(mesh, per_dev)
    now = time.perf_counter()
    stage["prep_host"] += now - t0
    t0 = now
    sh = NamedSharding(mesh, P("data", None))
    shm = NamedSharding(mesh, P("data"))
    args = [jax.device_put(jnp.asarray(c), sh) for c in cols]
    mask_dev = jax.device_put(jnp.asarray(mask), shm)
    stage["h2d"] += time.perf_counter() - t0
    return fn(*args, mask_dev)


def multi_pairing_sharded(pairs, mesh, chunk_size: int | None = None
                          ) -> "object":
    """Device multi-pairing over a mesh: prod Miller(P_i, Q_i), host final exp.

    Lane sets above the pipeline chunk size (chunk_size arg >
    LHTPU_BLS_CHUNK > default) split into fixed power-of-two chunks
    dispatched back-to-back: the host preps and uploads chunk k+1 while
    chunk k's Miller program runs on the mesh, the per-chunk replicated
    partials multiply down on device, and the batch pays ONE d2h fetch +
    ONE final exponentiation — the single-device overlap model of
    ops/dispatch_pipeline applied across chips.

    Stage wall times land in ``bls_verify_stage_seconds{backend="sharded"}``
    (prep_host / h2d / kernel / d2h / final_exp).  The kernel stage syncs
    the (combined) sharded result before timing — one batch-level sync the
    d2h fetch right after would pay anyway, so the pipeline is not
    serialized."""
    import time

    from lighthouse_tpu.common import tracing
    from lighthouse_tpu.crypto.bls.api import record_stage
    from lighthouse_tpu.crypto.bls.fields import final_exponentiation_fast
    from lighthouse_tpu.ops import dispatch_pipeline as dp

    with tracing.span("bls.multi_pairing_sharded", lanes=len(pairs),
                      devices=int(mesh.devices.size)):
        chunks = dp.plan_chunks(len(pairs), dp.chunk_size(chunk_size))
        stage = {"prep_host": 0.0, "h2d": 0.0}
        partials = []
        overlap_s = 0.0
        t_prev = None
        for ci, (lo, hi) in enumerate(chunks):
            faults.fire("chunk", index=ci)
            tc = time.perf_counter()
            partials.append(_dispatch_chunk(pairs[lo:hi], mesh, stage))
            now = time.perf_counter()
            if t_prev is not None:
                overlap_s += now - tc
            t_prev = now
        record_stage("sharded", "prep_host", stage["prep_host"])
        record_stage("sharded", "h2d", stage["h2d"])
        dp.record_pipeline(len(chunks), overlap_s, len(pairs))
        t0 = time.perf_counter()
        f = dp.combine_partials(partials)
        jax.block_until_ready(f)
        now = time.perf_counter()
        record_stage("sharded", "kernel", now - t0)
        t0 = now
        f_host = dev.fq12_from_device(jax.device_get(f))
        now = time.perf_counter()
        record_stage("sharded", "d2h", now - t0)
        t0 = now
        out = final_exponentiation_fast(f_host)
        record_stage("sharded", "final_exp", time.perf_counter() - t0)
        return out


def verify_signature_sets_sharded(
    sets: Sequence, *, n_devices: int | None = None, mesh=None,
    chunk_size: int | None = None
) -> bool:
    """Batch-verify signature sets with Miller-loop lanes sharded over a mesh.

    Agrees with the single-device "tpu" backend by construction: same host
    prep (ops/bls_backend.prepare_pairs), same Miller formulas, only the
    lane placement differs.
    """
    from jax.sharding import Mesh
    from lighthouse_tpu.crypto.bls.api import record_batch
    from lighthouse_tpu.ops.bls_backend import prepare_pairs

    if not sets:
        return False
    # supervisor-visible dispatch boundary (see bls_backend's twin hook)
    if faults.fire("sharded") == "corrupt":
        return faults.corrupt_verdict()
    record_batch("sharded", len(sets))
    pairs = prepare_pairs(sets)
    if pairs is None:
        return False
    if mesh is None:
        devs = jax.devices()
        n = n_devices or len(devs)
        mesh = Mesh(np.array(devs[:n]), axis_names=("data",))
    return multi_pairing_sharded(pairs, mesh, chunk_size=chunk_size).is_one()
