"""Store schema versioning and on-open migrations.

Rebuild of /root/reference/beacon_node/store/src/metadata.rs +
/root/reference/beacon_node/beacon_chain/src/schema_change.rs: the DB
records its schema version; on open, registered migration steps upgrade
it version-by-version, and an unknown/newer version is a hard error.
The database-manager CLI calls `migrate` explicitly for
downgrades-by-tool or offline upgrades.

Crash consistency: every step's writes AND its ``K_SCHEMA`` stamp
commit in ONE ``do_atomically`` batch — a crash anywhere inside the
walk leaves the stored version pointing at the last fully applied step,
and the next open simply resumes the walk from there.  Steps therefore
do not write directly: they append :class:`KeyValueOp` entries to the
batch they are handed.
"""

from __future__ import annotations

from typing import Callable

from lighthouse_tpu.store import envelope
from lighthouse_tpu.store.envelope import StoreCorruptionError
from lighthouse_tpu.store.kv import KeyValueOp

# This module OWNS the meta key bytes; hot_cold.py imports them so the
# on-disk encoding has exactly one definition.
P_META = b"met:"
K_SCHEMA = P_META + b"schema"
K_DB_CONFIG = P_META + b"db_config"
K_SPLIT = P_META + b"split"
K_GENESIS_STATE_ROOT = P_META + b"genesis_state_root"
K_HEAD = P_META + b"head"
K_FORK_CHOICE = P_META + b"fork_choice"
K_OP_POOL = P_META + b"op_pool"
# dirty-shutdown marker: b"dirty" while a HotColdDB is open, b"clean"
# after an orderly close; anything else (or absent on a non-fresh DB)
# triggers the startup integrity sweep.  Raw bytes, no envelope — a
# corrupt marker must read as "dirty", never as an error.
K_DIRTY = P_META + b"dirty"

# every meta record wrapped in the checksum envelope from v3 on
ENVELOPED_META = (K_SPLIT, K_GENESIS_STATE_ROOT, K_HEAD, K_FORK_CHOICE,
                  K_OP_POOL, K_DB_CONFIG)

CURRENT_SCHEMA_VERSION = 3


class MigrationError(ValueError):
    pass


# registry: from_version -> (to_version, step). Steps receive
# (HotColdDB, ops) and append their writes to `ops`; the walk commits
# ops + the version stamp as one atomic batch.
_UP: dict[int, tuple[int, Callable]] = {}
_DOWN: dict[int, tuple[int, Callable]] = {}


def register_migration(from_v: int, to_v: int, up: Callable,
                       down: Callable | None = None) -> None:
    _UP[from_v] = (to_v, up)
    if down is not None:
        _DOWN[to_v] = (from_v, down)


def _encode_version(version: int) -> bytes:
    raw = version.to_bytes(8, "little")
    # pre-v3 schemas store the raw integer (that is what their readers
    # expect after a downgrade); v3+ wraps it like every meta record
    return envelope.wrap(raw) if version >= 3 else raw


def read_schema_version(db) -> int:
    raw = db.hot.get(K_SCHEMA)
    if raw is None:
        return 0
    if envelope.is_enveloped(raw):
        payload = envelope.unwrap(raw, "met:schema")
        if len(payload) != 8:
            raise StoreCorruptionError(
                f"met:schema: version payload is {len(payload)} byte(s), "
                "expected 8")
        return int.from_bytes(payload, "little")
    if len(raw) == 8:  # legacy pre-v3 stamp
        return int.from_bytes(raw, "little")
    raise StoreCorruptionError(
        f"met:schema: {len(raw)} byte(s), neither an envelope nor a "
        "legacy 8-byte version stamp — refusing to guess what ran here")


def _commit_step(db, version: int, extra_ops=()) -> None:
    ops = [*extra_ops, KeyValueOp(K_SCHEMA, _encode_version(version))]
    db.hot.do_atomically(ops)


def initialize_fresh(db) -> int:
    """Fresh DB: stamp v1 then walk the registry to current, so every
    version's on-disk side effects are applied exactly as an upgrade
    would (no hand-maintained 'fresh init' duplicating the steps)."""
    _commit_step(db, 1)
    return migrate_schema(db)


def migrate_schema(db, target: int | None = None) -> int:
    """Walk registered steps from the stored version to `target`
    (default: CURRENT_SCHEMA_VERSION).  Returns the final version.

    Each step's writes and its version stamp are one atomic batch, so
    an interrupted walk resumes from the stored version on reopen."""
    target = CURRENT_SCHEMA_VERSION if target is None else target
    v = read_schema_version(db)
    if v == 0:
        # fresh DB: start from v1 and walk the registry like any upgrade
        _commit_step(db, 1)
        v = 1
    while v < target:
        if v not in _UP:
            raise MigrationError(
                f"no migration path from schema v{v} toward v{target}")
        to_v, step = _UP[v]
        ops: list[KeyValueOp] = []
        step(db, ops)
        _commit_step(db, to_v, ops)
        v = to_v
    while v > target:
        if v not in _DOWN:
            raise MigrationError(
                f"no downgrade path from schema v{v} toward v{target}")
        to_v, step = _DOWN[v]
        ops = []
        step(db, ops)
        _commit_step(db, to_v, ops)
        v = to_v
    return v


# --- v1 -> v2: persist the on-disk config ----------------------------------
# The reference's OnDiskStoreConfig guards against reopening a freezer with
# an incompatible slots_per_restore_point; v2 stores it in metadata and
# HotColdDB validates it on open.

def _v1_to_v2(db, ops) -> None:
    import json

    cfg = json.dumps({
        "slots_per_restore_point": db.slots_per_restore_point,
    }).encode()
    # raw at v2; the v3 step wraps it (matching what a real v2 DB holds)
    ops.append(KeyValueOp(K_DB_CONFIG, cfg))


def _v2_to_v1(db, ops) -> None:
    ops.append(KeyValueOp(K_DB_CONFIG, None))


register_migration(1, 2, _v1_to_v2, _v2_to_v1)


# --- v2 -> v3: checksum envelopes on meta records ---------------------------
# Wrap every existing meta record; the stamp commits in the same batch,
# so a reopened half-migrated DB re-runs the wrap (idempotent: already
# enveloped records are skipped).

def _v2_to_v3(db, ops) -> None:
    for key in ENVELOPED_META:
        raw = db.hot.get(key)
        if raw is not None and not envelope.is_enveloped(raw):
            ops.append(KeyValueOp(key, envelope.wrap(raw)))


def _v3_to_v2(db, ops) -> None:
    for key in ENVELOPED_META:
        raw = db.hot.get(key)
        if raw is not None and envelope.is_enveloped(raw):
            ops.append(KeyValueOp(key, envelope.unwrap(raw, key.decode())))


register_migration(2, 3, _v2_to_v3, _v3_to_v2)


def read_db_config(db) -> dict | None:
    import json

    raw = db.hot.get(K_DB_CONFIG)
    if raw is None:
        return None
    payload = (envelope.unwrap(raw, "met:db_config")
               if envelope.is_enveloped(raw) else raw)
    try:
        return json.loads(payload)
    except ValueError as e:
        raise StoreCorruptionError(f"met:db_config: undecodable ({e})")


__all__ = [
    "CURRENT_SCHEMA_VERSION",
    "ENVELOPED_META",
    "MigrationError",
    "StoreCorruptionError",
    "migrate_schema",
    "read_db_config",
    "read_schema_version",
    "register_migration",
]
