"""Store schema versioning and on-open migrations.

Rebuild of /root/reference/beacon_node/store/src/metadata.rs +
/root/reference/beacon_node/beacon_chain/src/schema_change.rs: the DB
records its schema version; on open, registered migration steps upgrade
it version-by-version (each step atomic), and an unknown/newer version is
a hard error.  The database-manager CLI calls `migrate` explicitly for
downgrades-by-tool or offline upgrades.
"""

from __future__ import annotations

from typing import Callable

from lighthouse_tpu.store.kv import KeyValueOp

# This module OWNS the schema/config keys; hot_cold.py imports them so the
# on-disk key bytes have exactly one definition.
P_META = b"met:"
K_SCHEMA = P_META + b"schema"
K_DB_CONFIG = P_META + b"db_config"

CURRENT_SCHEMA_VERSION = 2


class MigrationError(ValueError):
    pass


# registry: from_version -> (to_version, step). Steps receive the HotColdDB
# and must apply their writes atomically.
_UP: dict[int, tuple[int, Callable]] = {}
_DOWN: dict[int, tuple[int, Callable]] = {}


def register_migration(from_v: int, to_v: int, up: Callable,
                       down: Callable | None = None) -> None:
    _UP[from_v] = (to_v, up)
    if down is not None:
        _DOWN[to_v] = (from_v, down)


def read_schema_version(db) -> int:
    raw = db.hot.get(K_SCHEMA)
    if raw is None:
        return 0
    return int.from_bytes(raw, "little")


def _write_version(db, version: int, extra_ops=()) -> None:
    ops = [KeyValueOp(K_SCHEMA, version.to_bytes(8, "little")), *extra_ops]
    db.hot.do_atomically(ops)


def initialize_fresh(db) -> int:
    """Fresh DB: stamp v1 then walk the registry to current, so every
    version's on-disk side effects are applied exactly as an upgrade
    would (no hand-maintained 'fresh init' duplicating the steps)."""
    _write_version(db, 1)
    return migrate_schema(db)


def migrate_schema(db, target: int | None = None) -> int:
    """Walk registered steps from the stored version to `target`
    (default: CURRENT_SCHEMA_VERSION).  Returns the final version."""
    target = CURRENT_SCHEMA_VERSION if target is None else target
    v = read_schema_version(db)
    if v == 0:
        # fresh DB: start from v1 and walk the registry like any upgrade
        _write_version(db, 1)
        v = 1
    while v < target:
        if v not in _UP:
            raise MigrationError(
                f"no migration path from schema v{v} toward v{target}")
        to_v, step = _UP[v]
        step(db)
        _write_version(db, to_v)
        v = to_v
    while v > target:
        if v not in _DOWN:
            raise MigrationError(
                f"no downgrade path from schema v{v} toward v{target}")
        to_v, step = _DOWN[v]
        step(db)
        _write_version(db, to_v)
        v = to_v
    return v


# --- v1 -> v2: persist the on-disk config ----------------------------------
# The reference's OnDiskStoreConfig guards against reopening a freezer with
# an incompatible slots_per_restore_point; v2 stores it in metadata and
# HotColdDB.__init__ validates it on open.

def _v1_to_v2(db) -> None:
    import json

    cfg = json.dumps({
        "slots_per_restore_point": db.slots_per_restore_point,
    }).encode()
    db.hot.do_atomically([KeyValueOp(K_DB_CONFIG, cfg)])


def _v2_to_v1(db) -> None:
    db.hot.do_atomically([KeyValueOp(K_DB_CONFIG, None)])


register_migration(1, 2, _v1_to_v2, _v2_to_v1)


def read_db_config(db) -> dict | None:
    import json

    raw = db.hot.get(K_DB_CONFIG)
    return None if raw is None else json.loads(raw)


__all__ = [
    "CURRENT_SCHEMA_VERSION",
    "MigrationError",
    "migrate_schema",
    "read_db_config",
    "read_schema_version",
    "register_migration",
]
