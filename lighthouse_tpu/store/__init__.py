"""Persistence: KV engines + hot/cold split beacon database.

Reference: /root/reference/beacon_node/store.
"""

from lighthouse_tpu.store.hot_cold import (
    SCHEMA_VERSION,
    HotColdDB,
    HotStateSummary,
    StoreError,
)
from lighthouse_tpu.store.kv import (
    KeyValueOp,
    KeyValueStore,
    MemoryStore,
    NativeKVStore,
)

__all__ = [
    "HotColdDB",
    "HotStateSummary",
    "StoreError",
    "SCHEMA_VERSION",
    "KeyValueStore",
    "KeyValueOp",
    "MemoryStore",
    "NativeKVStore",
]
