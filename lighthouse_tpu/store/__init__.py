"""Persistence: KV engines + hot/cold split beacon database.

Reference: /root/reference/beacon_node/store.
"""

from lighthouse_tpu.store.crash import (
    CrashPointStore,
    InjectedCrash,
    InjectedIOError,
    StoreFaultPlan,
)
from lighthouse_tpu.store.envelope import StoreCorruptionError
from lighthouse_tpu.store.hot_cold import (
    HotColdDB,
    HotStateSummary,
    StoreError,
)
from lighthouse_tpu.store.kv import (
    KeyValueOp,
    KeyValueStore,
    MemoryStore,
    NativeKVStore,
    SqliteStore,
)
from lighthouse_tpu.store.migrations import (
    CURRENT_SCHEMA_VERSION,
    MigrationError,
    migrate_schema,
    read_schema_version,
)

__all__ = [
    "CURRENT_SCHEMA_VERSION",
    "CrashPointStore",
    "HotColdDB",
    "HotStateSummary",
    "InjectedCrash",
    "InjectedIOError",
    "KeyValueOp",
    "KeyValueStore",
    "MemoryStore",
    "MigrationError",
    "NativeKVStore",
    "SqliteStore",
    "StoreCorruptionError",
    "StoreError",
    "StoreFaultPlan",
    "migrate_schema",
    "read_schema_version",
]
