"""Historic-state reconstruction for the freezer.

Rebuild of /root/reference/beacon_node/store/src/reconstruct.rs: after a
checkpoint sync the freezer holds block roots (from backfill) but no
historic states.  Reconstruction replays forward from the oldest restore
point (or genesis anchor), writing each restore point's full state and
every slot's canonical state root, so `get_cold_state_by_slot` works for
the whole chain.  Runs incrementally: each call processes up to
`max_slots` and persists progress, mirroring the reference's batched
background reconstruction.
"""

from __future__ import annotations

from lighthouse_tpu.store.hot_cold import (
    P_COLD_STATE,
    P_COLD_STATE_ROOT,
    StoreError,
    _slot_key,
)
from lighthouse_tpu.store.kv import KeyValueOp


def oldest_reconstructed_slot(db) -> int | None:
    """Highest contiguous slot (from 0) whose cold state root exists."""
    slot = 0
    if db.cold.get(_slot_key(P_COLD_STATE, 0)) is None:
        return None
    while (slot + 1 < db.split_slot
           and db.cold.get(_slot_key(P_COLD_STATE_ROOT, slot + 1)) is not None):
        slot += 1
    return slot


def seed_genesis_restore_point(db, genesis_state) -> None:
    """Install the network's genesis state as the slot-0 restore point.

    A checkpoint-synced freezer has block roots (from backfill) but no
    states at all — reconstruction must be seeded with the genesis state
    from the network config (the reference requires the anchor's genesis
    state the same way, reconstruct.rs)."""
    if int(genesis_state.slot) != 0:
        raise StoreError("genesis restore point must be a slot-0 state")
    db.cold.do_atomically([
        KeyValueOp(_slot_key(P_COLD_STATE, 0), db._encode_state(genesis_state)),
        KeyValueOp(_slot_key(P_COLD_STATE_ROOT, 0),
                   genesis_state.hash_tree_root()),
    ])


def reconstruct_historic_states(db, max_slots: int | None = None,
                                genesis_state=None) -> int:
    """Replay forward from the last reconstructed slot, filling cold state
    roots and restore-point states.  Returns the number of slots
    processed; 0 when reconstruction is complete or cannot start.
    `genesis_state` seeds a stateless (checkpoint-synced) freezer."""
    from lighthouse_tpu.state_transition import per_slot_processing

    start = oldest_reconstructed_slot(db)
    if start is None and genesis_state is not None:
        seed_genesis_restore_point(db, genesis_state)
        start = oldest_reconstructed_slot(db)
    if start is None:
        return 0
    end = db.split_slot
    if max_slots is not None:
        # process exactly max_slots slots (start+1 .. start+max_slots)
        end = min(end, start + max_slots + 1)
    if start + 1 >= end:
        return 0

    state = db.get_cold_state_by_slot(start)
    if state is None:
        raise StoreError(f"restore point for slot {start} unloadable")
    processed = 0
    ops: list[KeyValueOp] = []
    slot = start
    while slot + 1 < end:
        next_slot = slot + 1
        block_root = db.cold_block_root_at_slot(next_slot)
        block = db.get_block(block_root) if block_root is not None else None
        per_slot_processing(state, db.spec)
        if block is not None and int(block.message.slot) == next_slot:
            from lighthouse_tpu.state_transition import (
                SignatureStrategy,
                process_block,
            )

            process_block(state, db.spec, block,
                          SignatureStrategy.NO_VERIFICATION)
        state_root = state.hash_tree_root()
        ops.append(KeyValueOp(
            _slot_key(P_COLD_STATE_ROOT, next_slot), state_root))
        if next_slot % db.slots_per_restore_point == 0:
            ops.append(KeyValueOp(
                _slot_key(P_COLD_STATE, next_slot), db._encode_state(state)))
        slot = next_slot
        processed += 1
        if len(ops) >= 256:
            db.cold.do_atomically(ops)
            ops = []
    if ops:
        db.cold.do_atomically(ops)
    return processed


__all__ = [
    "oldest_reconstructed_slot",
    "reconstruct_historic_states",
    "seed_genesis_restore_point",
]
